"""Cloud-native ingest: ranged chunk reads, zero-copy page staging,
predictive prefetch (docs/INGEST.md).

The subsystem replaces whole-file window decode with chunk-granular
byte-range reads overlapped with device compute:

* `source`  — pluggable ByteSource (local pread / HTTP Range with
  pooling + retry), range coalescing, the `fetch_ranges` funnel;
* `stats`   — the one ledger both decode paths report to
  (`gsky_ranged_reads_total`, `gsky_ingest_overlap_ratio`, …);
* `staging` — preallocated page-grid host buffers the scene cache
  decodes into and `device_put` consumes (no intermediate copies);
* `prefetch` — the `PrefetchPlanner` warming scenes ahead of the
  request stream (pan/zoom adjacency, WCS scan order), budgeted,
  pressure-aware and cancellable.

``GSKY_INGEST=0`` is the escape hatch: every caller checks
`ingest_enabled()` per request and falls back to the byte-identical
whole-file path.
"""

from __future__ import annotations

import os

from . import stats  # noqa: F401  (re-exported module)
from .source import (ByteSource, HTTPRangeSource, LocalFileSource,  # noqa: F401
                     coalesce_ranges, fetch_ranges, open_source,
                     reset_sources, source_for)
from .staging import (StagingPool, default_staging_pool,  # noqa: F401
                      reset_staging_pool)
from .prefetch import (PrefetchPlanner, default_planner,  # noqa: F401
                       reset_default_planner)


def ingest_enabled() -> bool:
    """GSKY_INGEST=0 escape hatch — read per call so a live server can
    flip back to whole-file decode without restart."""
    return os.environ.get("GSKY_INGEST", "1") != "0"


def window_route_frac() -> float:
    """Footprint fraction under which a non-resident scene is served
    through the ranged window path instead of whole-scene residency
    (``GSKY_INGEST_WINDOW_FRAC``).  Default 0 = routing off: declining
    residency makes the fused dispatch fall back to the modular window
    path, which is the right trade only when the operator knows the
    workload is cold-heavy (sparse pans over a huge archive) — so it is
    opt-in, unlike ranged reads and staging which change no behaviour."""
    try:
        return float(os.environ.get("GSKY_INGEST_WINDOW_FRAC", 0.0))
    except (TypeError, ValueError):
        return 0.0
