"""Preallocated host staging buffers sized to the page grid.

The scene-cache load used to materialise three full-scene host arrays
per load: the decoded window, its f32 cast, and the bucket-padded copy
`jax.device_put` ships.  With ingest on, decode writes straight into a
NaN-prefilled, page-grid-aligned staging buffer (the same (page_rows,
page_cols) multiples `pipeline/pages.py` cuts scenes into), NaN-encode
happens in place, and `device_put` consumes the very same buffer — one
allocation, zero intermediate copies, and pool pages stage from the
resulting device scene without re-pulling overlapping windows.

Reuse is upload-safe: a released buffer parks in a cooling list tied
to the device array it backed and only returns to the free list once
that upload is observably complete (``dev.is_ready()``) or the device
array itself has been collected — ``device_put`` is async, and
recycling the host memory under an in-flight DMA would corrupt the
scene.  Capacity is bounded by ``GSKY_STAGING_MB`` (default 128);
beyond it, `acquire` simply allocates an unpooled buffer (degradation
is an extra allocation, never a stall).
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class StagingPool:
    """Shape-keyed free list of NaN-prefilled f32 host buffers."""

    def __init__(self, max_mb: Optional[int] = None):
        self._lock = threading.Lock()
        self._free: Dict[Tuple[int, int], List[np.ndarray]] = {}
        self._cooling: List[Tuple[object, np.ndarray]] = []  # (dev ref, buf)
        self._bytes = 0
        self._max_bytes = (max_mb if max_mb is not None
                           else _env_int("GSKY_STAGING_MB", 128)) << 20
        self.allocated = 0
        self.reused = 0
        self.unpooled = 0

    def _drain_cooling_locked(self) -> None:
        still = []
        for ref, buf in self._cooling:
            dev = ref() if isinstance(ref, weakref.ref) else ref
            done = dev is None
            if not done:
                is_ready = getattr(dev, "is_ready", None)
                try:
                    done = bool(is_ready()) if callable(is_ready) else False
                except Exception:
                    done = True
            if done:
                self._free.setdefault(buf.shape, []).append(buf)
            else:
                still.append((ref, buf))
        self._cooling = still

    def acquire(self, rows: int, cols: int) -> np.ndarray:
        """A NaN-filled f32 (rows, cols) buffer — pooled when one of
        the shape is free (or cooled), freshly allocated otherwise."""
        shape = (int(rows), int(cols))
        with self._lock:
            self._drain_cooling_locked()
            bucket = self._free.get(shape)
            if bucket:
                buf = bucket.pop()
                self.reused += 1
                buf.fill(np.nan)
                return buf
            nbytes = shape[0] * shape[1] * 4
            pooled = self._bytes + nbytes <= self._max_bytes
            if pooled:
                self._bytes += nbytes
                self.allocated += 1
            else:
                self.unpooled += 1
        buf = np.full(shape, np.nan, np.float32)
        if not pooled:
            buf = _Unpooled(buf)
        return buf

    def release(self, buf: np.ndarray, dev=None) -> None:
        """Return a buffer.  With ``dev`` (the device array fed from
        this buffer) the buffer cools until the upload is done; without
        it the buffer is free immediately (caller guarantees no
        in-flight consumer)."""
        if isinstance(buf, _Unpooled):
            return
        base = buf if buf.base is None else buf.base
        if dev is not None and _aliases(dev, base):
            # CPU jax may zero-copy device_put: the "device" array IS
            # this host memory, forever.  Uncharge and forget the
            # buffer — recycling it would rewrite the resident scene.
            with self._lock:
                self._bytes = max(0, self._bytes - base.nbytes)
            return
        with self._lock:
            if dev is not None:
                try:
                    ref: object = weakref.ref(dev)
                except TypeError:
                    ref = dev
                self._cooling.append((ref, base))
            else:
                self._free.setdefault(base.shape, []).append(base)

    def stats(self) -> Dict:
        with self._lock:
            free = sum(len(v) for v in self._free.values())
            return {"allocated": self.allocated, "reused": self.reused,
                    "unpooled": self.unpooled, "free": free,
                    "cooling": len(self._cooling),
                    "pool_bytes": self._bytes,
                    "max_bytes": self._max_bytes}

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._cooling.clear()
            self._bytes = 0
            self.allocated = self.reused = self.unpooled = 0


def _aliases(dev, base: np.ndarray) -> bool:
    """True when a device array shares memory with the host buffer that
    fed it (CPU-backend zero-copy device_put).  Errs towards True —
    "can't prove it's safe" must mean "don't recycle"."""
    try:
        plats = {d.platform for d in dev.devices()}
        if plats and plats != {"cpu"}:
            return False
        return bool(np.shares_memory(np.asarray(dev), base))
    except Exception:
        return True


class _Unpooled(np.ndarray):
    """Marker subclass for over-budget buffers: behaves as a normal
    array, silently dropped on release."""

    def __new__(cls, arr: np.ndarray):
        return arr.view(cls)


_default: Optional[StagingPool] = None
_default_lock = threading.Lock()


def default_staging_pool() -> StagingPool:
    global _default
    with _default_lock:
        if _default is None:
            _default = StagingPool()
        return _default


def reset_staging_pool() -> None:
    """Test hook: drop the singleton so GSKY_STAGING_MB re-reads."""
    global _default
    with _default_lock:
        _default = None
