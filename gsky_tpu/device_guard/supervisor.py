"""Device supervisor: every TPU dispatch supervised, every failure
survivable (docs/RESILIENCE.md "Device failures").

PRs 3/6/9 made the *fleet* survive faults; this module supervises the
*device*.  Four failure shapes are classified and routed:

- **hang** — a device sync (readback / block_until_ready) runs under a
  monitored deadline (``GSKY_DEVICE_HANG_S``, :func:`supervised_sync`);
  exceeding it raises :class:`DeviceHang` and marks the device suspect.
- **crash** — an ``XlaRuntimeError`` (or any INTERNAL-status runtime
  failure) out of a dispatch marks the device suspect; the request
  fails retryably (:class:`DeviceGuardError` subclasses
  ``BackendUnavailable``, so the gateway answers 503 + Retry-After and
  the worker client fails over without a breaker penalty).
- **oom** — ``RESOURCE_EXHAUSTED`` triggers the one-shot relief
  protocol (pool trim + pressure escalation + registered batch-cap
  hooks) and a single retry before failing (:func:`run`).
- **corruption** — the readback integrity probe
  (:func:`integrity_check`; ±inf is never a legal output value — the
  pipeline encodes validity as NaN) quarantines poisoned pages via the
  pool audit when ``GSKY_POOL_AUDIT=1``, else falls back to a full
  rebuild.

State machine::

    healthy --incident--> suspect --backoff elapsed--> reinitializing
       ^                                                  |       |
       +------------------- rebuild ok -------------------+       |
                                          repeated rebuild failure v
                                                                 dead

A suspect device admits no dispatches until its jittered exponential
backoff (``GSKY_DEVICE_REINIT_BACKOFF`` = "base,cap" seconds) elapses;
the first dispatch past the deadline performs the rebuild inline —
teardown the page pool, probe the backend with a trivial synced op,
then warm-rehydrate the pool from the residency journal
(device_guard/journal.py).  Requests arriving mid-backoff get
:class:`DeviceReinitializing` with ``retry_after`` set to the remaining
wait, so the router routes around the node instead of queueing into it.

``GSKY_DEVICE_GUARD=0`` is the escape hatch: read per call, every
entry point returns to the exact pre-guard code path (asserted
byte-identical in tier-1).
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from ..resilience.breaker import BackendUnavailable

HEALTHY, SUSPECT, REINITIALIZING, DEAD = 0, 1, 2, 3
STATE_NAMES = {HEALTHY: "healthy", SUSPECT: "suspect",
               REINITIALIZING: "reinitializing", DEAD: "dead"}

# consecutive failed rebuilds before the node declares itself dead and
# reports fatal through the fleet handshake
MAX_REINIT_FAILURES = 6


class DeviceGuardError(BackendUnavailable):
    """A supervised device failure.  Subclasses ``BackendUnavailable``
    so the gateway's existing handler answers 503 + Retry-After, and
    carries ``retryable`` so retry policies treat it like a transport
    fault rather than a caller bug."""

    retryable = True


class DeviceHang(DeviceGuardError):
    """A device sync exceeded its watchdog deadline."""


class DeviceCorruption(DeviceGuardError):
    """The output-integrity probe rejected a readback."""


class DeviceReinitializing(DeviceGuardError):
    """The device is mid-backoff or mid-rebuild; retry elsewhere."""


class DeviceDead(DeviceGuardError):
    """Rebuilds keep failing; only operator intervention recovers."""

    retryable = False


def guard_enabled() -> bool:
    """Escape hatch, read per call so it is live-tunable — the
    GSKY_TILE_PIPELINE / GSKY_PAGED idiom."""
    return os.environ.get("GSKY_DEVICE_GUARD", "1") != "0"


def hang_deadline_s() -> float:
    try:
        return float(os.environ.get("GSKY_DEVICE_HANG_S", "30"))
    except ValueError:
        return 30.0


def pool_audit_enabled() -> bool:
    return os.environ.get("GSKY_POOL_AUDIT", "") == "1"


def _backoff_spec() -> tuple:
    """GSKY_DEVICE_REINIT_BACKOFF = "base,cap" seconds (default
    "0.5,8"): attempt N waits min(cap, base * 2**N), jittered."""
    raw = os.environ.get("GSKY_DEVICE_REINIT_BACKOFF", "0.5,8")
    try:
        parts = [float(x) for x in raw.split(",")]
        base = max(0.01, parts[0])
        cap = max(base, parts[1]) if len(parts) > 1 else max(base, 8.0)
        return base, cap
    except (ValueError, IndexError):
        return 0.5, 8.0


def classify(exc: BaseException) -> Optional[str]:
    """Map an exception out of a device dispatch to an incident kind
    ("hang" / "oom" / "crash" / "corrupt"), or None for errors that are
    not the device's fault.  Matching is on status strings / type
    names, not jaxlib imports, so injected faults and real
    ``XlaRuntimeError`` failures ride the identical path."""
    if isinstance(exc, DeviceHang):
        return "hang"
    if isinstance(exc, DeviceCorruption):
        return "corrupt"
    msg = f"{type(exc).__name__}: {exc}"
    if "RESOURCE_EXHAUSTED" in msg or "Resource exhausted" in msg:
        return "oom"
    if type(exc).__name__ == "XlaRuntimeError" or "INTERNAL:" in msg:
        return "crash"
    return None


class DeviceSupervisor:
    """The per-process device state machine.  Thread-safe; the clock is
    injectable for tests (the PressureMonitor pattern)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.RLock()
        self._rng = random.Random(0xD06)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._state = HEALTHY
            self._since = self._clock()
            self._incident = ""     # kind that took the device out
            self._next_attempt = 0.0
            self._failures = 0      # consecutive failed rebuilds
            self.reinits = 0
            self.hangs = 0
            self.crashes = 0
            self.ooms = 0
            self.oom_retries = 0
            self.corruptions = 0
            self.quarantined_pages = 0
            self.rehydrated_pages = 0
            self.last_error = ""
            self.incidents: deque = deque(maxlen=32)

    # -- state ---------------------------------------------------------

    def state(self) -> int:
        with self._lock:
            return self._state

    def state_name(self) -> str:
        return STATE_NAMES[self.state()]

    def staging_ok(self) -> bool:
        """Page staging grows device residency — decline it the moment
        the device is anything but healthy (pages.table_for hook)."""
        return not guard_enabled() or self.state() == HEALTHY

    def _note(self, kind: str, site: str, exc=None) -> None:  # gskylint: holds-lock
        self.incidents.append({
            "kind": kind, "site": site, "t": round(self._clock(), 3),
            "error": str(exc)[:200] if exc is not None else ""})
        if exc is not None:
            self.last_error = f"{type(exc).__name__}: {exc}"[:200]

    def _mark_suspect(self, kind: str) -> None:  # gskylint: holds-lock
        # holds self._lock
        if self._state in (DEAD, REINITIALIZING):
            return
        self._incident = kind
        if self._state != SUSPECT:
            self._state = SUSPECT
            self._since = self._clock()
        base, cap = _backoff_spec()
        delay = min(cap, base * (2.0 ** self._failures))
        delay *= 0.5 + self._rng.random()       # jitter 0.5x .. 1.5x
        self._next_attempt = self._clock() + delay

    # -- incident recording --------------------------------------------

    def record_hang(self, site: str, exc=None) -> None:
        with self._lock:
            self.hangs += 1
            self._note("hang", site, exc)
            self._mark_suspect("hang")

    def record_crash(self, site: str, exc=None) -> None:
        with self._lock:
            self.crashes += 1
            self._note("crash", site, exc)
            self._mark_suspect("crash")

    def record_oom(self, site: str, exc=None, fatal: bool = False) -> None:
        """A RESOURCE_EXHAUSTED.  Non-fatal OOMs ride the relief+retry
        protocol and do NOT suspect the device; a fatal one (the retry
        also exhausted) does."""
        with self._lock:
            self.ooms += 1
            self._note("oom", site, exc)
            if fatal:
                self._mark_suspect("oom")

    def record_corruption(self, site: str, exc=None) -> None:
        """A poisoned readback.  With GSKY_POOL_AUDIT=1 the pool's
        checksum audit runs first: if it finds and quarantines the
        poisoned pages, the device stays in service (re-staging heals
        it); otherwise fall back to a full suspect->rebuild cycle."""
        with self._lock:
            self.corruptions += 1
            self._note("corrupt", site, exc)
        quarantined = 0
        if pool_audit_enabled():
            try:
                from ..pipeline import pages
                if pages._default is not None:
                    quarantined = pages._default.audit()
            except Exception:
                quarantined = 0
        with self._lock:
            self.quarantined_pages += quarantined
            if quarantined <= 0:
                self._mark_suspect("corrupt")

    # -- admission + rebuild -------------------------------------------

    def admit(self, site: str = "dispatch") -> None:
        """Gate a dispatch on device health.  Healthy passes for free;
        suspect raises retryably until the backoff elapses, then the
        admitting thread performs the rebuild inline (the request pays
        the rehydration latency — everyone after it gets a warm pool)."""
        if not guard_enabled():
            return
        with self._lock:
            st = self._state
            if st == HEALTHY:
                return
            if st == DEAD:
                raise DeviceDead(
                    f"device dead after {self._failures} failed rebuilds"
                    f" (last: {self.last_error or self._incident})",
                    site=site, retry_after=60.0)
            now = self._clock()
            if st == REINITIALIZING or now < self._next_attempt:
                raise DeviceReinitializing(
                    f"device {STATE_NAMES[st]} after {self._incident}",
                    site=site,
                    retry_after=max(0.1, self._next_attempt - now))
            self._state = REINITIALIZING
        ok = False
        try:
            ok = self._reinitialize()
        finally:
            with self._lock:
                if ok:
                    self._state = HEALTHY
                    self._failures = 0
                    self._incident = ""
                    self._since = self._clock()
                else:
                    self._failures += 1
                    if self._failures >= MAX_REINIT_FAILURES:
                        self._state = DEAD
                    else:
                        self._state = SUSPECT
                        self._mark_suspect(self._incident or "crash")
        if not ok:
            raise DeviceReinitializing(
                f"device rebuild failed ({self.last_error})", site=site,
                retry_after=max(0.1, self._next_attempt - self._clock()))

    def _reinitialize(self) -> bool:
        """Tear down + rebuild: journal-dump and drop the page pool,
        prove the backend answers with a trivial synced op (under the
        hang watchdog — a still-wedged device must fail the rebuild,
        not block it), then warm-rehydrate the pool."""
        with self._lock:
            self.reinits += 1
        try:
            pool = None
            try:
                from ..pipeline import pages
                pool = pages._default
            except Exception:
                pool = None
            if pool is not None:
                pool.teardown()
            import jax
            import jax.numpy as jnp
            try:
                backend = jax.default_backend()
            except Exception:
                backend = "cpu"
            if backend not in ("cpu",):
                # a real accelerator rebuild must not reuse executables
                # compiled against the pre-incident device state
                try:
                    jax.clear_caches()
                except Exception:  # cache clear is best-effort on older jax
                    pass
            supervised_sync(
                "device.probe",
                lambda: jax.block_until_ready(
                    jnp.zeros((8,), jnp.float32) + 1.0))
            restored = 0
            if pool is not None:
                restored = pool.rehydrate()
            with self._lock:
                self.rehydrated_pages += restored
            return True
        except Exception as e:   # noqa: BLE001 - any failure = not ok
            with self._lock:
                self.last_error = f"{type(e).__name__}: {e}"[:200]
            return False

    # -- reporting ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            now = self._clock()
            return {
                "enabled": guard_enabled(),
                "state": STATE_NAMES[self._state],
                "state_code": self._state,
                "since_s": round(now - self._since, 3),
                "incident": self._incident,
                "retry_in_s": round(max(0.0, self._next_attempt - now), 3)
                if self._state in (SUSPECT, REINITIALIZING) else 0.0,
                "reinit_failures": self._failures,
                "reinits": self.reinits,
                "hangs": self.hangs,
                "crashes": self.crashes,
                "ooms": self.ooms,
                "oom_retries": self.oom_retries,
                "corruptions": self.corruptions,
                "quarantined_pages": self.quarantined_pages,
                "rehydrated_pages": self.rehydrated_pages,
                "hang_deadline_s": hang_deadline_s(),
                "audit": pool_audit_enabled(),
                "last_error": self.last_error,
                "incidents": list(self.incidents),
            }


_default = DeviceSupervisor()


def default_supervisor() -> DeviceSupervisor:
    return _default


def staging_ok() -> bool:
    return _default.staging_ok()


# hooks run by the OOM relief protocol (the executor registers a
# batch-cap reduction here so the retry and all later waves are smaller)
_oom_hooks: List[Callable[[], None]] = []


def register_oom_hook(fn: Callable[[], None]) -> None:
    if fn not in _oom_hooks:
        _oom_hooks.append(fn)


_UNSET = object()

# -- two-in-flight wave supervision -------------------------------------
#
# The pipelined wave scheduler (pipeline/waves.py) keeps TWO waves in
# flight: wave N executing on device while wave N+1 stages its uploads.
# A staging-side sync that exceeds the watchdog is almost never the
# staging wave's fault — device_put serialises behind the executing
# program's stream, so a hung kernel presents as a hung *upload* on the
# assembly thread.  Execution windows let the watchdog attribute such a
# hang to the wave that is actually wedging the device.

_exec_lock = threading.Lock()
_exec_windows: dict = {}     # id(token) -> (site, t_start)
_exec_seq = [0]


def _staging_site(site: str) -> bool:
    """Staging-class sites: device uploads issued AHEAD of the program
    that will consume them (``wave.stage`` / ``mesh.stage``)."""
    return site.endswith(".stage")


class execution_window:
    """Marks ``site`` as the device program currently executing, for
    hang attribution while a second (staging) wave is in flight."""

    def __init__(self, site: str):
        self.site = site

    def __enter__(self):
        with _exec_lock:
            _exec_seq[0] += 1
            self._key = _exec_seq[0]
            _exec_windows[self._key] = (self.site, time.monotonic())
        return self

    def __exit__(self, *exc):
        with _exec_lock:
            _exec_windows.pop(self._key, None)
        return False


def attribute_hang(site: str) -> str:
    """Resolve which wave a watchdog timeout belongs to.

    A hang at an executing site is its own; a hang at a *staging* site
    while an older execution window is still open is attributed to the
    executing wave (the staging upload queued behind the wedged
    program).  With no execution window open, the staging site keeps
    the blame — the upload itself wedged."""
    if not _staging_site(site):
        return site
    with _exec_lock:
        live = sorted(_exec_windows.values(), key=lambda p: p[1])
    return live[0][0] if live else site


def supervised_sync(site: str, thunk: Callable,
                    deadline_s: Optional[float] = None):
    """Run a device sync under the hang watchdog.

    The sync executes on a daemon thread joined with the deadline: a
    hung ``np.asarray`` / ``block_until_ready`` cannot be interrupted
    from its own thread, so on timeout the orphaned thread is abandoned
    to the wedged runtime and the *caller* gets :class:`DeviceHang`
    (the supervisor is marked suspect first).  Fault-injection site
    ``device`` fires inside the watchdog scope, so ``device:hang:..``
    specs exercise the real deadline path.
    """
    if not guard_enabled():
        return thunk()
    deadline = hang_deadline_s() if deadline_s is None else deadline_s
    out = [_UNSET, None]

    def _run():
        try:
            from ..resilience import faults
            faults.inject("device")
            if _staging_site(site):
                out[0] = thunk()
            else:
                # window held by the SYNC thread: a hung dispatch keeps
                # its window open after the watchdog abandons it, so a
                # staging hang queued behind it attributes correctly
                with execution_window(site):
                    out[0] = thunk()
        except BaseException as e:   # noqa: BLE001 - re-raised below
            out[1] = e

    t = threading.Thread(target=_run, daemon=True, name="gsky-devsync")
    t.start()
    t.join(deadline if deadline > 0 else None)
    if t.is_alive():
        blame = attribute_hang(site)
        _default.record_hang(blame)
        detail = "" if blame == site else \
            f" (attributed to executing {blame!r})"
        raise DeviceHang(
            f"device sync {site!r} exceeded {deadline:.3g}s"
            f" watchdog{detail}", site=blame)
    if out[1] is not None:
        raise out[1]
    return out[0]


def _oom_relief() -> None:
    """The one-shot RESOURCE_EXHAUSTED relief protocol: trim the page
    pool's cold half, escalate the pressure monitor (cache relief +
    admission clamp + brownout), and run registered batch-cap hooks."""
    try:
        from ..pipeline import pages
        if pages._default is not None:
            pages._default.trim(0.5)
    except Exception:  # no page pool allocated yet - nothing to trim
        pass
    try:
        from ..resilience.pressure import default_monitor
        default_monitor().escalate()
    except Exception:  # pressure monitor absent - relief is best-effort
        pass
    for fn in list(_oom_hooks):
        try:
            fn()
        except Exception:  # one failing OOM hook must not stop the rest
            pass


def run(site: str, thunk: Callable, reduced: Optional[Callable] = None):
    """Execute a device dispatch under full supervision: admission
    gate, fault injection, hang watchdog, incident classification, and
    the OOM relief+retry protocol.  ``reduced``, when given, is the
    reduced-batch variant used for the post-relief retry.

    With ``GSKY_DEVICE_GUARD=0`` this is exactly ``thunk()``.
    """
    if not guard_enabled():
        return thunk()
    sup = _default
    sup.admit(site)
    try:
        return supervised_sync(site, thunk)
    except DeviceGuardError:
        raise                   # hang: already recorded and typed
    except Exception as e:
        kind = classify(e)
        if kind == "oom":
            sup.record_oom(site, e)
            _oom_relief()
            retry = reduced if reduced is not None else thunk
            try:
                result = supervised_sync(site, retry)
            except DeviceGuardError:
                raise
            except Exception as e2:
                sup.record_oom(site, e2, fatal=True)
                raise DeviceGuardError(
                    f"device OOM at {site!r} persisted after relief:"
                    f" {e2}", site=site) from e2
            with sup._lock:
                sup.oom_retries += 1
            return result
        if kind == "crash":
            sup.record_crash(site, e)
            raise DeviceGuardError(
                f"device crash at {site!r}: {e}", site=site) from e
        raise


def integrity_check(site: str, arr) -> None:
    """The cheap output-integrity probe: sample the readback on a
    stride and reject it if any value is ±inf.  NaN is the pipeline's
    legal validity encoding and appears in every off-footprint region;
    inf is produced by NOTHING in the render path, so its presence
    means the device (or the DMA back from it) corrupted the buffer."""
    if not guard_enabled():
        return
    try:
        a = np.asarray(arr)
    except Exception:
        return
    if a.dtype.kind != "f" or a.size == 0:
        return
    flat = a.reshape(-1)
    step = max(1, flat.size // 4096)
    if np.isinf(flat[::step]).any():
        _default.record_corruption(site)
        raise DeviceCorruption(
            f"readback at {site!r} failed the integrity probe"
            " (non-finite beyond NaN validity)", site=site)


def _poison(arr):
    """device:corrupt injection: flip alternate floats to inf on a COPY
    of the readback — the shape a flaky HBM/DMA bit-flip presents."""
    a = np.array(arr, copy=True)
    if a.dtype.kind == "f" and a.size:
        a.reshape(-1)[::2] = np.inf
    return a


def guarded_readback(site: str, thunk: Callable):
    """Supervised readback: :func:`run` (watchdog + classification)
    plus corruption injection and the integrity probe on the result."""
    if not guard_enabled():
        return thunk()
    arr = run(site, thunk)
    from ..resilience import faults
    if faults.flag("device", "corrupt"):
        arr = _poison(arr)
    integrity_check(site, arr)
    return arr


def reset() -> None:
    """Test hook: fresh supervisor state.  Registered OOM hooks are
    kept — they are wired once at executor construction and must
    survive test resets the way the executor singleton does."""
    with _exec_lock:
        _exec_windows.clear()
    _default.reset()
