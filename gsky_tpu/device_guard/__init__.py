"""Device guard: supervised dispatch + warm recovery.

See supervisor.py for the state machine and docs/RESILIENCE.md
("Device failures") for the operational story.
"""

from .supervisor import (  # noqa: F401
    DEAD,
    HEALTHY,
    REINITIALIZING,
    SUSPECT,
    STATE_NAMES,
    DeviceCorruption,
    DeviceDead,
    DeviceGuardError,
    DeviceHang,
    DeviceReinitializing,
    DeviceSupervisor,
    classify,
    default_supervisor,
    guard_enabled,
    guarded_readback,
    hang_deadline_s,
    integrity_check,
    pool_audit_enabled,
    register_oom_hook,
    reset,
    run,
    staging_ok,
    supervised_sync,
)
from . import journal  # noqa: F401
