"""Page-residency journal: the durable half of warm pool recovery.

The HBM page pool (pipeline/pages.py) is the state every serving-loop
optimisation leans on — and a device incident throws all of it away.
This journal records *which pages were resident and how hot they were*
so a rebuilt pool can re-stage its working set from scenes still in the
host-side scene cache instead of cold-starting into a miss storm.

The format deliberately mirrors the kernel race ledger
(ops/kernel_ledger.py): one JSONL file (``GSKY_POOL_JOURNAL``, default
under the metrics log dir when the server configures one, else the
system tmp dir), records appended atomically (O_APPEND, one line per
event, kept under PIPE_BUF), corrupt or newer-schema lines skipped on
replay, delete the file to forget everything.

Event schema (one JSON object per line)::

    {"v": 1, "op": "stage", "serial": 12, "pi": 0, "pj": 3,
     "ts": 1754000000.0, "pid": 42}
    {"v": 1, "op": "heat",  "serial": 12, "pi": 0, "pj": 3, "hits": 17, ...}
    {"v": 1, "op": "drop",  "serial": 12, ...}

``stage`` is appended when a page is first staged (cold path only, so
the write rate tracks decode churn, not the hit rate).  ``heat`` lines
are dumped by ``PagePool.teardown()`` — the supervisor tears the pool
down with the host process alive, so the exact pre-incident hot set
with in-memory hit counts is available and journaled.  ``drop`` voids
all earlier events for a scene serial (scene-cache eviction: those
pages can no longer be re-staged).

``replay()`` merges the log into a hottest-first page list; staleness
(a serial no longer resident in the scene cache) is the *caller's*
check — replay only orders.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

_ENV = "GSKY_POOL_JOURNAL"
_DEFAULT_NAME = "gsky_pool_journal.jsonl"

SCHEMA_VERSION = 1

_OPS = ("stage", "heat", "drop")

_lock = threading.Lock()
# set by the server from its metrics -log_dir; env always wins
_default_dir: Optional[str] = None


def set_default_dir(path: str) -> None:
    """Point the default journal location at the metrics log dir
    (called by server startup; GSKY_POOL_JOURNAL still overrides)."""
    global _default_dir
    _default_dir = path or None


def journal_enabled() -> bool:
    """``GSKY_POOL_JOURNAL=0`` disables journaling (and therefore warm
    recovery) without touching the rest of the device guard."""
    return os.environ.get(_ENV, "") != "0"


def journal_path() -> str:
    p = os.environ.get(_ENV)
    if p and p != "0":
        return p
    if _default_dir:
        return os.path.join(_default_dir, _DEFAULT_NAME)
    return os.path.join(tempfile.gettempdir(), _DEFAULT_NAME)


def _append(doc: Dict) -> None:
    """Append one event atomically.  Never raises — the journal is an
    optimisation; a lost line only costs one page of warmth."""
    try:
        doc = {"v": SCHEMA_VERSION, **doc,
               "ts": round(time.time(), 3), "pid": os.getpid()}
        data = (json.dumps(doc, separators=(",", ":")) + "\n").encode()
        if len(data) > 4096:    # PIPE_BUF floor: stay atomic or stay out
            return
        path = journal_path()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with _lock:
            fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                         0o644)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
    except Exception:   # noqa: BLE001 - never fail staging over IO
        pass


def _chip_doc(doc: Dict, chip) -> Dict:
    """Mesh serving annotates events with the owning chip index; the
    field is additive — schema v1 `replay()` reads only the keys it
    knows, so journals mixing chip-tagged and untagged lines replay on
    either side of an upgrade."""
    if chip is not None:
        doc["chip"] = int(chip)
    return doc


def record_stage(serial: int, pi: int, pj: int, chip=None) -> None:
    if journal_enabled():
        _append(_chip_doc({"op": "stage", "serial": int(serial),
                           "pi": int(pi), "pj": int(pj)}, chip))


def record_heat(serial: int, pi: int, pj: int, hits: int,
                chip=None) -> None:
    if journal_enabled():
        _append(_chip_doc({"op": "heat", "serial": int(serial),
                           "pi": int(pi), "pj": int(pj),
                           "hits": int(hits)}, chip))


def record_drop(serial: int, chip=None) -> None:
    if journal_enabled():
        _append(_chip_doc({"op": "drop", "serial": int(serial)}, chip))


def replay(chip_map: Optional[Dict] = None,
           score_map: Optional[Dict] = None
           ) -> List[Tuple[int, int, int]]:
    """Merge the journal into a hottest-first ``[(serial, pi, pj)]``.
    ``chip_map`` (optional out-param) collects the per-chip ownership
    tags mesh serving appends — see :func:`replay_chips`.
    ``score_map`` (optional out-param) collects each page's merged
    heat score — see :func:`replay_scored`.

    Priority is (accumulated heat + stage count, recency): a page the
    pool dumped with 17 hits outranks a page staged once and never
    shared.  Corrupt lines, unknown ops, newer-schema lines, and events
    voided by a later ``drop`` are all skipped — a torn write or a
    stale file must never poison a rebuild.
    """
    if not journal_enabled():
        return []
    score: Dict[Tuple[int, int, int], float] = {}
    last: Dict[Tuple[int, int, int], int] = {}
    chips: Dict[Tuple[int, int, int], int] = {}
    try:
        with open(journal_path(), "r", encoding="utf-8",
                  errors="replace") as fp:
            for idx, line in enumerate(fp):
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(doc, dict):
                    continue
                v = doc.get("v", 1)
                if not isinstance(v, int) or v > SCHEMA_VERSION:
                    continue
                op = doc.get("op")
                if op not in _OPS:
                    continue
                try:
                    serial = int(doc["serial"])
                except (KeyError, TypeError, ValueError):
                    continue
                if op == "drop":
                    for k in [k for k in score if k[0] == serial]:
                        score.pop(k, None)
                        last.pop(k, None)
                    continue
                try:
                    key = (serial, int(doc["pi"]), int(doc["pj"]))
                except (KeyError, TypeError, ValueError):
                    continue
                if key[1] < 0 or key[2] < 0:
                    continue
                w = 1.0
                if op == "heat":
                    try:
                        w += max(0, int(doc.get("hits", 0)))
                    except (TypeError, ValueError):
                        pass
                score[key] = score.get(key, 0.0) + w
                last[key] = idx
                try:
                    chips[key] = int(doc["chip"])
                except (KeyError, TypeError, ValueError):
                    pass
    except OSError:
        return []
    if chip_map is not None:
        chip_map.update(chips)
    if score_map is not None:
        score_map.update(score)
    return sorted(score, key=lambda k: (-score[k], -last[k]))


def replay_scored() -> List[Tuple[int, int, int, float]]:
    """Heat export for the cache fabric (`fabric/replicate.py`):
    hottest-first ``[(serial, pi, pj, score)]`` where score is the
    merged heat+stage weight `replay()` orders by.  The absolute value
    only matters relative to the other pages in the same journal —
    popularity-weighted replication keys off the ranking and ratio."""
    scores: Dict[Tuple[int, int, int], float] = {}
    order = replay(score_map=scores)
    return [(s, pi, pj, scores[(s, pi, pj)]) for s, pi, pj in order]


def replay_chips() -> Tuple[List[Tuple[int, int, int]],
                            Dict[Tuple[int, int, int], int]]:
    """`replay()` plus the chip-ownership tags mesh serving journals:
    (hottest-first page list, {(serial, pi, pj): chip}).  Pages
    journaled without a chip tag are absent from the map —
    `MeshPools.rehydrate_all` hashes those to their owner."""
    chips: Dict[Tuple[int, int, int], int] = {}
    return replay(chip_map=chips), chips


def export_hot(limit: int = 2048) -> List[Tuple[int, int, int, float]]:
    """The handoff payload a preempted node ships to its ring successor
    (fleet/elastic): the hottest-first scored page list, capped so the
    notice fits one bounded RPC even after a long serving run."""
    return replay_scored()[:max(int(limit), 0)]


def merge_scored(entries, cap: int = 2048) -> int:
    """Fold a peer's exported heat (``[(serial, pi, pj, score)]``, the
    :func:`export_hot` shape) into THIS node's journal as ``heat``
    lines, so the inherited hot set survives a local restart and ranks
    against locally-observed heat on the next replay.  Malformed
    entries are skipped — the sender may be mid-crash.  Returns the
    number of entries merged."""
    n = 0
    for e in entries:
        if n >= cap:
            break
        try:
            s, pi, pj = int(e[0]), int(e[1]), int(e[2])
            score = float(e[3]) if len(e) > 3 else 1.0
        except (TypeError, ValueError, IndexError):
            continue
        if pi < 0 or pj < 0:
            continue
        # score already folds the peer's stage+heat weight; -1 undoes
        # the +1 replay() adds per line so replayed rank is preserved
        record_heat(s, pi, pj, max(int(score) - 1, 0))
        n += 1
    return n


def clear() -> None:
    """Forget the recorded residency (test hook / operator reset) —
    the delete-the-file knob, same as the kernel ledger."""
    try:
        os.remove(journal_path())
    except OSError:
        pass


def stats() -> Dict:
    path = journal_path()
    doc: Dict = {"path": path, "enabled": journal_enabled(),
                 "present": os.path.exists(path)}
    doc["entries"] = len(replay())
    return doc
