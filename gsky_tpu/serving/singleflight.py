"""Asyncio in-flight request deduplication (singleflight).

When N identical tile requests arrive while the first is still
rendering, exactly one walks the MAS-index -> decode -> TPU pipeline;
the other N-1 await the leader's future and share its result bytes —
or its error: a failing render fails every waiter once instead of
being retried N times against an already-struggling backend (the
groupcache/golang.org/x/sync "singleflight" contract).

Flights are keyed on the same canonical digest as the response cache,
so the dedup window is exactly the cache-miss window.  Completed
flights are forgotten immediately — reuse across time is the response
cache's job, not this tier's.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Dict, Tuple


class _Call:
    __slots__ = ("loop", "future", "waiters")

    def __init__(self, loop, future):
        self.loop = loop
        self.future = future
        self.waiters = 0


class SingleFlight:
    """``await flight.do(key, fn)`` -> ``(result, joined)``.

    ``fn`` is an async callable executed by exactly one caller per key
    at a time; concurrent callers with the same key get the leader's
    result (``joined=True``).  Futures are loop-bound, so a caller on a
    *different* event loop (multi-loop test harnesses) safely bypasses
    dedup and executes ``fn`` itself.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._calls: Dict[str, _Call] = {}
        self.leaders = 0
        self.joined = 0

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._calls)

    async def do(self, key: str,
                 fn: Callable[[], Any]) -> Tuple[Any, bool]:
        loop = asyncio.get_running_loop()
        with self._lock:
            call = self._calls.get(key)
            if call is None:
                call = self._calls[key] = _Call(loop, loop.create_future())
                self.leaders += 1
                lead = True
            elif call.loop is loop:
                call.waiters += 1
                self.joined += 1
                lead = False
            else:
                call = None     # cross-loop: render independently
                lead = True

        if call is None:
            return await fn(), False
        if not lead:
            # shield: one waiter's disconnect must not cancel the
            # shared future out from under the others
            return await asyncio.shield(call.future), True

        task = asyncio.ensure_future(fn())
        try:
            result = await asyncio.shield(task)
        except asyncio.CancelledError:
            # the LEADER's client disconnected — the joined waiters'
            # clients did not.  If anyone joined, let the render finish
            # in the background and hand them the result; only an
            # unwatched flight aborts the render.
            with self._lock:
                abandoned = call.waiters == 0
                if abandoned:
                    self._calls.pop(key, None)
            if abandoned:
                task.cancel()
                call.future.cancel()
            else:
                task.add_done_callback(
                    lambda t: self._finish_orphan(key, call, t))
            raise
        except BaseException as e:
            with self._lock:
                self._calls.pop(key, None)
                waiters = call.waiters
            if waiters > 0:
                call.future.set_exception(e)
            else:       # nobody listening: avoid un-retrieved warnings
                call.future.cancel()
            raise
        else:
            with self._lock:
                self._calls.pop(key, None)
            call.future.set_result(result)
            return result, False

    def _finish_orphan(self, key: str, call: _Call, task) -> None:
        """Complete a flight whose leader was cancelled mid-render:
        relay the finished render (or its error) to the waiters."""
        with self._lock:
            self._calls.pop(key, None)
        fut = call.future
        if fut.done():
            return
        if task.cancelled():
            fut.cancel()
        elif task.exception() is not None:
            fut.set_exception(task.exception())
        else:
            fut.set_result(task.result())
