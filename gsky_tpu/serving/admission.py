"""Per-service-class admission control with load shedding.

The pipelines behind the gateway have finite concurrency (device HBM,
decode threads, worker pool slots); past that point extra in-flight
requests only grow queueing delay until every request times out at
once — the classic latency collapse.  Admission control bounds the
in-flight renders per service class (WMS tiles are cheap and plentiful,
WCS exports are heavy, WPS drills heavier), queues a short overflow,
and shifts from queueing to *shedding* once a request has waited past
its deadline: a fast OGC-exception 503 with ``Retry-After`` costs the
client a retry, not a timeout, and costs the server nothing.

Limits come from ``GSKY_ADMIT_{WMS,WCS,WPS,DAP4}``; the queue-wait
deadline from ``GSKY_ADMIT_QUEUE_S``.  The primitives are
``threading``-based (awaited via ``asyncio.to_thread``) so one
process-wide controller serves any number of event loops.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import threading
from typing import Callable, Dict, Optional


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


DEFAULT_LIMITS = {
    "WMS": _env_int("GSKY_ADMIT_WMS", 32),
    "WCS": _env_int("GSKY_ADMIT_WCS", 8),
    "WPS": _env_int("GSKY_ADMIT_WPS", 4),
    "DAP4": _env_int("GSKY_ADMIT_DAP4", 8),
}
DEFAULT_QUEUE_DEADLINE_S = _env_float("GSKY_ADMIT_QUEUE_S", 5.0)


class AdmissionShed(Exception):
    """Raised when a request waited past the queue deadline; maps to
    HTTP 503 + Retry-After at the OWS layer.

    ``alt_node``, when set, names the least-loaded healthy worker shard
    at shed time — surfaced as an ``X-GSKY-Alt-Node`` header so a
    multi-gateway deployment's balancer can steer the retry toward
    spare fleet capacity instead of re-queueing blind."""

    def __init__(self, service_class: str, retry_after: int,
                 alt_node: Optional[str] = None):
        super().__init__(
            f"{service_class} service at capacity; retry after "
            f"{retry_after}s")
        self.service_class = service_class
        self.retry_after = retry_after
        self.alt_node = alt_node


def _fleet_advisor() -> Optional[str]:
    """Default shed advisor: the least-loaded healthy node across the
    live fleet routers (None when no fleet is wired)."""
    try:
        from ..fleet import least_loaded_node
        return least_loaded_node()
    except Exception:
        return None


class _ClassState:
    __slots__ = ("limit", "sem", "in_use", "queued", "shed", "admitted")

    def __init__(self, limit: int):
        self.limit = limit
        self.sem = threading.Semaphore(limit)
        self.in_use = 0
        self.queued = 0
        self.shed = 0
        self.admitted = 0


def _release_orphaned_permit(st: _ClassState):
    """Done-callback for a queued acquire whose request was cancelled
    (client disconnect): the worker thread cannot be interrupted and may
    still win the permit after the request is gone — hand it straight
    back so the class's capacity is never leaked."""
    def _cb(task) -> None:
        try:
            acquired = (not task.cancelled()
                        and task.exception() is None and task.result())
        except BaseException:
            acquired = False
        if acquired:
            st.sem.release()
    return _cb


class AdmissionController:
    def __init__(self, limits: Optional[Dict[str, int]] = None,
                 queue_deadline_s: float = DEFAULT_QUEUE_DEADLINE_S,
                 shed_advisor: Optional[Callable[[], Optional[str]]]
                 = _fleet_advisor):
        merged = dict(DEFAULT_LIMITS)
        if limits:
            merged.update(limits)
        self._lock = threading.Lock()
        self._classes = {svc: _ClassState(n) for svc, n in merged.items()}
        self.queue_deadline_s = queue_deadline_s
        self.shed_advisor = shed_advisor

    def _state(self, service_class: str) -> _ClassState:
        st = self._classes.get(service_class)
        if st is None:      # unknown class: fail open under WMS limits
            st = self._classes.get("WMS")
            if st is None:
                with self._lock:
                    st = self._classes.setdefault(
                        service_class, _ClassState(32))
        return st

    @contextlib.asynccontextmanager
    async def admit(self, service_class: str):
        st = self._state(service_class)
        ok = st.sem.acquire(blocking=False)
        if not ok:
            with self._lock:
                st.queued += 1
            # block in a worker thread, not the event loop
            waiter = asyncio.ensure_future(asyncio.to_thread(
                st.sem.acquire, True, self.queue_deadline_s))
            try:
                ok = await asyncio.shield(waiter)
            except asyncio.CancelledError:
                waiter.add_done_callback(_release_orphaned_permit(st))
                raise
            finally:
                with self._lock:
                    st.queued -= 1
        if not ok:
            with self._lock:
                st.shed += 1
            alt = None
            if self.shed_advisor is not None:
                try:
                    alt = self.shed_advisor()
                except Exception:
                    alt = None
            raise AdmissionShed(
                service_class,
                retry_after=max(1, int(round(self.queue_deadline_s))),
                alt_node=alt)
        with self._lock:
            st.in_use += 1
            st.admitted += 1
        try:
            yield
        finally:
            with self._lock:
                st.in_use -= 1
            st.sem.release()

    @property
    def total_shed(self) -> int:
        with self._lock:
            return sum(st.shed for st in self._classes.values())

    def stats(self) -> Dict:
        with self._lock:
            return {
                "queue_deadline_s": self.queue_deadline_s,
                "classes": {
                    svc: {"limit": st.limit, "in_use": st.in_use,
                          "queued": st.queued, "admitted": st.admitted,
                          "shed": st.shed}
                    for svc, st in self._classes.items()}}
