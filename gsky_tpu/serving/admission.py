"""Per-service-class admission control with load shedding.

The pipelines behind the gateway have finite concurrency (device HBM,
decode threads, worker pool slots); past that point extra in-flight
requests only grow queueing delay until every request times out at
once — the classic latency collapse.  Admission control bounds the
in-flight renders per service class (WMS tiles are cheap and plentiful,
WCS exports are heavy, WPS drills heavier), queues a short overflow,
and shifts from queueing to *shedding* once a request has waited past
its deadline: a fast OGC-exception 503 with ``Retry-After`` costs the
client a retry, not a timeout, and costs the server nothing.

Two operating modes:

* **Fixed** (``GSKY_ADMIT_ADAPTIVE=0``): the original static permits —
  one ``threading.Semaphore`` per class sized by ``GSKY_ADMIT_*``,
  awaited via ``asyncio.to_thread``.  Byte-identical to the historical
  behaviour.
* **Adaptive** (default): an AIMD controller per class tracks the
  latency of recently completed renders against a slow-moving baseline
  and shrinks the in-flight limit multiplicatively when latency leaves
  the knee (recent EWMA > ``GSKY_ADMIT_RATIO`` x baseline), growing it
  back additively while latency is healthy.  The ``GSKY_ADMIT_*``
  value is the *ceiling*; the floor is ceiling/8 (min 1).  Host
  memory pressure (``resilience/pressure.py``) clamps the effective
  limit further (x0.5 elevated, x0.25 critical).  Waiters queue in a
  **weighted-fair per-tenant queue with priority aging**: each grant
  goes to the waiter whose tenant has consumed the least
  weight-normalised service, minus an aging credit
  (``GSKY_ADMIT_AGING`` per waited second) so no tenant starves.
  Tenant weights come from ``GSKY_TENANT_WEIGHTS``
  (``"bulk:0.25,premium:4"``; default 1.0).

Limits come from ``GSKY_ADMIT_{WMS,WCS,WPS,DAP4}``; the queue-wait
deadline from ``GSKY_ADMIT_QUEUE_S``.  Both are re-resolved every time
a controller is built (or ``reconfigure()`` runs on a SIGHUP reload) —
never latched at import time.  The primitives are ``threading``-based
(awaited via ``asyncio.to_thread``) so one process-wide controller
serves any number of event loops.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..resilience.cancel import current_token
from ..resilience.pressure import pressure_state


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# class -> (env knob, default ceiling).  Resolved at controller build,
# NOT at import: a SIGHUP reload rebuilds the controller and must see
# the environment as it is *now*.
_LIMIT_KNOBS = {
    "WMS": ("GSKY_ADMIT_WMS", 32),
    "WCS": ("GSKY_ADMIT_WCS", 8),
    "WPS": ("GSKY_ADMIT_WPS", 4),
    "DAP4": ("GSKY_ADMIT_DAP4", 8),
}


def default_limits() -> Dict[str, int]:
    return {svc: _env_int(env, d) for svc, (env, d) in _LIMIT_KNOBS.items()}


def default_queue_deadline_s() -> float:
    return _env_float("GSKY_ADMIT_QUEUE_S", 5.0)


def _tenant_weights() -> Dict[str, float]:
    """GSKY_TENANT_WEIGHTS="bulk:0.25,premium:4" -> {..}; default 1.0."""
    out: Dict[str, float] = {}
    spec = os.environ.get("GSKY_TENANT_WEIGHTS", "")
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause or ":" not in clause:
            continue
        name, _, w = clause.rpartition(":")
        try:
            out[name.strip()] = max(0.01, float(w))
        except ValueError:
            continue
    return out


# Backwards-compatible module constants (import-time snapshot). Nothing
# inside this module reads them any more — they remain only so existing
# imports keep resolving.
DEFAULT_LIMITS = default_limits()
DEFAULT_QUEUE_DEADLINE_S = default_queue_deadline_s()


class AdmissionShed(Exception):
    """Raised when a request waited past the queue deadline; maps to
    HTTP 503 + Retry-After at the OWS layer.

    ``alt_node``, when set, names the least-loaded healthy worker shard
    at shed time — surfaced as an ``X-GSKY-Alt-Node`` header so a
    multi-gateway deployment's balancer can steer the retry toward
    spare fleet capacity instead of re-queueing blind."""

    def __init__(self, service_class: str, retry_after: int,
                 alt_node: Optional[str] = None):
        super().__init__(
            f"{service_class} service at capacity; retry after "
            f"{retry_after}s")
        self.service_class = service_class
        self.retry_after = retry_after
        self.alt_node = alt_node


def _fleet_advisor() -> Optional[str]:
    """Default shed advisor: the least-loaded healthy node across the
    live fleet routers (None when no fleet is wired)."""
    try:
        from ..fleet import least_loaded_node
        return least_loaded_node()
    except Exception:
        return None


class _ClassState:
    __slots__ = ("limit", "ceiling", "floor", "sem", "in_use", "queued",
                 "shed", "admitted", "cancelled", "adjustments",
                 "baseline_s", "recent_s", "last_adjust_t", "waiters",
                 "tenant_served", "tenant_queued")

    def __init__(self, limit: int):
        self.limit = limit               # current (adaptive) limit
        self.ceiling = limit             # configured GSKY_ADMIT_* value
        self.floor = max(1, limit // 8)
        self.sem = threading.Semaphore(limit)   # fixed-mode primitive
        self.in_use = 0
        self.queued = 0
        self.shed = 0
        self.admitted = 0
        self.cancelled = 0               # permits released by cancel
        self.adjustments = 0             # AIMD limit changes
        self.baseline_s = 0.0            # slow latency EWMA
        self.recent_s = 0.0              # fast latency EWMA
        self.last_adjust_t = 0.0
        self.waiters: list = []          # adaptive-mode fair queue
        self.tenant_served: Dict[str, float] = {}
        self.tenant_queued: Dict[str, int] = {}


class _Waiter:
    __slots__ = ("tenant", "event", "state", "t_enq")
    WAITING, GRANTED, ABANDONED = 0, 1, 2

    def __init__(self, tenant: str, clock: float):
        self.tenant = tenant
        self.event = threading.Event()
        self.state = _Waiter.WAITING
        self.t_enq = clock


def _release_orphaned_permit(st: _ClassState):
    """Done-callback for a queued acquire whose request was cancelled
    (client disconnect): the worker thread cannot be interrupted and may
    still win the permit after the request is gone — hand it straight
    back so the class's capacity is never leaked."""
    def _cb(task) -> None:
        try:
            acquired = (not task.cancelled()
                        and task.exception() is None and task.result())
        except BaseException:
            acquired = False
        if acquired:
            st.sem.release()
    return _cb


class AdmissionController:
    def __init__(self, limits: Optional[Dict[str, int]] = None,
                 queue_deadline_s: Optional[float] = None,
                 shed_advisor: Optional[Callable[[], Optional[str]]]
                 = _fleet_advisor,
                 adaptive: Optional[bool] = None):
        self._lock = threading.Lock()
        self.shed_advisor = shed_advisor
        self.adaptive = (os.environ.get("GSKY_ADMIT_ADAPTIVE", "1") != "0"
                         if adaptive is None else adaptive)
        self._explicit_limits = dict(limits) if limits else None
        self._explicit_deadline = queue_deadline_s
        self._classes: Dict[str, _ClassState] = {}
        self.queue_deadline_s = 0.0
        self.reconfigure()

    def reconfigure(self) -> None:
        """(Re)resolve limits and the queue deadline from the
        environment — run at build time and again on SIGHUP reload so
        ``GSKY_ADMIT_*`` changes land without a restart.  Live counters
        carry over; ceilings, floors and fixed-mode semaphores are
        rebuilt from the fresh values."""
        merged = default_limits()
        if self._explicit_limits:
            merged.update(self._explicit_limits)
        with self._lock:
            self.queue_deadline_s = (
                default_queue_deadline_s()
                if self._explicit_deadline is None
                else self._explicit_deadline)
            for svc, n in merged.items():
                st = self._classes.get(svc)
                if st is None:
                    self._classes[svc] = _ClassState(n)
                elif st.ceiling != n:
                    st.ceiling = n
                    st.floor = max(1, n // 8)
                    st.limit = min(max(st.limit, st.floor), n)
                    st.sem = threading.Semaphore(n)
                    st.adjustments += 1

    def _state(self, service_class: str) -> _ClassState:
        st = self._classes.get(service_class)
        if st is None:      # unknown class: fail open under WMS limits
            st = self._classes.get("WMS")
            if st is None:
                with self._lock:
                    st = self._classes.setdefault(
                        service_class, _ClassState(32))
        return st

    # ---- adaptive machinery -------------------------------------------

    def _effective_limit(self, st: _ClassState) -> int:
        """The AIMD limit, clamped further under memory pressure."""
        limit = st.limit
        try:
            p = pressure_state()
        except Exception:
            p = 0
        if p >= 2:
            limit = max(1, int(limit * 0.25))
        elif p == 1:
            limit = max(1, int(limit * 0.5))
        return limit

    def observe(self, service_class: str, latency_s: float) -> None:
        """Fold one completed render's latency into the class's AIMD
        controller.  Multiplicative decrease when the fast EWMA leaves
        the knee (recent > ratio x baseline), additive recovery toward
        the ceiling while latency tracks the baseline."""
        if not self.adaptive:
            return
        st = self._state(service_class)
        ratio = _env_float("GSKY_ADMIT_RATIO", 1.5)
        interval = _env_float("GSKY_ADMIT_INTERVAL_S", 1.0)
        now = time.monotonic()
        with self._lock:
            if st.baseline_s <= 0.0:
                st.baseline_s = st.recent_s = latency_s
            else:
                st.recent_s += 0.3 * (latency_s - st.recent_s)
                st.baseline_s += 0.05 * (latency_s - st.baseline_s)
            if now - st.last_adjust_t < interval:
                return
            threshold = max(st.baseline_s * ratio, st.baseline_s + 0.05)
            if st.recent_s > threshold and st.limit > st.floor:
                st.limit = max(st.floor, int(st.limit * 0.7))
                st.adjustments += 1
                st.last_adjust_t = now
            elif st.recent_s <= st.baseline_s * 1.1 \
                    and st.limit < st.ceiling:
                st.limit += 1
                st.adjustments += 1
                st.last_adjust_t = now

    def _grant_waiters(self, st: _ClassState) -> None:
        """Weighted-fair scheduler: while capacity is free, grant the
        waiter whose tenant has the least weight-normalised service,
        minus an aging credit so long-queued tenants always drain.
        Caller holds the lock."""
        weights = _tenant_weights()
        aging = _env_float("GSKY_ADMIT_AGING", 0.5)
        now = time.monotonic()
        while st.waiters and st.in_use < self._effective_limit(st):
            best = None
            best_score = None
            for w in st.waiters:
                if w.state != _Waiter.WAITING:
                    continue
                wt = weights.get(w.tenant, 1.0)
                score = (st.tenant_served.get(w.tenant, 0.0) / wt
                         - aging * (now - w.t_enq))
                # FIFO within a tenant: earlier enqueue wins ties
                if best_score is None or score < best_score or \
                        (score == best_score and w.t_enq < best.t_enq):
                    best, best_score = w, score
            if best is None:
                break
            best.state = _Waiter.GRANTED
            st.waiters.remove(best)
            st.in_use += 1
            st.admitted += 1
            self._charge(st, best.tenant)
            best.event.set()

    def _charge(self, st: _ClassState, tenant: str) -> None:
        """One unit of service against the tenant's ledger, decaying
        the whole ledger so old consumption stops mattering (caller
        holds the lock)."""
        served = st.tenant_served
        served[tenant] = served.get(tenant, 0.0) + 1.0
        if served[tenant] > 1e6:            # keep the floats bounded
            for t in list(served):
                served[t] *= 0.5
        # decay: every charge fades everyone slightly, so fairness is
        # about the recent past, not the process lifetime
        for t in list(served):
            served[t] *= 0.995
            if served[t] < 1e-3:
                del served[t]

    def _release_adaptive(self, st: _ClassState,
                          cancelled: bool = False) -> None:
        with self._lock:
            st.in_use -= 1
            if cancelled:
                st.cancelled += 1
            self._grant_waiters(st)

    @contextlib.asynccontextmanager
    async def _admit_adaptive(self, st: _ClassState, service_class: str,
                              tenant: str):
        granted = False
        tok = None
        with self._lock:
            if not st.waiters and st.in_use < self._effective_limit(st):
                st.in_use += 1
                st.admitted += 1
                self._charge(st, tenant)
                granted = True
            else:
                w = _Waiter(tenant, time.monotonic())
                st.waiters.append(w)
                st.queued += 1
                st.tenant_queued[tenant] = \
                    st.tenant_queued.get(tenant, 0) + 1
        if not granted:
            tok = current_token()
            try:
                # block in a worker thread, not the event loop; shield
                # so a cancelled request can still hand a won permit back
                waiter_fut = asyncio.ensure_future(asyncio.to_thread(
                    w.event.wait, self.queue_deadline_s))
                try:
                    await asyncio.shield(waiter_fut)
                except asyncio.CancelledError:
                    with self._lock:
                        if w.state == _Waiter.WAITING:
                            w.state = _Waiter.ABANDONED
                            try:
                                st.waiters.remove(w)
                            except ValueError:
                                pass
                            st.cancelled += 1
                        else:       # granted in the race: hand it back
                            st.in_use -= 1
                            st.cancelled += 1
                            self._grant_waiters(st)
                    w.event.set()   # release the worker thread now
                    raise
                with self._lock:
                    if w.state == _Waiter.GRANTED:
                        granted = True
                    else:
                        w.state = _Waiter.ABANDONED
                        try:
                            st.waiters.remove(w)
                        except ValueError:
                            pass
            finally:
                with self._lock:
                    st.queued -= 1
                    n = st.tenant_queued.get(tenant, 1) - 1
                    if n <= 0:
                        st.tenant_queued.pop(tenant, None)
                    else:
                        st.tenant_queued[tenant] = n
        if not granted:
            with self._lock:
                st.shed += 1
            alt = None
            if self.shed_advisor is not None:
                try:
                    alt = self.shed_advisor()
                except Exception:
                    alt = None
            raise AdmissionShed(
                service_class,
                retry_after=max(1, int(round(self.queue_deadline_s))),
                alt_node=alt)
        if tok is None:
            tok = current_token()
        t0 = time.monotonic()
        try:
            yield
        except asyncio.CancelledError:
            self._release_adaptive(st, cancelled=True)
            raise
        except BaseException:
            self._release_adaptive(st)
            raise
        else:
            self._release_adaptive(
                st, cancelled=tok is not None and tok.cancelled())
            self.observe(service_class, time.monotonic() - t0)

    # ---- fixed (legacy) machinery -------------------------------------

    @contextlib.asynccontextmanager
    async def _admit_fixed(self, st: _ClassState, service_class: str):
        ok = st.sem.acquire(blocking=False)
        if not ok:
            with self._lock:
                st.queued += 1
            # block in a worker thread, not the event loop
            waiter = asyncio.ensure_future(asyncio.to_thread(
                st.sem.acquire, True, self.queue_deadline_s))
            try:
                ok = await asyncio.shield(waiter)
            except asyncio.CancelledError:
                waiter.add_done_callback(_release_orphaned_permit(st))
                with self._lock:
                    st.cancelled += 1
                raise
            finally:
                with self._lock:
                    st.queued -= 1
        if not ok:
            with self._lock:
                st.shed += 1
            alt = None
            if self.shed_advisor is not None:
                try:
                    alt = self.shed_advisor()
                except Exception:
                    alt = None
            raise AdmissionShed(
                service_class,
                retry_after=max(1, int(round(self.queue_deadline_s))),
                alt_node=alt)
        with self._lock:
            st.in_use += 1
            st.admitted += 1
        try:
            yield
        finally:
            with self._lock:
                st.in_use -= 1
            st.sem.release()

    def admit(self, service_class: str, tenant: str = ""):
        """Async context manager bounding one in-flight render.

        ``tenant`` (API key / client IP / namespace) keys the adaptive
        mode's weighted-fair queue; the fixed mode ignores it."""
        st = self._state(service_class)
        if self.adaptive:
            return self._admit_adaptive(st, service_class,
                                        tenant or "anon")
        return self._admit_fixed(st, service_class)

    @property
    def total_shed(self) -> int:
        with self._lock:
            return sum(st.shed for st in self._classes.values())

    @property
    def total_adjustments(self) -> int:
        with self._lock:
            return sum(st.adjustments for st in self._classes.values())

    @property
    def total_cancelled(self) -> int:
        with self._lock:
            return sum(st.cancelled for st in self._classes.values())

    def stats(self) -> Dict:
        with self._lock:
            tenants = {}
            for svc, st in self._classes.items():
                for t, n in st.tenant_queued.items():
                    tenants[f"{t}/{svc}"] = n
            return {
                "queue_deadline_s": self.queue_deadline_s,
                "adaptive": self.adaptive,
                "classes": {
                    svc: {"limit": st.limit, "ceiling": st.ceiling,
                          "effective_limit": self._effective_limit(st)
                          if self.adaptive else st.limit,
                          "in_use": st.in_use,
                          "queued": st.queued, "admitted": st.admitted,
                          "shed": st.shed, "cancelled": st.cancelled,
                          "adjustments": st.adjustments,
                          "recent_ms": round(st.recent_s * 1e3, 2),
                          "baseline_ms": round(st.baseline_s * 1e3, 2)}
                    for svc, st in self._classes.items()},
                "tenants": tenants}
