"""Serving gateway: the tier between `server/ows.py` and the TPU
pipelines.

Three cooperating pieces (plus the HTTP cache semantics the OWS layer
adds on top):

- :mod:`.response_cache` — byte-budgeted LRU of fully-encoded
  responses, canonical keying, per-layer TTLs, reload invalidation
- :mod:`.singleflight` — in-flight dedup: N concurrent identical
  requests trigger exactly one pipeline render
- :mod:`.admission` — per-service-class bounded concurrency with a
  queue-wait deadline that sheds overload as 503 + Retry-After

`default_gateway` is the process-wide instance (the same module-level
singleton pattern as `pipeline.scene_cache.default_scene_cache`);
servers can be handed a private gateway for isolation.
"""

from __future__ import annotations

from typing import Dict, Optional

from .admission import (AdmissionController, AdmissionShed,
                        DEFAULT_QUEUE_DEADLINE_S)
from .response_cache import (CachedResponse, ResponseCache, canonical_key,
                             layer_fingerprint, make_entry, quantise_bbox)
from .singleflight import SingleFlight

__all__ = [
    "AdmissionController", "AdmissionShed", "CachedResponse",
    "ResponseCache", "ServingGateway", "SingleFlight", "canonical_key",
    "default_gateway", "layer_fingerprint", "make_entry",
    "quantise_bbox",
]


class ServingGateway:
    """Response cache + singleflight + admission, composed."""

    def __init__(self, cache: Optional[ResponseCache] = None,
                 flight: Optional[SingleFlight] = None,
                 admission: Optional[AdmissionController] = None):
        self.cache = cache or ResponseCache()
        self.flight = flight or SingleFlight()
        self.admission = admission or AdmissionController()

    def invalidate_for_configs(self, configs) -> int:
        """ConfigWatcher reload hook: eagerly drop cached responses
        whose layer config changed or vanished (the fingerprint folded
        into every cache key already orphans them; this returns the
        bytes now).  The admission controller re-resolves its
        ``GSKY_ADMIT_*`` knobs on the same reload — they must never be
        latched at import time."""
        try:
            self.admission.reconfigure()
        except Exception:  # reconfigure is best-effort on reload
            pass
        fps = {ns: {layer_fingerprint(l) for l in cfg.layers}
               for ns, cfg in configs.items()}
        return self.cache.invalidate(fps)

    def cache_counters(self) -> Dict:
        """The compact counter block `server/metrics.py::_cache_stats`
        folds into every metrics record."""
        return {"hits": self.cache.hits, "misses": self.cache.misses,
                "inflight_joined": self.flight.joined,
                "shed": self.admission.total_shed}

    def stats(self) -> Dict:
        """The full /debug document block."""
        return {"response_cache": self.cache.stats(),
                "singleflight": {"leaders": self.flight.leaders,
                                 "joined": self.flight.joined,
                                 "inflight": self.flight.inflight},
                "admission": self.admission.stats()}


default_gateway = ServingGateway()
