"""Byte-budgeted LRU cache of fully-encoded OWS responses.

The scene/drill caches (`pipeline/scene_cache.py`, `pipeline/
drill_cache.py`) amortise *input* and *device* work; this tier sits in
front of the pipelines entirely and replays the finished bytes
(PNG/JPEG/GeoTIFF + content type) for byte-identical requests — the
output-cache role memcached/varnish plays in front of a production tile
server, and the only tier whose hit costs zero device time.

Keying is canonical, not textual: the key is built from the *parsed*
request (layer, resolved style, CRS, bbox quantised to the tile grid,
size, format, times, extra dimensions), so equivalent KVP spellings —
1.1.1 lon/lat vs 1.3.0 lat/lon bbox order, case differences, parameter
order — land on the same entry.  A fingerprint of the layer's resolved
config is folded into every key: a SIGHUP reload that changes a layer
re-fingerprints it, so stale entries can never hit again even before
the eager `invalidate()` sweep prunes them.

Entries carry a TTL derived from the layer's ``cache_max_age`` and are
evicted LRU by body bytes against a process-wide budget
(``GSKY_RESPONSE_CACHE_BYTES``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


DEFAULT_CACHE_BYTES = _env_int("GSKY_RESPONSE_CACHE_BYTES", 256 << 20)
DEFAULT_MAX_ENTRY_BYTES = _env_int("GSKY_RESPONSE_CACHE_MAX_ENTRY",
                                   32 << 20)
# how long past its TTL an entry stays replayable for stale-on-error
# serving (breaker-open / dead-backend fallback); 0 disables retention
DEFAULT_STALE_GRACE = _env_int("GSKY_RESPONSE_CACHE_STALE_S", 600)


def quantise_bbox(xmin: float, ymin: float, xmax: float, ymax: float,
                  width: int, height: int) -> Tuple[int, int, int, int]:
    """Snap bbox coordinates to 1/256th-of-a-pixel of the requested
    grid.  Clients emit the same tile with differing float formatting
    (trailing digits, axis-order normalisation residue); quantising to
    the tile grid makes those spellings collide while keeping genuinely
    different tiles apart (a 1/256-px shift is far below a resampling
    kernel's support)."""
    qx = max((xmax - xmin), 1e-12) / max(width, 1) / 256.0
    qy = max((ymax - ymin), 1e-12) / max(height, 1) / 256.0
    return (int(round(xmin / qx)), int(round(ymin / qy)),
            int(round(xmax / qx)), int(round(ymax / qy)))


def _plain(obj):
    if dataclasses.is_dataclass(obj):
        return {f.name: _plain(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
                if not f.name.startswith("_")
                and f.name != "timestamp_token"}  # volatile MAS token
    if isinstance(obj, (list, tuple)):
        return [_plain(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _plain(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def layer_fingerprint(layer) -> str:
    """Stable digest of a layer's resolved config (styles, palettes,
    scaling, dates, ... — everything that shapes the rendered bytes).
    Memoised on the layer object: config reloads build fresh Layer
    instances, so a changed layer gets a fresh fingerprint and its old
    cache entries are orphaned."""
    fp = getattr(layer, "_serving_fp", None)
    if fp is None:
        doc = json.dumps(_plain(layer), sort_keys=True,
                         separators=(",", ":"), default=repr)
        fp = hashlib.sha1(doc.encode()).hexdigest()[:16]
        try:
            object.__setattr__(layer, "_serving_fp", fp)
        except (AttributeError, TypeError):
            pass
    return fp


def canonical_key(**parts) -> str:
    """Digest of the canonical request parts; hashable, fixed-size."""
    doc = json.dumps({k: _plain(v) for k, v in sorted(parts.items())},
                     sort_keys=True, separators=(",", ":"), default=repr)
    return hashlib.sha1(doc.encode()).hexdigest()


@dataclass
class CachedResponse:
    body: bytes
    content_type: str
    status: int
    etag: str
    namespace: str
    layer: str
    layer_fp: str
    max_age: int
    expires: float                        # monotonic deadline
    headers: Tuple[Tuple[str, str], ...] = ()   # e.g. Content-Disposition
    stale: bool = False     # past TTL, kept only for stale-on-error


def make_entry(body: bytes, content_type: str, status: int,
               namespace: str, layer: str, layer_fp: str, max_age: int,
               headers: Tuple[Tuple[str, str], ...] = ()
               ) -> CachedResponse:
    etag = '"' + hashlib.sha256(body).hexdigest()[:32] + '"'
    return CachedResponse(
        body=body, content_type=content_type, status=status,
        etag=etag, namespace=namespace, layer=layer, layer_fp=layer_fp,
        max_age=max_age, expires=time.monotonic() + max_age,
        headers=headers)


class ResponseCache:
    """Thread-safe LRU of CachedResponse keyed by canonical request
    digest, bounded by total body bytes."""

    def __init__(self, max_bytes: int = DEFAULT_CACHE_BYTES,
                 max_entry_bytes: int = DEFAULT_MAX_ENTRY_BYTES,
                 stale_grace: int = DEFAULT_STALE_GRACE):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, CachedResponse]" = OrderedDict()
        self._bytes = 0
        self.max_bytes = max_bytes
        self.max_entry_bytes = max_entry_bytes
        self.stale_grace = stale_grace
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0
        self.invalidations = 0
        self.stale_hits = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes(self) -> int:
        return self._bytes

    def get(self, key: str) -> Optional[CachedResponse]:
        now = time.monotonic()
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            if now >= ent.expires:
                # expired entries stay resident (still LRU-bounded) for
                # stale_grace so get_stale() can replay them while a
                # backend is down; they never serve as normal hits and
                # count exactly one expiration each
                if not ent.stale:
                    ent.stale = True
                    self.expirations += 1
                if now >= ent.expires + self.stale_grace:
                    self._drop(key)
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent

    def peek(self, key: str) -> Optional[CachedResponse]:
        """A fresh entry without touching hit/miss counters or LRU
        order — fabric peer probes (`fabric/replay.py`) must not
        distort local cache stats or keep entries artificially warm."""
        now = time.monotonic()
        with self._lock:
            ent = self._entries.get(key)
            if ent is None or ent.stale or now >= ent.expires:
                return None
            return ent

    def get_stale(self, key: str) -> Optional[CachedResponse]:
        """An entry usable for stale-on-error replay: fresh OR expired
        within the stale grace window.  Does not count a hit/miss."""
        now = time.monotonic()
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            if now >= ent.expires + self.stale_grace:
                self._drop(key)
                return None
            self.stale_hits += 1
            self._entries.move_to_end(key)
            return ent

    def put(self, key: str, ent: CachedResponse) -> bool:
        n = len(ent.body)
        if n > self.max_entry_bytes or n > self.max_bytes \
                or ent.max_age <= 0:
            return False
        with self._lock:
            if key in self._entries:
                self._drop(key)
            self._entries[key] = ent
            self._bytes += n
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                old, _ = next(iter(self._entries.items()))
                self._drop(old)
                self.evictions += 1
            return True

    def _drop(self, key: str) -> None:  # gskylint: holds-lock
        ent = self._entries.pop(key, None)
        if ent is not None:
            self._bytes -= len(ent.body)

    def invalidate(self, namespace_fps: Dict[str, Set[str]]) -> int:
        """Eager reload sweep: drop every entry whose namespace is gone
        or whose layer fingerprint no longer exists in that namespace's
        freshly-loaded config.  (Correctness doesn't depend on this —
        fingerprints in the key already orphan stale entries — but the
        sweep returns the bytes to the budget immediately.)"""
        dropped = 0
        with self._lock:
            for key in list(self._entries):
                ent = self._entries[key]
                fps = namespace_fps.get(ent.namespace)
                if fps is None or ent.layer_fp not in fps:
                    self._drop(key)
                    dropped += 1
            self.invalidations += dropped
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> Dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "max_bytes": self.max_bytes, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions,
                    "expirations": self.expirations,
                    "invalidations": self.invalidations,
                    "stale_hits": self.stale_hits}
