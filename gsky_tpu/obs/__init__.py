"""Observability: request-scoped tracing, a flight recorder, and a
dependency-free Prometheus registry.

Three concerns, one seam:

* ``trace`` — a ContextVar-carried ``trace_id``/``span_id`` created at
  the OWS request boundary and threaded through the gateway, the tile
  stages, the batcher, the export pipeline, and — via gRPC metadata —
  into the worker processes, whose child spans ride back on the RPC
  result and stitch into one tree.
* ``recorder`` — an always-on in-memory ring of the last N complete
  traces plus a reservoir of the slowest/degraded ones, dumped as JSONL
  on demand (``/debug/trace``) or automatically on SLO violation.
* ``prom`` — counters, gauges, and log-bucketed histograms rendered in
  Prometheus text exposition format at ``/metrics``.  Histograms are
  observed at the same measurement points that feed ``/debug`` so the
  two endpoints cannot drift; the rest is collected at scrape time from
  the live stats objects.

``GSKY_TRACE=0`` disables tracing entirely (spans become no-ops on a
pre-checked fast path); ``GSKY_TRACE_FILE`` + ``GSKY_TRACE_SAMPLE``
enable sampled JSONL file export.  See docs/OBSERVABILITY.md.
"""

from .trace import (  # noqa: F401
    Span,
    Trace,
    adopt_spans,
    bind,
    current_context,
    current_span_id,
    current_trace,
    current_trace_id,
    event,
    record_span,
    remote_trace,
    set_attr,
    span,
    start_trace,
    trace_enabled,
    traceparent,
)
from .recorder import (  # noqa: F401
    FlightRecorder,
    default_recorder,
    reset_recorder,
)
from .prom import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    log_buckets,
    parse_exposition,
    reset_registry,
)
from . import metrics  # noqa: F401  (registers default metric families)
from .metrics import (  # noqa: F401
    BATCH_FLUSHES,
    ENCODE_SECONDS,
    REQUESTS,
    REQUEST_SECONDS,
    RPC_SECONDS,
    STAGE_SECONDS,
    TRACE_EVENTS,
    render_metrics,
)
