"""Default metric families and scrape-time collectors.

Two sourcing rules keep ``/metrics`` honest:

* Distributions (latency, stage durations, RPC times) are observed at
  the exact measurement points that already feed ``/debug`` — in
  ``server/metrics.py`` fold-in, the worker client, the batcher, and
  the encode pool — never from a second clock.
* Monotonic counters and level gauges that already exist as live stats
  objects (caches, fleet router, resilience registry, encode pool,
  compile probe, flight recorder) are *collected at scrape time* from
  those objects, so there is one counter, not two copies to drift.

Everything registers against ``prom.default_registry()``; the OWS
``/metrics`` route just calls ``render_metrics()``.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Tuple

from .prom import default_registry, log_buckets

_REG = default_registry()

REQUESTS = _REG.counter(
    "gsky_requests_total", "OWS requests by service class and status.",
    ["service", "status"])
REQUEST_SECONDS = _REG.histogram(
    "gsky_request_seconds", "End-to-end OWS request latency.",
    ["service"], buckets=log_buckets(0.002, 120.0))
STAGE_SECONDS = _REG.histogram(
    "gsky_stage_seconds",
    "Per-stage durations (tile pipeline, export pipeline, worker side).",
    ["stage"], buckets=log_buckets(0.0005, 60.0))
RPC_SECONDS = _REG.histogram(
    "gsky_worker_rpc_seconds", "Worker RPC round-trip by op and outcome.",
    ["op", "outcome"], buckets=log_buckets(0.001, 60.0))
ENCODE_SECONDS = _REG.histogram(
    "gsky_encode_seconds", "Encode-pool time by phase (wait vs cpu).",
    ["phase"], buckets=log_buckets(0.0005, 10.0))
BATCH_FLUSHES = _REG.counter(
    "gsky_batch_flushes_total", "Render-batcher flushes by trigger.",
    ["kind"])
WAVE_DISPATCHES = _REG.counter(
    "gsky_wave_dispatches_total",
    "Wave-scheduler device program invocations by result kind.",
    ["kind"])
WAVE_OCCUPANCY = _REG.histogram(
    "gsky_wave_occupancy",
    "Requests coalesced per wave dispatch.",
    buckets=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0])
WAVE_ASSEMBLY_MS = _REG.histogram(
    "gsky_wave_assembly_ms",
    "Wave assembly + dispatch-enqueue time (milliseconds).",
    buckets=log_buckets(0.01, 100.0))
WAVE_GAP_MS = _REG.histogram(
    "gsky_wave_gap_ms",
    "Host-side idle gap between consecutive wave dispatch enqueues "
    "(milliseconds) - the inter-wave stutter the pipelined scheduler "
    "closes (docs/PERF.md 'Continuous device occupancy').",
    buckets=log_buckets(0.01, 1000.0))
WAVE_STAGED = _REG.counter(
    "gsky_wave_staged_total",
    "Wave groups staged ahead of dispatch by the assembly stage "
    "(double-buffered input ring uploads).")
MESH_WAVES = _REG.counter(
    "gsky_mesh_waves_total",
    "Mesh wave dispatches by partition layout.",
    ["layout"])
MESH_CHIP_OCCUPANCY = _REG.histogram(
    "gsky_mesh_chip_occupancy",
    "Wave entries landing on each chip per mesh dispatch.",
    buckets=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0])
MESH_SHARD_SKEW_MS = _REG.histogram(
    "gsky_mesh_shard_skew_ms",
    "Per-chip readback readiness spread per mesh wave (milliseconds).",
    buckets=log_buckets(0.01, 1000.0))
TRACE_EVENTS = _REG.counter(
    "gsky_trace_events_total",
    "Cross-cutting events (retry, breaker_open, hedge, reroute, shed).",
    ["kind"])
PLAN_SUPERBLOCKS = _REG.counter(
    "gsky_plan_superblocks_total",
    "Shared-halo superblocks dispatched by the dataflow autoplanner.")
PLAN_BYTES_SAVED = _REG.counter(
    "gsky_plan_gather_bytes_saved_total",
    "HBM gather bytes the superblock plan avoided vs per-tile windows.")
PLAN_BLOCK_SHAPE = _REG.counter(
    "gsky_plan_block_shape",
    "Cost-model Pallas block-shape decisions by chosen shape.",
    ["shape"])
PLAN_ROUTE = _REG.counter(
    "gsky_plan_route_total",
    "Autoplanner group routing between ragged slot pad and bucketed "
    "pulls (the PR 8 crossover).",
    ["path"])
FABRIC_REPLAY = _REG.counter(
    "gsky_fabric_replay_total",
    "Gateway peer-replay fetch outcomes (docs/FABRIC.md): hit/miss/"
    "error/deadline/breaker_open/owner_local/disabled.",
    ["outcome"])
FABRIC_PAGE_FILLS = _REG.counter(
    "gsky_fabric_page_fills_total",
    "Page-pool fills by source: peer (fabric page RPC) vs cold "
    "(decode + stage from storage).",
    ["source"])

Rows = Iterable[Tuple[Dict[str, str], float]]


def _g(name: str, help_: str, rows: Rows):
    return (name, "gauge", help_, list(rows))


def _c(name: str, help_: str, rows: Rows):
    return (name, "counter", help_, list(rows))


def _collect_caches():
    """Hit/miss counters for every process-wide cache tier, lifted from
    the same ``cache_stats()`` block `/debug` folds into its records."""
    out: List = []
    try:
        from ..server.metrics import cache_stats
        hits, misses = [], []
        for cache, st in (cache_stats() or {}).items():
            hits.append(({"cache": cache}, float(st.get("hits", 0))))
            misses.append(({"cache": cache}, float(st.get("misses", 0))))
        if hits:
            out.append(_c("gsky_cache_hits_total",
                          "Cache hits by cache tier.", hits))
            out.append(_c("gsky_cache_misses_total",
                          "Cache misses by cache tier.", misses))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    try:
        from ..serving import default_gateway
        st = default_gateway.stats()
        fl = st.get("singleflight") or {}
        out.append(_c("gsky_singleflight_total",
                      "Single-flight render outcomes.",
                      [({"outcome": "leader"}, float(fl.get("leaders", 0))),
                       ({"outcome": "joined"}, float(fl.get("joined", 0)))]))
        adm = (st.get("admission") or {}).get("classes") or {}
        if adm:
            out.append(_g("gsky_admission_in_use",
                          "In-flight admitted requests.",
                          [({"service": s}, float(c.get("in_use", 0)))
                           for s, c in adm.items()]))
            out.append(_g("gsky_admission_queued",
                          "Requests queued at admission.",
                          [({"service": s}, float(c.get("queued", 0)))
                           for s, c in adm.items()]))
            out.append(_c("gsky_admission_shed_total",
                          "Requests shed at admission.",
                          [({"service": s}, float(c.get("shed", 0)))
                           for s, c in adm.items()]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_fleet():
    out: List = []
    try:
        from ..fleet import fleet_stats
        stats = fleet_stats() or {}
        nodes_rows, routed, rerouted, hedge_rows = [], [], [], []
        for name, st in stats.items():
            health = st.get("health") or {}
            states: Dict[str, int] = {}
            for _, h in health.items():
                s = (h or {}).get("state", "unknown")
                states[s] = states.get(s, 0) + 1
            for s, n in states.items():
                nodes_rows.append(({"router": name, "state": s}, float(n)))
            routed.append(({"router": name}, float(st.get("routed", 0))))
            rerouted.append(({"router": name},
                             float(st.get("rerouted", 0))))
            hg = st.get("hedge") or {}
            for outcome, key in (("fired", "hedges"), ("won", "hedge_wins"),
                                 ("denied", "hedges_denied")):
                hedge_rows.append(({"router": name, "outcome": outcome},
                                   float(hg.get(key, 0))))
        if stats:
            out.append(_g("gsky_fleet_nodes",
                          "Fleet nodes by router and health state.",
                          nodes_rows))
            out.append(_c("gsky_fleet_routed_total",
                          "Tasks routed by the fleet router.", routed))
            out.append(_c("gsky_fleet_rerouted_total",
                          "Tasks rerouted off their preferred node.",
                          rerouted))
            out.append(_c("gsky_fleet_hedges_total",
                          "Hedged RPCs by outcome.", hedge_rows))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_resilience():
    out: List = []
    try:
        from ..resilience import registry as _rr
        st = _rr.stats()
        out.append(_c("gsky_retries_total", "Retries by site.",
                      [({"site": s}, float(n))
                       for s, n in (st.get("retries") or {}).items()]))
        out.append(_c("gsky_retry_exhausted_total",
                      "Retry budgets exhausted by site.",
                      [({"site": s}, float(n))
                       for s, n in (st.get("retry_exhausted") or {})
                       .items()]))
        out.append(_c("gsky_degraded_responses_total",
                      "Responses served degraded.",
                      [({}, float(st.get("degraded_responses", 0)))]))
        out.append(_c("gsky_deadline_exhausted_total",
                      "Requests that ran out of deadline budget.",
                      [({}, float(st.get("deadline_exhausted", 0)))]))
        breakers = st.get("breakers") or {}
        if breakers:
            out.append(_g("gsky_breaker_open",
                          "Circuit breaker state (1 = open/half-open).",
                          [({"site": s},
                            0.0 if (b or {}).get("state") == "closed"
                            else 1.0)
                           for s, b in breakers.items()]))
            out.append(_c("gsky_breaker_opens_total",
                          "Circuit breaker trips by site.",
                          [({"site": s}, float((b or {}).get("opens", 0)))
                           for s, b in breakers.items()]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_runtime():
    out: List = []
    try:
        from ..server.prewarm import compile_count
        out.append(_c("gsky_compiles_total",
                      "Backend compiles observed by the jax.monitoring "
                      "probe.", [({}, float(compile_count()))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    try:
        from ..io.png import encode_pool_stats
        st = encode_pool_stats() or {}
        out.append(_g("gsky_encode_pool_pending",
                      "Encode jobs queued or running on the pool.",
                      [({}, float(st.get("pending", 0)))]))
        out.append(_g("gsky_encode_pool_workers",
                      "Encode-pool worker threads.",
                      [({}, float(st.get("workers", 0)))]))
        out.append(_c("gsky_encode_pool_encoded_total",
                      "Encode jobs completed.",
                      [({}, float(st.get("encoded", 0)))]))
        out.append(_c("gsky_encode_pool_errors_total",
                      "Encode jobs that raised.",
                      [({}, float(st.get("errors", 0)))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    try:
        from .recorder import default_recorder
        st = default_recorder().stats()
        out.append(_c("gsky_traces_recorded_total",
                      "Traces captured by the flight recorder.",
                      [({}, float(st.get("recorded", 0)))]))
        out.append(_c("gsky_traces_slo_violations_total",
                      "Traces past the SLO threshold.",
                      [({}, float(st.get("slo_violations", 0)))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_batcher():
    """RenderBatcher engagement + padding bill and the page-pool
    residency stats (the ragged paged rendering telemetry,
    docs/KERNELS.md)."""
    out: List = []
    try:
        from ..pipeline.executor import default_executor
        b = default_executor._batcher
        st = b.stats()
        out.append(_g("gsky_batch_knee",
                      "Adaptive coalesce cap (tiles per flush).",
                      [({}, float(st.get("batch_knee", 0)))]))
        out.append(_c("gsky_batches_total",
                      "Batch flushes by dispatch kind.",
                      [({"kind": "windowed"},
                        float(st.get("win_batches", 0))),
                       ({"kind": "full"},
                        float(st.get("full_batches", 0))),
                       ({"kind": "paged"},
                        float(st.get("paged_batches", 0)))]))
        out.append(_c("gsky_pad_waste_bytes_total",
                      "Bytes moved for pow2/bucket padding instead of "
                      "payload across batch flushes.",
                      [({}, float(st.get("pad_waste_bytes", 0)))]))
        out.append(_c("gsky_paged_dispatches_total",
                      "Executor dispatches served by the paged path vs "
                      "declined to buckets.",
                      [({"outcome": "engaged"},
                        float(default_executor.paged_engaged)),
                       ({"outcome": "declined"},
                        float(default_executor.paged_declined))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    try:
        from ..pipeline import pages
        if pages._default is not None:   # don't allocate just to report
            st = pages._default.stats()
            out.append(_g("gsky_page_pool_resident",
                          "Pages resident in the pool.",
                          [({}, float(st.get("resident", 0)))]))
            out.append(_g("gsky_page_pool_capacity",
                          "Page pool capacity (pages).",
                          [({}, float(st.get("capacity", 0)))]))
            out.append(_c("gsky_page_pool_staged_total",
                          "Pages staged into the pool.",
                          [({}, float(st.get("staged", 0)))]))
            out.append(_c("gsky_page_pool_hits_total",
                          "Page-table hits on already-staged pages.",
                          [({}, float(st.get("hits", 0)))]))
            out.append(_c("gsky_page_pool_evictions_total",
                          "LRU page evictions.",
                          [({}, float(st.get("evictions", 0)))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_overload():
    """Overload-survival surfaces (docs/RESILIENCE.md "Overload &
    brownout"): the adaptive admission limits the AIMD controller is
    running at, per-tenant queue depths behind them, cancellation
    counts by pipeline stage, and the memory-pressure state driving
    brownout."""
    out: List = []
    try:
        from ..serving import default_gateway
        st = default_gateway.admission.stats()
        classes = st.get("classes") or {}
        if classes:
            out.append(_g("gsky_admit_limit",
                          "Current adaptive admission limit per "
                          "service class.",
                          [({"class": s}, float(c.get("limit", 0)))
                           for s, c in classes.items()]))
        tenants = st.get("tenants") or {}
        if tenants:
            out.append(_g("gsky_admit_queue_depth",
                          "Requests queued at admission per "
                          "tenant/service-class pair.",
                          [({"tenant_class": k}, float(v))
                           for k, v in tenants.items()]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    try:
        from ..resilience import cancel_stats
        stages = (cancel_stats() or {}).get("stages") or {}
        if stages:
            out.append(_c("gsky_cancelled_total",
                          "Request cancellations observed per "
                          "pipeline stage.",
                          [({"stage": s}, float(v))
                           for s, v in stages.items()]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    try:
        from ..resilience.pressure import default_monitor
        out.append(_g("gsky_pressure_state",
                      "Memory-pressure state (0 nominal, 1 brownout, "
                      "2 critical).",
                      [({}, float(default_monitor().stats()
                                  .get("state", 0)))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_ingest():
    """Cloud-native ingest surfaces (docs/INGEST.md): ranged-read
    volume, prefetch outcome counts, and how much of the ranged-read
    time hid under an in-flight device dispatch."""
    out: List = []
    try:
        from ..ingest import stats as ingest_stats
        st = ingest_stats.snapshot()
        out.append(_c("gsky_ranged_reads_total",
                      "Coalesced byte-range requests issued by the "
                      "ingest read path.",
                      [({}, float(st.get("ranged_reads", 0)))]))
        out.append(_c("gsky_ranged_read_bytes_total",
                      "Bytes fetched through ranged reads.",
                      [({}, float(st.get("ranged_read_bytes", 0)))]))
        pf = st.get("prefetch") or {}
        out.append(_c("gsky_prefetch_total",
                      "Prefetch outcomes: predicted-and-used (hit), "
                      "requested-but-not-ready (miss), warmed-but-"
                      "expired (wasted).",
                      [({"outcome": k}, float(pf.get(k, 0)))
                       for k in ("hit", "miss", "wasted")]))
        out.append(_g("gsky_ingest_overlap_ratio",
                      "Fraction of ranged-read seconds spent while a "
                      "device dispatch was in flight.",
                      [({}, float(st.get("overlap_ratio", 0.0)))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_device():
    """Device-guard surfaces (docs/RESILIENCE.md "Device failures"):
    the supervisor's state machine position, incident counters, and the
    warm-recovery (journal rehydration) volume."""
    out: List = []
    try:
        from ..device_guard import default_supervisor
        st = default_supervisor().stats()
        out.append(_g("gsky_device_state",
                      "Device supervisor state (0 healthy, 1 suspect, "
                      "2 reinitializing, 3 dead).",
                      [({}, float(st.get("state_code", 0)))]))
        out.append(_c("gsky_device_reinits_total",
                      "Device teardown+rebuild cycles.",
                      [({}, float(st.get("reinits", 0)))]))
        out.append(_c("gsky_device_hangs_total",
                      "Dispatches abandoned by the hang watchdog.",
                      [({}, float(st.get("hangs", 0)))]))
        out.append(_c("gsky_device_incidents_total",
                      "Device incidents by kind.",
                      [({"kind": "crash"}, float(st.get("crashes", 0))),
                       ({"kind": "oom"}, float(st.get("ooms", 0))),
                       ({"kind": "corrupt"},
                        float(st.get("corruptions", 0)))]))
        out.append(_c("gsky_pool_rehydrated_pages_total",
                      "Hot pages re-staged into a rebuilt page pool "
                      "from the residency journal.",
                      [({}, float(st.get("rehydrated_pages", 0)))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_waves():
    """Wave-scheduler surfaces (docs/PERF.md "Wave-level serving"):
    readback-queue level plus the counters already kept on the live
    scheduler object — collected at scrape time, never a second copy.
    The dispatch/occupancy/assembly distributions are the module-level
    families above, observed at the dispatch site itself."""
    out: List = []
    try:
        from ..pipeline import waves
        if waves._default is not None:   # don't boot threads to report
            st = waves._default.stats()
            out.append(_g("gsky_wave_readback_queue_depth",
                          "Wave result blocks awaiting async readback.",
                          [({}, float(st.get("readback_queue_depth",
                                             0)))]))
            out.append(_c("gsky_wave_requests_total",
                          "Requests submitted to the wave scheduler.",
                          [({}, float(st.get("requests", 0)))]))
            out.append(_c("gsky_wave_fallbacks_total",
                          "Wave entries served via their per-call leg "
                          "after a device incident.",
                          [({}, float(st.get("fallbacks", 0)))]))
            out.append(_c("gsky_wave_cancelled_total",
                          "Wave entries dropped at assembly or "
                          "readback for request cancellation.",
                          [({}, float(st.get("cancelled", 0)))]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_mesh():
    """Mesh-serving surfaces (docs/MESH.md): chip count and per-layout
    entry totals from the live dispatcher — collected at scrape time
    so there is one counter, not two copies to drift.  The per-wave
    layout/occupancy/skew distributions are the module-level families
    above, observed at the dispatch site itself."""
    out: List = []
    try:
        from ..mesh.dispatch import active_mesh
        md = active_mesh()
        if md is not None:   # don't build a mesh to report
            st = md.stats()
            out.append(_g("gsky_mesh_chips",
                          "Chips in the serving mesh.",
                          [({}, float(st.get("chips", 0)))]))
            ent = st.get("entries_by_layout") or {}
            if ent:
                out.append(_c("gsky_mesh_entries_total",
                              "Wave entries dispatched by layout.",
                              [({"layout": k}, float(v))
                               for k, v in sorted(ent.items())]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_expr():
    """Fused band-algebra surfaces (docs/KERNELS.md "Expression
    epilogue"): compile-cache traffic, distinct fused programs, and
    how expression renders routed.  Rendered only once the expression
    tier has seen traffic — a process that never parses an expression
    keeps its exposition byte-identical."""
    out: List = []
    try:
        from ..ops.expr import expr_cache_stats
        from ..ops.paged import expr_fused_stats
        cs = expr_cache_stats()
        fs = expr_fused_stats()
        live = (cs.get("hits", 0) or cs.get("misses", 0)
                or fs.get("programs", 0) or fs.get("paths"))
        if live:
            out.append(_c("gsky_expr_cache_hits_total",
                          "Expression compile-cache hits.",
                          [({}, float(cs.get("hits", 0)))]))
            out.append(_c("gsky_expr_cache_misses_total",
                          "Expression compile-cache misses (fresh "
                          "parses).",
                          [({}, float(cs.get("misses", 0)))]))
            out.append(_g("gsky_expr_programs",
                          "Distinct expression fingerprints with a "
                          "fused paged program this process.",
                          [({}, float(fs.get("programs", 0)))]))
            paths = fs.get("paths") or {}
            if paths:
                out.append(_c("gsky_expr_fused_total",
                              "Expression renders by dispatch path.",
                              [({"path": k}, float(v))
                               for k, v in sorted(paths.items())]))
    except Exception:  # subsystem unbooted - skip its families, a scrape never fails
        pass
    return out


def _collect_tsan():
    """Lockset race-sanitizer surfaces (docs/ANALYSIS.md): only the
    race count — a non-zero value fails the GSKY_TSAN=1 CI soak leg,
    and scraping it keeps the family parser-proven like every other."""
    out: List = []
    try:
        from .tsan import tsan_stats
        st = tsan_stats()
        if st.get("installed") or st.get("enabled"):
            out.append(_c("gsky_tsan_races_total",
                          "Data races reported by the lockset "
                          "sanitizer (GSKY_TSAN=1).",
                          [({}, float(st.get("races", 0)))]))
            out.append(_g("gsky_tsan_tracked_vars",
                          "Shared variables under lockset tracking.",
                          [({}, float(st.get("tracked_vars", 0)))]))
    except Exception:
        # scrape-time collectors must never break /metrics
        pass
    return out


def _collect_fabric():
    """Cache-fabric surfaces (docs/FABRIC.md): the replica-page gauge
    from the popularity-weighted replication planner.  Reported when
    the fabric is on or has ever planned — a fabric-less process keeps
    its exposition byte-identical."""
    out: List = []
    try:
        from .. import fabric
        from ..fabric import replicate
        st = replicate.stats()
        if fabric.fabric_enabled() or st.get("rounds"):
            out.append(_g("gsky_fabric_replica_pages",
                          "Pages this node holds (or is due to hold) "
                          "under the popularity-weighted replication "
                          "plan.",
                          [({}, float(st.get("replica_pages", 0)))]))
    except Exception:
        # scrape-time collectors must never break /metrics
        pass
    return out


def _collect_elastic():
    """Elastic-fleet surfaces (docs/FLEET.md "Elastic fleet"): node
    counts by lifecycle state, scale decisions, preemption notices and
    warm-handoff page outcomes.  Reported only when elastic has left a
    trace in this process (``GSKY_ELASTIC=1``, a live autoscaler, or a
    non-zero counter) — a fixed fleet keeps its exposition
    byte-identical."""
    out: List = []
    try:
        from ..fleet import elastic
        if elastic.dormant():
            return out
        counts: Dict[str, float] = {}
        for a in elastic.autoscalers():
            for state, n in a.node_counts().items():
                counts[state] = counts.get(state, 0) + n
        if counts:
            out.append(_g("gsky_elastic_nodes",
                          "Worker nodes by elastic lifecycle state.",
                          [({"state": s}, float(n))
                           for s, n in sorted(counts.items())]))
        c = elastic.counters()
        out.append(_c("gsky_elastic_decisions_total",
                      "Autoscaler scale decisions by direction.",
                      [({"dir": d}, float(n))
                       for d, n in sorted(c["decisions"].items())]))
        out.append(_c("gsky_preemptions_total",
                      "Preemption notices handled, by whether a grace "
                      "window allowed the drain + journal handoff.",
                      [({"graceful": "true"},
                        float(c["preemptions"]["graceful"])),
                       ({"graceful": "false"},
                        float(c["preemptions"]["nograce"]))]))
        out.append(_c("gsky_handoff_pages_total",
                      "Hot pages inherited on preemption handoff: "
                      "refilled from peer HBM vs left to cold staging.",
                      [({"source": s}, float(n))
                       for s, n in sorted(
                           c["handoff_pages"].items())]))
    except Exception:
        # scrape-time collectors must never break /metrics
        pass
    return out


# -- temporal serving (animation waves + streamed DAP4) ----------------
#
# Recorded by the OWS animation handler and the DAP4 streaming leg
# (docs/PERF.md "Temporal waves"); collected at scrape time from this
# one copy.  A process that never served an animation or a streamed
# DAP4 response keeps its exposition byte-identical.

_TEMPORAL_LOCK = threading.Lock()
_TEMPORAL: Dict[str, float] = {
    "sequences": 0, "frames": 0, "waves": 0, "cancelled": 0,
    "degraded": 0, "dap_streams": 0, "dap_streamed_bytes": 0,
    "dap_peak_buffer_bytes": 0}


def record_anim_sequence(frames: int, waves: int,
                         degraded: bool = False,
                         cancelled: bool = False) -> None:
    """One animation sequence completed: ``frames`` rendered across
    ``waves`` wave dispatches (the amortisation the temporal path
    exists for)."""
    with _TEMPORAL_LOCK:
        _TEMPORAL["sequences"] += 1
        _TEMPORAL["frames"] += int(frames)
        _TEMPORAL["waves"] += int(waves)
        if degraded:
            _TEMPORAL["degraded"] += 1
        if cancelled:
            _TEMPORAL["cancelled"] += 1


def record_dap_stream(nbytes: int, peak_buffer: int) -> None:
    """One streamed DAP4 response: bytes on the wire and the largest
    resident buffer the rechunker held (the bounded-RSS evidence)."""
    with _TEMPORAL_LOCK:
        _TEMPORAL["dap_streams"] += 1
        _TEMPORAL["dap_streamed_bytes"] += int(nbytes)
        _TEMPORAL["dap_peak_buffer_bytes"] = max(
            _TEMPORAL["dap_peak_buffer_bytes"], int(peak_buffer))


def temporal_stats() -> Dict[str, float]:
    """The /debug ``temporal`` block (and the test hook)."""
    with _TEMPORAL_LOCK:
        st = dict(_TEMPORAL)
    st["frames_per_wave"] = round(
        st["frames"] / st["waves"], 4) if st["waves"] else 0.0
    return st


def reset_temporal() -> None:
    """Test hook: zero the temporal counters."""
    with _TEMPORAL_LOCK:
        for k in _TEMPORAL:
            _TEMPORAL[k] = 0


def _collect_temporal():
    """Temporal-serving surfaces (docs/PERF.md "Temporal waves"):
    animation sequence/frame amortisation and streamed-DAP4 volume.
    Rendered only once either path has served — exposition stays
    byte-identical otherwise."""
    out: List = []
    try:
        st = temporal_stats()
        if not (st["sequences"] or st["dap_streams"]):
            return out
        out.append(_c("gsky_anim_sequences_total",
                      "Animation sequences served by the temporal "
                      "wave path, by outcome.",
                      [({"outcome": "ok"},
                        float(st["sequences"] - st["cancelled"])),
                       ({"outcome": "cancelled"},
                        float(st["cancelled"]))]))
        out.append(_g("gsky_anim_frames_per_wave",
                      "Mean animation frames amortised per wave "
                      "dispatch (frames / waves, process lifetime).",
                      [({}, float(st["frames_per_wave"]))]))
        out.append(_c("gsky_dap_streamed_bytes_total",
                      "Bytes streamed by the bounded-RSS DAP4 export "
                      "leg (GSKY_DAP_STREAM).",
                      [({}, float(st["dap_streamed_bytes"]))]))
    except Exception:
        # scrape-time collectors must never break /metrics
        pass
    return out


for _fn in (_collect_caches, _collect_fleet, _collect_resilience,
            _collect_runtime, _collect_batcher, _collect_overload,
            _collect_ingest, _collect_device, _collect_waves,
            _collect_mesh, _collect_expr, _collect_tsan,
            _collect_fabric, _collect_elastic, _collect_temporal):
    _REG.register_collector(_fn)


def render_metrics() -> str:
    return default_registry().render()
