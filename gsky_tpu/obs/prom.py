"""Dependency-free Prometheus instrumentation.

Counters, gauges, and log-bucketed histograms with the 0.0.4 text
exposition format, plus scrape-time collector callbacks that lift the
codebase's existing stats objects (caches, fleet, resilience, encode
pool, compile probe) into gauge families — the live counters stay the
single source of truth, so ``/debug`` and ``/metrics`` cannot drift.

A strict ``parse_exposition`` lives here too: the tier-1 tests and the
soak harness both round-trip ``/metrics`` through it, so a formatting
regression fails fast instead of silently breaking a real scraper.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def log_buckets(lo: float, hi: float,
                per_decade: int = 3) -> Tuple[float, ...]:
    """Log-spaced bucket boundaries from ``lo`` to at least ``hi``.
    ``per_decade=3`` gives the classic 1-2-5 ladder."""
    if lo <= 0 or hi <= lo:
        raise ValueError("need 0 < lo < hi")
    steps = {3: (1.0, 2.0, 5.0), 2: (1.0, 3.0), 1: (1.0,)}.get(per_decade)
    if steps is None:
        steps = tuple(10 ** (i / per_decade) for i in range(per_decade))
    out: List[float] = []
    decade = 10.0 ** math.floor(math.log10(lo))
    while True:
        for s in steps:
            v = decade * s
            if v < lo * (1 - 1e-9):
                continue
            out.append(float(f"{v:.6g}"))
            if v >= hi * (1 - 1e-9):
                return tuple(out)
        decade *= 10.0


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _labels_text(labels: Sequence[Tuple[str, str]]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels)
    return "{" + body + "}"


class _Metric:
    mtype = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):  # noqa: A002
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}

    def _key(self, kwargs: Dict[str, str]) -> Tuple[str, ...]:
        if set(kwargs) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(kwargs)}")
        return tuple(str(kwargs[ln]) for ln in self.labelnames)

    def labels(self, **kwargs):
        key = self._key(kwargs)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child()
                self._children[key] = child
        return child

    def _child(self):
        raise NotImplementedError

    def _default_child(self):
        """The unlabelled child, for label-less metrics."""
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels")
        with self._lock:
            child = self._children.get(())
            if child is None:
                child = self._child()
                self._children[()] = child
        return child

    def samples(self) -> List[Tuple[str, List[Tuple[str, str]], float]]:
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Counter(_Metric):
    mtype = "counter"

    def _child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def samples(self):
        with self._lock:
            items = list(self._children.items())
        return [(self.name, list(zip(self.labelnames, key)), c.value)
                for key, c in items]


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class Gauge(_Metric):
    mtype = "gauge"

    def _child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    def samples(self):
        with self._lock:
            items = list(self._children.items())
        return [(self.name, list(zip(self.labelnames, key)), g.value)
                for key, g in items]


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * len(buckets)       # per-bucket, not cumulative
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    break


DEFAULT_BUCKETS = log_buckets(0.001, 60.0)


class Histogram(_Metric):
    mtype = "histogram"

    def __init__(self, name: str, help: str,  # noqa: A002
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bk = tuple(sorted(float(b) for b in buckets))
        if not bk or any(b <= 0 for b in bk if b != float("inf")):
            raise ValueError("buckets must be positive and non-empty")
        if bk and bk[-1] != float("inf"):
            bk = bk + (float("inf"),)
        self.buckets = bk

    def _child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def samples(self):
        with self._lock:
            items = list(self._children.items())
        out = []
        for key, h in items:
            base = list(zip(self.labelnames, key))
            with h._lock:
                counts = list(h.counts)
                total, ssum = h.count, h.sum
            cum = 0
            for b, n in zip(h.buckets, counts):
                cum += n
                out.append((self.name + "_bucket",
                            base + [("le", _fmt(b))], float(cum)))
            out.append((self.name + "_sum", list(base), ssum))
            out.append((self.name + "_count", list(base), float(total)))
        return out


# ---------------------------------------------------------------------------
# registry

# A collector callback returns families:
#   (name, type, help, [(labels_dict, value), ...])
CollectorFn = Callable[[], Iterable[
    Tuple[str, str, str, Iterable[Tuple[Dict[str, str], float]]]]]


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[CollectorFn] = []

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            have = self._metrics.get(metric.name)
            if have is not None:
                return have
            self._metrics[metric.name] = metric
        return metric

    def register_collector(self, fn: CollectorFn) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def counter(self, name, help, labelnames=()):  # noqa: A002
        return self.register(Counter(name, help, labelnames))

    def gauge(self, name, help, labelnames=()):  # noqa: A002
        return self.register(Gauge(name, help, labelnames))

    def histogram(self, name, help, labelnames=(),  # noqa: A002
                  buckets=DEFAULT_BUCKETS):
        return self.register(Histogram(name, help, labelnames, buckets))

    def render(self) -> str:
        """Text exposition format 0.0.4."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        lines: List[str] = []
        seen: set = set()
        for m in sorted(metrics, key=lambda m: m.name):
            lines.append(f"# HELP {m.name} {_escape(m.help)}")
            lines.append(f"# TYPE {m.name} {m.mtype}")
            for name, labels, value in m.samples():
                lines.append(f"{name}{_labels_text(labels)} {_fmt(value)}")
            seen.add(m.name)
        for fn in collectors:
            try:
                families = list(fn())
            except Exception:  # one bad collector must not break the scrape
                continue
            for name, mtype, help_, samples in families:
                if name in seen or not _NAME_RE.match(name):
                    continue
                seen.add(name)
                lines.append(f"# HELP {name} {_escape(help_)}")
                lines.append(f"# TYPE {name} {mtype}")
                for labels, value in samples:
                    lt = _labels_text(sorted(labels.items()))
                    try:
                        lines.append(f"{name}{lt} {_fmt(float(value))}")
                    except (TypeError, ValueError):
                        continue
        return "\n".join(lines) + "\n"


_DEFAULT = Registry()


def default_registry() -> Registry:
    return _DEFAULT


def reset_registry() -> Registry:
    """Test hook: fresh default registry (module metric families keep
    pointing at the old one; tests build their own metrics)."""
    global _DEFAULT
    _DEFAULT = Registry()
    return _DEFAULT


# ---------------------------------------------------------------------------
# strict parser (shared by tests and the soak harness)

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{(.*)\})?"                        # label body
    r"\s+(-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|\+?Inf|NaN))"
    r"(?:\s+-?[0-9]+)?$")                   # optional timestamp
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _base_name(name: str) -> str:
    for suf in ("_bucket", "_sum", "_count", "_total"):
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def parse_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse Prometheus text exposition, strictly.

    Returns ``{family: {"type", "help", "samples": {(name, labels): v}}}``
    where ``labels`` is a sorted tuple of (k, v) pairs.  Raises
    ``ValueError`` on any malformed line, samples without a preceding
    TYPE, duplicate series, or histograms whose cumulative buckets are
    non-monotonic or whose ``+Inf`` bucket disagrees with ``_count``.
    """
    families: Dict[str, Dict[str, Any]] = {}
    typed: Dict[str, str] = {}
    for ln, raw in enumerate(text.split("\n"), 1):
        line = raw.rstrip("\r")
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                raise ValueError(f"line {ln}: malformed HELP: {line!r}")
            families.setdefault(parts[2], {"type": None, "help": None,
                                           "samples": {}})
            families[parts[2]]["help"] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or not _NAME_RE.match(parts[2]):
                raise ValueError(f"line {ln}: malformed TYPE: {line!r}")
            if parts[3] not in ("counter", "gauge", "histogram", "summary",
                                "untyped"):
                raise ValueError(f"line {ln}: unknown type {parts[3]!r}")
            if parts[2] in typed:
                raise ValueError(f"line {ln}: duplicate TYPE for {parts[2]}")
            typed[parts[2]] = parts[3]
            families.setdefault(parts[2], {"type": None, "help": None,
                                           "samples": {}})
            families[parts[2]]["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue                        # free comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {ln}: malformed sample: {line!r}")
        name, labelbody, value = m.group(1), m.group(2), m.group(3)
        base = _base_name(name)
        fam = base if base in typed else name
        if fam not in typed:
            raise ValueError(f"line {ln}: sample {name} without TYPE")
        labels: List[Tuple[str, str]] = []
        if labelbody:
            consumed = 0
            for lm in _LABEL_PAIR_RE.finditer(labelbody):
                labels.append((lm.group(1), lm.group(2)))
                consumed = lm.end()
                if consumed < len(labelbody):
                    if labelbody[consumed] != ",":
                        raise ValueError(
                            f"line {ln}: bad label separator: {line!r}")
                    consumed += 1
            if consumed < len(labelbody):
                raise ValueError(f"line {ln}: trailing label junk: {line!r}")
        key = (name, tuple(sorted(labels)))
        fam_d = families[fam]
        if key in fam_d["samples"]:
            raise ValueError(f"line {ln}: duplicate series {key}")
        if value in ("Inf", "+Inf"):
            v = float("inf")
        elif value == "NaN":
            v = float("nan")
        else:
            v = float(value)
        fam_d["samples"][key] = v

    # histogram invariants
    for fam, d in families.items():
        if d["type"] != "histogram":
            continue
        series: Dict[Tuple[Tuple[str, str], ...],
                     List[Tuple[float, float]]] = {}
        counts: Dict[Tuple[Tuple[str, str], ...], float] = {}
        for (name, labels), v in d["samples"].items():
            if name == fam + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    raise ValueError(f"{fam}: bucket without le")
                rest = tuple(kv for kv in labels if kv[0] != "le")
                bound = float("inf") if le in ("+Inf", "Inf") else float(le)
                series.setdefault(rest, []).append((bound, v))
            elif name == fam + "_count":
                counts[tuple(labels)] = v
        for rest, buckets in series.items():
            buckets.sort()
            cum = [n for _, n in buckets]
            if any(b > a for b, a in zip(cum, cum[1:])):
                raise ValueError(f"{fam}{dict(rest)}: non-monotonic buckets")
            if not buckets or buckets[-1][0] != float("inf"):
                raise ValueError(f"{fam}{dict(rest)}: missing +Inf bucket")
            if rest in counts and buckets[-1][1] != counts[rest]:
                raise ValueError(
                    f"{fam}{dict(rest)}: +Inf bucket != _count")
    return families
