"""Flight recorder: keep the traces you will wish you had.

An always-on in-memory ring holds the last N completed traces; a
separate reservoir keeps the slowest and any degraded / erroring /
deadline-exceeded ones so a burst of fast requests cannot evict the one
trace that explains an SLO page.  ``/debug/trace`` lists both,
``/debug/trace/<id>`` returns the full span tree, and everything dumps
as JSONL.

File export is optional: ``GSKY_TRACE_FILE`` names a JSONL sink,
``GSKY_TRACE_SAMPLE`` (0..1, default 0 — explicit opt-in) samples the
healthy traffic written there.  SLO violations (``GSKY_TRACE_SLO_S``,
default 2s) are always written when a file is configured, sampled or
not.
"""

from __future__ import annotations

import collections
import heapq
import json
import os
import random
import threading
from typing import Any, Dict, List, Optional


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None,
                 reservoir: Optional[int] = None,
                 slo_s: Optional[float] = None,
                 trace_file: Optional[str] = None,
                 sample: Optional[float] = None):
        self.capacity = capacity if capacity is not None else \
            _env_int("GSKY_TRACE_RING", 64)
        self.reservoir_cap = reservoir if reservoir is not None else \
            _env_int("GSKY_TRACE_RESERVOIR", 16)
        self.slo_s = slo_s if slo_s is not None else \
            _env_float("GSKY_TRACE_SLO_S", 2.0)
        self.trace_file = trace_file if trace_file is not None else \
            os.environ.get("GSKY_TRACE_FILE") or None
        self.sample = sample if sample is not None else \
            _env_float("GSKY_TRACE_SAMPLE", 0.0)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, self.capacity))
        # min-heap of (dur_s, seq, trace): the fastest "interesting"
        # trace is evicted first once the reservoir is full
        self._reservoir: List[tuple] = []
        self._seq = 0
        self.recorded = 0
        self.evicted = 0
        self.slo_violations = 0
        self._file_lock = threading.Lock()

    # -- classification ----------------------------------------------
    def _interesting(self, trace: Dict[str, Any]) -> bool:
        if (trace.get("dur_s") or 0.0) >= self.slo_s:
            return True
        status = trace.get("status")
        if isinstance(status, int) and status >= 500:
            return True
        if trace.get("degraded"):
            return True
        attrs = trace.get("attrs") or {}
        return bool(attrs.get("deadline_exceeded") or attrs.get("error"))

    # -- recording ----------------------------------------------------
    def record(self, trace: Dict[str, Any]) -> None:
        dur = float(trace.get("dur_s") or 0.0)
        slow = dur >= self.slo_s
        interesting = self._interesting(trace)
        with self._lock:
            self.recorded += 1
            if len(self._ring) == self._ring.maxlen:
                self.evicted += 1
            self._ring.append(trace)
            if slow:
                self.slo_violations += 1
            if interesting:
                self._seq += 1
                entry = (dur, self._seq, trace)
                if len(self._reservoir) < self.reservoir_cap:
                    heapq.heappush(self._reservoir, entry)
                elif self._reservoir and dur > self._reservoir[0][0]:
                    heapq.heapreplace(self._reservoir, entry)
        if self.trace_file and (
                slow or (self.sample > 0 and random.random() < self.sample)):
            self._write_file(trace)

    def _write_file(self, trace: Dict[str, Any]) -> None:
        try:
            line = json.dumps(trace, default=str)
            with self._file_lock:
                with open(self.trace_file, "a", encoding="utf-8") as fh:
                    fh.write(line + "\n")
        except Exception:  # trace file write is best-effort telemetry
            pass

    # -- query --------------------------------------------------------
    def lookup(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for t in reversed(self._ring):
                if t.get("trace_id") == trace_id:
                    return t
            for _, _, t in self._reservoir:
                if t.get("trace_id") == trace_id:
                    return t
        return None

    def traces(self) -> List[Dict[str, Any]]:
        """All retained traces, ring first (oldest→newest), then any
        reservoir-only ones (slowest-last)."""
        with self._lock:
            out = list(self._ring)
            seen = {t.get("trace_id") for t in out}
            extra = [t for _, _, t in sorted(self._reservoir)
                     if t.get("trace_id") not in seen]
        return out + extra

    def slowest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            best = None
            for t in self._ring:
                if best is None or (t.get("dur_s") or 0) > \
                        (best.get("dur_s") or 0):
                    best = t
            for _, _, t in self._reservoir:
                if best is None or (t.get("dur_s") or 0) > \
                        (best.get("dur_s") or 0):
                    best = t
        return best

    def summary(self) -> List[Dict[str, Any]]:
        out = []
        for t in self.traces():
            dur = t.get("dur_s") or 0.0
            out.append({
                "trace_id": t.get("trace_id"),
                "name": t.get("name"),
                "t0": t.get("t0"),
                "dur_ms": round(dur * 1000.0, 3),
                "status": t.get("status"),
                "spans": len(t.get("spans") or ()),
                "processes": sorted({s.get("process") or "?"
                                     for s in t.get("spans") or ()}),
                "degraded": t.get("degraded") or [],
                "slow": dur >= self.slo_s,
            })
        return out

    def dump_jsonl(self) -> str:
        return "\n".join(json.dumps(t, default=str)
                         for t in self.traces()) + "\n"

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "recorded": self.recorded,
                "retained": len(self._ring),
                "reservoir": len(self._reservoir),
                "evicted": self.evicted,
                "slo_violations": self.slo_violations,
                "slo_s": self.slo_s,
                "capacity": self.capacity,
            }


_DEFAULT: Optional[FlightRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def default_recorder() -> FlightRecorder:
    global _DEFAULT
    rec = _DEFAULT
    if rec is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = FlightRecorder()
            rec = _DEFAULT
    return rec


def reset_recorder() -> None:
    """Test hook: drop the singleton so env knobs are re-read."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
