"""Request-scoped distributed tracing over a ContextVar.

A trace is born at the OWS request boundary (``start_trace``), carried
implicitly through ``async``/``await`` and ``asyncio.to_thread`` by the
interpreter's context machinery, and *explicitly* re-bound (``bind``,
``contextvars.Context.run``) where the request crosses into raw
``threading.Thread`` stages or long-lived executor pools, which start
from an empty context.  The worker hop serialises the context into gRPC
metadata (``traceparent`` → ``x-gsky-trace``) and the worker's child
spans ride back on the RPC result (``remote_trace`` / ``adopt_spans``)
so the gateway ends up holding one stitched tree.

Overhead discipline: ``span()`` costs one ContextVar read when no trace
is active, and ``GSKY_TRACE=0`` (read once per request, like the other
``GSKY_*`` escape hatches) means no trace is ever activated.  Span
bodies never raise out of the instrumentation — a broken sink must not
fail a render.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

# (trace, current span id); None when the code path is untraced.
_CURRENT: contextvars.ContextVar[Optional[Tuple["Trace", str]]] = \
    contextvars.ContextVar("gsky_trace", default=None)

_ID_LOCK = threading.Lock()
_ID_STATE = [int.from_bytes(os.urandom(8), "big")]


def _new_id() -> str:
    # os.urandom per span is measurable on the hot path; a counter
    # seeded once from the OS is unique enough for correlation ids.
    with _ID_LOCK:
        _ID_STATE[0] = (_ID_STATE[0] + 0x9E3779B97F4A7C15) & (2 ** 64 - 1)
        x = _ID_STATE[0]
    # xorshift-style mix so consecutive ids don't share prefixes
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & (2 ** 64 - 1)
    x ^= x >> 27
    return format(x, "016x")


def trace_enabled() -> bool:
    """Master switch, read per request: ``GSKY_TRACE=0`` disables."""
    return os.environ.get("GSKY_TRACE", "1").lower() not in (
        "0", "false", "no", "off")


class Span:
    """One timed operation inside a trace.  Mutable while open; the
    instrumented code may attach attributes (``set``) and point events
    (``event``) through the handle yielded by ``span()``."""

    __slots__ = ("span_id", "parent_id", "name", "process", "t0",
                 "dur_s", "attrs", "events", "_pc0")

    def __init__(self, span_id: str, parent_id: Optional[str], name: str,
                 process: str, attrs: Optional[Dict[str, Any]] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.process = process
        self.t0 = time.time()
        self._pc0 = time.perf_counter()
        self.dur_s: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[Dict[str, Any]] = []

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def event(self, name: str, **attrs) -> None:
        ev: Dict[str, Any] = {"name": name, "t": time.time()}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def close(self) -> None:
        if self.dur_s is None:
            self.dur_s = time.perf_counter() - self._pc0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "span_id": self.span_id, "parent_id": self.parent_id,
            "name": self.name, "process": self.process,
            "t0": self.t0, "dur_s": self.dur_s}
        if self.attrs:
            d["attrs"] = self.attrs
        if self.events:
            d["events"] = self.events
        return d


class _NullSpan:
    """Shared no-op handle yielded when no trace is active."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def event(self, name: str, **attrs) -> None:
        pass


_NULL = _NullSpan()


class Trace:
    """A collection of spans sharing one ``trace_id``.  Thread-safe:
    stage threads and RPC fanout workers append concurrently."""

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None, process: str = "gateway",
                 attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id or _new_id()
        self.process = process
        self.root = Span(_new_id(), parent_id, name, process, attrs)
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._open: Dict[str, Span] = {}           # open child spans by id
        self._foreign: List[Dict[str, Any]] = []   # adopted remote spans
        self.status: Optional[int] = None
        self.degraded: List[str] = []

    # -- recording ----------------------------------------------------
    def add(self, sp: Span) -> None:
        with self._lock:
            self._spans.append(sp)

    def adopt(self, span_dicts: Sequence[Dict[str, Any]]) -> None:
        """Merge spans exported by another process (same trace_id)."""
        with self._lock:
            self._foreign.extend(dict(d) for d in span_dicts)

    # -- export -------------------------------------------------------
    def span_dicts(self) -> List[Dict[str, Any]]:
        """All spans including the root, start-ordered."""
        self.root.close()
        with self._lock:
            out = [self.root.to_dict()]
            out.extend(s.to_dict() for s in self._spans)
            out.extend(self._foreign)
        out.sort(key=lambda d: d.get("t0") or 0.0)
        return out

    def to_dict(self) -> Dict[str, Any]:
        self.root.close()
        return {
            "trace_id": self.trace_id,
            "name": self.root.name,
            "t0": self.root.t0,
            "dur_s": self.root.dur_s,
            "status": self.status,
            "degraded": list(self.degraded),
            "attrs": dict(self.root.attrs),
            "spans": self.span_dicts(),
        }


# ---------------------------------------------------------------------------
# context accessors

def current_context() -> Optional[Tuple[Trace, str]]:
    return _CURRENT.get()


def current_trace() -> Optional[Trace]:
    cur = _CURRENT.get()
    return cur[0] if cur is not None else None


def current_trace_id() -> Optional[str]:
    cur = _CURRENT.get()
    return cur[0].trace_id if cur is not None else None


def current_span_id() -> Optional[str]:
    cur = _CURRENT.get()
    return cur[1] if cur is not None else None


def traceparent() -> Optional[str]:
    """``trace_id-span_id`` wire form for the gRPC metadata hop."""
    cur = _CURRENT.get()
    if cur is None:
        return None
    return f"{cur[0].trace_id}-{cur[1]}"


def set_attr(**attrs) -> None:
    """Attach attributes to the innermost open span (root if no child
    is open).  No-op when untraced."""
    cur = _CURRENT.get()
    if cur is None:
        return
    trace, span_id = cur
    if span_id == trace.root.span_id:
        trace.root.attrs.update(attrs)
        return
    with trace._lock:
        sp = trace._open.get(span_id)
        if sp is None:
            for cand in reversed(trace._spans):
                if cand.span_id == span_id:
                    sp = cand
                    break
    if sp is not None:
        sp.attrs.update(attrs)
        return
    trace.root.attrs.update(attrs)


def event(name: str, **attrs) -> None:
    """Record a point event on the trace root (retry, breaker-open,
    hedge fired, reroute...).  Events on the root rather than the
    innermost span so cross-cutting layers (resilience, fleet) need no
    span handle.  No-op when untraced."""
    cur = _CURRENT.get()
    if cur is None:
        return
    try:
        cur[0].root.event(name, **attrs)
    except Exception:  # tracing must never fail the traced request
        pass


# ---------------------------------------------------------------------------
# span lifecycle

@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Any]:
    """Open a child span of the current context.  Yields the ``Span``
    (or a shared no-op handle when untraced) so callers can ``.set()``
    attributes discovered mid-flight."""
    cur = _CURRENT.get()
    if cur is None:
        yield _NULL
        return
    trace, parent = cur
    sp = Span(_new_id(), parent, name, trace.process, attrs or None)
    with trace._lock:
        trace._open[sp.span_id] = sp
    tok = _CURRENT.set((trace, sp.span_id))
    try:
        yield sp
    except BaseException as exc:
        sp.attrs.setdefault("error", type(exc).__name__)
        raise
    finally:
        _CURRENT.reset(tok)
        sp.close()
        with trace._lock:
            trace._open.pop(sp.span_id, None)
            trace._spans.append(sp)


def record_span(name: str, dur_s: float, t0: Optional[float] = None,
                **attrs) -> None:
    """Add an already-measured interval as a closed child span of the
    current context — for seams that time themselves (stage gates,
    admission waits) where wrapping the code in ``span()`` would
    double-clock it.  No-op when untraced."""
    cur = _CURRENT.get()
    if cur is None:
        return
    trace, parent = cur
    try:
        sp = Span(_new_id(), parent, name, trace.process, attrs or None)
        sp.t0 = float(t0) if t0 is not None else time.time() - float(dur_s)
        sp.dur_s = float(dur_s)
        trace.add(sp)
    except Exception:  # tracing must never fail the traced request
        pass


@contextlib.contextmanager
def start_trace(name: str, process: str = "gateway",
                **attrs) -> Iterator[Optional[Trace]]:
    """Create a new trace rooted at ``name`` and activate it for the
    enclosed block.  Yields the ``Trace`` (None when ``GSKY_TRACE=0``).
    On exit the completed trace is handed to the flight recorder."""
    if not trace_enabled():
        yield None
        return
    trace = Trace(name, process=process, attrs=attrs or None)
    tok = _CURRENT.set((trace, trace.root.span_id))
    try:
        yield trace
    except BaseException as exc:
        trace.root.attrs.setdefault("error", type(exc).__name__)
        raise
    finally:
        _CURRENT.reset(tok)
        trace.root.close()
        try:
            from .recorder import default_recorder
            default_recorder().record(trace.to_dict())
        except Exception:  # recorder handoff is best-effort telemetry
            pass


@contextlib.contextmanager
def bind(ctx: Optional[Tuple[Trace, str]]) -> Iterator[None]:
    """Re-establish a captured context inside a raw thread (stage
    threads and executor pools start from an empty Context).  Pass the
    result of ``current_context()`` captured on the submitting side."""
    if ctx is None:
        yield
        return
    tok = _CURRENT.set(ctx)
    try:
        yield
    finally:
        _CURRENT.reset(tok)


@contextlib.contextmanager
def remote_trace(header: Optional[str], name: str,
                 process: str = "worker", **attrs) -> Iterator[Optional[Trace]]:
    """Worker-side continuation of a propagated context.  ``header`` is
    the ``traceparent()`` wire form from gRPC metadata; the new local
    root becomes a child of the caller's RPC span.  The collected spans
    (``trace.span_dicts()``) are shipped back on the RPC result rather
    than recorded locally."""
    if not header:
        yield None
        return
    try:
        tid, _, sid = header.partition("-")
        if not tid or not sid:
            yield None
            return
    except Exception:
        yield None
        return
    trace = Trace(name, trace_id=tid, parent_id=sid, process=process,
                  attrs=attrs or None)
    tok = _CURRENT.set((trace, trace.root.span_id))
    try:
        yield trace
    except BaseException as exc:
        trace.root.attrs.setdefault("error", type(exc).__name__)
        raise
    finally:
        _CURRENT.reset(tok)
        trace.root.close()


def adopt_spans(span_dicts: Optional[Sequence[Dict[str, Any]]]) -> None:
    """Stitch spans returned by a worker into the live trace."""
    if not span_dicts:
        return
    cur = _CURRENT.get()
    if cur is None:
        return
    try:
        cur[0].adopt(span_dicts)
    except Exception:  # adopted remote spans are advisory
        pass
