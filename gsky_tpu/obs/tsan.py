"""Lockset race sanitizer (Eraser-style), opt-in via ``GSKY_TSAN=1``.

The static GSKY-LOCK check (tools/gskylint) proves *lexical* lock
discipline; this module catches what syntax cannot — aliased
structures, callbacks that outlive their ``with`` block, and the
cross-thread interleavings of the wave ticker/drainer threads, the
page pool's staging vs. teardown paths, and the encode pools.

Algorithm (Savage et al., "Eraser", SOSP '97, write-set variant):

* every instrumented lock tracks, per thread, the set of locks held;
* every *write* to a tracked shared variable ``v`` refines its
  candidate set ``C(v) ∩= locks_held(current thread)`` once a second
  thread has touched it (first-writer accesses are exempt: objects
  are routinely built single-threaded before publication);
* ``C(v) = ∅`` with two distinct writer threads ⇒ no single lock
  consistently protected ``v`` — a race report carrying both stacks
  (the previous conflicting write's and the current one's).

Instrumentation has two hooks:

* :func:`install` monkeypatches ``threading.Lock``/``RLock`` so every
  lock created afterwards participates in lockset tracking (existing
  locks simply never appear in locksets — races guarded only by a
  pre-install lock can false-positive, so install() runs before the
  server boots: tools/soak.py and server/main.py call
  :func:`maybe_install` first thing);
* :func:`track` swizzles one object's class so attribute writes are
  checked; the wave scheduler, page pool, and render batcher
  self-register at construction when tsan is enabled (a disabled
  process pays a single ``if`` per constructor).

Everything is a no-op unless ``GSKY_TSAN=1`` (read at call time, not
import — the knob survives SIGHUP reconfigure like every other one).
Reports are collected, deduplicated per (class, attribute), and
surfaced via :func:`races` / :func:`report`; the CI wave-soak leg
runs with ``GSKY_TSAN=1`` and fails on any report.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

_REAL_LOCK = threading.Lock          # bound pre-install, used internally
_REAL_RLOCK = threading.RLock

_STACK_DEPTH = 12                    # frames kept per access record


def enabled() -> bool:
    """GSKY_TSAN=1 turns the sanitizer on (call-time read)."""
    return os.environ.get("GSKY_TSAN", "0") == "1"


# -- lockset bookkeeping ------------------------------------------------

_tls = threading.local()


def _held() -> frozenset:
    return frozenset(getattr(_tls, "held", ()) or ())


def _push(lock_id: int) -> None:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    held.append(lock_id)


def _pop(lock_id: int) -> None:
    held = getattr(_tls, "held", None)
    if held and lock_id in held:
        held.reverse()
        held.remove(lock_id)
        held.reverse()


class TsanLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper that records
    holdership in the per-thread lockset.  Delegates everything to a
    real lock, so semantics (blocking, timeouts, context manager,
    Condition compatibility) are untouched."""

    __slots__ = ("_lock", "_id")

    def __init__(self, rlock: bool = False):
        self._lock = _REAL_RLOCK() if rlock else _REAL_LOCK()
        self._id = id(self)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            _push(self._id)
        return got

    def release(self):
        self._lock.release()
        _pop(self._id)

    def locked(self):
        return self._lock.locked() if hasattr(self._lock, "locked") \
            else False

    def __getattr__(self, attr):
        # delegate the long tail of private lock protocol —
        # _at_fork_reinit (os.register_at_fork), _is_owned /
        # _release_save / _acquire_restore (Condition over RLock)
        return getattr(self._lock, attr)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TsanLock {self._id:#x} over {self._lock!r}>"


# -- race records -------------------------------------------------------

class _VarState:
    """Per (object id, attribute) Eraser write-state."""

    __slots__ = ("first_thread", "lockset", "last_write", "shared")

    def __init__(self, thread_id: int, held: frozenset, stack):
        self.first_thread = thread_id
        self.lockset: Optional[frozenset] = None   # None = universe
        self.last_write: Tuple[int, str, object] = \
            (thread_id, threading.current_thread().name, stack)
        self.shared = False


class RaceReport:
    def __init__(self, name: str, attr: str, prev, cur):
        self.name = name
        self.attr = attr
        self.prev_thread, self.prev_stack = prev
        self.cur_thread, self.cur_stack = cur

    def render(self) -> str:
        prev = "".join(traceback.format_list(self.prev_stack)) \
            if self.prev_stack else "  <no stack>\n"
        cur = "".join(traceback.format_list(self.cur_stack)) \
            if self.cur_stack else "  <no stack>\n"
        return (f"RACE on {self.name}.{self.attr}: no common lock "
                f"across writer threads\n"
                f"  previous write [{self.prev_thread}]:\n{prev}"
                f"  current write  [{self.cur_thread}]:\n{cur}")


class _Collector:
    def __init__(self):
        self._lock = _REAL_LOCK()
        self._vars: Dict[Tuple[int, str], _VarState] = {}
        self._races: List[RaceReport] = []
        self._seen: set = set()

    def note_write(self, obj, name: str, attr: str) -> None:
        if not enabled():
            return      # a tracked singleton outliving GSKY_TSAN=1
        tid = threading.get_ident()
        held = _held()
        stack = traceback.extract_stack(limit=_STACK_DEPTH)[:-3]
        key = (id(obj), attr)
        with self._lock:
            st = self._vars.get(key)
            if st is None:
                self._vars[key] = _VarState(tid, held, stack)
                return
            prev = st.last_write
            st.last_write = (tid, threading.current_thread().name,
                             stack)
            if tid == st.first_thread and not st.shared:
                return            # still thread-confined
            st.shared = True
            st.lockset = held if st.lockset is None \
                else (st.lockset & held)
            if st.lockset:
                return
            dedup = (name, attr)
            if dedup in self._seen:
                return
            self._seen.add(dedup)
            self._races.append(RaceReport(
                name, attr, (prev[1], prev[2]),
                (threading.current_thread().name, stack)))

    def races(self) -> List[RaceReport]:
        with self._lock:
            return list(self._races)

    def reset(self) -> None:
        with self._lock:
            self._vars.clear()
            self._races.clear()
            self._seen.clear()


_collector = _Collector()


def races() -> List[RaceReport]:
    return _collector.races()


def race_count() -> int:
    return len(_collector.races())


def report() -> str:
    rs = _collector.races()
    if not rs:
        return "tsan: no races detected"
    return "\n".join(r.render() for r in rs)


def reset() -> None:
    _collector.reset()


# -- attribute-write instrumentation ------------------------------------

_swizzled: Dict[type, type] = {}


def track(obj, name: Optional[str] = None) -> bool:
    """Start checking attribute writes on ``obj``.  Returns True when
    tracking is live.  Implemented by swizzling the instance onto a
    per-class subclass whose ``__setattr__`` notes the write — zero
    cost for untracked instances of the same class.  Classes with
    ``__slots__`` and no ``__dict__`` cannot be swizzled safely and
    are declined."""
    if not enabled():
        return False
    cls = type(obj)
    if cls in _swizzled.values():
        return True              # already a tracking subclass
    sub = _swizzled.get(cls)
    if sub is None:
        if not hasattr(obj, "__dict__"):
            return False
        label = name or cls.__name__

        def _setattr(self, attr, value,
                     _base=cls, _label=label):
            _collector.note_write(self, _label, attr)
            _base.__setattr__(self, attr, value)

        try:
            sub = type(cls.__name__, (cls,),
                       {"__setattr__": _setattr,
                        "__tsan_tracked__": True})
        except TypeError:
            return False
        _swizzled[cls] = sub
    try:
        object.__setattr__(obj, "__class__", sub)
    except TypeError:
        return False
    return True


# -- threading.Lock patch ----------------------------------------------

_installed = False


def install() -> bool:
    """Patch ``threading.Lock``/``RLock`` so locks created from here
    on participate in lockset tracking.  Idempotent."""
    global _installed
    if _installed:
        return True
    threading.Lock = lambda: TsanLock(rlock=False)    # type: ignore
    threading.RLock = lambda: TsanLock(rlock=True)    # type: ignore
    _installed = True
    return True


def uninstall() -> None:
    global _installed
    threading.Lock = _REAL_LOCK                       # type: ignore
    threading.RLock = _REAL_RLOCK                     # type: ignore
    _installed = False


def maybe_install() -> bool:
    """install() iff GSKY_TSAN=1 — the one-liner boot hook."""
    if enabled():
        return install()
    return False


def installed() -> bool:
    return _installed


def tsan_stats() -> Dict:
    """The /debug ``tsan`` block and the gsky_tsan_races_total family
    (obs/metrics.py) read this; cheap when disabled."""
    with _collector._lock:
        tracked = len(_collector._vars)
        nraces = len(_collector._races)
    return {"enabled": enabled(), "installed": _installed,
            "tracked_vars": tracked, "races": nraces}
