"""Graceful drain: stop accepting, finish in-flight, then exit.

One :class:`DrainController` guards a serving surface (a worker node's
RPC dispatch, the OWS request handler).  Normal operation tracks every
in-flight task through :meth:`track`; a drain (SIGTERM) flips the
accept gate — new work is refused with :class:`Draining` — and
:meth:`wait_drained` blocks until the in-flight count reaches zero (or
the timeout lapses, for a supervisor that will SIGKILL anyway).

Zero-dropped-request restarts fall out: the load balancer / fleet
router sees ``Draining`` refusals (or the draining heartbeat state) and
re-routes new work, while everything already admitted completes and is
delivered before the process exits.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional


class Draining(ConnectionError):
    """New work refused: this process is draining.

    A ``ConnectionError`` subclass deliberately, like
    :class:`resilience.faults.InjectedFault`: callers' existing
    transport-failure handling (failover to the next node, retry
    classification) applies unchanged.
    """

    retryable = True

    def __init__(self, what: str = "server"):
        super().__init__(f"{what} is draining")


class DrainController:
    def __init__(self, name: str = "server"):
        self.name = name
        self._cond = threading.Condition()
        self._inflight = 0
        self._draining = False
        self.refused = 0
        self.completed = 0
        self.abandoned = 0
        self.drained_at: Optional[float] = None

    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    @contextlib.contextmanager
    def track(self):
        """Admit one task for its lifetime; raises :class:`Draining`
        instead when the gate is closed."""
        with self._cond:
            if self._draining:
                self.refused += 1
                raise Draining(self.name)
            self._inflight += 1
        try:
            yield
        finally:
            with self._cond:
                self._inflight -= 1
                self.completed += 1
                if self._inflight == 0:
                    self._cond.notify_all()

    def start_drain(self) -> None:
        """Close the accept gate (idempotent)."""
        with self._cond:
            if not self._draining:
                self._draining = True
                self.drained_at = time.monotonic()
            if self._inflight == 0:
                self._cond.notify_all()

    def wait_drained(self, timeout_s: float = 30.0) -> bool:
        """Block until every in-flight task finished; True on success,
        False when the timeout lapsed with work still running."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(left)
            return True

    def drain(self, timeout_s: float = 30.0) -> bool:
        self.start_drain()
        return self.wait_drained(timeout_s)

    def abandon_inflight(self) -> int:
        """Grace-deadline failover: the drain timed out with work still
        running, and the process is about to exit (supervisor SIGKILL,
        preemption deadline).  Count the stranded tasks explicitly —
        their callers will see a transport failure, which the fleet
        router classifies as retryable and fails over — instead of
        exiting with silent in-flight loss.  Returns the count."""
        with self._cond:
            n = self._inflight
            self.abandoned += n
            return n

    def stats(self) -> dict:
        with self._cond:
            return {"draining": self._draining,
                    "inflight": self._inflight,
                    "refused": self.refused,
                    "completed": self.completed,
                    "abandoned": self.abandoned}
