"""Fleet router: health-gated consistent-hash routing + hedge policy.

One :class:`FleetRouter` fronts one node set (one ``worker_nodes``
list).  It owns the hash ring, the health monitor and the hedge
policy, tracks per-node in-flight load for bounded-load routing, and
keeps the locality ledger (did a repeat tile key land on the same node
as last time?) that the fleet soak asserts on.

Env knobs (all ``GSKY_FLEET_*``; see docs/FLEET.md):

- ``GSKY_FLEET=0``            disable keyed routing (legacy round-robin)
- ``GSKY_FLEET_VNODES``       virtual nodes per ring member (64)
- ``GSKY_FLEET_BOUND``        bounded-load factor c (1.25; 0 = off)
- ``GSKY_FLEET_PROBE_S``      active heartbeat period (2.0; 0 = passive)
- ``GSKY_FLEET_SUSPECT_PHI`` / ``GSKY_FLEET_DEAD_PHI``  (3 / 8)
- ``GSKY_FLEET_HEDGE=0``      disable hedged dispatch
- ``GSKY_FLEET_HEDGE_BUDGET`` hedge tokens earned per primary (0.1)
- ``GSKY_FLEET_HEDGE_MS``     floor of the adaptive hedge delay (50)
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Callable, Dict, List, Optional

from .health import HealthMonitor
from .hedge import HedgePolicy
from .ring import HashRing


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# process-wide router registry: /debug's `fleet` block and the
# admission controller's least-loaded-shard advisor read through it
_ROUTERS: "weakref.WeakSet[FleetRouter]" = weakref.WeakSet()
_routers_lock = threading.Lock()


def register_router(router: "FleetRouter") -> None:
    with _routers_lock:
        _ROUTERS.add(router)


def routers() -> List["FleetRouter"]:
    with _routers_lock:
        return list(_ROUTERS)


def fleet_stats() -> Dict:
    """The /debug ``fleet`` block: one entry per live router."""
    out: Dict = {}
    for r in routers():
        out[r.name] = r.stats()
    return out


def least_loaded_node() -> Optional[str]:
    """The least-loaded healthy node across every registered router —
    the shed-target hint admission control attaches to its 503s."""
    best = None
    best_load = None
    for r in routers():
        for node in r.ring.nodes:
            if not r.monitor.healthy(node):
                continue
            load = r.load_of(node)
            if best_load is None or load < best_load:
                best, best_load = node, load
    return best


class FleetRouter:
    def __init__(self, nodes, name: str = "worker",
                 probe: Optional[Callable[[str], object]] = None,
                 vnodes: Optional[int] = None,
                 bound: Optional[float] = None,
                 hedge: Optional[HedgePolicy] = None,
                 monitor: Optional[HealthMonitor] = None):
        self.name = name
        self.enabled = os.environ.get("GSKY_FLEET", "1") != "0"
        self.ring = HashRing(
            nodes, vnodes=vnodes if vnodes is not None
            else _env_int("GSKY_FLEET_VNODES", 64))
        self.bound = bound if bound is not None \
            else _env_float("GSKY_FLEET_BOUND", 1.25)
        self.monitor = monitor or HealthMonitor(
            nodes, probe=probe,
            interval_s=_env_float("GSKY_FLEET_PROBE_S", 2.0),
            suspect_phi=_env_float("GSKY_FLEET_SUSPECT_PHI", 3.0),
            dead_phi=_env_float("GSKY_FLEET_DEAD_PHI", 8.0))
        self.hedge_enabled = os.environ.get(
            "GSKY_FLEET_HEDGE", "1") != "0"
        self.hedge = hedge or HedgePolicy(
            budget=_env_float("GSKY_FLEET_HEDGE_BUDGET", 0.1),
            min_delay_s=_env_float("GSKY_FLEET_HEDGE_MS", 50.0) / 1e3)
        self._lock = threading.Lock()
        self._load: Dict[str, int] = {}
        # locality ledger: route key -> node it last ran on
        self._last_node: Dict[str, str] = {}
        self.locality_hits = 0
        self.locality_misses = 0
        self.routed = 0
        self.rerouted = 0
        self.rr_fallback = 0
        register_router(self)

    # -- membership ----------------------------------------------------------

    def set_nodes(self, nodes) -> None:
        """Reconcile membership (elastic fleet scale/replace): rebuild
        the ring (generation bump), track new nodes in the health
        monitor, and purge departed nodes from the phi trackers, the
        per-node in-flight map and the locality ledger — without the
        purge a flapping fleet grows unbounded state (ISSUE 18)."""
        old = set(self.ring.nodes)
        self.ring.set_nodes(nodes)
        new = set(self.ring.nodes)
        self.monitor.ensure(sorted(new - old))
        gone = old - new
        if not gone:
            return
        self.monitor.forget(sorted(gone))
        with self._lock:
            for n in gone:
                self._load.pop(n, None)
            self._last_node = {k: v for k, v in self._last_node.items()
                               if v not in gone}

    # -- load accounting -----------------------------------------------------

    def load_of(self, node: str) -> int:
        with self._lock:
            return self._load.get(node, 0)

    def task_started(self, node: str) -> None:
        with self._lock:
            self._load[node] = self._load.get(node, 0) + 1

    def task_finished(self, node: str) -> None:
        with self._lock:
            self._load[node] = max(self._load.get(node, 0) - 1, 0)

    # -- routing -------------------------------------------------------------

    def candidates(self, key: Optional[str]) -> List[str]:
        """Ordered dispatch candidates for a task.

        With a key (and routing enabled): the ring preference walk,
        healthy nodes first, bounded-load spill applied, suspect nodes
        kept behind every healthy one, dead/draining nodes last (they
        are still *attemptable* when nothing else is left — one failed
        RPC beats refusing a request the node might serve).
        """
        nodes = self.ring.nodes
        if not nodes:
            return []
        if key is None or not self.enabled:
            return nodes
        with self._lock:
            load = dict(self._load)
        healthy = self.ring.route(
            key, eligible=self.monitor.healthy, load=load,
            bound=self.bound)
        pref = self.ring.preference(key)
        suspect = [n for n in pref
                   if n not in set(healthy) and self.monitor.routable(n)]
        rest = [n for n in pref
                if n not in set(healthy) and n not in set(suspect)]
        return healthy + suspect + rest

    def peers_for(self, key: str, n: Optional[int] = None,
                  exclude: Optional[str] = None) -> List[str]:
        """Ring-adjacent peer selection for the cache fabric
        (docs/FABRIC.md): the key's preference walk filtered to
        currently-routable nodes, optionally excluding the asking node
        itself.  Unlike :meth:`candidates` this never pads with dead
        nodes — a fabric fill is an optimisation, so an unroutable
        peer is simply not asked."""
        out = [m for m in self.ring.preference(key)
               if m != exclude and self.monitor.routable(m)]
        return out if n is None else out[:n]

    def record_locality(self, key: str, node: str) -> None:
        with self._lock:
            prev = self._last_node.get(key)
            if prev is not None:
                if prev == node:
                    self.locality_hits += 1
                else:
                    self.locality_misses += 1
            # bound the ledger: locality is about *recent* repeats
            if len(self._last_node) > 65536:
                self._last_node.clear()
            self._last_node[key] = node
            self.routed += 1

    def record_reroute(self) -> None:
        with self._lock:
            self.rerouted += 1

    def record_rr(self) -> None:
        with self._lock:
            self.rr_fallback += 1
            self.routed += 1

    def node_result(self, node: str, ok: bool,
                    latency_s: Optional[float] = None,
                    fatal: bool = False,
                    draining: bool = False) -> None:
        """Fold one RPC outcome into health + hedge state."""
        if draining:
            # answered, but only to say goodbye: keep the beat history
            # warm (not a failure) yet route nothing new at it
            self.monitor.record_heartbeat(node)
            self.monitor.record_draining(node)
            return
        if ok:
            self.monitor.record_heartbeat(node)
            if latency_s is not None:
                self.hedge.observe(latency_s)
        else:
            self.monitor.record_failure(node, fatal=fatal)

    def locality_rate(self) -> Optional[float]:
        with self._lock:
            total = self.locality_hits + self.locality_misses
            if total == 0:
                return None
            return self.locality_hits / total

    def close(self) -> None:
        self.monitor.stop()

    def stats(self) -> Dict:
        with self._lock:
            loc_total = self.locality_hits + self.locality_misses
            out = {
                "enabled": self.enabled,
                "ring": {"nodes": self.ring.nodes,
                         "generation": self.ring.generation,
                         "vnodes": self.ring.vnodes,
                         "bound": self.bound},
                "load": dict(self._load),
                "routed": self.routed,
                "rerouted": self.rerouted,
                "rr_fallback": self.rr_fallback,
                "locality": {
                    "hits": self.locality_hits,
                    "misses": self.locality_misses,
                    "rate": round(self.locality_hits / loc_total, 4)
                    if loc_total else None},
            }
        out["health"] = self.monitor.snapshot()
        hs = self.hedge.stats()
        hs["delay_s"] = round(self.hedge.delay_s(), 4)
        hs["enabled"] = self.hedge_enabled
        out["hedge"] = hs
        return out
