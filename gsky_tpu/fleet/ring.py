"""Consistent-hash ring with bounded-load routing.

The ring maps canonical tile keys onto worker nodes so that repeat
requests for the same tile land on the same shard — keeping that
shard's scene cache, kernel ledger and XLA compile cache hot — while a
node death moves only that node's arc of the keyspace (~K/n keys for K
keys over n nodes), not a full reshuffle the way modulo hashing would.

Hashing is ``md5`` over stable strings (never Python ``hash()``:
``PYTHONHASHSEED`` would silently change placement between processes),
with ``vnodes`` virtual points per node to even out arc lengths.

Bounded load (the "consistent hashing with bounded loads" result used
by production CDN front-ends): a node already carrying more than
``bound`` times its fair share of the observed in-flight load is
skipped and the key *spills* to the next node on its preference walk —
a deterministic order, so two gateways under the same load picture
spill the same way.  This keeps one hot tile from melting its home
shard while preserving locality for everything else.
"""

from __future__ import annotations

import bisect
import hashlib
import math
import threading
from typing import Callable, Dict, List, Optional, Sequence


def _hash64(s: str) -> int:
    """Stable 64-bit hash of a string (first 8 md5 bytes)."""
    return int.from_bytes(
        hashlib.md5(s.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over named nodes.

    ``generation`` increments on every membership change so observers
    (metrics, the soak) can tell a rebalance happened.
    """

    def __init__(self, nodes: Sequence[str], vnodes: int = 64):
        self.vnodes = max(int(vnodes), 1)
        self._lock = threading.Lock()
        self.generation = 0
        self._nodes: List[str] = []
        self._points: List[int] = []
        self._owners: List[str] = []
        self.set_nodes(nodes)

    def set_nodes(self, nodes: Sequence[str]) -> None:
        """Replace the membership; a no-op when the set is unchanged."""
        uniq = sorted(set(nodes))
        with self._lock:
            if uniq == self._nodes:
                return
            pts: List[tuple] = []
            for n in uniq:
                for v in range(self.vnodes):
                    pts.append((_hash64(f"{n}#{v}"), n))
            pts.sort()
            self._nodes = uniq
            self._points = [p for p, _ in pts]
            self._owners = [o for _, o in pts]
            self.generation += 1

    @property
    def nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def preference(self, key: str, n: Optional[int] = None) -> List[str]:
        """The first ``n`` DISTINCT nodes clockwise from ``key``'s point
        — position 0 is the key's home shard, positions 1.. are its
        deterministic failover/spill order."""
        with self._lock:
            if not self._nodes:
                return []
            want = len(self._nodes) if n is None else min(n, len(self._nodes))
            h = _hash64(key)
            i = bisect.bisect_right(self._points, h)
            out: List[str] = []
            seen = set()
            for k in range(len(self._points)):
                owner = self._owners[(i + k) % len(self._points)]
                if owner not in seen:
                    seen.add(owner)
                    out.append(owner)
                    if len(out) >= want:
                        break
            return out

    def owner(self, key: str) -> Optional[str]:
        pref = self.preference(key, 1)
        return pref[0] if pref else None

    def successor(self, node: str) -> Optional[str]:
        """The next DISTINCT node clockwise from ``node``'s primary
        vnode point — the shard that inherits the largest share of
        ``node``'s arc when it leaves, and therefore the natural heir
        for its page-residency journal on preemption (fleet/elastic).
        Deterministic across processes for a given membership."""
        with self._lock:
            if node not in self._nodes or len(self._nodes) < 2:
                return None
            h = _hash64(f"{node}#0")
            i = bisect.bisect_right(self._points, h)
            for k in range(len(self._points)):
                owner = self._owners[(i + k) % len(self._points)]
                if owner != node:
                    return owner
        return None

    def route(self, key: str,
              eligible: Optional[Callable[[str], bool]] = None,
              load: Optional[Dict[str, int]] = None,
              bound: float = 0.0) -> List[str]:
        """Ordered candidates for ``key``: the preference walk filtered
        to ``eligible`` nodes, with over-loaded nodes (more than
        ``bound`` x the fair share of the total observed load) demoted
        behind the rest — spilled, in the same deterministic walk order.

        With no eligible node at all, returns the unfiltered preference
        walk so the caller can still attempt (and fail over) rather
        than refusing outright.
        """
        pref = self.preference(key)
        if eligible is not None:
            ok = [n for n in pref if eligible(n)]
            pref = ok or pref
        if not load or bound <= 0.0 or len(pref) <= 1:
            return pref
        total = sum(max(load.get(n, 0), 0) for n in pref)
        if total <= 0:
            return pref
        # fair share rounded up: a bound of 1.25 over 2 nodes with 4
        # in-flight allows ceil(1.25 * 4 / 2) = 3 per node
        cap = math.ceil(bound * total / len(pref))
        under = [n for n in pref if load.get(n, 0) < cap]
        over = [n for n in pref if load.get(n, 0) >= cap]
        return (under + over) if under else pref
