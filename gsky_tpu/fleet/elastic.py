"""Elastic fleet: preemptible-worker autoscaling with warm handoff.

Production TPU capacity is spot-priced and preemptible: a fixed worker
set either over-provisions for the Zipf peak or browns out under it.
This module closes the control loop ROADMAP item 4 names, across the
subsystems earlier PRs built one edge each of:

- **demand** — :class:`DemandSignal` samples the admission
  controller's queue depth and AIMD effective limits (serving/
  admission), per-node in-flight load (fleet/router), wave occupancy
  (pipeline/waves, when live) and the pressure state (resilience/
  pressure) into one smoothed utilisation number.
- **decision** — :class:`Autoscaler` maps the smoothed signal onto
  scale-up / scale-down decisions between ``GSKY_ELASTIC_MIN`` and
  ``GSKY_ELASTIC_MAX``, with hysteresis (N consecutive ticks past a
  threshold) and a cooldown so a noisy signal cannot flap the fleet.
  Every decision is logged and countered
  (``gsky_elastic_decisions_total{dir}``).
- **actuation** — a pluggable :class:`NodeProvider`.
  :class:`LocalSubprocessProvider` spawns ``gsky_tpu.worker.server``
  subprocesses for tests and the soak; the interface (``launch`` /
  ``preempt`` / ``terminate`` / ``alive``) is where real TPU
  provisioning plugs in.
- **preemption as a first-class event** — a ``node:preempt:<grace>``
  notice (fault-injectable via resilience/faults, or delivered as a
  ``preempt`` control RPC) starts the PR 6 drain handshake under a
  hard grace deadline, ships the node's page-residency journal (heat
  scores included) to its ring successor, and exits.  The successor —
  and any scale-up replacement — rehydrates hottest-first from peer
  HBM over the PR 16 page RPC instead of cold-staging from storage.
- **readiness gate** — a new node joins the ring only after its
  ``worker_info`` probe reports warm (pool warm fraction over the
  journal hot set), so cold joiners never drag p99; the ring's
  bounded-load spill absorbs the gap mid-scale.

Everything is dormant unless ``GSKY_ELASTIC=1``: with the gate off no
autoscaler runs, no metric family renders, and the fixed fleet is
byte-identical to a build that never imported this module.

Knobs (all read per call, never latched at import — gskylint
GSKY-ENV; documented in docs/CONFIG.md):

- ``GSKY_ELASTIC``              master gate (default 0)
- ``GSKY_ELASTIC_MIN/MAX``      node-count bounds (1 / 4)
- ``GSKY_ELASTIC_INTERVAL_S``   control-loop tick (2.0)
- ``GSKY_ELASTIC_UP/DOWN``      demand thresholds (0.8 / 0.25)
- ``GSKY_ELASTIC_UP_TICKS/DOWN_TICKS``  hysteresis (2 / 5)
- ``GSKY_ELASTIC_COOLDOWN_S``   min seconds between decisions (30)
- ``GSKY_ELASTIC_ALPHA``        demand EWMA weight (0.3)
- ``GSKY_ELASTIC_WARM_FRAC``    readiness warm fraction (0.5)
- ``GSKY_ELASTIC_READY_TIMEOUT_S``  join-anyway deadline (120)
- ``GSKY_ELASTIC_HANDOFF_MAX``  journal entries shipped on preempt (2048)
- ``GSKY_PREEMPT_GRACE_S``      default notice grace window (10)
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence

from .ring import HashRing

log = logging.getLogger("gsky.fleet.elastic")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def elastic_enabled() -> bool:
    return os.environ.get("GSKY_ELASTIC", "0") == "1"


def preempt_grace_s() -> float:
    return max(_env_float("GSKY_PREEMPT_GRACE_S", 10.0), 0.0)


def handoff_max() -> int:
    return max(_env_int("GSKY_ELASTIC_HANDOFF_MAX", 2048), 0)


def warm_fraction_target() -> float:
    return min(max(_env_float("GSKY_ELASTIC_WARM_FRAC", 0.5), 0.0), 1.0)


# -- counters (module-level: the worker side has no autoscaler object) --------

_stats_lock = threading.Lock()


def _zero_stats() -> Dict:
    return {
        "decisions": {"up": 0, "down": 0},
        "preemptions": {"graceful": 0, "nograce": 0},
        "handoff_pages": {"peer": 0, "cold": 0},
        "handoffs_shipped": 0,
        "handoff_entries_shipped": 0,
        "handoff_ship_failures": 0,
        "ready_waits": 0,
        "ready_timeouts": 0,
    }


_stats: Dict = _zero_stats()


def reset_stats() -> None:
    """Test hook: zero the process-wide elastic counters."""
    global _stats
    with _stats_lock:
        _stats = _zero_stats()


def note_decision(direction: str) -> None:
    with _stats_lock:
        d = _stats["decisions"]
        d[direction] = d.get(direction, 0) + 1


def note_preemption(graceful: bool) -> None:
    with _stats_lock:
        key = "graceful" if graceful else "nograce"
        _stats["preemptions"][key] += 1


def note_handoff_pages(source: str, n: int) -> None:
    if n <= 0:
        return
    with _stats_lock:
        hp = _stats["handoff_pages"]
        hp[source] = hp.get(source, 0) + n


def note_handoff_shipped(entries: int, ok: bool) -> None:
    with _stats_lock:
        if ok:
            _stats["handoffs_shipped"] += 1
            _stats["handoff_entries_shipped"] += entries
        else:
            _stats["handoff_ship_failures"] += 1


def note_ready_wait(timed_out: bool) -> None:
    with _stats_lock:
        _stats["ready_waits"] += 1
        if timed_out:
            _stats["ready_timeouts"] += 1


def counters() -> Dict:
    with _stats_lock:
        return json.loads(json.dumps(_stats))   # deep copy


# -- autoscaler registry (the /debug block and metrics read through it) -------

_SCALERS: "weakref.WeakSet[Autoscaler]" = weakref.WeakSet()
_scalers_lock = threading.Lock()


def register_autoscaler(a: "Autoscaler") -> None:
    with _scalers_lock:
        _SCALERS.add(a)


def autoscalers() -> List["Autoscaler"]:
    with _scalers_lock:
        return list(_SCALERS)


def elastic_stats() -> Dict:
    """The /debug ``elastic`` block: process counters + one entry per
    live autoscaler."""
    out: Dict = {"enabled": elastic_enabled(), "counters": counters()}
    scalers = {}
    for a in autoscalers():
        scalers[a.name] = a.stats()
    if scalers:
        out["autoscalers"] = scalers
    return out


def dormant() -> bool:
    """True when elastic has left no trace in this process — used by
    the metrics collector to keep the exposition byte-identical under
    ``GSKY_ELASTIC=0``."""
    if elastic_enabled() or autoscalers():
        return False
    with _stats_lock:
        return _stats == _zero_stats()


# -- control RPCs -------------------------------------------------------------

def control_rpc(addr: str, operation: str, doc: Optional[Dict] = None,
                timeout: float = 5.0) -> Dict:
    """One control-plane RPC (``preempt`` / ``journal_handoff`` /
    ``worker_info``) against one node; returns the parsed ``info_json``
    dict.  Raises on transport or peer error — control callers decide
    their own degradation."""
    import grpc

    from ..worker import gskyrpc_pb2 as pb
    from ..worker.server import METHOD
    ch = grpc.insecure_channel(addr)
    try:
        call = ch.unary_unary(
            METHOD, request_serializer=pb.Task.SerializeToString,
            response_deserializer=pb.Result.FromString)
        task = pb.Task(operation=operation)
        if doc is not None:
            task.path = json.dumps(doc)
        res = call(task, timeout=timeout)
        if res.error:
            raise RuntimeError(res.error)
        try:
            return json.loads(res.info_json or "{}")
        except ValueError:
            return {}
    finally:
        ch.close()


def probe_info(addr: str, timeout: float = 5.0) -> Optional[Dict]:
    """``worker_info`` probe returning the info dict, None on failure."""
    try:
        return control_rpc(addr, "worker_info", timeout=timeout)
    except Exception:
        return None


def successor_for(self_addr: str, peers: Sequence[str]) -> Optional[str]:
    """The ring successor a preempted node ships its journal to, when
    the notice did not name one: deterministic over the known peer set
    so the dying node and the autoscaler agree without coordination."""
    members = sorted(set(list(peers) + [self_addr]))
    if len(members) < 2:
        return None
    return HashRing(members, vnodes=32).successor(self_addr)


# -- node providers -----------------------------------------------------------

class NodeProvider:
    """Where real TPU provisioning plugs in.  Addresses returned by
    :meth:`launch` are gRPC ``host:port`` strings; a launched node may
    still be booting — the autoscaler gates ring membership on the
    readiness probe, not on ``launch`` returning."""

    def launch(self) -> str:
        raise NotImplementedError

    def terminate(self, addr: str) -> None:
        raise NotImplementedError

    def preempt(self, addr: str, grace_s: float,
                successor: Optional[str] = None,
                peers: Sequence[str] = ()) -> bool:
        """Deliver a preemption notice (the cloud's ~30s warning).  The
        default delivery is the ``preempt`` control RPC; a provider
        whose substrate signals differently (SIGTERM, metadata server)
        overrides this."""
        try:
            control_rpc(addr, "preempt",
                        {"v": 1, "grace_s": float(grace_s),
                         "successor": successor, "peers": list(peers)},
                        timeout=5.0)
            return True
        except Exception:
            return False

    def alive(self, addr: str) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalSubprocessProvider(NodeProvider):
    """Worker nodes as local subprocesses — the provider the unit soak
    and tests scale, mirroring how ``tools/soak.py`` spawns its fleet.
    Real chips obviously don't launch this way; the value is that every
    elastic code path (readiness, handoff, preemption) runs against
    real worker processes with real gRPC in between."""

    def __init__(self, extra_env: Optional[Dict[str, str]] = None,
                 pool_size: int = 1, host: str = "127.0.0.1",
                 log_dir: Optional[str] = None):
        self.extra_env = dict(extra_env or {})
        self.pool_size = int(pool_size)
        self.host = host
        self.log_dir = log_dir
        self._lock = threading.Lock()
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: List = []

    @staticmethod
    def free_port() -> int:
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def launch(self) -> str:
        port = self.free_port()
        addr = f"{self.host}:{port}"
        env = {**os.environ, **self.extra_env,
               "GSKY_ELASTIC_SELF": addr}
        out = subprocess.DEVNULL
        if self.log_dir:
            out = open(os.path.join(
                self.log_dir, f"worker-{port}.log"), "w")
            self._logs.append(out)
        # close_fds=False (with cwd=None) routes Popen through
        # posix_spawn: launching from a heavily-threaded serving
        # process must not fork — a child forked mid-render can
        # deadlock on another thread's allocator lock before exec
        proc = subprocess.Popen(
            [sys.executable, "-m", "gsky_tpu.worker.server",
             "-p", str(port), "-host", self.host,
             "-n", str(self.pool_size), "-oom_threshold", "0"],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            close_fds=False)
        with self._lock:
            self._procs[addr] = proc
        return addr

    def terminate(self, addr: str) -> None:
        with self._lock:
            proc = self._procs.pop(addr, None)
        if proc is None:
            return
        try:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
        except Exception:  # already exited / reaped
            pass

    def alive(self, addr: str) -> bool:
        with self._lock:
            proc = self._procs.get(addr)
        return proc is not None and proc.poll() is None

    def addrs(self) -> List[str]:
        with self._lock:
            return sorted(self._procs)

    def close(self) -> None:
        for addr in self.addrs():
            self.terminate(addr)
        for fp in self._logs:
            try:
                fp.close()
            except Exception:  # log file already closed
                pass


# -- demand signal ------------------------------------------------------------

class DemandSignal:
    """Folds the serving stack's existing telemetry into one smoothed
    utilisation number (1.0 = running at the configured limit; >1.0 =
    queueing).  Sources are all optional — a gateway without admission
    control still scales on in-flight load alone.

    - admission: max over service classes of
      ``(in_use + queued) / effective_limit`` — queue depth pushes the
      signal past 1 exactly when AIMD is refusing to grow.
    - fleet: total in-flight across nodes / (nodes x per-node target).
    - waves: device occupancy fraction, when the wave scheduler is live.
    - pressure: state 1 scales the sample x1.25, state 2 x1.5 —
      memory pressure is demand for *more nodes*, not more per-node
      concurrency.
    """

    def __init__(self, admission=None, router=None,
                 occupancy: Optional[Callable[[], Optional[float]]] = None,
                 pressure: Optional[Callable[[], int]] = None,
                 node_conc: int = 8, alpha: Optional[float] = None):
        self.admission = admission
        self.router = router
        self.occupancy = occupancy
        self.pressure = pressure
        self.node_conc = max(int(node_conc), 1)
        self.alpha = alpha
        self.smoothed: Optional[float] = None
        self.last_raw: Optional[float] = None
        self.last_parts: Dict[str, float] = {}

    def _admission_util(self) -> Optional[float]:
        if self.admission is None:
            return None
        try:
            st = self.admission.stats()
        except Exception:
            return None
        util = None
        for cls in (st.get("classes") or {}).values():
            eff = cls.get("effective_limit") or cls.get("limit") or 0
            if eff <= 0:
                continue
            u = (cls.get("in_use", 0) + cls.get("queued", 0)) / eff
            util = u if util is None else max(util, u)
        return util

    def _fleet_util(self) -> Optional[float]:
        if self.router is None:
            return None
        try:
            nodes = self.router.ring.nodes
            if not nodes:
                return None
            total = sum(self.router.load_of(n) for n in nodes)
            return total / (len(nodes) * self.node_conc)
        except Exception:
            return None

    def sample(self) -> float:
        parts: Dict[str, float] = {}
        vals: List[float] = []
        a = self._admission_util()
        if a is not None:
            parts["admission"] = round(a, 4)
            vals.append(a)
        f = self._fleet_util()
        if f is not None:
            parts["fleet"] = round(f, 4)
            vals.append(f)
        if self.occupancy is not None:
            try:
                occ = self.occupancy()
            except Exception:
                occ = None
            if occ is not None:
                parts["waves"] = round(float(occ), 4)
                vals.append(float(occ))
        raw = max(vals) if vals else 0.0
        if self.pressure is not None:
            try:
                p = int(self.pressure())
            except Exception:
                p = 0
            if p:
                parts["pressure"] = p
                raw *= 1.25 if p == 1 else 1.5
        alpha = self.alpha if self.alpha is not None \
            else min(max(_env_float("GSKY_ELASTIC_ALPHA", 0.3), 0.01), 1.0)
        self.last_raw = raw
        self.last_parts = parts
        if self.smoothed is None:
            self.smoothed = raw
        else:
            self.smoothed += alpha * (raw - self.smoothed)
        return self.smoothed


# -- the control loop ---------------------------------------------------------

class Autoscaler:
    """Samples demand, scales membership through the provider, and
    treats preemption as routine: a node that reports draining or goes
    dead is purged from the ring and (when below the floor or demand
    holds) replaced by a launch that warms from peers before joining.

    ``client`` is the routing surface being scaled: anything with
    ``nodes`` (list), ``set_nodes(addrs)`` and ``fleet`` (a
    :class:`~gsky_tpu.fleet.router.FleetRouter`) — in production the
    worker :class:`~gsky_tpu.worker.client.WorkerClient`."""

    def __init__(self, provider: NodeProvider, client, *,
                 name: str = "worker",
                 min_nodes: Optional[int] = None,
                 max_nodes: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 up: Optional[float] = None,
                 down: Optional[float] = None,
                 up_ticks: Optional[int] = None,
                 down_ticks: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 ready_timeout_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 demand: Optional[DemandSignal] = None,
                 probe: Optional[Callable[[str], Optional[Dict]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.provider = provider
        self.client = client
        self.name = name
        self.min_nodes = max(min_nodes if min_nodes is not None
                             else _env_int("GSKY_ELASTIC_MIN", 1), 0)
        self.max_nodes = max(max_nodes if max_nodes is not None
                             else _env_int("GSKY_ELASTIC_MAX", 4),
                             self.min_nodes or 1)
        self.interval_s = interval_s if interval_s is not None \
            else _env_float("GSKY_ELASTIC_INTERVAL_S", 2.0)
        self.up = up if up is not None \
            else _env_float("GSKY_ELASTIC_UP", 0.8)
        self.down = down if down is not None \
            else _env_float("GSKY_ELASTIC_DOWN", 0.25)
        self.up_ticks = max(up_ticks if up_ticks is not None
                            else _env_int("GSKY_ELASTIC_UP_TICKS", 2), 1)
        self.down_ticks = max(down_ticks if down_ticks is not None
                              else _env_int("GSKY_ELASTIC_DOWN_TICKS", 5), 1)
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _env_float("GSKY_ELASTIC_COOLDOWN_S", 30.0)
        self.ready_timeout_s = ready_timeout_s if ready_timeout_s is not None \
            else _env_float("GSKY_ELASTIC_READY_TIMEOUT_S", 120.0)
        self.drain_grace_s = drain_grace_s if drain_grace_s is not None \
            else preempt_grace_s()
        self.demand = demand or DemandSignal(router=client.fleet)
        self.probe = probe or probe_info
        self._clock = clock
        self._lock = threading.Lock()
        # addr -> {"t0": launch time, "deadline": join-anyway time}
        self._pending: Dict[str, Dict] = {}
        self._leaving: Dict[str, float] = {}   # addr -> removal time
        self._above = 0
        self._below = 0
        self._last_decision: Optional[float] = None
        self.decisions: List[Dict] = []
        self.preempted_seen: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        register_autoscaler(self)

    # -- membership helpers ---------------------------------------------------

    def _active(self) -> List[str]:
        return list(self.client.nodes)

    def _record(self, direction: str, reason: str, **kw) -> None:
        ev = {"dir": direction, "reason": reason,
              "t": round(self._clock(), 3), **kw}
        with self._lock:
            self.decisions.append(ev)
            if len(self.decisions) > 256:
                del self.decisions[:128]
        if direction in ("up", "down"):
            note_decision(direction)
        log.info("elastic %s: %s %s", self.name, direction, ev)

    # -- scale actions --------------------------------------------------------

    def _launch(self, reason: str) -> Optional[str]:
        try:
            addr = self.provider.launch()
        except Exception:
            log.exception("elastic %s: launch failed", self.name)
            self._record("launch_failed", reason)
            return None
        now = self._clock()
        with self._lock:
            self._pending[addr] = {
                "t0": now, "deadline": now + self.ready_timeout_s}
        self._record("up", reason, node=addr)
        self._last_decision = now
        return addr

    def _join_if_ready(self) -> None:
        with self._lock:
            pending = dict(self._pending)
        if not pending:
            return
        now = self._clock()
        for addr, ent in pending.items():
            if not self.provider.alive(addr):
                with self._lock:
                    self._pending.pop(addr, None)
                self._record("join_abandoned", "died_booting", node=addr)
                continue
            info = self.probe(addr)
            el = (info or {}).get("elastic") or {}
            ready = bool(el.get("ready")) if info is not None else False
            timed_out = now >= ent["deadline"]
            if not ready and not timed_out:
                continue
            if timed_out and info is None:
                # never answered a single probe: joining would route
                # live traffic at a black hole — give up on the node
                with self._lock:
                    self._pending.pop(addr, None)
                self._record("join_abandoned", "never_answered",
                             node=addr)
                try:
                    self.provider.terminate(addr)
                except Exception:  # provider may already have reaped it
                    pass
                continue
            with self._lock:
                self._pending.pop(addr, None)
            note_ready_wait(timed_out and not ready)
            nodes = self._active()
            if addr not in nodes:
                self.client.set_nodes(nodes + [addr])
            self._record(
                "join", "ready" if ready else "ready_timeout", node=addr,
                wait_s=round(now - ent["t0"], 3),
                warm_fraction=el.get("warm_fraction"))

    def _scale_down(self, reason: str) -> None:
        nodes = self._active()
        if len(nodes) <= self.min_nodes:
            return
        fleet = self.client.fleet
        victim = min(nodes, key=lambda n: (fleet.load_of(n), n))
        successor = fleet.ring.successor(victim)
        peers = [n for n in nodes if n != victim]
        # remove from the ring FIRST: no new work routes at the victim
        # while it drains, and the bounded-load spill absorbs its arc
        self.client.set_nodes(peers)
        now = self._clock()
        with self._lock:
            self._leaving[victim] = now
        self._record("down", reason, node=victim, successor=successor)
        self._last_decision = now

        def _retire():
            ok = self.provider.preempt(
                victim, self.drain_grace_s, successor=successor,
                peers=peers)
            if not ok:
                log.warning("elastic %s: preempt notice to %s failed; "
                            "terminating", self.name, victim)
            self._stop.wait(self.drain_grace_s + 2.0)
            self.provider.terminate(victim)
            with self._lock:
                self._leaving.pop(victim, None)

        threading.Thread(target=_retire, daemon=True,
                         name=f"gsky-elastic-retire-{victim}").start()

    def _reconcile_departures(self) -> int:
        """Purge nodes that died or announced draining (external
        preemption); returns how many were removed."""
        from .health import DEAD, DRAINING
        fleet = self.client.fleet
        nodes = self._active()
        gone: List[str] = []
        for n in nodes:
            st = fleet.monitor.state(n)
            if st not in (DEAD, DRAINING):
                continue
            with self._lock:
                leaving = n in self._leaving
            if not leaving and n not in self.preempted_seen:
                self.preempted_seen.add(n)
                note_preemption(st == DRAINING)
                self._record("preempted", st, node=n)
            gone.append(n)
        if gone:
            self.client.set_nodes([n for n in nodes if n not in gone])
        return len(gone)

    # -- the loop -------------------------------------------------------------

    def tick(self) -> float:
        """One control-loop iteration (public for tests); returns the
        smoothed demand sample."""
        self._join_if_ready()
        self._reconcile_departures()
        demand = self.demand.sample()
        nodes = self._active()
        with self._lock:
            n_total = len(nodes) + len(self._pending)
        now = self._clock()
        cooled = (self._last_decision is None
                  or now - self._last_decision >= self.cooldown_s)
        if demand > self.up:
            self._above += 1
            self._below = 0
        elif demand < self.down:
            self._below += 1
            self._above = 0
        else:
            self._above = self._below = 0
        if n_total < self.min_nodes:
            # below the floor (preemption took us under): replace
            # immediately, cooldown does not apply to the floor
            for _ in range(self.min_nodes - n_total):
                self._launch("floor")
        elif (self._above >= self.up_ticks and cooled
                and n_total < self.max_nodes):
            self._above = 0
            self._launch("demand")
        elif (self._below >= self.down_ticks and cooled
                and len(nodes) > self.min_nodes):
            self._below = 0
            self._scale_down("idle")
        return demand

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"gsky-elastic-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("elastic %s: tick failed", self.name)

    # -- reporting ------------------------------------------------------------

    def node_counts(self) -> Dict[str, int]:
        with self._lock:
            pending, leaving = len(self._pending), len(self._leaving)
        return {"active": len(self._active()),
                "pending": pending, "leaving": leaving}

    def stats(self) -> Dict:
        with self._lock:
            decisions = list(self.decisions[-32:])
            pending = sorted(self._pending)
            leaving = sorted(self._leaving)
        return {
            "nodes": self._active(),
            "pending": pending,
            "leaving": leaving,
            "min": self.min_nodes, "max": self.max_nodes,
            "demand": {
                "smoothed": round(self.demand.smoothed, 4)
                if self.demand.smoothed is not None else None,
                "raw": round(self.demand.last_raw, 4)
                if self.demand.last_raw is not None else None,
                "parts": dict(self.demand.last_parts),
                "up": self.up, "down": self.down},
            "decisions": decisions,
        }
