"""Fleet membership + heartbeat health with phi-accrual suspicion.

Every worker node gets a :class:`NodeHealth` record fed by heartbeats
(successful RPCs, periodic ``worker_info`` probes) and failure reports
(transport errors, breaker trips).  Instead of a binary alive/dead
timeout, suspicion is *accrued*: phi grows continuously with the time
since the last heartbeat, scaled by the node's own observed heartbeat
cadence (the phi-accrual failure detector of Hayashibara et al., as
deployed in Cassandra/Akka).  Two thresholds map phi onto three states:

- ``healthy``   — phi < suspect_phi: full routing weight
- ``suspect``   — suspect_phi <= phi < dead_phi: deprioritised (routed
  only when no healthy candidate remains)
- ``dead``      — phi >= dead_phi (or an explicit report): not routed;
  its ring arc re-routes to the next nodes until it heartbeats again

A fourth, explicit state — ``draining`` — is entered when the node
*says* it is draining (SIGTERM handshake): not routable, but not a
failure either.

The monitor is passive by default (the caller feeds heartbeats from
its real RPC traffic); :meth:`HealthMonitor.start` adds an active
probe thread for idle periods.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

log = logging.getLogger("gsky.fleet.health")

HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"
DRAINING = "draining"

# phi of a node that has NEVER heartbeated: optimistic (routable) so a
# cold fleet can bootstrap, but below dead so a first failure can kill it
_PHI_UNKNOWN = 0.0
_LOG10E = math.log10(math.e)


class NodeHealth:
    """Heartbeat history + explicit reports for one node."""

    __slots__ = ("node", "last_beat", "mean_interval", "beats",
                 "failures", "reported_dead", "draining")

    def __init__(self, node: str):
        self.node = node
        self.last_beat: Optional[float] = None
        # EWMA of inter-heartbeat intervals; seeded by the first probe
        self.mean_interval: Optional[float] = None
        self.beats = 0
        self.failures = 0
        self.reported_dead = False
        self.draining = False


class HealthMonitor:
    """Phi-accrual health over a node set.

    ``probe(node)`` (optional) returns truthy when the node answered —
    used by the active probe loop; heartbeats can equally be fed from
    real traffic via :meth:`record_heartbeat`.
    """

    def __init__(self, nodes: Sequence[str],
                 probe: Optional[Callable[[str], bool]] = None,
                 interval_s: float = 2.0,
                 suspect_phi: float = 3.0, dead_phi: float = 8.0,
                 min_interval_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic):
        self.probe = probe
        self.interval_s = float(interval_s)
        self.suspect_phi = float(suspect_phi)
        self.dead_phi = float(dead_phi)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._nodes: Dict[str, NodeHealth] = {
            n: NodeHealth(n) for n in nodes}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- feeding -------------------------------------------------------------

    def record_heartbeat(self, node: str) -> None:
        now = self._clock()
        with self._lock:
            nh = self._nodes.get(node)
            if nh is None:
                nh = self._nodes[node] = NodeHealth(node)
            if nh.last_beat is not None:
                dt = max(now - nh.last_beat, 1e-6)
                if nh.mean_interval is None:
                    nh.mean_interval = dt
                else:
                    nh.mean_interval += 0.2 * (dt - nh.mean_interval)
            nh.last_beat = now
            nh.beats += 1
            nh.reported_dead = False
            nh.draining = False

    def record_failure(self, node: str, fatal: bool = False) -> None:
        """An explicit failure report (transport error, breaker trip).
        ``fatal=True`` (connection refused, breaker open) marks the node
        dead immediately instead of waiting for phi to accrue."""
        with self._lock:
            nh = self._nodes.get(node)
            if nh is None:
                nh = self._nodes[node] = NodeHealth(node)
            nh.failures += 1
            if fatal:
                nh.reported_dead = True

    def record_draining(self, node: str) -> None:
        with self._lock:
            nh = self._nodes.get(node)
            if nh is not None:
                nh.draining = True

    # -- membership ----------------------------------------------------------

    def ensure(self, nodes: Sequence[str]) -> None:
        """Track ``nodes`` (fresh optimistic records for unknown ones)."""
        with self._lock:
            for n in nodes:
                if n not in self._nodes:
                    self._nodes[n] = NodeHealth(n)

    def forget(self, nodes: Sequence[str]) -> int:
        """Drop departed nodes' records entirely.  Without this, a
        flapping elastic fleet grows one phi tracker per address ever
        seen — the stale-member leak ISSUE 18 closes.  Returns how many
        records were actually removed."""
        removed = 0
        with self._lock:
            for n in nodes:
                if self._nodes.pop(n, None) is not None:
                    removed += 1
        return removed

    def set_nodes(self, nodes: Sequence[str]) -> None:
        """Reconcile the tracked set: add unknown nodes, purge the rest."""
        keep = set(nodes)
        with self._lock:
            for n in list(self._nodes):
                if n not in keep:
                    del self._nodes[n]
            for n in keep:
                if n not in self._nodes:
                    self._nodes[n] = NodeHealth(n)

    # -- reading -------------------------------------------------------------

    def phi(self, node: str, now: Optional[float] = None) -> float:
        """Suspicion level: ``-log10 P(heartbeat gap >= observed gap)``
        under an exponential inter-arrival model — phi 3 means the
        silence is ~1000x the node's typical gap tail."""
        with self._lock:
            nh = self._nodes.get(node)
            if nh is None or nh.last_beat is None:
                return _PHI_UNKNOWN
            mean = max(nh.mean_interval or self.interval_s,
                       self.min_interval_s)
        t = (now if now is not None else self._clock()) - nh.last_beat
        return max(t, 0.0) / mean * _LOG10E

    def state(self, node: str, now: Optional[float] = None) -> str:
        with self._lock:
            nh = self._nodes.get(node)
            if nh is None:
                return DEAD
            if nh.draining:
                return DRAINING
            if nh.reported_dead:
                return DEAD
        p = self.phi(node, now)
        if p >= self.dead_phi:
            return DEAD
        if p >= self.suspect_phi:
            return SUSPECT
        return HEALTHY

    def nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._nodes)

    def routable(self, node: str) -> bool:
        return self.state(node) in (HEALTHY, SUSPECT)

    def healthy(self, node: str) -> bool:
        return self.state(node) == HEALTHY

    def snapshot(self) -> Dict[str, Dict]:
        now = self._clock()
        out: Dict[str, Dict] = {}
        for n in self.nodes():
            with self._lock:
                nh = self._nodes[n]
                beats, fails = nh.beats, nh.failures
            out[n] = {"state": self.state(n, now),
                      "phi": round(self.phi(n, now), 2),
                      "beats": beats, "failures": fails}
        return out

    # -- active probing ------------------------------------------------------

    def start(self) -> None:
        if self.probe is None or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="gsky-fleet-health")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            for n in self.nodes():
                if self._stop.is_set():
                    return
                try:
                    ok = self.probe(n)
                except Exception:
                    ok = False
                if ok == DRAINING:
                    # answered, but only to say goodbye: keep the beat
                    # history warm yet route nothing new at it
                    self.record_heartbeat(n)
                    self.record_draining(n)
                elif ok:
                    self.record_heartbeat(n)
                else:
                    self.record_failure(n)
