"""Fleet fault tolerance: membership + health, consistent-hash
routing with bounded load, hedged dispatch, graceful drain.

See docs/FLEET.md for the full design; the short version:

- :class:`HealthMonitor` accrues phi-suspicion per node from
  heartbeats (real RPC traffic and/or active ``worker_info`` probes)
  and maps it onto healthy / suspect / dead / draining states.
- :class:`HashRing` keys canonical tile keys onto nodes with virtual
  nodes and a deterministic preference walk; ``route()`` adds the
  bounded-load spill.
- :class:`HedgePolicy` + :func:`hedged_call` duplicate stragglers past
  an adaptive p99 delay, within a token-bucket hedge budget.
- :class:`DrainController` + :class:`Draining` implement the SIGTERM
  stop-accepting / finish-in-flight / deregister protocol on both the
  worker node and the OWS server.
- :class:`FleetRouter` composes the above per node set;
  :func:`fleet_stats` aggregates every live router for /debug.
"""

from .drain import DrainController, Draining
from .health import (DEAD, DRAINING, HEALTHY, SUSPECT, HealthMonitor,
                     NodeHealth)
from .hedge import HedgePolicy, hedged_call
from .ring import HashRing
from .router import (FleetRouter, fleet_stats, least_loaded_node,
                     register_router, routers)


def tile_route_key(layer: str, srs: str, bbox, width: int,
                   height: int) -> str:
    """Canonical routing key for a tile/drill task: the same key the
    serving cache uses to identify a rendered tile, minus volatile
    parts (time is deliberately excluded so an animation over one tile
    stays on one shard's warm scene cache)."""
    bb = ",".join(f"{float(v):.6f}" for v in bbox)
    return f"{layer}|{srs}|{bb}|{int(width)}x{int(height)}"


__all__ = [
    "DEAD", "DRAINING", "HEALTHY", "SUSPECT",
    "DrainController", "Draining",
    "FleetRouter", "HashRing", "HealthMonitor", "HedgePolicy",
    "NodeHealth",
    "fleet_stats", "hedged_call", "least_loaded_node",
    "register_router", "routers", "tile_route_key",
]
