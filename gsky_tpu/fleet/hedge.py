"""Hedged dispatch: duplicate a straggling task, take the first result.

The tail-at-scale defence (Dean & Barroso): after waiting an *adaptive*
delay — tracking the observed p99 of recent task latencies — a task
that has not finished is duplicated onto the next node in its ring
preference order, and whichever copy finishes first wins; the loser is
cancelled so its slot frees immediately.  Because the delay tracks the
p99, roughly 1% of tasks hedge under steady state — and a *budget*
bounds it hard: hedges spend from a token pool refilled at
``budget`` tokens per primary dispatch (default 0.1 → hedging can never
add more than ~10% fleet load, no matter how sick the tail gets).

The mechanics are future-agnostic: anything with ``done()``,
``cancel()``, ``result()`` and ``add_done_callback(fn)`` works — gRPC
call futures and ``concurrent.futures.Future`` both qualify.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple


class HedgePolicy:
    """Adaptive hedge delay + token-bucket hedge budget."""

    def __init__(self, percentile: float = 0.99,
                 min_delay_s: float = 0.05, max_delay_s: float = 5.0,
                 initial_delay_s: float = 1.0, budget: float = 0.1,
                 window: int = 256, min_samples: int = 20):
        self.percentile = float(percentile)
        self.min_delay_s = float(min_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.initial_delay_s = float(initial_delay_s)
        self.budget = float(budget)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self._lock = threading.Lock()
        self._lat: List[float] = []
        self._i = 0
        self._n = 0
        # token bucket, capped so an idle hour can't bank a hedge storm
        self._tokens = 1.0
        self._token_cap = max(10.0, 1.0)
        # counters (read by /debug)
        self.primaries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.hedges_denied = 0

    def observe(self, latency_s: float) -> None:
        """Feed one completed-task latency into the rolling window."""
        with self._lock:
            if len(self._lat) < self.window:
                self._lat.append(latency_s)
            else:
                self._lat[self._i] = latency_s
                self._i = (self._i + 1) % self.window
            self._n += 1

    def delay_s(self) -> float:
        """Current hedge delay: the windowed p-th percentile latency,
        clamped; the configured initial delay until enough samples."""
        with self._lock:
            lat = list(self._lat)
        if len(lat) < self.min_samples:
            d = self.initial_delay_s
        else:
            lat.sort()
            d = lat[min(int(len(lat) * self.percentile), len(lat) - 1)]
        return min(max(d, self.min_delay_s), self.max_delay_s)

    def on_primary(self) -> None:
        """A primary dispatch earns ``budget`` hedge tokens."""
        with self._lock:
            self.primaries += 1
            self._tokens = min(self._tokens + self.budget,
                               self._token_cap)

    def try_hedge(self) -> bool:
        """Spend one hedge token; False when the budget is exhausted."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.hedges += 1
                return True
            self.hedges_denied += 1
            return False

    def record_win(self) -> None:
        with self._lock:
            self.hedge_wins += 1

    def stats(self) -> dict:
        with self._lock:
            return {"primaries": self.primaries, "hedges": self.hedges,
                    "hedge_wins": self.hedge_wins,
                    "hedges_denied": self.hedges_denied,
                    "budget": self.budget,
                    "tokens": round(self._tokens, 2),
                    "window": len(self._lat)}
        # delay_s() takes the lock itself; callers add it separately


def hedged_call(primary: Callable[[], object],
                hedge: Optional[Callable[[], object]],
                delay_s: float,
                timeout_s: float,
                on_hedge_cancelled: Optional[Callable[[], None]] = None,
                ) -> Tuple[object, bool]:
    """Run ``primary()`` (returns a future); if it has not completed
    after ``delay_s``, launch ``hedge()`` and return whichever future
    finishes first — ``(result, hedge_won)`` — cancelling the loser.

    ``hedge`` is only invoked past the delay (never eagerly), so a
    fast primary costs exactly one dispatch.  ``on_hedge_cancelled``
    fires after the losing hedge is cancelled, letting the caller free
    whatever permit the hedge dispatch consumed.  If the *winner*
    failed, the other future's result is taken when available; both
    failing raises the primary's error.
    """
    done = threading.Event()
    fut1 = primary()
    fut1.add_done_callback(lambda f: done.set())
    if not done.wait(delay_s) and hedge is not None:
        fut2 = None
        try:
            fut2 = hedge()
        except Exception:
            fut2 = None          # hedge dispatch itself failed: ignore
        if fut2 is not None:
            fut2.add_done_callback(lambda f: done.set())
            t_end = time.monotonic() + max(timeout_s, 0.0)
            winner = None
            while winner is None:
                if fut1.done():
                    winner, loser, hedge_won = fut1, fut2, False
                elif fut2.done():
                    winner, loser, hedge_won = fut2, fut1, True
                elif not done.wait(max(t_end - time.monotonic(), 0.01)):
                    winner, loser, hedge_won = fut1, fut2, False
                done.clear()
            # a winner that ERRORED forfeits to a loser that can still
            # answer (or already has)
            try:
                res = winner.result()
            except Exception:
                try:
                    res = loser.result(timeout=max(
                        t_end - time.monotonic(), 0.01))
                    hedge_won = not hedge_won
                    winner, loser = loser, winner
                except Exception:
                    loser.cancel()
                    if loser is fut2 and on_hedge_cancelled is not None:
                        on_hedge_cancelled()
                    raise
            loser.cancel()
            if loser is fut2 and on_hedge_cancelled is not None:
                on_hedge_cancelled()
            return res, hedge_won
    return fut1.result(), False
