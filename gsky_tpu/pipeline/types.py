"""Request/granule types for the pipelines.

Mirrors the reference's `processor/tile_types.go` (ConfigPayLoad,
GeoTileRequest, GeoTileGranule) and `drill_types.go` — flattened into the
fields the TPU pipeline actually needs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..geo.crs import CRS, EPSG3857
from ..geo.transform import BBox, GeoTransform
from ..ops.expr import BandExpressions, parse_band_expressions


@dataclass
class MaskSpec:
    """A quality/cloud mask band (`utils.Mask`, `utils/config.go:70-80`)."""

    id: str                               # namespace of the mask band
    value: str = ""                       # binary mask string
    bit_tests: List[str] = field(default_factory=list)
    data_source: str = ""                 # other collection, if any
    inclusive: bool = False               # mask selects KEPT pixels instead


@dataclass
class AxisSelector:
    """Selection on a non-spatial axis (WCS subset / WMS dim_*):
    either a value range or explicit indices (`utils/wcs.go:228-510`
    AxisParam + AxisIdxSelector)."""

    name: str
    start: Optional[float] = None
    end: Optional[float] = None
    in_values: Optional[List[float]] = None
    idx_start: Optional[int] = None
    idx_end: Optional[int] = None
    idx_step: int = 1
    order: int = 0        # output ordering
    aggregate: int = 1    # 1 = aggregate over axis (mosaic), 0 = expand


@dataclass
class GeoTileRequest:
    """One tile render request (GetMap tile / WCS sub-tile)."""

    collection: str                       # MAS gpath
    bands: Sequence[str]                  # rgb_products entries
    bbox: BBox
    crs: CRS = EPSG3857
    width: int = 256
    height: int = 256
    start_time: Optional[float] = None    # unix seconds
    end_time: Optional[float] = None
    axes: List[AxisSelector] = field(default_factory=list)
    mask: Optional[MaskSpec] = None
    resample: str = "near"                # near | bilinear | cubic
    nodata_out: float = float("nan")
    overview_level: int = -1              # -1 = auto
    query_limit: int = 0
    polygon_segments: int = 2
    metrics: Optional[object] = None
    # P2(b) index-query subdivision (`tile_indexer.go:201-258`): when the
    # request is coarser than index_res_limit (degrees/pixel) and the
    # layer extent is known, the MAS query splits into index tiles of
    # 256*index_tile_{x,y}_size pixels each
    spatial_extent: Optional[Tuple[float, float, float, float]] = None
    index_tile_x_size: float = 0.0
    index_tile_y_size: float = 0.0
    index_res_limit: float = 0.0
    # P2(c) per-granule dst sub-tiling on the worker RPC path
    # (`tile_grpc.go:143-198`): <=1.0 means a fraction of the dst tile,
    # >1 an absolute pixel bound; 0 disables
    grpc_tile_x_size: float = 0.0
    grpc_tile_y_size: float = 0.0

    _exprs: Optional[BandExpressions] = None

    @property
    def band_exprs(self) -> BandExpressions:
        if self._exprs is None:
            object.__setattr__(self, "_exprs",
                               parse_band_expressions(list(self.bands)))
        return self._exprs

    def dst_gt(self) -> GeoTransform:
        return GeoTransform.from_bbox(self.bbox, self.width, self.height)


@dataclass
class Granule:
    """One unit of warp work: (file, variable/band, axis combination) —
    `GeoTileGranule` (`tile_types.go:60-90`) without the channel plumbing."""

    path: str
    ds_name: str
    namespace: str                        # output namespace (+axis suffix)
    base_namespace: str                   # the MAS namespace it came from
    band: int                             # 1-based band / time index + 1
    time_index: Optional[int]             # NetCDF time index
    timestamp: float
    srs: str
    geo_transform: List[float]
    nodata: float
    array_type: str = "Float32"
    is_netcdf: bool = False
    var_name: str = ""
    # curvilinear products: crawler geo_loc record (x_var/y_var 2-D
    # geolocation arrays + offsets/steps) — drives the geolocation-array
    # warp path instead of the affine geo_transform
    geo_loc: Optional[Dict] = None
    # dataset footprint WKT in the file's SRS (MAS polygon column) —
    # lets the RPC fan-out skip sub-tiles a granule can't touch
    polygon: str = ""


@dataclass
class TileResult:
    """Per-namespace float32 canvases + validity masks."""

    data: Dict[str, np.ndarray]           # namespace -> (H, W) float32
    valid: Dict[str, np.ndarray]          # namespace -> (H, W) bool
    namespaces: List[str]                 # output order
    granule_count: int = 0
    file_count: int = 0


@dataclass
class GeoDrillRequest:
    """WPS polygon drill request (`drill_types.go`)."""

    collection: str
    bands: Sequence[str]
    geometry_wkt: str                     # in EPSG:4326 (GeoJSON input)
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    clip_lower: float = -3.0e38
    clip_upper: float = 3.0e38
    deciles: int = 0
    pixel_count: bool = False
    band_strides: int = 1
    approx: bool = True                   # use crawler stats fast path
    # VRT granules (`drill_indexer.go:318-346`, `vrt_manager.go`):
    # vrt_url names the template, vrt_xml is its text; rendered
    # per-granule with {Data, Masks, RasterX/YSize} context
    vrt_url: str = ""
    vrt_xml: str = ""
    mask_namespaces: Sequence[str] = ()   # namespaces feeding .Masks
    # large-polygon tiling (`drill_indexer.go:115-137` +
    # getTiledGeometries): the polygon splits into index tiles of this
    # size in degrees; 0 disables
    index_tile_x_size: float = 0.0
    index_tile_y_size: float = 0.0

    _exprs: Optional[BandExpressions] = None

    @property
    def band_exprs(self) -> BandExpressions:
        if self._exprs is None:
            object.__setattr__(self, "_exprs",
                               parse_band_expressions(list(self.bands)))
        return self._exprs


@dataclass
class DrillResult:
    """Per-date aggregated statistics: rows indexed by timestamp."""

    dates: List[float]                                  # unix, sorted
    values: Dict[str, List[float]]                      # namespace -> series
    counts: Dict[str, List[int]]
    raw_namespaces: List[str] = field(default_factory=list)
