"""Stage-pipelined WCS export engine: plan once, overlap everything.

Large GetCoverage exports used to fan out one `asyncio.to_thread` per
output tile, and each tile ran the whole chain serially — its own MAS
index query, its own granule decode, upload, warp and block encode.
Neighbouring tiles re-asked the index the same question and re-decoded
the granule windows they share, and nothing overlapped: while a tile's
block compressed on host, the device idled.

This engine restructures the export the way arXiv:2506.06235 structures
cloud->GPU EO ingestion (bounded staged pipeline, decode under compute)
and arXiv:1909.07190 structures overlapped tiling (plan footprints
jointly, fetch shared inputs once):

* **Planner** — ONE `TilePipeline.index` call over the full export bbox
  (instead of one per tile); granules are assigned to output tiles by
  footprint intersection, so the per-tile render sees exactly the
  granules the per-tile query would have returned (over-inclusion is
  harmless: a granule with no pixels in a tile contributes no valid
  taps).  Each distinct (path, band, var, time) source is decoded ONCE
  for the whole export — via the device scene cache when cacheable,
  via one memoised union window otherwise — no matter how many tiles
  it spans.

* **Three bounded stages** — a decode thread pool warms source scenes
  for tile i+1 while the warp stage (single thread: the device stream
  is one queue) renders tile i and the encode pool compresses/writes
  tile i-1.  Stages connect through bounded queues (depth
  ``GSKY_EXPORT_QUEUE_DEPTH``), so a slow writer backpressures decode
  instead of ballooning RAM.  Warp outputs are pushed device->host with
  `copy_to_host_async` (the `executor._prefetch` discipline) before
  they enter the encode queue, so the pull overlaps the next tile's
  warp.

* **Observability** — per-stage busy seconds, queue high-water marks
  and dedup counts come back as a stats dict; the OWS server folds them
  into `server.metrics.MetricsLogger` and `/debug` serves them under
  ``export_pipeline``.

Escape hatch: ``GSKY_EXPORT_PIPELINE=0`` restores the per-tile serial
path (read per request, so A/B benchmarking needs no restart).

Knobs: ``GSKY_EXPORT_DECODE_WORKERS`` (default 4),
``GSKY_EXPORT_ENCODE_WORKERS`` (default 4),
``GSKY_EXPORT_QUEUE_DEPTH`` (default 4).
"""

from __future__ import annotations

import concurrent.futures as cf
import contextvars
import dataclasses
import logging
import os
import queue
import re
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..geo.crs import parse_crs
from ..geo.transform import BBox, transform_bbox
from ..obs import span as obs_span
from ..resilience import check_partial
from .decode import decode_window
from .executor import _prefetch
from .tile import _empty_result, evaluate_expressions, ns_prio
from .types import Granule

log = logging.getLogger("gsky.export")

_DONE = object()      # end-of-stream sentinel on the stage queues


def pipeline_enabled() -> bool:
    """GSKY_EXPORT_PIPELINE gate, read per request (default on) so a
    bench can A/B the overlap without restarting the server."""
    return os.environ.get("GSKY_EXPORT_PIPELINE", "1") != "0"


def _env_int(name: str, default: int, lo: int = 1, hi: int = 64) -> int:
    try:
        return max(lo, min(hi, int(os.environ.get(name, default))))
    except ValueError:
        return default


_NUM = re.compile(r"[-+]?[0-9]+(?:\.[0-9]*)?(?:[eE][-+]?[0-9]+)?")


def _wkt_bounds(wkt: str) -> Optional[BBox]:
    """Coordinate bounds of a WKT geometry — footprint enough for tile
    assignment without a geometry library.  None when unparseable."""
    if not wkt:
        return None
    nums = [float(m.group()) for m in _NUM.finditer(wkt)]
    if len(nums) < 4 or len(nums) % 2:
        return None
    xs, ys = nums[0::2], nums[1::2]
    return BBox(min(xs), min(ys), max(xs), max(ys))


def _scene_key(g: Granule) -> tuple:
    # the scene cache's identity (sans level): one decode per source
    return (g.path, g.band, g.var_name, g.time_index)


class ExportPipeline:
    """One WCS GetCoverage export: plan, then run the staged render.

    Output goes either to ``writer`` (a `GeoTIFFWriter`, streaming
    exports) or into the caller's ``out``/``valid`` whole-coverage
    arrays (in-RAM exports) — the same two sinks the serial per-tile
    path uses, block-for-block identical.
    """

    def __init__(self, pipe, base_req, tiles, ns_names: Sequence[str],
                 bbox: BBox, width: int, height: int,
                 nodata: float = -9999.0, writer=None,
                 out: Optional[Dict[str, np.ndarray]] = None,
                 valid: Optional[Dict[str, np.ndarray]] = None):
        self.pipe = pipe
        self.base_req = base_req
        self.tiles = list(tiles)      # [(bbox, ox, oy, tw, th), ...]
        self.ns_names = list(ns_names)
        self.bbox = bbox
        self.width = width
        self.height = height
        self.nodata = nodata
        self.writer = writer
        self.out = out
        self.valid = valid
        self.decode_workers = _env_int("GSKY_EXPORT_DECODE_WORKERS", 4)
        self.encode_workers = _env_int("GSKY_EXPORT_ENCODE_WORKERS", 4)
        self.queue_depth = _env_int("GSKY_EXPORT_QUEUE_DEPTH", 4)
        self._stop = threading.Event()
        self._errors: List[BaseException] = []
        self._err_lock = threading.Lock()
        # scene key -> DeviceScene | None, filled by the decode stage
        self._warm: Dict[tuple, object] = {}
        # scene key -> DecodedWindow | None: the ONE union-window decode
        # for sources the scene cache can't hold
        self._memo: Dict[tuple, object] = {}
        self._memo_lock = threading.Lock()
        # scene keys whose memo decode RAISED (vs. merely not
        # intersecting): feeds the partial-failure degradation policy
        self._memo_failed: set = set()
        # tile index -> co-submission batch id (filled by _plan)
        self._batch_of: List[int] = list(range(len(self.tiles)))
        self.stats: Dict[str, object] = {}

    # -- control -------------------------------------------------------------

    def cancel(self) -> None:
        """Stop between tiles; in-flight stage work finishes, queued
        work is dropped.  The caller owns sink cleanup (the OWS handler
        closes + unlinks the partial stream file, as it did for the
        serial path)."""
        self._stop.set()

    def _fail(self, e: BaseException) -> None:
        with self._err_lock:
            self._errors.append(e)
        self._stop.set()

    # -- bounded-queue helpers (never deadlock a cancelled run) --------------

    def _put(self, q: queue.Queue, item) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _take(self, q: queue.Queue):
        while not self._stop.is_set():
            try:
                return q.get(timeout=0.05)
            except queue.Empty:
                continue
        return _DONE

    # -- planner -------------------------------------------------------------

    def _plan(self) -> List[List[Granule]]:
        """ONE index query over the full export bbox, then per-tile
        granule assignment by footprint intersection in the dst CRS."""
        full_req = dataclasses.replace(
            self.base_req, bbox=self.bbox, width=self.width,
            height=self.height)
        granules = self.pipe.index(full_req)
        dst_crs = self.base_req.crs
        bounds: List[Optional[BBox]] = []
        for g in granules:
            bb = _wkt_bounds(g.polygon)
            if bb is not None and g.srs:
                try:
                    src = parse_crs(g.srs)
                    bb = transform_bbox(bb, src, dst_crs)
                    # buffer against reprojection edge error: a granule
                    # the per-tile MAS query would return must never be
                    # dropped here (extra inclusions are free)
                    bb = bb.buffer(0.005 * max(bb.width, bb.height))
                except Exception:
                    bb = None
            else:
                bb = None      # no footprint: ride on every tile
            bounds.append(bb)
        plan = []
        for (tb, _, _, _, _) in self.tiles:
            plan.append([g for g, bb in zip(granules, bounds)
                         if bb is None or bb.intersects(tb)])
        self.stats["granules"] = len(granules)
        self.stats["granule_tile_refs"] = sum(len(gs) for gs in plan)
        self._batch_of = self._plan_batches(plan)
        return plan

    def _plan_batches(self, plan: List[List[Granule]]) -> List[int]:
        """Superblock planning over the tile assignment: consecutive
        tiles that share at least one source batch together (id per
        tile), so the warp stage can CO-SUBMIT them and the wave
        scheduler hands the dataflow autoplanner neighbouring windows
        to merge into shared-halo superblock gathers.  With the
        planner or waves off every tile is its own batch and the warp
        stage stays strictly serial — today's behaviour."""
        n = len(self.tiles)
        batch = [0] * n
        try:
            from . import autoplan
            from .waves import waves_enabled
            if not (autoplan.plan_enabled() and waves_enabled()):
                return list(range(n))
        except Exception:   # planner unavailable: serial warp
            return list(range(n))
        cap = _env_int("GSKY_EXPORT_COSUBMIT", 4, lo=1, hi=16)
        keys = [set(map(_scene_key, gs)) for gs in plan]
        bid, size = 0, 1
        for i in range(1, n):
            if size < cap and keys[i] & keys[i - 1]:
                batch[i] = bid
                size += 1
            else:
                bid += 1
                batch[i] = bid
                size = 1
        return batch

    # -- stage 1: decode / warm ----------------------------------------------

    def _warm_one(self, g: Granule) -> None:
        key = _scene_key(g)
        ex = self.pipe.executor
        s = ex.warm_scene(g, self._full_gt(), self.base_req.crs,
                          self.height, self.width)
        self._warm[key] = s
        if s is None and not g.geo_loc:
            # uncacheable: decode the ONE union window over the whole
            # export extent now, so no tile ever re-reads this source
            self._memo_window(g)

    def _full_gt(self):
        from ..geo.transform import GeoTransform
        return GeoTransform.from_bbox(self.bbox, self.width, self.height)

    def _memo_window(self, g: Granule):
        key = _scene_key(g)
        with self._memo_lock:
            if key in self._memo:
                return self._memo[key]
        failed = False
        try:
            w = decode_window(g, self.bbox, self.base_req.crs,
                              self.base_req.resample,
                              dst_hw=(self.height, self.width))
        except Exception:
            w = None
            failed = True
        with self._memo_lock:
            self._memo.setdefault(key, w)
            if failed:
                self._memo_failed.add(key)
            return self._memo[key]

    def _decode_stage(self, plan: List[List[Granule]],
                      q_warp: queue.Queue) -> None:
        """Walk tiles in output order, warming each tile's not-yet-seen
        sources through a small thread pool, and feed the warp queue.
        Runs ahead of the warp stage only as far as the bounded queue
        allows — that bound IS the pipeline's lookahead."""
        busy = 0.0
        seen: set = set()
        try:
            with cf.ThreadPoolExecutor(
                    self.decode_workers,
                    thread_name_prefix="gsky-export-decode") as pool:
                for tile, gs in zip(self.tiles, plan):
                    if self._stop.is_set():
                        return
                    t0 = time.monotonic()
                    fresh = []
                    for g in gs:
                        k = _scene_key(g)
                        if k not in seen:
                            seen.add(k)
                            fresh.append(g)
                    if fresh:
                        list(pool.map(self._warm_one, fresh))
                    # a tile with any uncacheable source falls back to
                    # the union-window path, which needs windows for ALL
                    # its granules — memoised, so shared windows still
                    # decode once across tiles
                    if any(self._warm.get(_scene_key(g)) is None
                           and not g.geo_loc for g in gs):
                        list(pool.map(self._memo_window,
                                      [g for g in gs if not g.geo_loc]))
                    busy += time.monotonic() - t0
                    self.stats["warp_queue_max"] = max(
                        self.stats.get("warp_queue_max", 0),
                        q_warp.qsize() + 1)
                    if not self._put(q_warp, (tile, gs)):
                        return
            self._put(q_warp, _DONE)
        except BaseException as e:     # noqa: BLE001 - must surface
            self._fail(e)
        finally:
            self.stats["decode_s"] = round(
                self.stats.get("decode_s", 0.0) + busy, 6)
            self.stats["scenes_warmed"] = len(seen)
            self.stats["scenes_uncacheable"] = sum(
                1 for v in self._warm.values() if v is None)
            self.stats["windows_decoded"] = len(self._memo)

    # -- stage 2: warp (runs on the caller's thread) -------------------------

    def _render_tile(self, req, gs: List[Granule]):
        """Render one tile from pre-warmed sources — the engine-side
        twin of `TilePipeline._render_fused`, with the decode fallback
        replaced by the export-wide memo windows."""
        exprs = req.band_exprs
        H, W = req.height, req.width
        if not gs:
            return _empty_result(exprs, H, W)
        if self.pipe.remote is not None or req.mask is not None:
            # modular path (mask bands / worker fan-out): the pipeline
            # still gets plan-once indexing and stage overlap; window
            # dedup is the scene cache's business on this route
            return self.pipe.render(req, gs)
        ex = self.pipe.executor
        names, ns_ids, prio = ns_prio(gs)
        sc = ex.warp_mosaic_scenes(gs, ns_ids, prio, req.dst_gt(),
                                   req.crs, H, W, len(names),
                                   req.resample)
        if sc is None:
            ws = [self._memo_window(g) if not g.geo_loc else None
                  for g in gs]
            # this runs on the warp stage (the request's to_thread
            # context), so degradation marks reach the OWS handler
            with self._memo_lock:
                failed = sum(1 for g in gs
                             if _scene_key(g) in self._memo_failed)
            check_partial(failed, len(gs), "decode")
            live = [(g, w) for g, w in zip(gs, ws) if w is not None]
            if not live:
                return _empty_result(exprs, H, W)
            names, ns_ids, prio = ns_prio([g for g, _ in live])
            sc = ex.warp_mosaic([w for _, w in live], ns_ids, prio,
                                req.dst_gt(), req.crs, H, W,
                                len(names), req.resample)
        canv, vals = sc
        data_env = {n: canv[i] for i, n in enumerate(names)}
        valid_env = {n: vals[i] for i, n in enumerate(names)}
        return evaluate_expressions(
            exprs, data_env, valid_env, H, W,
            granule_count=len(gs),
            file_count=len({g.path for g in gs}))

    def _flush_batch(self, batch, q_encode, pool) -> bool:
        """Render one co-submission batch and hand the results to the
        encoders in output order.  A multi-tile batch renders its tiles
        CONCURRENTLY — each on its own context copy — so their wave
        entries land in the same scheduler tick and the autoplanner can
        superblock their shared gather windows; a single-tile batch is
        the serial path unchanged."""
        if not batch:
            return True
        reqs = [dataclasses.replace(self.base_req, bbox=tb, width=tw,
                                    height=th)
                for (tb, _ox, _oy, tw, th), _gs in batch]
        if pool is not None and len(batch) > 1:
            futs = [pool.submit(contextvars.copy_context().run,
                                self._render_tile, rq, gs)
                    for rq, (_t, gs) in zip(reqs, batch)]
            results = [f.result() for f in futs]
            self.stats["plan_batches"] = \
                self.stats.get("plan_batches", 0) + 1
            self.stats["plan_batched_tiles"] = \
                self.stats.get("plan_batched_tiles", 0) + len(batch)
        else:
            results = [self._render_tile(rq, gs)
                       for rq, (_t, gs) in zip(reqs, batch)]
        for ((_tb, ox, oy, tw, th), _gs), res in zip(batch, results):
            # start every device->host copy NOW: the encode stage's
            # np.asarray then completes an in-flight transfer while
            # this thread warps the next tile
            for n in res.namespaces:
                for env in (res.data, res.valid):
                    v = env.get(n)
                    if hasattr(v, "copy_to_host_async"):
                        _prefetch(v)
            self.stats["encode_queue_max"] = max(
                self.stats.get("encode_queue_max", 0),
                q_encode.qsize() + 1)
            if not self._put(q_encode, ((ox, oy, tw, th), res)):
                return False
        return True

    def _warp_stage(self, q_warp: queue.Queue,
                    q_encode: queue.Queue) -> None:
        busy = 0.0
        from collections import Counter
        co = max(Counter(self._batch_of).values(), default=1)
        pool = cf.ThreadPoolExecutor(
            co, thread_name_prefix="gsky-export-warp") if co > 1 \
            else None
        try:
            batch: List = []
            bid = None
            i = 0
            while True:
                item = self._take(q_warp)
                if item is _DONE:
                    break
                b = self._batch_of[i] if i < len(self._batch_of) else i
                i += 1
                t0 = time.monotonic()
                if bid is not None and b != bid:
                    ok = self._flush_batch(batch, q_encode, pool)
                    batch = []
                    if not ok:
                        return
                bid = b
                batch.append(item)
                busy += time.monotonic() - t0
            t0 = time.monotonic()
            self._flush_batch(batch, q_encode, pool)
            busy += time.monotonic() - t0
        except BaseException as e:     # noqa: BLE001
            self._fail(e)
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
            self.stats["warp_s"] = round(busy, 6)

    # -- stage 3: encode / write ---------------------------------------------

    def _encode_one(self, ox: int, oy: int, tw: int, th: int, res) -> None:
        if self.writer is not None:
            block = np.full((len(self.ns_names), th, tw), self.nodata,
                            np.float32)
            for i, n in enumerate(self.ns_names):
                if n in res.data:
                    d = np.asarray(res.data[n])
                    v = np.asarray(res.valid[n])
                    block[i] = np.where(v, d, self.nodata)
            self.writer.write_region(ox, oy, block)
            return
        for n in self.ns_names:
            if n in res.data:
                self.out[n][oy:oy + th, ox:ox + tw] = \
                    np.asarray(res.data[n])
                self.valid[n][oy:oy + th, ox:ox + tw] = \
                    np.asarray(res.valid[n])

    def _encode_stage(self, q_encode: queue.Queue, busy: List[float]
                      ) -> None:
        try:
            while True:
                item = self._take(q_encode)
                if item is _DONE:
                    return
                (ox, oy, tw, th), res = item
                t0 = time.monotonic()
                self._encode_one(ox, oy, tw, th, res)
                busy[0] += time.monotonic() - t0
        except BaseException as e:     # noqa: BLE001
            self._fail(e)

    # -- driver --------------------------------------------------------------

    def run(self) -> Dict:
        """Execute the export; returns the stats dict.  Raises the first
        stage error (the OWS handler's existing cleanup path then closes
        and unlinks any partial stream file)."""
        t0 = time.monotonic()
        self.stats = {"tiles": len(self.tiles), "index_queries": 1,
                      "decode_workers": self.decode_workers,
                      "encode_workers": self.encode_workers,
                      "queue_depth": self.queue_depth}
        # request-scoped cancellation: a client disconnect (or deadline
        # expiry) fires the token, which trips the engine's existing
        # stop flag — every stage loop already checks it, so decode /
        # warp / encode threads drain within one queue hop instead of
        # finishing an export nobody will download
        from ..resilience import current_token
        tok = current_token()
        unhook = tok.on_cancel(self.cancel) if tok else None
        with obs_span("export.plan") as psp:
            plan = self._plan()
            psp.set(tiles=len(self.tiles),
                    granules=self.stats.get("granules", 0))
        q_warp: queue.Queue = queue.Queue(self.queue_depth)
        q_encode: queue.Queue = queue.Queue(self.queue_depth)

        def _traced(span_name, fn, *args):
            # stage threads start from an empty contextvars.Context;
            # re-bind this request's context (trace included) and wrap
            # the stage's lifetime in one span.  One Context copy per
            # thread — a Context cannot be entered concurrently.
            ctx = contextvars.copy_context()

            def tgt():
                def body():
                    with obs_span(span_name):
                        fn(*args)
                ctx.run(body)
            return tgt

        decode_t = threading.Thread(
            target=_traced("export.decode_stage",
                           self._decode_stage, plan, q_warp),
            name="gsky-export-plan", daemon=True)
        enc_busy = [[0.0] for _ in range(self.encode_workers)]
        encoders = [threading.Thread(
            target=_traced("export.encode_stage",
                           self._encode_stage, q_encode, enc_busy[i]),
            name=f"gsky-export-encode-{i}", daemon=True)
            for i in range(self.encode_workers)]
        decode_t.start()
        for t in encoders:
            t.start()
        try:
            with obs_span("export.warp_stage"):
                self._warp_stage(q_warp, q_encode)
        finally:
            # wake every stage: workers blocked on a bounded queue must
            # observe either a sentinel or the stop flag
            for _ in encoders:
                self._put(q_encode, _DONE)
            decode_t.join()
            for t in encoders:
                t.join()
            if unhook is not None:
                unhook()
        with self._err_lock:
            if self._errors:
                raise self._errors[0]
        if tok is not None:
            tok.check("export")     # raises RequestCancelled when fired
        if self._stop.is_set():
            raise RuntimeError("export cancelled")
        self.stats["encode_s"] = round(sum(b[0] for b in enc_busy), 6)
        self.stats["wall_s"] = round(time.monotonic() - t0, 6)
        refs = self.stats.get("granule_tile_refs", 0)
        self.stats["dedup_saved"] = max(
            0, refs - int(self.stats.get("scenes_warmed", 0)))
        # wave engagement: export blocks render through the executor,
        # so under GSKY_WAVES the warp stage's tiles share wave
        # dispatches with concurrent WMS/drill traffic — surface the
        # scheduler's amortisation alongside the export's own numbers
        try:
            from .waves import wave_stats
            wst = wave_stats()
            if wst:
                self.stats["wave_dispatches"] = wst.get("dispatches", 0)
                self.stats["wave_requests"] = wst.get("requests", 0)
        except Exception:  # wave stats are advisory telemetry
            pass
        return self.stats
