from .types import GeoTileRequest, GeoDrillRequest, Granule, MaskSpec
from .tile import TilePipeline
from .drill import DrillPipeline
from .extent import compute_reprojection_extent

__all__ = ["GeoTileRequest", "GeoDrillRequest", "Granule", "MaskSpec",
           "TilePipeline", "DrillPipeline", "compute_reprojection_extent"]
