"""The tile pipeline: index -> decode -> batched TPU warp -> mosaic ->
band expressions.

The reference wires TileIndexer -> GeoRasterGRPC -> RasterMerger as
channel-connected goroutine stages (`processor/tile_pipeline.go:51-146`);
here the same dataflow is a function: the indexer is one MAS query +
granule expansion, the worker fan-out is one batched device dispatch, and
the merger is a vectorised mosaic + jit'd expressions.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..geo.crs import EPSG4326
from ..index.client import MASClient
from ..index.store import fmt_time
from ..ops import mosaic as M
from ..ops.expr import BandExpressions
from ..resilience import check_partial
from .decode import decode_all
from .executor import WarpExecutor, _prefetch, default_executor
from .granule import expand_granules
from .types import GeoTileRequest, Granule, TileResult

log = logging.getLogger("gsky.tile")

_index_pool = None   # module-level fan-out pool (see _index_fanout)


def ns_prio(gs: Sequence[Granule]):
    """(ns_names, ns_ids, prio) for a granule set: namespace slots in
    first-seen order, mosaic priorities newest-first
    (`ops.mosaic.priority_order`).  Shared by the fused tile path and the
    export engine so both dispatch identically for the same granules."""
    ns_names: List[str] = []
    ns_index: Dict[str, int] = {}
    for g in gs:
        if g.namespace not in ns_index:
            ns_index[g.namespace] = len(ns_names)
            ns_names.append(g.namespace)
    ns_ids = [ns_index[g.namespace] for g in gs]
    order = M.priority_order([g.timestamp for g in gs])
    prio = [0.0] * len(gs)
    for rank, i in enumerate(order):
        prio[i] = float(len(gs) - rank)
    return ns_names, ns_ids, prio


class TilePipeline:
    def __init__(self, mas: MASClient, executor: Optional[WarpExecutor] = None,
                 decode_workers: int = 8, remote=None):
        """``remote``: an optional `worker.WorkerClient`; when set, the
        warp stage fans granules out to worker nodes over gRPC
        (`processor/tile_grpc.go`) instead of decoding+warping
        in-process."""
        self.mas = mas
        self.executor = executor or default_executor
        self.decode_workers = decode_workers
        self.remote = remote

    @staticmethod
    def _index_fanout():
        # one MODULE-level pool: the OWS server rebuilds pipelines on
        # config reload, and a per-pipeline pool would strand 8
        # non-daemon threads per discarded instance
        global _index_pool
        if _index_pool is None:
            import concurrent.futures as cf
            _index_pool = cf.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="gsky-index")
        return _index_pool

    # -- indexing ------------------------------------------------------------

    def index(self, req: GeoTileRequest) -> List[Granule]:
        """MAS query + axis intersection (the TileIndexer stage)."""
        exprs = req.band_exprs
        namespaces = list(exprs.var_list)
        if req.mask is not None and req.mask.id \
                and not req.mask.data_source:
            if req.mask.id not in namespaces:
                namespaces.append(req.mask.id)
        kw = dict(srs=req.crs.name(), wkt=req.bbox.to_polygon_wkt(),
                  namespaces=",".join(namespaces),
                  nseg=req.polygon_segments, limit=req.query_limit)
        if req.start_time is not None:
            kw["time"] = fmt_time(req.start_time)
        if req.end_time is not None:
            kw["until"] = fmt_time(req.end_time)
        datasets = self._index_query(req, kw, req.collection)
        granules = expand_granules(datasets, req.start_time, req.end_time,
                                   req.axes)
        # separately indexed mask collection (`tile_indexer.go:265-284`),
        # subdivided under the same P2(b) policy as the data collection
        if req.mask is not None and req.mask.data_source:
            mkw = dict(kw)
            mkw["namespaces"] = req.mask.id
            mds = self._index_query(req, mkw, req.mask.data_source)
            granules += expand_granules(mds, req.start_time, req.end_time,
                                        req.axes)
        return granules

    def _index_query(self, req: GeoTileRequest, kw: Dict,
                     collection: str):
        """One MAS ?intersects, or — for coarse-resolution requests over
        a known layer extent — P2(b) spatial subdivision into concurrent
        index-tile queries (`tile_indexer.go:201-258`): the 256-px
        virtual grid over the clipped bbox splits into index tiles of
        256*index_tile_{x,y}_size pixels, each queried separately, so no
        single index query scans a continent at low zoom."""
        sub = self._index_subdivision(req)
        if sub is None:
            return self.mas.intersects(collection, **kw)
        if not sub:                # clipped bbox empty: nothing to ask
            return []

        def one(wkt4326):
            skw = dict(kw, srs="EPSG:4326", wkt=wkt4326)
            # failures propagate: a MAS outage must surface as an error
            # response, not render as an empty (or partially empty) tile
            return self.mas.intersects(collection, **skw)

        parts = list(self._index_fanout().map(one, sub))
        # a granule spanning several index tiles comes back once per
        # tile; identity-dedup keeps mosaic priorities unique
        seen = set()
        out = []
        for ds in (d for part in parts for d in part):
            k = (ds.file_path, ds.ds_name, ds.namespace)
            if k not in seen:
                seen.add(k)
                out.append(ds)
        return out

    def _index_subdivision(self, req: GeoTileRequest):
        """None = query as one; [] = empty; else sub-bbox WKTs (4326)."""
        if req.index_res_limit <= 0 or req.query_limit > 0 \
                or not req.spatial_extent:
            return None
        from ..geo.transform import BBox as _BBox
        from ..geo.transform import transform_bbox
        try:
            ll = transform_bbox(req.bbox, req.crs, EPSG4326)
        except ValueError:
            return None
        ext = req.spatial_extent
        xmin = max(ll.xmin, ext[0])
        ymin = max(ll.ymin, ext[1])
        xmax = min(ll.xmax, ext[2])
        ymax = min(ll.ymax, ext[3])
        if xmax < xmin or ymax < ymin:
            return []
        res_w = res_h = 256                  # virtual index raster
        xres = (xmax - xmin) / res_w
        yres = (ymax - ymin) / res_h
        if max(xres, yres) <= req.index_res_limit:
            return None
        mx = int(res_w * req.index_tile_x_size)
        my = int(res_h * req.index_tile_y_size)
        mx = mx if mx > 0 else res_w
        my = my if my > 0 else res_h
        if mx >= res_w and my >= res_h:
            return None
        subs = []
        for y in range(0, res_h, my):
            for x in range(0, res_w, mx):
                subs.append(_BBox(
                    xmin + x * xres, ymin + y * yres,
                    min(xmin + (x + mx) * xres, xmax),
                    min(ymin + (y + my) * yres, ymax)).to_polygon_wkt())
        return subs

    # -- full render ---------------------------------------------------------

    def _render_fused(self, req: GeoTileRequest,
                      granules: List[Granule]) -> TileResult:
        """Single-dispatch fast path (no mask band, local executor):
        decode -> fused warp+per-namespace mosaic
        (`ops.warp.warp_scenes_ctrl_scored` over padded windows) ->
        expressions.  Minimises device round trips: one upload set, one
        execution, results stay on device until encode."""
        exprs = req.band_exprs
        H, W = req.height, req.width

        # fastest path: scenes already resident in HBM — zero source upload
        ns_names, ns_ids, prio = ns_prio(granules)
        sc = self.executor.warp_mosaic_scenes(
            granules, ns_ids, prio, req.dst_gt(), req.crs, H, W,
            len(ns_names), req.resample)
        if sc is None:
            errs: List[Exception] = []
            ws = decode_all(granules, req.bbox, req.crs, req.resample,
                            self.decode_workers, dst_hw=(H, W), errors=errs)
            check_partial(len(errs), len(granules), "decode")
            live = [(g, w) for g, w in zip(granules, ws) if w is not None]
            if not live:
                return _empty_result(exprs, H, W)
            ns_names, ns_ids, prio = ns_prio([g for g, _ in live])
            sc = self.executor.warp_mosaic(
                [w for _, w in live], ns_ids, prio, req.dst_gt(), req.crs,
                H, W, len(ns_names), req.resample)
        canv, vals = sc
        data_env = {n: canv[i] for i, n in enumerate(ns_names)}
        valid_env = {n: vals[i] for i, n in enumerate(ns_names)}
        return evaluate_expressions(
            exprs, data_env, valid_env, H, W,
            granule_count=len(granules),
            file_count=len({g.path for g in granules}))

    def _timed_index(self, req: GeoTileRequest,
                     spans: Optional[Dict[str, float]] = None):
        """`index()` with the MAS-query seconds recorded into ``spans``
        (the staged tile path's per-request "index" stage span)."""
        if spans is None:
            return self.index(req)
        t0 = time.perf_counter()
        try:
            return self.index(req)
        finally:
            spans["index_s"] = spans.get("index_s", 0.0) \
                + time.perf_counter() - t0

    def composite_prep(self, req: GeoTileRequest,
                       stats: Optional[Dict[str, int]] = None,
                       spans: Optional[Dict[str, float]] = None):
        """Qualification + ONE index pass for the fused composite path:
        (granules, ns_ids, prio, n_ns) or None.  Split from the dispatch
        half so the staged tile pipeline can run indexing, scene decode
        and device dispatch as separately bounded stages.

        Expression-bearing requests (non-trivial band algebra) return
        the 5-tuple `_expr_prep` form instead — granules stay at
        index 0, so stage consumers are agnostic."""
        if self.remote is not None or req.mask is not None:
            return None
        exprs = req.band_exprs
        if any(ce._ast[0] != "var" for ce in exprs.expressions):
            return self._expr_prep(req, exprs, stats, spans)
        granules = self._timed_index(req, spans)
        if not granules:
            return None
        if stats is not None:
            stats["granules"] = len(granules)
            stats["files"] = len({g.path for g in granules})
        ns_names, ns_ids, prio = ns_prio(granules)
        return granules, ns_ids, prio, len(ns_names)

    def _expr_prep(self, req: GeoTileRequest, exprs: BandExpressions,
                   stats: Optional[Dict[str, int]] = None,
                   spans: Optional[Dict[str, float]] = None):
        """Fused band-algebra qualification (GSKY_EXPR_FUSE): ONE index
        pass, variables resolved to namespaces with the same rules as
        `evaluate_expressions` (exact match, else unique `var#axis`
        candidate), granules mapped to fingerprint SLOT ids.  Returns
        (granules, ns_ids, prio, n_slots, fp) or None — the unfused
        post-warp leg then runs, byte-identically (the GSKY_EXPR_FUSE=0
        escape hatch is this None, unconditionally)."""
        from ..ops.expr import expr_fuse_enabled, fingerprint
        if len(exprs.expressions) != 1:
            return None
        ce = exprs.expressions[0]
        if ce._ast[0] == "var" or not ce.variables:
            return None
        if not expr_fuse_enabled():
            # a render that WOULD have fused rides the post-warp leg;
            # the counter keeps the escape hatch observable
            from ..ops.paged import note_expr_fused
            note_expr_fused("unfused")
            return None
        granules = self._timed_index(req, spans)
        if not granules:
            return None
        if stats is not None:
            stats["granules"] = len(granules)
            stats["files"] = len({g.path for g in granules})
        fp = fingerprint(ce)
        names = {g.namespace for g in granules}
        slot_of: Dict[str, int] = {}
        for i, var in enumerate(fp.slots):
            if var in names:
                slot_of[var] = i
                continue
            cands = [k for k in names if k.split("#")[0] == var]
            if len(cands) == 1:
                slot_of[cands[0]] = i
            # unresolved slot: no granules ever map to it, so it stays
            # all-invalid — exactly the unfused leg's missing-band
            # zeros/invalid output after scale-to-byte
        # granules of unreferenced namespaces are dropped: the output
        # is independent of them, and subset re-ranking preserves each
        # kept namespace's relative priority order (same mosaic winners)
        kept = [g for g in granules if g.namespace in slot_of]
        if not kept:
            return None
        ns_ids = [slot_of[g.namespace] for g in kept]
        order = M.priority_order([g.timestamp for g in kept])
        prio = [0.0] * len(kept)
        for rank, i in enumerate(order):
            prio[i] = float(len(kept) - rank)
        return kept, ns_ids, prio, len(fp.slots), fp

    def animation_prep(self, req: GeoTileRequest,
                       times: Sequence[float],
                       stats: Optional[Dict[str, int]] = None,
                       spans: Optional[Dict[str, float]] = None):
        """ONE index pass for a TIME-range animation: the whole
        sequence is resolved with a single MAS query over
        [min(times), max(times)] and partitioned per frame with the
        same point semantics as a single-timestep request
        (`granule._select_time_indices`: |timestamp - t| < 1s, untimed
        granules in every frame), so frame k's granule set — and hence
        its rendered bytes — matches what a lone GetMap at times[k]
        would have produced.  A frame with no exact match takes the
        nearest available timestep (WMS-T nearest-value semantics).

        Returns a list aligned with ``times`` of `composite_prep`-form
        tuples (granules, ns_ids, prio, n_ns), or None when the
        request doesn't qualify for the fused composite path (mask
        band, remote workers, non-trivial band algebra) — callers then
        render each frame independently."""
        if self.remote is not None or req.mask is not None:
            return None
        exprs = req.band_exprs
        if any(ce._ast[0] != "var" for ce in exprs.expressions):
            return None
        span_req = dataclasses.replace(
            req, start_time=min(times), end_time=max(times) + 1.0)
        granules = self._timed_index(span_req, spans)
        if not granules:
            return None
        if stats is not None:
            stats["granules"] = len(granules)
            stats["files"] = len({g.path for g in granules})
        untimed = [g for g in granules if g.timestamp == 0.0]
        timed = [g for g in granules if g.timestamp != 0.0]
        frames = []
        for t in times:
            fg = [g for g in timed if abs(g.timestamp - t) < 1.0]
            if not fg and timed:
                # nearest-available fallback: consecutive frames
                # between source timesteps resolve to the SAME granule
                # set, which is what lets the autoplanner merge their
                # superblocks and gather shared pages once per sequence
                best = min(abs(g.timestamp - t) for g in timed)
                fg = [g for g in timed if abs(g.timestamp - t) == best]
            fg = fg + untimed
            if not fg:
                frames.append(None)
                continue
            ns_names, ns_ids, prio = ns_prio(fg)
            frames.append((fg, ns_ids, prio, len(ns_names)))
        return frames

    def composite_dispatch(self, req: GeoTileRequest, made,
                           offset: float = 0.0, scale: float = 0.0,
                           clip: float = 0.0, colour_scale: int = 0,
                           auto: bool = True):
        if len(made) == 5:      # `_expr_prep` form: fused band algebra
            granules, ns_ids, prio, n_slots, fp = made
            out = self.executor.render_expr_byte(
                granules, ns_ids, prio, req.dst_gt(), req.crs,
                req.height, req.width, n_slots, fp, req.resample,
                offset, scale, clip, colour_scale, auto)
            if out is None:
                from ..ops.paged import note_expr_fused
                note_expr_fused("unfused")
            return out
        granules, ns_ids, prio, n_ns = made
        return self.executor.render_byte_scenes(
            granules, ns_ids, prio, req.dst_gt(), req.crs,
            req.height, req.width, n_ns, req.resample,
            offset, scale, clip, colour_scale, auto)

    def render_composite_byte(self, req: GeoTileRequest,
                              offset: float = 0.0, scale: float = 0.0,
                              clip: float = 0.0, colour_scale: int = 0,
                              auto: bool = True,
                              stats: Optional[Dict[str, int]] = None):
        """One-dispatch GetMap: index -> fused scene warp + mosaic +
        first-valid composite + byte scaling on device; returns the
        PNG-ready uint8 (H, W) jax array (255 = nodata), or None when
        the request doesn't qualify for the fused path (mask band,
        remote workers, non-trivial band expressions, uncacheable
        scenes) — callers then use `process()` + `ops.scale`.
        """
        made = self.composite_prep(req, stats)
        if made is None:
            return None
        return self.composite_dispatch(req, made, offset, scale, clip,
                                       colour_scale, auto)

    def _bands_prep(self, req: GeoTileRequest, n_bands: int = 0,
                    stats: Optional[Dict[str, int]] = None,
                    spans: Optional[Dict[str, float]] = None):
        """Shared index + namespace/selection resolution for the fused
        multi-band paths: (granules, ns_index, out_sel) or None.  ONE
        index pass feeds both rungs of the RGB ladder."""
        if self.remote is not None or req.mask is not None:
            return None
        exprs = req.band_exprs
        if not exprs.expressions or \
                (n_bands and len(exprs.expressions) != n_bands) or \
                any(ce._ast[0] != "var" for ce in exprs.expressions):
            return None
        granules = self._timed_index(req, spans)
        if not granules:
            return None
        if stats is not None:
            stats["granules"] = len(granules)
            stats["files"] = len({g.path for g in granules})
        ns_index: Dict[str, int] = {}
        for g in granules:
            if g.namespace not in ns_index:
                ns_index[g.namespace] = len(ns_index)
        out_sel = []
        for ce in exprs.expressions:
            var = ce.variables[0]
            if var in ns_index:
                out_sel.append(ns_index[var])
                continue
            cands = [k for k in ns_index if k.split("#")[0] == var]
            if len(cands) != 1:
                return None
            out_sel.append(ns_index[cands[0]])
        return granules, ns_index, out_sel

    def _bands_dispatch(self, req: GeoTileRequest, granules, ns_index,
                        out_sel, offset, scale, clip, colour_scale,
                        auto):
        ns_ids = [ns_index[g.namespace] for g in granules]
        order = M.priority_order([g.timestamp for g in granules])
        prio = [0.0] * len(granules)
        for rank, i in enumerate(order):
            prio[i] = float(len(granules) - rank)
        return self.executor.render_bands_byte(
            granules, ns_ids, prio, req.dst_gt(), req.crs,
            req.height, req.width, len(ns_index), out_sel, req.resample,
            offset, scale, clip, colour_scale, auto)

    def render_bands_byte(self, req: GeoTileRequest,
                          offset: float = 0.0, scale: float = 0.0,
                          clip: float = 0.0, colour_scale: int = 0,
                          auto: bool = True,
                          stats: Optional[Dict[str, int]] = None):
        """One-dispatch multi-band GetMap (RGB styles): index -> fused
        scene warp + per-namespace mosaic + per-band byte scaling on
        device; returns uint8 (n_bands, H, W) in expression order, or
        None when the request doesn't qualify (mask band, remote
        workers, non-trivial expressions, unmatched namespaces,
        uncacheable scenes)."""
        made = self._bands_prep(req, stats=stats)
        if made is None:
            return None
        granules, ns_index, out_sel = made
        return self._bands_dispatch(req, granules, ns_index, out_sel,
                                    offset, scale, clip, colour_scale,
                                    auto)

    def _rgba_try(self, req: GeoTileRequest, granules, ns_index, out_sel,
                  offset, scale, clip, colour_scale, auto):
        """The channel-packed RGBA dispatch over an ALREADY-indexed
        granule set, or None when the set doesn't fit the single-scene
        true-colour shape."""
        if len(granules) != 3 or len(ns_index) != 3 \
                or sorted(out_sel) != [0, 1, 2]:
            return None
        return self.executor.render_rgba_byte(
            granules, out_sel, req.dst_gt(), req.crs, req.height,
            req.width, req.resample, offset, scale, clip, colour_scale,
            auto)

    def render_rgba_byte(self, req: GeoTileRequest,
                         offset: float = 0.0, scale: float = 0.0,
                         clip: float = 0.0, colour_scale: int = 0,
                         auto: bool = True,
                         stats: Optional[Dict[str, int]] = None):
        """One-dispatch RGB GetMap for the single-scene true-colour
        shape: index -> channel-packed warp + per-band scaling + alpha
        on device (`executor.render_rgba_byte`).  Returns the PNG-ready
        uint8 (H, W, 4) jax array, or None when the request doesn't
        qualify (callers then use `render_bands_byte` / `process`)."""
        made = self._bands_prep(req, n_bands=3, stats=stats)
        if made is None:
            return None
        granules, ns_index, out_sel = made
        return self._rgba_try(req, granules, ns_index, out_sel, offset,
                              scale, clip, colour_scale, auto)

    def render_rgb_auto(self, req: GeoTileRequest,
                        offset: float = 0.0, scale: float = 0.0,
                        clip: float = 0.0, colour_scale: int = 0,
                        auto: bool = True,
                        stats: Optional[Dict[str, int]] = None):
        """RGB fast-path ladder over ONE index pass: the channel-packed
        RGBA kernel when the granule set fits it, else the per-band
        planes kernel.  Returns ("rgba", dev (H,W,4)) /
        ("planes", dev (3,H,W)) / None."""
        made = self._bands_prep(req, n_bands=3, stats=stats)
        if made is None:
            return None
        granules, ns_index, out_sel = made
        out = self._rgba_try(req, granules, ns_index, out_sel, offset,
                             scale, clip, colour_scale, auto)
        if out is not None:
            return ("rgba", out)
        out = self._bands_dispatch(req, granules, ns_index, out_sel,
                                   offset, scale, clip, colour_scale,
                                   auto)
        return None if out is None else ("planes", out)

    def process(self, req: GeoTileRequest) -> TileResult:
        granules = self.index(req)
        return self.render(req, granules)

    def render(self, req: GeoTileRequest, granules: List[Granule]) -> TileResult:
        exprs = req.band_exprs
        H, W = req.height, req.width
        if not granules:
            return _empty_result(exprs, H, W)

        mask_id = req.mask.id if req.mask is not None else None
        if mask_id is None and self.remote is None:
            return self._render_fused(req, granules)
        # mask bands always resample nearest: interpolating bitfields is
        # meaningless (the reference's warp kernel is nearest-only anyway)
        is_mask = [mask_id is not None and g.base_namespace == mask_id
                   for g in granules]
        warped: List[Optional[Tuple[np.ndarray, np.ndarray]]] = \
            [None] * len(granules)
        for method, idxs in (
                (req.resample, [i for i, m in enumerate(is_mask) if not m]),
                ("near", [i for i, m in enumerate(is_mask) if m])):
            if not idxs:
                continue
            if self.remote is not None:
                wr = self.remote.warp_many([granules[i] for i in idxs],
                                           req, method)
                for k, i in enumerate(idxs):
                    warped[i] = wr[k]
                continue
            # curvilinear granules have no affine window; they warp
            # from the device scene cache via the geolocation ctrl
            # path even on this modular (mask-band) route
            reg = [i for i in idxs if not granules[i].geo_loc]
            gl = [i for i in idxs if granules[i].geo_loc]
            if reg:
                errs: List[Exception] = []
                ws = decode_all([granules[i] for i in reg], req.bbox,
                                req.crs, method, self.decode_workers,
                                dst_hw=(H, W), errors=errs)
                check_partial(len(errs), len(reg), "decode")
                wr = self.executor.warp_all(ws, req.dst_gt(), req.crs,
                                            H, W, method)
                for k, i in enumerate(reg):
                    warped[i] = wr[k]
            if gl:
                # one batched dispatch, each granule its own namespace
                # slot so per-granule rasters come back for the mask
                # machinery; on failure retry per granule so a single
                # uncacheable file degrades alone
                sc = self.executor.warp_mosaic_scenes(
                    [granules[i] for i in gl], list(range(len(gl))),
                    [1.0] * len(gl), req.dst_gt(), req.crs, H, W,
                    len(gl), method)
                if sc is not None:
                    canv, vals = sc
                    for k, i in enumerate(gl):
                        warped[i] = (canv[k], vals[k])
                else:
                    for i in gl:
                        one = self.executor.warp_mosaic_scenes(
                            [granules[i]], [0], [1.0], req.dst_gt(),
                            req.crs, H, W, 1, method)
                        if one is None:
                            log.warning(
                                "curvilinear granule %s uncacheable; "
                                "rendered empty", granules[i].path)
                            continue
                        warped[i] = (one[0][0], one[1][0])
        # group warped granules by base namespace
        by_ns: Dict[str, List[Tuple[Granule, np.ndarray, np.ndarray]]] = {}
        mask_by_stamp: Dict[float, np.ndarray] = {}
        for g, wr in zip(granules, warped):
            if wr is None:
                continue
            data, ok = wr
            if mask_id is not None and g.base_namespace == mask_id:
                import jax.numpy as jnp
                excl = M.compute_bit_mask(
                    _restore_int(data, g.array_type),
                    req.mask.value or None, req.mask.bit_tests)
                excl = jnp.where(jnp.asarray(ok), excl, False)
                if req.mask.inclusive:
                    excl = ~excl & ok
                prev = mask_by_stamp.get(g.timestamp)
                mask_by_stamp[g.timestamp] = \
                    excl if prev is None else (prev | excl)
                if mask_id not in [n for n in exprs.var_list]:
                    continue
            by_ns.setdefault(g.namespace, []).append((g, data, ok))

        # mosaic per namespace (newest wins, older fills holes)
        data_env: Dict[str, np.ndarray] = {}
        valid_env: Dict[str, np.ndarray] = {}
        for ns, items in by_ns.items():
            rasters = [d for _, d, _ in items]
            valids = []
            for g, _, ok in items:
                excl = mask_by_stamp.get(g.timestamp)
                valids.append(ok & ~excl if excl is not None else ok)
            stamps = [g.timestamp for g, _, _ in items]
            out, okm = M.mosaic_stack(rasters, valids, stamps)
            data_env[ns] = out
            valid_env[ns] = okm

        return evaluate_expressions(exprs, data_env, valid_env, H, W,
                                    granule_count=len(granules),
                                    file_count=len({g.path for g in granules}))


def evaluate_expressions(exprs: BandExpressions,
                         data_env: Dict[str, np.ndarray],
                         valid_env: Dict[str, np.ndarray],
                         H: int, W: int, granule_count: int = 0,
                         file_count: int = 0) -> TileResult:
    """Band-expression evaluation over mosaic canvases — the merger's
    final stage (`processor/tile_merger.go:523-731`).  Variables the index
    produced with axis suffixes (`var#axis=value`) are matched to the
    plain variable when unambiguous."""
    import jax.numpy as jnp

    out_data: Dict[str, np.ndarray] = {}
    out_valid: Dict[str, np.ndarray] = {}
    names: List[str] = []

    def lookup(var: str) -> Optional[str]:
        if var in data_env:
            return var
        cands = [k for k in data_env if k.split("#")[0] == var]
        return cands[0] if len(cands) == 1 else None

    for ce, name in zip(exprs.expressions, exprs.expr_names):
        env = {}
        venv = {}
        missing = False
        for var in ce.variables:
            k = lookup(var)
            if k is None:
                missing = True
                break
            env[var] = jnp.asarray(data_env[k])
            venv[var] = jnp.asarray(valid_env[k])
        if missing:
            out_data[name] = np.zeros((H, W), np.float32)
            out_valid[name] = np.zeros((H, W), bool)
        elif ce._ast[0] == "var":
            k = lookup(ce.variables[0])
            out_data[name] = data_env[k].astype(np.float32)
            out_valid[name] = valid_env[k]
        else:
            # stays on device: TileResult arrays are pulled to host only
            # at encode time (one sync per response).  Consumers
            # (encoders, WCS merge) pull next, so start the copies now —
            # transfers then overlap across concurrent requests
            o, ok = ce.eval_masked(env, venv)
            out_data[name] = _prefetch(o.astype(jnp.float32))
            out_valid[name] = _prefetch(ok)
        names.append(name)

    # axis-expanded outputs with no expression (`var#axis=value` pass
    # through as extra namespaces)
    for k in data_env:
        if "#" in k and k not in out_data:
            out_data[k] = data_env[k].astype(np.float32)
        if "#" in k and k not in out_valid:
            out_valid[k] = valid_env[k]
            names.append(k)

    return TileResult(out_data, out_valid, names, granule_count, file_count)


def _restore_int(data: np.ndarray, array_type: str) -> np.ndarray:
    """Warped mask bands come back float32; restore the integer type for
    bitwise tests."""
    from ..ops.raster import DTYPE_NP
    dt = DTYPE_NP.get(array_type, np.int32)
    if np.dtype(dt).kind not in "iu":
        dt = np.int32
    return data.astype(dt)


def _empty_result(exprs: BandExpressions, H: int, W: int) -> TileResult:
    data = {n: np.zeros((H, W), np.float32) for n in exprs.expr_names}
    valid = {n: np.zeros((H, W), bool) for n in exprs.expr_names}
    return TileResult(data, valid, list(exprs.expr_names), 0, 0)
