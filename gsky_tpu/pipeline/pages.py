"""Paged gather-window pool: the HBM residency layer behind ragged
paged rendering (`ops.paged`, docs/KERNELS.md).

Scenes are cut into a fixed grid of (page_rows, page_cols) f32 pages
(page (pi, pj) covers scene rows [pi*PR, (pi+1)*PR), cols [pj*PC,
(pj+1)*PC); validity stays NaN-encoded, exactly the scene-cache
convention).  Pages live in ONE preallocated device pool array of
shape (capacity, PR, PC) and are content-keyed on (scene serial, pi,
pj): a window is staged into pages at most once per residency, and
overlapping tiles — adjacent GetMap tiles over the same granule, the
common WMS pattern — share the staged pages instead of re-pulling
overlapping gather windows, which is where the bucketed path paid its
padded-pull byte cost.

Slot 0 is a reserved all-NaN null page used to pad page tables (and
backs the zero-extent padding granules of a ragged batch): a kernel
tap through slot 0 is always invalid, never garbage.

Staging runs under `jax.jit` with the pool buffer DONATED, so each
stage is an in-place page write, not a pool-sized copy.  Donation
invalidates the previous Python reference, so the coherence rule is
strict: every pool-array access — staging in `table_for` AND the
dispatch enqueue that consumes a snapshot — happens under `self.lock`
(use `locked_pool()` around the kernel call).  Once a dispatch is
enqueued the device stream owns the value (jax arrays are immutable
values; later donation copies if the buffer is still held), so the
lock only needs to cover the enqueue, not the execution.

Eviction is LRU over page keys with one hard rule: slots PINNED by a
built-but-not-yet-dispatched table are never evicted (`table_for`
returns None instead — the caller falls back to the bucketed path).
Pins are taken by `table_for` and must be released with `unpin` after
the dispatch is enqueued; without the rule a concurrent request could
recycle a queued batch item's pages between enqueue-to-batcher and
flush.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import warnings
import zlib
from collections import OrderedDict
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.paged import page_shape


def _pool_capacity(pr: int, pc: int) -> int:
    """Pool page count from GSKY_PAGE_POOL_MB (default 64 MiB): at the
    default 128x512 f32 page (256 KiB) that is 256 pages — dozens of
    concurrent 1-4 page windows plus sharing headroom."""
    try:
        mb = int(os.environ.get("GSKY_PAGE_POOL_MB", "64"))
    except ValueError:
        mb = 64
    page_bytes = pr * pc * 4
    return max(2, (max(1, mb) << 20) // page_bytes)


@functools.partial(jax.jit, donate_argnums=(0,))
def _stage(pool, scene, ij, slot):
    """Write scene page (ij[0], ij[1]) into pool[slot] in place.  The
    scene is NaN-padded up to page multiples BEFORE the dynamic_slice
    (slice sizes larger than a dim are an error, and the pad is the
    validity encoding for the off-scene region anyway)."""
    pr, pc = pool.shape[1], pool.shape[2]
    sh, sw = scene.shape
    ph = -(-sh // pr) * pr
    pw = -(-sw // pc) * pc
    sp = jnp.pad(scene.astype(jnp.float32),
                 ((0, ph - sh), (0, pw - sw)),
                 constant_values=jnp.nan)
    page = jax.lax.dynamic_slice(sp, (ij[0] * pr, ij[1] * pc), (pr, pc))
    zero = jnp.zeros((), slot.dtype)    # match index dtypes under x64
    return jax.lax.dynamic_update_slice(pool, page[None],
                                        (slot, zero, zero))


@functools.partial(jax.jit, donate_argnums=(0,))
def _stage_ready(pool, page, slot):
    """Write an already-cut (PR, PC) page into pool[slot] in place —
    the fabric peer-fill path, where the page arrives as bytes and
    there is no host scene to slice from."""
    zero = jnp.zeros((), slot.dtype)
    return jax.lax.dynamic_update_slice(
        pool, page.astype(jnp.float32)[None], (slot, zero, zero))


def _note_fill(source: str) -> None:
    """gsky_fabric_page_fills_total{source=peer|cold} breadcrumb."""
    try:
        from ..obs.metrics import FABRIC_PAGE_FILLS
        FABRIC_PAGE_FILLS.labels(source=source).inc()
    except Exception:  # metrics are best-effort on the staging path
        pass


class PagePool:
    """Device-resident page pool + LRU page table.  Thread-safe; see
    the module docstring for the lock/pin coherence rules."""

    def __init__(self, capacity: int | None = None,
                 page_rows: int | None = None,
                 page_cols: int | None = None):
        pr, pc = page_shape()
        self.page_rows = int(page_rows or pr)
        self.page_cols = int(page_cols or pc)
        if capacity is None:
            capacity = _pool_capacity(self.page_rows, self.page_cols)
        self.capacity = max(2, int(capacity))
        self.lock = threading.RLock()
        self._pool = None            # lazy: first use allocates
        self._slots = OrderedDict()  # (serial, pi, pj) -> slot, LRU
        self._free = list(range(self.capacity - 1, 0, -1))
        self._pins: Dict[int, int] = {}   # slot -> pin count
        self._heat: Dict[tuple, int] = {}  # key -> hits since staged
        # stage-time page CRCs, kept only under GSKY_POOL_AUDIT=1
        self._checksums: Dict[tuple, int] = {}
        # audited-poisoned slots still pinned by an in-flight dispatch:
        # unpin() returns them to the free list once the pin drops
        self._quarantine_pins: set = set()
        # stats (under lock)
        self.staged = 0
        self.hits = 0
        self.evictions = 0
        self.declined = 0
        self.teardowns = 0
        self.trimmed = 0
        self.rehydrated = 0
        self.quarantined = 0
        self.peer_filled = 0   # pages staged from fabric peers
        # async-staging handoff generation: bumped by teardown so a
        # wave staged against this pool BEFORE a device incident
        # refuses to dispatch against the rebuilt pool (its pinned
        # slot indices no longer name the pages its tables meant)
        self._handoff_gen = 0
        from ..obs import tsan
        if tsan.enabled():
            # lockset tracking across staging / dispatch / teardown
            # threads (docs/ANALYSIS.md "Race sanitizer")
            tsan.track(self, "PagePool")

    # owning-chip index (mesh serving): None on the shared pool; a
    # ChipPagePool (mesh/pools.py) sets it and journal lines carry it
    chip = None

    # -- internals (hold self.lock) -----------------------------------

    def _ensure_pool(self):  # gskylint: holds-lock
        if self._pool is None:
            # slot 0 (and every unstaged slot) is all-NaN: a tap into
            # an unstaged page is invalid, never stale garbage
            self._pool = jnp.full(
                (self.capacity, self.page_rows, self.page_cols),
                jnp.nan, jnp.float32)

    def _place(self, dev):  # gskylint: holds-lock
        """Placement hook for the staged scene array: the shared pool
        leaves uploads wherever the scene cache put them; a per-chip
        pool overrides this to `device_put` onto its owning chip."""
        return dev

    def _take_slot(self):  # gskylint: holds-lock
        if self._free:
            return self._free.pop()
        for key in self._slots:    # LRU order: oldest first
            slot = self._slots[key]
            if self._pins.get(slot):
                continue
            del self._slots[key]
            self._heat.pop(key, None)
            self._checksums.pop(key, None)
            self.evictions += 1
            return slot
        return None                 # everything pinned: caller declines

    def _stage_locked(self, dev, serial: int, pi: int, pj: int):
        key = (int(serial), int(pi), int(pj))
        slot = self._slots.get(key)
        if slot is not None:
            self._slots.move_to_end(key)
            self.hits += 1
            self._heat[key] = self._heat.get(key, 0) + 1
            return slot
        slot = self._take_slot()
        if slot is None:
            return None
        self._ensure_pool()
        with warnings.catch_warnings():
            # donating a CPU-backed buffer warns; the fallback copy is
            # still correct, just not in-place
            warnings.simplefilter("ignore")
            self._pool = _stage(self._pool, self._place(dev),
                                jnp.asarray((pi, pj), jnp.int32),
                                jnp.int32(slot))
        self._slots[key] = slot
        self.staged += 1
        _note_fill("cold")
        from ..device_guard import (guard_enabled, journal,
                                    pool_audit_enabled)
        if guard_enabled():
            # warm-recovery breadcrumb: cold stages only, so the write
            # rate tracks decode churn, not the (much hotter) hit rate
            journal.record_stage(*key, chip=self.chip)
            if pool_audit_enabled():
                # stage-time CRC for the corruption audit: one page
                # readback per cold stage — the documented cost of
                # GSKY_POOL_AUDIT=1
                self._checksums[key] = zlib.crc32(
                    np.asarray(self._pool[slot]).tobytes())
        return slot

    # -- public --------------------------------------------------------

    def table_for(self, dev, serial: int, i0: int, i1: int,
                  j0: int, j1: int):
        """Stage pages (i0..i1) x (j0..j1) of scene `dev` and return
        their slots row-major as (npages,) int32, PINNED — or None when
        the pool can't hold the request's working set (caller falls
        back to the bucketed path; partial pins are rolled back).  The
        caller owns the pins and must `unpin` the returned slots once
        its dispatch is enqueued (or abandoned)."""
        from ..device_guard import staging_ok
        from ..resilience.pressure import staging_allowed
        if not staging_allowed() or not staging_ok():
            # critical memory pressure, or the device supervisor is
            # anything but healthy: growing HBM residency now risks the
            # whole process (or stages into a pool about to be torn
            # down) — decline and let the caller fall back to the
            # bucketed dispatch path
            with self.lock:
                self.declined += 1
            return None
        slots = []
        with self.lock:
            for pi in range(int(i0), int(i1) + 1):
                for pj in range(int(j0), int(j1) + 1):
                    s = self._stage_locked(dev, serial, pi, pj)
                    if s is None:
                        self.declined += 1
                        for t in slots:   # roll back partial pins
                            self._pins[t] -= 1
                            if not self._pins[t]:
                                del self._pins[t]
                        return None
                    self._pins[s] = self._pins.get(s, 0) + 1
                    slots.append(s)
        return np.asarray(slots, np.int32)

    def stage_page(self, serial: int, pi: int, pj: int, page) -> bool:
        """Stage one already-cut page delivered by a fabric peer
        (`fabric/pagerpc.py`): no host scene involved, the bytes ARE
        the page.  Shape must match the pool's page grid exactly —
        content keys only make sense between pools cut the same way.
        Returns False on shape mismatch or a full/pinned pool."""
        arr = np.asarray(page, np.float32)
        if arr.shape != (self.page_rows, self.page_cols):
            return False
        key = (int(serial), int(pi), int(pj))
        with self.lock:
            if key in self._slots:
                return True          # already resident: nothing to do
            slot = self._take_slot()
            if slot is None:
                self.declined += 1
                return False
            self._ensure_pool()
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                self._pool = _stage_ready(self._pool, jnp.asarray(arr),
                                          jnp.int32(slot))
            self._slots[key] = slot
            self.staged += 1
            self.peer_filled += 1
            from ..device_guard import (guard_enabled, journal,
                                        pool_audit_enabled)
            if guard_enabled():
                journal.record_stage(*key, chip=self.chip)
                if pool_audit_enabled():
                    self._checksums[key] = zlib.crc32(
                        np.asarray(self._pool[slot]).tobytes())
        _note_fill("peer")
        return True

    def has_page(self, serial: int, pi: int, pj: int) -> bool:
        """Residency probe (no LRU touch, no heat)."""
        with self.lock:
            return (int(serial), int(pi), int(pj)) in self._slots

    def read_page(self, serial: int, pi: int, pj: int):
        """Read a resident page back to host for a peer (the serving
        half of the page-fetch RPC).  Passive: no LRU touch, no heat —
        a peer's warm-up must not distort local eviction order.
        Returns a (PR, PC) float32 ndarray or None when not resident."""
        key = (int(serial), int(pi), int(pj))
        with self.lock:
            slot = self._slots.get(key)
            if slot is None or self._pool is None:
                return None
            return np.asarray(self._pool[slot])

    def prewarm(self, dev, serial: int, i0: int, i1: int,
                j0: int, j1: int) -> bool:
        """Prefetch hook: stage a page window without keeping pins —
        the planner warms pages it predicts a request will touch, and
        the request's own `table_for` then hits.  Best-effort: declines
        (pool full / pressure) are fine, the real request just stages
        as usual."""
        slots = self.table_for(dev, serial, i0, i1, j0, j1)
        if slots is None:
            return False
        self.unpin(slots)
        return True

    def unpin(self, slots) -> None:
        """Release pins taken by `table_for` (idempotence is the
        caller's job: once per returned table)."""
        with self.lock:
            for s in np.asarray(slots).reshape(-1).tolist():
                n = self._pins.get(int(s), 0) - 1
                if n > 0:
                    self._pins[int(s)] = n
                else:
                    self._pins.pop(int(s), None)
                    if int(s) in self._quarantine_pins:
                        # audited-poisoned while a dispatch held it:
                        # now that the pin is gone, recycle the slot
                        self._quarantine_pins.discard(int(s))
                        self._free.append(int(s))

    @contextlib.contextmanager
    def locked_pool(self):
        """The pool array to dispatch against, with staging locked out
        for the duration — enqueue the kernel call INSIDE the block so
        no concurrent stage donates the buffer between read and use."""
        with self.lock:
            self._ensure_pool()
            yield self._pool

    # -- async-staging handoff (pipelined waves) -----------------------

    def handoff(self) -> int:
        """Capture the staging generation at wave-assembly time.  The
        pipelined wave scheduler stages uploads one wave AHEAD of
        dispatch; the token pins the meaning of its slot indices."""
        with self.lock:
            return self._handoff_gen

    def handoff_ok(self, gen: int) -> bool:
        """True while a :meth:`handoff` token is still dispatchable —
        no teardown has recycled the slot namespace since assembly.
        (LRU eviction cannot invalidate a staged wave: its table slots
        stay pinned across the handoff.)"""
        with self.lock:
            return self._handoff_gen == int(gen)

    def drop_scene(self, serial: int):
        """Free every unpinned page of a scene (cache eviction hook);
        pinned pages stay resident until their dispatch retires them
        through normal LRU."""
        with self.lock:
            dead = [k for k, s in self._slots.items()
                    if k[0] == int(serial) and not self._pins.get(s)]
            for k in dead:
                self._free.append(self._slots.pop(k))
                self._heat.pop(k, None)
                self._checksums.pop(k, None)
        from ..device_guard import guard_enabled, journal
        if guard_enabled():
            # void the scene's journal entries: its pages can no longer
            # be re-staged, so a rebuild must not chase them
            journal.record_drop(serial)

    # -- device-guard lifecycle (docs/RESILIENCE.md) -------------------

    def teardown(self) -> None:
        """Device-incident teardown: journal the hot set, then drop the
        device array and every piece of residency bookkeeping.

        The supervisor runs this with the *host* process alive — only
        the device state is suspect — so the exact pre-incident hot set
        with in-memory hit counts is available and dumped as ``heat``
        journal lines for :meth:`rehydrate`.  Pins are cleared: every
        dispatch that held one has already failed through the
        supervisor by the time a teardown runs."""
        from ..device_guard import guard_enabled, journal
        with self.lock:
            if guard_enabled():
                for key in self._slots:
                    journal.record_heat(*key, hits=self._heat.get(key, 0),
                                        chip=self.chip)
            self._pool = None
            self._slots.clear()
            self._pins.clear()
            self._heat.clear()
            self._checksums.clear()
            self._quarantine_pins.clear()
            self._free = list(range(self.capacity - 1, 0, -1))
            self.teardowns += 1
            self._handoff_gen += 1

    def rehydrate(self) -> int:
        """Warm recovery: re-stage the journal's hottest pages from
        scenes still resident in the host scene cache, hottest first,
        until the journal or the pool runs out.  Entries whose serial
        is no longer resident (or whose page coordinates fall outside
        the scene's page grid — a stale journal against a reloaded
        world) are skipped.  Returns the number of pages restored."""
        from ..device_guard import journal
        entries = journal.replay()
        if not entries:
            return 0
        restored = 0
        try:
            from .. import fabric
            if fabric.pages_enabled():
                # ask ring-adjacent peers for the hot set first: peer
                # HBM/host memory beats re-decoding from storage, and
                # whatever peers can't serve falls through to the
                # scene-cache loop below
                from ..fabric import pagerpc
                restored += pagerpc.fill_from_peers(self, entries)
        except Exception:  # fabric is best-effort; recovery continues
            pass
        try:
            from .scene_cache import default_scene_cache as sc
            with sc._lock:
                scenes = {s.serial: s.dev for s in sc._scenes.values()}
        except Exception:
            with self.lock:
                self.rehydrated += restored
            return restored
        for serial, pi, pj in entries:
            with self.lock:
                if (serial, pi, pj) in self._slots:
                    continue        # already peer-filled above
            dev = scenes.get(serial)
            if dev is None:
                continue            # stale: scene evicted since
            gh = -(-int(dev.shape[0]) // self.page_rows)
            gw = -(-int(dev.shape[1]) // self.page_cols)
            if pi >= gh or pj >= gw:
                continue            # stale: outside the scene's grid
            with self.lock:
                if not self._free and (serial, pi, pj) not in self._slots:
                    break   # pool full: never LRU-evict warmth we just
                    # restored to make room for colder journal entries
                if self._stage_locked(dev, serial, pi, pj) is not None:
                    restored += 1
        with self.lock:
            self.rehydrated += restored
        return restored

    def trim(self, frac: float = 0.5) -> int:
        """OOM relief: release the coldest ``frac`` of unpinned pages
        so staging churn stops competing for HBM while the pressure
        monitor's cache relief frees the real bytes.  Returns the
        number of pages released."""
        with self.lock:
            victims = [k for k in self._slots
                       if not self._pins.get(self._slots[k])]
            victims = victims[:int(len(victims) * max(0.0, min(1.0, frac)))]
            for k in victims:
                self._free.append(self._slots.pop(k))
                self._heat.pop(k, None)
                self._checksums.pop(k, None)
            self.trimmed += len(victims)
            return len(victims)

    def audit(self) -> int:
        """Integrity audit: convict and quarantine poisoned resident
        pages.  Two passes — a cheap on-device ±inf scan
        (`ops.paged.pool_inf_counts`; inf is written by nothing in the
        staging path), then, under ``GSKY_POOL_AUDIT=1``, a CRC sweep
        against stage-time checksums.  Quarantined slots leave the page
        table immediately (future lookups miss and re-stage from the
        scene cache); a quarantined slot still pinned by an in-flight
        dispatch is recycled when its pin drops.  Returns the number of
        pages quarantined."""
        from ..ops.paged import pool_inf_counts
        with self.lock:
            if self._pool is None or not self._slots:
                return 0
            bad = []
            try:
                infs = np.asarray(pool_inf_counts(self._pool))
            except Exception:
                infs = None
            host = None
            if self._checksums:
                host = np.asarray(self._pool)
            for key, slot in list(self._slots.items()):
                poisoned = bool(infs is not None and infs[slot] > 0)
                if not poisoned and host is not None:
                    want = self._checksums.get(key)
                    if want is not None and \
                            zlib.crc32(host[slot].tobytes()) != want:
                        poisoned = True
                if not poisoned:
                    continue
                bad.append(key)
                self._slots.pop(key)
                self._heat.pop(key, None)
                self._checksums.pop(key, None)
                if self._pins.get(slot):
                    self._quarantine_pins.add(slot)
                else:
                    self._free.append(slot)
            self.quarantined += len(bad)
            return len(bad)

    def stats(self):
        with self.lock:
            return {
                "capacity": self.capacity,
                "page_shape": [self.page_rows, self.page_cols],
                "resident": len(self._slots),
                "pinned": len(self._pins),
                "staged": self.staged,
                "hits": self.hits,
                "evictions": self.evictions,
                "declined": self.declined,
                "teardowns": self.teardowns,
                "trimmed": self.trimmed,
                "rehydrated": self.rehydrated,
                "quarantined": self.quarantined,
                "peer_filled": self.peer_filled,
                "pool_bytes": (self.capacity * self.page_rows
                               * self.page_cols * 4),
            }


def union_table(members, i0: int, i1: int, j0: int, j1: int):
    """Halo-aware multi-tile page table: merge member slot rows into
    ONE row-major table over the union page rect (i0..i1) x (j0..j1).

    ``members`` is a list of (slots, mi0, mi1, mj0, mj1) where
    ``slots`` is the member's row-major (npages,) table over its own
    rect — exactly what `table_for` returned for it.  Pages are
    content-keyed, so members covering the same (pi, pj) agree on the
    slot; positions no member covers (halo gaps) keep slot 0, the
    reserved all-NaN null page, so a stray tap through a gap is
    invalid, never garbage.  No new pins and no staging: the union
    reuses the members' already-pinned slots (the autoplan superblock
    gather, docs/PERF.md "Dataflow planning")."""
    nj = int(j1) - int(j0) + 1
    ni = int(i1) - int(i0) + 1
    out = np.zeros(ni * nj, np.int32)
    for slots, mi0, mi1, mj0, mj1 in members:
        row = np.asarray(slots, np.int32).reshape(-1)
        mnj = int(mj1) - int(mj0) + 1
        for pi in range(int(mi0), int(mi1) + 1):
            for pj in range(int(mj0), int(mj1) + 1):
                out[(pi - int(i0)) * nj + (pj - int(j0))] = \
                    row[(pi - int(mi0)) * mnj + (pj - int(mj0))]
    return out


_default = None
_default_lock = threading.Lock()


def default_page_pool() -> PagePool:
    global _default
    with _default_lock:
        if _default is None:
            _default = PagePool()
        return _default


def reset_default_pool():
    """Test hook: drop the singleton so the next caller re-reads the
    GSKY_PAGE_* knobs."""
    global _default
    with _default_lock:
        _default = None
