"""Wave-level device serving: amortise the per-dispatch host tax
across whole admission waves.

BENCH_r05 (PERF.md) measured the per-dispatch overhead on a real v5e:
a 256px mosaic tile costs ~78.8 ms synchronous against ~12.8 ms
pipelined, and a 1000-point drill ~73.4 ms against ~4.7 ms — the
device is idle most of every request; the ~75 ms is host-side dispatch
tax (upload enqueue, program launch, sync) paid PER CALL.  The ragged
paged kernels (ops/paged.py) already serve any tile shape from one
program, so nothing but the call convention forces tax-per-tile.

This module stops dispatching per tile/drill.  Every scheduler tick,
everything currently eligible — WMS tile renders, drill reductions,
WCS export blocks, mixed — is coalesced into one paged program
invocation per result kind:

- requests enqueue a wave entry (payload + per-request completion
  future) and block on the future, cancellation-aware;
- the ASSEMBLY stage waits ``GSKY_WAVE_TICK_MS`` for companions, then
  drains up to ``GSKY_WAVE_MAX`` entries (clamped by the brownout
  level under pressure), drops cancelled entries at assembly, groups
  by (kind, statics, pool), runs the dataflow planner
  (`autoplan.plan_wave_group`), stacks page tables and param rows
  exactly like `RenderBatcher._execute_paged` — padding rows carrying
  ns_id -1 so every real row is bit-independent of its wave
  companions — and uploads the stacks into a persistent
  double-buffered input `_StagingRing` (two donated staging slots per
  (kind, statics) program family);
- the DISPATCH stage pops staged waves off a host-written wave queue
  and enqueues the device programs back-to-back, so wave N+1 plans,
  stacks, and uploads while wave N executes — the inter-wave host gap
  the r05 record measured as 0.01–3.5% HBM utilisation
  (docs/PERF.md "Continuous device occupancy");
- results land in an on-device `OutputRing` (donated in/out buffers,
  ops/paged.py) that persists ACROSS waves — pow2-padded result
  blocks reuse the same ring lanes wave after wave — and a readback
  queue drains them asynchronously on a third thread with ONE batched
  `device_guard.guarded_readback` per wave (the integrity probe runs
  once on the stacked output), so consumers in `tile_stages` /
  `export` / `drill` never block the NEXT wave's dispatch;
- every staged upload runs under `device_guard.run("wave.stage")` and
  every group dispatch under `device_guard.run("dispatch.wave")`; the
  watchdog supervises both in-flight waves and attributes a
  staging-side hang to the EXECUTING wave (supervisor.execution_window
  — a device_put queued behind a wedged kernel is not the staging
  wave's fault).  An incident fails the wave's requests over
  INDIVIDUALLY (each entry re-renders through its per-call bucketed
  closure), never as a wave.

A tick that carries both tiles and drills dispatches one program per
(kind, statics) group — the mixed wave amortises the tick, admission
and readback machinery; kinds cannot share one XLA program without a
mega-kernel.  ``GSKY_WAVES=0`` restores per-call dispatch
byte-identically, and ``GSKY_WAVE_PIPELINE=0`` restores the
synchronous ticker (assemble + dispatch on one thread) byte-identically
— the pipelined path reuses the exact same stacking and kernel code,
only the thread it runs on changes — see tests/test_waves.py.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import CancelledError, Future
from concurrent.futures import TimeoutError as _FutTimeout
from queue import Empty, Queue
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .. import device_guard
from ..obs.metrics import (WAVE_ASSEMBLY_MS, WAVE_DISPATCHES,
                           WAVE_GAP_MS, WAVE_OCCUPANCY, WAVE_STAGED)


def waves_enabled() -> bool:
    """Wave dispatch gate: on by default wherever the paged kernels
    serve (GSKY_PAGED + pallas available); GSKY_WAVES=0 restores
    per-call dispatch byte-identically.  Plain-CPU XLA serving keeps
    per-call dispatch — the wave stacking rides the paged programs."""
    from ..ops.paged import paged_enabled
    return os.environ.get("GSKY_WAVES", "1") != "0" and paged_enabled()


def wave_max() -> int:
    """Hard cap on entries per wave (GSKY_WAVE_MAX, default 16) —
    bounds the stacked program's memory footprint and the blast radius
    of one device incident."""
    try:
        v = int(os.environ.get("GSKY_WAVE_MAX", "16"))
    except ValueError:
        v = 16
    return max(1, min(64, v))


def wave_tick_ms() -> float:
    """Coalescing window (GSKY_WAVE_TICK_MS, default 2 ms): how long
    the ticker waits for companions after the first entry arrives.
    Zero dispatches back-to-back (still coalescing whatever queued
    while the previous wave ran)."""
    try:
        v = float(os.environ.get("GSKY_WAVE_TICK_MS", "2"))
    except ValueError:
        v = 2.0
    return max(0.0, min(100.0, v))


def wave_pipeline_enabled() -> bool:
    """Two-stage pipeline gate (GSKY_WAVE_PIPELINE, default on):
    assembly stages wave N+1's plan/stack/uploads while wave N
    executes.  ``0`` restores the synchronous ticker byte-identically
    — same stacking, same kernels, one thread.  Read per tick so tests
    and operators can flip it live."""
    return os.environ.get("GSKY_WAVE_PIPELINE", "1") != "0"


def wave_queue_depth() -> int:
    """Staged waves the assembly stage may run AHEAD of dispatch
    (GSKY_WAVE_QUEUE, default 1, clamp 1..4): 1 is classic double
    buffering — one wave executing, one staged.  Brownout clamps the
    effective depth to 1 (pressure applies to the queue, the same
    lever `_effective_max` applies to occupancy)."""
    try:
        v = int(os.environ.get("GSKY_WAVE_QUEUE", "1"))
    except ValueError:
        v = 1
    return max(1, min(4, v))


def wave_stage_slots() -> int:
    """Donated staging slots per (kind, statics) program family
    (GSKY_WAVE_STAGE_SLOTS, default 2, clamp 2..4).  A slot holds one
    wave's uploaded input stacks from stage-time until its program is
    enqueued; two slots let wave N+1 upload while wave N's inputs are
    still feeding the device."""
    try:
        v = int(os.environ.get("GSKY_WAVE_STAGE_SLOTS", "2"))
    except ValueError:
        v = 2
    return max(2, min(4, v))


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _Entry:
    __slots__ = ("kind", "key", "payload", "fallback", "future",
                 "token", "cleanup", "_cleaned", "t_enq")

    def __init__(self, kind, key, payload, fallback, token, cleanup):
        self.kind = kind
        self.key = key
        self.payload = payload
        self.fallback = fallback
        self.future: Future = Future()
        self.token = token
        self.cleanup = cleanup
        self._cleaned = cleanup is None
        self.t_enq = time.perf_counter()

    def cleanup_once(self):
        if not self._cleaned:
            self._cleaned = True
            try:
                self.cleanup()
            except Exception:   # pragma: no cover - unpin best-effort
                pass


class _StageSlot:
    __slots__ = ("bufs", "busy")

    def __init__(self):
        self.bufs: Dict = {}     # name -> previous device generation
        self.busy = False


class _StagingRing:
    """Double-buffered device input slots, one ring per (kind,
    statics) program family.

    ``acquire`` takes the family's next free slot (host-side wait —
    never under the device watchdog); ``upload`` refreshes the slot's
    device buffers from the new wave's host stacks, donating the
    previous generation when shape and dtype match
    (`ops.paged._stage_refresh_fn`) so the staging arena stays two
    buffers per family instead of growing per wave; ``release`` (at
    dispatch enqueue) frees the slot for wave N+2.  The device
    stream's WAR ordering makes donating a slot the PREVIOUS program
    is still reading safe — the overwrite queues behind it, the same
    contract the OutputRing's donated writes rely on."""

    def __init__(self, slots: Optional[int] = None):
        self._slots_n = slots
        self._fams: Dict[tuple, List[_StageSlot]] = {}
        self._cursor: Dict[tuple, int] = {}
        self._cv = threading.Condition()
        # counters (under _cv)
        self.staged = 0
        self.reused = 0

    def _n(self) -> int:
        return self._slots_n if self._slots_n else wave_stage_slots()

    def acquire(self, family: tuple, should_stop=None) -> tuple:
        """Block until a slot of ``family`` frees up; returns the slot
        token.  ``should_stop`` (callable) aborts the wait — shutdown
        must not strand the assembly thread on a dead dispatcher."""
        with self._cv:
            slots = self._fams.get(family)
            if slots is None or len(slots) != self._n():
                slots = [_StageSlot() for _ in range(self._n())]
                self._fams[family] = slots
                self._cursor[family] = 0
            while True:
                n = len(slots)
                start = self._cursor[family]
                for k in range(n):
                    i = (start + k) % n
                    if not slots[i].busy:
                        slots[i].busy = True
                        self._cursor[family] = (i + 1) % n
                        return (family, i)
                if should_stop is not None and should_stop():
                    raise RuntimeError("staging ring shut down")
                self._cv.wait(timeout=0.1)

    def upload(self, token: tuple, host: Dict) -> Dict:
        """Upload the wave's host stacks into the acquired slot.
        Values already on device (drill stacks) pass through; host
        arrays refresh the slot's previous buffer in place when the
        shape matches, else allocate fresh."""
        from ..ops.paged import _stage_refresh_fn
        family, i = token
        with self._cv:
            slot = self._fams[family][i]
        dev: Dict = {}
        reused = 0
        for name, arr in host.items():
            if arr is None:
                continue
            prev = slot.bufs.get(name)
            if (isinstance(arr, np.ndarray) and prev is not None
                    and tuple(prev.shape) == tuple(arr.shape)
                    and str(prev.dtype) == str(arr.dtype)):
                dev[name] = _stage_refresh_fn()(prev, arr)
                reused += 1
            else:
                dev[name] = jnp.asarray(arr)
        slot.bufs = dev
        with self._cv:
            self.staged += 1
            self.reused += reused
        return dev

    def release(self, token: Optional[tuple]):
        if token is None:
            return
        family, i = token
        with self._cv:
            fam = self._fams.get(family)
            if fam is not None and i < len(fam):
                fam[i].busy = False
            self._cv.notify_all()

    def stats(self) -> Dict:
        with self._cv:
            return {"families": len(self._fams),
                    "slots_per_family": self._n(),
                    "staged": self.staged,
                    "slot_reuse": self.reused}


class _StagedWave:
    """One assembled wave group parked on the host-written wave queue:
    entries + plan + pre-uploaded device inputs, waiting for the
    dispatch stage."""
    __slots__ = ("kind", "key", "entries", "plan", "dev", "slot",
                 "mesh", "pool_gen", "t_staged")

    def __init__(self, kind, key, entries, plan=None, dev=None,
                 slot=None, mesh=None, pool_gen=None):
        self.kind = kind
        self.key = key
        self.entries = entries
        self.plan = plan
        self.dev = dev
        self.slot = slot
        self.mesh = mesh
        self.pool_gen = pool_gen
        self.t_staged = time.perf_counter()


class WaveScheduler:
    """Two-stage wave pipeline over the paged kernels.

    Threads start lazily on first submit (a server that never enables
    waves never pays for them) and are daemons: process exit never
    hangs on a drained queue.  With GSKY_WAVE_PIPELINE=1 (default) the
    ticker thread is the ASSEMBLY stage and a dispatcher thread drains
    the staged-wave queue; with 0 the ticker assembles AND dispatches
    synchronously (the pre-pipeline behaviour, byte-identical)."""

    def __init__(self, max_entries: Optional[int] = None,
                 tick_ms: Optional[float] = None,
                 ring_rows: Optional[int] = None,
                 manual_dispatch: bool = False):
        from ..ops.paged import OutputRing
        self._max = max_entries
        self._tick_ms = tick_ms
        self.ring = OutputRing(ring_rows)
        self.staging = _StagingRing()
        self._lock = threading.Lock()
        self._pending: List[_Entry] = []
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._readback_q: Queue = Queue()
        # host-written wave queue: assembly appends staged waves, the
        # dispatch stage pops them back-to-back
        self._staged_q: deque = deque()
        self._q_cv = threading.Condition()
        self._ticker: Optional[threading.Thread] = None
        self._drainer: Optional[threading.Thread] = None
        self._dispatcher: Optional[threading.Thread] = None
        # tests drive dispatch_once() deterministically
        self._manual_dispatch = bool(manual_dispatch)
        # counters (under _lock)
        self.dispatches = 0          # device program invocations
        self.waves = 0               # scheduler ticks that dispatched
        self.requests = 0            # entries submitted
        self.fallbacks = 0           # entries served via per-call leg
        self.cancelled = 0           # entries dropped for cancellation
        self.occupancy: Dict[int, int] = {}   # group size -> count
        self.readback_depth_max = 0
        self.assembly_ms_last = 0.0
        self.stage_ms_last = 0.0
        self.staged_waves = 0        # groups staged ahead of dispatch
        # inter-wave dispatch gap accounting (under _lock)
        self._t_dispatch_end: Optional[float] = None
        self._gap_ms: List[float] = []
        self.gap_total_ms = 0.0
        self.busy_total_ms = 0.0
        from ..obs import tsan
        if tsan.enabled():
            # lockset tracking across the assembly/dispatch/drainer/
            # request threads (docs/ANALYSIS.md "Race sanitizer")
            tsan.track(self, "WaveScheduler")

    # -- knobs ---------------------------------------------------------

    def _wave_max(self) -> int:
        return self._max if self._max else wave_max()

    def _tick_s(self) -> float:
        ms = self._tick_ms if self._tick_ms is not None \
            else wave_tick_ms()
        return ms / 1e3

    def _effective_max(self) -> int:
        """Brownout/pressure clamp: a degraded device gets smaller
        waves (same shape as the batcher's OOM knee ratchet)."""
        m = self._wave_max()
        try:
            from ..resilience.pressure import brownout_level
            lv = brownout_level()
        except Exception:   # pragma: no cover - pressure optional
            lv = 0
        if lv >= 2:
            return max(1, m // 4)
        if lv == 1:
            return max(1, m // 2)
        return m

    def _effective_queue_depth(self) -> int:
        """Pressure clamp on assembly run-ahead: under brownout the
        pipeline degrades to strict double buffering (depth 1)."""
        d = wave_queue_depth()
        try:
            from ..resilience.pressure import brownout_level
            if brownout_level() >= 1:
                return 1
        except Exception:   # pragma: no cover - pressure optional
            pass
        return d

    # -- submission ----------------------------------------------------

    def _submit(self, entry: _Entry) -> _Entry:
        self._ensure_threads()
        with self._lock:
            self._pending.append(entry)
            self.requests += 1
        self._kick.set()
        return entry

    @staticmethod
    def _wait(entry: _Entry):
        """Block on the entry's future, cancellation-aware: a request
        whose client disconnected stops waiting within one poll tick
        while its wave still executes for the surviving companions."""
        while True:
            try:
                return entry.future.result(timeout=0.05)
            except _FutTimeout:
                if entry.token is not None:
                    entry.token.check("wave")
            except CancelledError:
                if entry.token is not None:
                    entry.token.check("wave")
                raise

    # -- threads -------------------------------------------------------

    def _ensure_threads(self):
        if self._ticker is not None and self._ticker.is_alive():
            return
        with self._lock:
            if self._ticker is None or not self._ticker.is_alive():
                self._stop.clear()
                self._ticker = threading.Thread(
                    target=self._ticker_loop, name="gsky-wave-ticker",
                    daemon=True)
                self._ticker.start()
            if self._drainer is None or not self._drainer.is_alive():
                self._drainer = threading.Thread(
                    target=self._drain_loop, name="gsky-wave-readback",
                    daemon=True)
                self._drainer.start()
            if (not self._manual_dispatch
                    and (self._dispatcher is None
                         or not self._dispatcher.is_alive())):
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop,
                    name="gsky-wave-dispatch", daemon=True)
                self._dispatcher.start()

    def _ticker_loop(self):
        while not self._stop.is_set():
            self._kick.wait(timeout=0.25)
            if self._stop.is_set():
                return
            with self._lock:
                if not self._pending:
                    self._kick.clear()
                    continue
            tick = self._tick_s()
            if tick > 0:
                time.sleep(tick)
            try:
                if wave_pipeline_enabled():
                    self.assemble_once()
                else:
                    self.run_wave()
            except Exception:   # pragma: no cover - keep ticking
                pass

    def _dispatch_loop(self):
        while True:
            sg = self._q_get(timeout=0.25)
            if sg is None:
                if self._stop.is_set():
                    return
                continue
            try:
                self._dispatch_staged(sg)
            except Exception:   # pragma: no cover - keep dispatching
                pass

    def _drain_loop(self):
        while True:
            try:
                item = self._readback_q.get(timeout=0.25)
            except Empty:
                if self._stop.is_set():
                    return
                continue
            if item is None:
                return
            # one batched guarded_readback per WAVE: every group's
            # result blocks pull in a single supervised sync and the
            # integrity probe runs once over the stacked outputs —
            # per-entry failover preserved on incident
            groups = item
            for _kind, _es, devs, obs in groups:
                if obs is not None:
                    # mesh wave: per-chip shard probe BEFORE the
                    # gather — records readiness skew on this (async)
                    # thread so dispatch never blocks on a straggler
                    obs(devs)
            flat = [d for _k, _e, devs, _o in groups for d in devs]
            try:
                host = device_guard.guarded_readback(
                    "wave.readback",
                    lambda: tuple(np.asarray(d) for d in flat))
            except Exception as exc:
                for _kind, entries, _d, _o in groups:
                    self._failover(entries, exc)
                continue
            i0 = 0
            for _kind, entries, devs, _obs in groups:
                lanes = host[i0:i0 + len(devs)]
                i0 += len(devs)
                for i, e in enumerate(entries):
                    if e.token is not None and e.token.cancelled():
                        with self._lock:
                            self.cancelled += 1
                        e.future.cancel()
                        continue
                    res = lanes[0][i] if len(lanes) == 1 \
                        else tuple(h[i] for h in lanes)
                    if not e.future.cancelled():
                        e.future.set_result(res)

    # -- staged-wave queue ---------------------------------------------

    def _q_put(self, sg: _StagedWave):
        with self._q_cv:
            self._staged_q.append(sg)
            self._q_cv.notify_all()

    def _q_get(self, timeout: float = 0.0) -> Optional[_StagedWave]:
        deadline = time.monotonic() + timeout
        with self._q_cv:
            while not self._staged_q:
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    return None
                self._q_cv.wait(timeout=left)
            sg = self._staged_q.popleft()
            self._q_cv.notify_all()
            return sg

    def _q_wait_space(self):
        """Assembly backpressure: block while the wave queue is at its
        (pressure-clamped) depth — the queue is the run-ahead bound."""
        with self._q_cv:
            while (len(self._staged_q) >= self._effective_queue_depth()
                   and not self._stop.is_set()):
                self._q_cv.wait(timeout=0.1)

    # -- wave assembly -------------------------------------------------

    def _drain_groups(self) -> Dict[tuple, List[_Entry]]:
        """Shared front half of both legs: drain up to the effective
        cap, drop cancelled entries (releasing their pins NOW — a dead
        request must not ride the wave nor hold pins), group by
        (kind, statics)."""
        with self._lock:
            cap = self._effective_max()
            take = self._pending[:cap]
            del self._pending[:cap]
            leftover = bool(self._pending)
        if leftover:
            self._kick.set()
        live: List[_Entry] = []
        for e in take:
            if e.token is not None and e.token.cancelled():
                e.cleanup_once()
                e.future.cancel()
                with self._lock:
                    self.cancelled += 1
            else:
                live.append(e)
        groups: Dict[tuple, List[_Entry]] = {}
        for e in live:
            groups.setdefault((e.kind, e.key), []).append(e)
        return groups

    @staticmethod
    def _mesh():
        # mesh serving (GSKY_MESH=1): every group consults the
        # partition rules; disabled, md is None and the single-chip
        # dispatch runs byte-identically
        try:
            from ..mesh.dispatch import default_mesh
            return default_mesh()
        except Exception:   # pragma: no cover - mesh boot failure
            return None

    def run_wave(self) -> int:
        """Assemble and dispatch one wave SYNCHRONOUSLY (the
        GSKY_WAVE_PIPELINE=0 leg, and the deterministic step tests and
        bench call directly).  Returns the number of entries
        dispatched."""
        t0 = time.perf_counter()
        groups = self._drain_groups()
        if not groups:
            return 0
        dispatched = 0
        md = self._mesh()
        readback = []
        for (kind, _key), es in groups.items():
            try:
                if md is not None:
                    devs = self._timed_dispatch(
                        lambda m=md, k=kind, g=es:
                        m.dispatch_wave(self, k, g))
                else:
                    # dataflow autoplanner (GSKY_PLAN): superblock the
                    # group's gathers / pick block shapes BEFORE the
                    # device guard so a planner defect degrades to the
                    # unplanned dispatch, never to a device incident
                    plan = None
                    try:
                        from . import autoplan
                        plan = autoplan.plan_wave_group(kind, es)
                    except Exception:   # planning is an optimisation
                        plan = None
                    devs = self._timed_dispatch(
                        lambda k=kind, g=es, p=plan:
                        self._dispatch_group(k, g, p))
            except Exception as exc:
                # device incident mid-wave: the wave never fails as a
                # unit — each request re-renders per-call
                self._failover(es, exc)
                continue
            dispatched += len(es)
            self._note_dispatched(kind, es)
            readback.append(
                (kind, es, devs,
                 md.observe_shards if md is not None else None))
        if readback:
            self._readback_q.put(readback)
            with self._lock:
                self.readback_depth_max = max(
                    self.readback_depth_max, self._readback_q.qsize())
        if dispatched:
            with self._lock:
                self.waves += 1
                self.assembly_ms_last = (time.perf_counter() - t0) * 1e3
            try:
                WAVE_ASSEMBLY_MS.observe(
                    (time.perf_counter() - t0) * 1e3)
            except Exception:  # prom telemetry only
                pass
        return dispatched

    def assemble_once(self) -> int:
        """The pipelined ASSEMBLY stage: drain, plan, stack, upload
        into the staging ring, and park the staged wave on the
        dispatch queue.  Returns the number of entries staged.  Runs
        on the ticker thread; the dispatch stage runs concurrently."""
        t0 = time.perf_counter()
        groups = self._drain_groups()
        if not groups:
            return 0
        staged_n = 0
        md = self._mesh()
        for (kind, key), es in groups.items():
            self._q_wait_space()
            if self._stop.is_set():
                self._failover(es, RuntimeError(
                    "wave scheduler shut down"))
                continue
            try:
                sg = self._stage_group(kind, key, es, md)
            except Exception as exc:
                self._failover(es, exc)
                continue
            staged_n += len(es)
            with self._lock:
                self.staged_waves += 1
                self.stage_ms_last = (time.perf_counter() - t0) * 1e3
            try:
                WAVE_STAGED.inc()
            except Exception:  # prom telemetry only
                pass
            self._q_put(sg)
        if staged_n:
            with self._lock:
                self.assembly_ms_last = (time.perf_counter() - t0) * 1e3
            try:
                WAVE_ASSEMBLY_MS.observe(
                    (time.perf_counter() - t0) * 1e3)
            except Exception:  # prom telemetry only
                pass
        return staged_n

    def _stage_group(self, kind: str, key: tuple, es: List[_Entry],
                     md=None) -> _StagedWave:
        """Plan + stack + upload one group's inputs ahead of dispatch.
        The host stacks are built exactly as the synchronous dispatch
        would build them (same values, same dtypes), then uploaded
        under ``device_guard.run("wave.stage")`` — a staging-class
        site, so a hang here is attributed to the EXECUTING wave."""
        plan = None
        pool_gen = None
        if md is not None:
            dev = device_guard.run(
                "mesh.stage",
                lambda: md.stage_wave(self, kind, es))
            return _StagedWave(kind, key, es, mesh=md, dev=dev)
        if kind in ("byte", "scored", "expr"):
            try:
                from . import autoplan
                plan = autoplan.plan_wave_group(kind, es,
                                                stage="assembly")
            except Exception:   # planning is an optimisation
                plan = None
            pool = es[0].payload["pool"]
            pool_gen = pool.handoff()
            if plan is not None and plan.route == "bucketed":
                # the bucketed leg re-renders from each entry's own
                # XLA payload at dispatch — nothing to pre-upload
                return _StagedWave(kind, key, es, plan=plan,
                                   pool_gen=pool_gen)
            N = len(es)
            Np = _pow2(N)
            host: Dict = {
                "ctrls": np.stack([e.payload["ctrl"] for e in es]
                                  + [es[0].payload["ctrl"]] * (Np - N))
            }
            if kind in ("byte", "expr"):
                host["sps"] = np.stack(
                    [e.payload["sp"] for e in es]
                    + [es[0].payload["sp"]] * (Np - N))
            if kind == "expr":
                host["consts"] = np.stack(
                    [e.payload["consts"] for e in es]
                    + [es[0].payload["consts"]] * (Np - N))
            if plan is not None and plan.route == "superblock":
                host["tables"] = np.asarray(plan.tables)
                host["params"] = np.asarray(plan.params)
                host["sb_of"] = np.asarray(plan.sb_of)
            else:
                host["tables"], host["params"] = \
                    self._stack_tables(es, Np)
        elif kind == "drill":
            host = {
                "data": jnp.stack(
                    [jnp.asarray(e.payload["data"]) for e in es]
                    + [jnp.asarray(es[0].payload["data"])]
                    * (_pow2(len(es)) - len(es))),
                "valid": jnp.stack(
                    [jnp.asarray(e.payload["valid"]) for e in es]
                    + [jnp.asarray(es[0].payload["valid"])]
                    * (_pow2(len(es)) - len(es))),
            }
        else:
            raise ValueError(f"unknown wave kind {kind!r}")
        slot = self.staging.acquire((kind, key),
                                    should_stop=self._stop.is_set)
        try:
            dev = device_guard.run(
                "wave.stage",
                lambda: self.staging.upload(slot, host))
        except Exception:
            self.staging.release(slot)
            raise
        return _StagedWave(kind, key, es, plan=plan, dev=dev,
                           slot=slot, pool_gen=pool_gen)

    def dispatch_once(self, timeout: float = 0.0) -> int:
        """Pop one staged wave and dispatch it (the pipelined DISPATCH
        stage; tests call this directly to step deterministically).
        Returns entries dispatched, 0 when the queue stayed empty."""
        sg = self._q_get(timeout=timeout)
        if sg is None:
            return 0
        return self._dispatch_staged(sg)

    def _dispatch_staged(self, sg: _StagedWave) -> int:
        es = sg.entries
        cancelled = [e for e in es
                     if e.token is not None and e.token.cancelled()]
        if len(cancelled) == len(es):
            # the whole staged wave died while queued: skip the device
            # program entirely, release pins AND the staging slot
            self.staging.release(sg.slot)
            for e in es:
                e.cleanup_once()
                e.future.cancel()
            with self._lock:
                self.cancelled += len(es)
            return 0
        # partially-cancelled waves still dispatch: the dead lanes are
        # already baked into the staged stacks and are discarded at
        # readback (the drainer's token check)
        if sg.pool_gen is not None:
            pool = es[0].payload["pool"]
            if not pool.handoff_ok(sg.pool_gen):
                self.staging.release(sg.slot)
                self._failover(es, RuntimeError(
                    "page pool torn down between wave assembly and"
                    " dispatch"))
                return 0
        try:
            if sg.mesh is not None:
                devs = self._timed_dispatch(
                    lambda: sg.mesh.dispatch_wave(
                        self, sg.kind, es, staged=sg.dev))
            else:
                devs = self._timed_dispatch(
                    lambda: self._dispatch_group(
                        sg.kind, es, sg.plan, staged=sg.dev))
        except Exception as exc:
            self._failover(es, exc)
            return 0
        finally:
            # program enqueued (or failed): the slot may be donated by
            # wave N+2 — the device stream serialises the overwrite
            self.staging.release(sg.slot)
        self._note_dispatched(sg.kind, es)
        with self._lock:
            self.waves += 1
        self._readback_q.put(
            [(sg.kind, es, devs,
              sg.mesh.observe_shards if sg.mesh is not None
              else None)])
        with self._lock:
            self.readback_depth_max = max(
                self.readback_depth_max, self._readback_q.qsize())
        return len(es)

    # -- dispatch accounting -------------------------------------------

    def _timed_dispatch(self, thunk):
        """Run one group dispatch under the device guard, recording
        the host-side inter-wave gap (idle time since the previous
        dispatch enqueue finished) and the busy window."""
        t0 = time.perf_counter()
        gap_ms = None
        with self._lock:
            if self._t_dispatch_end is not None:
                gap_ms = (t0 - self._t_dispatch_end) * 1e3
        try:
            return device_guard.run("dispatch.wave", thunk)
        finally:
            t1 = time.perf_counter()
            with self._lock:
                if gap_ms is not None:
                    self._gap_ms.append(gap_ms)
                    if len(self._gap_ms) > 2048:
                        del self._gap_ms[:1024]
                    self.gap_total_ms += gap_ms
                self.busy_total_ms += (t1 - t0) * 1e3
                self._t_dispatch_end = t1
            if gap_ms is not None:
                try:
                    WAVE_GAP_MS.observe(gap_ms)
                except Exception:  # prom telemetry only
                    pass

    def _note_dispatched(self, kind: str, es: List[_Entry]):
        with self._lock:
            self.dispatches += 1
            n = len(es)
            self.occupancy[n] = self.occupancy.get(n, 0) + 1
        try:
            WAVE_DISPATCHES.labels(kind=kind).inc()
            WAVE_OCCUPANCY.observe(float(len(es)))
        except Exception:  # prom telemetry only
            pass

    def _failover(self, entries: List[_Entry], exc: Exception):
        for e in entries:
            e.cleanup_once()
            if e.future.cancelled():
                continue
            if e.fallback is None:
                e.future.set_exception(exc)
                continue
            with self._lock:
                self.fallbacks += 1
            try:
                e.future.set_result(e.fallback())
            except Exception as fe:   # pragma: no cover
                if not e.future.done():
                    e.future.set_exception(fe)

    # -- per-kind dispatch ---------------------------------------------

    def _dispatch_group(self, kind: str, es: List[_Entry], plan=None,
                        staged=None):
        if kind == "byte":
            return self._dispatch_byte(es, plan, staged)
        if kind == "scored":
            return self._dispatch_scored(es, plan, staged)
        if kind == "expr":
            return self._dispatch_expr(es, plan, staged)
        if kind == "drill":
            return self._dispatch_drill(es, staged)
        raise ValueError(f"unknown wave kind {kind!r}")

    def _stack_tables(self, es: List[_Entry], Np: int):
        """Shared ragged stacking: granule axis to the wave's LARGEST
        tile, page slots likewise; padding rows carry ns_id -1 + a
        null page table, so they gather nothing and every real row is
        bit-independent of its companions (the parity property the
        GSKY_WAVES=0 escape hatch is tested against).  Returns HOST
        arrays — the sync leg uploads them at dispatch, the pipelined
        leg through the staging ring one wave ahead."""
        from ..ops.paged import PARAMS_W
        T = max(e.payload["tables"].shape[0] for e in es)
        S = max(e.payload["tables"].shape[1] for e in es)
        tables = np.zeros((Np, T, S), np.int32)
        params = np.zeros((Np, T, PARAMS_W), np.float32)
        params[:, :, 10] = -1.0     # ns_id: padding rows
        for i, e in enumerate(es):
            ti, si = e.payload["tables"].shape
            tables[i, :ti, :si] = e.payload["tables"]
            params[i, :ti] = e.payload["params16"]
        return tables, params.reshape(Np * T, PARAMS_W)

    def _dispatch_byte(self, es: List[_Entry], plan=None, staged=None):
        from ..ops import paged
        from ..ops.paged import render_byte_paged_raced
        pool = es[0].payload["pool"]
        method, n_ns, out_hw, step, auto, colour_scale = es[0].key[0]
        try:
            N = len(es)
            Np = _pow2(N)

            def _xla():
                # per-tile bucketed XLA legs stacked to the wave
                # contract (runs when racing, demoted, or when the
                # planner's byte estimator routed the group here)
                from ..ops.warp import render_scenes_ctrl
                from .executor import _dev_win0    # lazy: avoids cycle
                outs = []
                for e in es:
                    stack, bparams, bwin, bwin0 = e.payload["xla"]
                    outs.append(render_scenes_ctrl(
                        stack, jnp.asarray(e.payload["ctrl"]),
                        jnp.asarray(bparams),
                        jnp.asarray(e.payload["sp"]), method, n_ns,
                        out_hw, step, auto, colour_scale, win=bwin,
                        win0=_dev_win0(bwin0)))
                outs += [outs[0]] * (Np - N)
                return jnp.stack(outs)

            if plan is not None and plan.route == "bucketed":
                # scattered mix: the ragged slot pad would move more
                # HBM bytes than the per-tile pulls (the PR 8 caveat)
                paged.note_gather(plan.bucketed_bytes)
                dev = _xla()
                return (self.ring.put(dev),)
            blk = plan.blk if plan is not None else None
            sb_of = None
            if staged is not None:
                tables = staged["tables"]
                params = staged["params"]
                ctrls = staged["ctrls"]
                sps = staged["sps"]
                sb_of = staged.get("sb_of")
            else:
                ctrls = jnp.asarray(np.stack(
                    [e.payload["ctrl"] for e in es]
                    + [es[0].payload["ctrl"]] * (Np - N)))
                sps = jnp.asarray(np.stack(
                    [e.payload["sp"] for e in es]
                    + [es[0].payload["sp"]] * (Np - N)))
                if plan is not None and plan.route == "superblock":
                    tables = jnp.asarray(plan.tables)
                    params = jnp.asarray(plan.params)
                    sb_of = jnp.asarray(plan.sb_of)
                else:
                    t_h, p_h = self._stack_tables(es, Np)
                    tables, params = jnp.asarray(t_h), jnp.asarray(p_h)
            with pool.locked_pool() as parr:
                dev = render_byte_paged_raced(
                    parr, tables, params, ctrls, sps, method, n_ns,
                    out_hw, step, auto, colour_scale, _xla, blk=blk,
                    sb_of=sb_of)
            # the full pow2 block goes through the ring (one compile
            # per lattice point — prewarm covers it); the wave pad is
            # discarded host-side at readback and never reaches a link
            return (self.ring.put(dev),)
        finally:
            for e in es:
                e.cleanup_once()

    def _dispatch_scored(self, es: List[_Entry], plan=None,
                         staged=None):
        from ..ops import paged
        from ..ops.paged import warp_scored_paged_raced
        pool = es[0].payload["pool"]
        method, n_ns, out_hw, step = es[0].key[0]
        try:
            N = len(es)
            Np = _pow2(N)

            def _xla():
                from ..ops.warp import warp_scenes_ctrl_scored
                from .executor import _dev_win0    # lazy: avoids cycle
                cs, bs = [], []
                for e in es:
                    stack, bparams, bwin, bwin0 = e.payload["xla"]
                    c, b = warp_scenes_ctrl_scored(
                        stack, jnp.asarray(e.payload["ctrl"]),
                        jnp.asarray(bparams), method, n_ns, out_hw,
                        step, win=bwin, win0=_dev_win0(bwin0))
                    cs.append(c)
                    bs.append(b)
                cs += [cs[0]] * (Np - N)
                bs += [bs[0]] * (Np - N)
                return jnp.stack(cs), jnp.stack(bs)

            if plan is not None and plan.route == "bucketed":
                paged.note_gather(plan.bucketed_bytes)
                canv, best = _xla()
                valid = best > -jnp.inf
                return (self.ring.put(canv),
                        self.ring.put(valid))
            blk = plan.blk if plan is not None else None
            sb_of = None
            if staged is not None:
                tables = staged["tables"]
                params = staged["params"]
                ctrls = staged["ctrls"]
                sb_of = staged.get("sb_of")
            else:
                ctrls = jnp.asarray(np.stack(
                    [e.payload["ctrl"] for e in es]
                    + [es[0].payload["ctrl"]] * (Np - N)))
                if plan is not None and plan.route == "superblock":
                    tables = jnp.asarray(plan.tables)
                    params = jnp.asarray(plan.params)
                    sb_of = jnp.asarray(plan.sb_of)
                else:
                    t_h, p_h = self._stack_tables(es, Np)
                    tables, params = jnp.asarray(t_h), jnp.asarray(p_h)
            with pool.locked_pool() as parr:
                canv, best = warp_scored_paged_raced(
                    parr, tables, params, ctrls, method,
                    n_ns, out_hw, step, _xla, blk=blk, sb_of=sb_of)
            # fold best -> validity ON DEVICE: the -inf invalid marker
            # must not reach guarded_readback (the integrity probe
            # treats inf as DMA corruption — correctly, everywhere
            # else), and the consumer only ever wants the mask
            valid = best > -jnp.inf
            return (self.ring.put(canv), self.ring.put(valid))
        finally:
            for e in es:
                e.cleanup_once()

    def _dispatch_expr(self, es: List[_Entry], plan=None, staged=None):
        """Expression wave: every lane shares one fused paged program
        (the group key carries the fingerprint, so all lanes evaluate
        the same STRUCTURE; constants ride as a traced (Np, C) row).
        The body mirrors `_dispatch_byte` — same planner routes, same
        ring discipline — with `render_expr_paged_raced` at the
        bottom."""
        from ..ops import paged
        from ..ops.expr import fingerprint_hash
        from ..ops.paged import render_expr_paged_raced
        pool = es[0].payload["pool"]
        (method, n_ns, out_hw, step, auto, colour_scale,
         fp) = es[0].key[0]
        try:
            N = len(es)
            Np = _pow2(N)

            def _xla():
                # per-tile unfused legs (bucketed scored mosaic + the
                # same epilogue + scale) stacked to the wave contract
                from ..ops.paged import expr_epilogue
                from ..ops.scale import scale_to_byte
                from ..ops.warp import warp_scenes_ctrl_scored
                from .executor import _dev_win0    # lazy: avoids cycle
                outs = []
                for e in es:
                    stack, bparams, bwin, bwin0 = e.payload["xla"]
                    c, b = warp_scenes_ctrl_scored(
                        stack, jnp.asarray(e.payload["ctrl"]),
                        jnp.asarray(bparams), method, n_ns, out_hw,
                        step, win=bwin, win0=_dev_win0(bwin0))
                    plane, ok = expr_epilogue(
                        c[None], b[None], fp,
                        jnp.asarray(e.payload["consts"][None]))
                    sp = e.payload["sp"]
                    outs.append(scale_to_byte(
                        plane[0], ok[0], float(sp[0]), float(sp[1]),
                        float(sp[2]), colour_scale, auto))
                outs += [outs[0]] * (Np - N)
                return jnp.stack(outs)

            if plan is not None and plan.route == "bucketed":
                paged.note_gather(plan.bucketed_bytes)
                dev = _xla()
                return (self.ring.put(dev),)
            blk = plan.blk if plan is not None else None
            sb_of = None
            if staged is not None:
                tables = staged["tables"]
                params = staged["params"]
                ctrls = staged["ctrls"]
                sps = staged["sps"]
                consts = staged["consts"]
                sb_of = staged.get("sb_of")
            else:
                ctrls = jnp.asarray(np.stack(
                    [e.payload["ctrl"] for e in es]
                    + [es[0].payload["ctrl"]] * (Np - N)))
                sps = jnp.asarray(np.stack(
                    [e.payload["sp"] for e in es]
                    + [es[0].payload["sp"]] * (Np - N)))
                consts = jnp.asarray(np.stack(
                    [e.payload["consts"] for e in es]
                    + [es[0].payload["consts"]] * (Np - N)))
                if plan is not None and plan.route == "superblock":
                    tables = jnp.asarray(plan.tables)
                    params = jnp.asarray(plan.params)
                    sb_of = jnp.asarray(plan.sb_of)
                else:
                    t_h, p_h = self._stack_tables(es, Np)
                    tables, params = jnp.asarray(t_h), jnp.asarray(p_h)
            with pool.locked_pool() as parr:
                dev = render_expr_paged_raced(
                    parr, tables, params, ctrls, sps, consts, method,
                    n_ns, out_hw, step, auto, colour_scale, fp,
                    fingerprint_hash(fp), _xla, blk=blk, sb_of=sb_of)
            return (self.ring.put(dev),)
        finally:
            for e in es:
                e.cleanup_once()

    def _dispatch_drill(self, es: List[_Entry], staged=None):
        from ..ops.paged import wave_drill_stats
        clip_lo, clip_hi, pix = es[0].key[1:]
        K = len(es)
        Kp = _pow2(K)
        if staged is not None:
            data, valid = staged["data"], staged["valid"]
        else:
            # jnp.stack keeps device-resident drill windows on device —
            # the stacked reduction never pulls pixels to host
            data = jnp.stack(
                [jnp.asarray(e.payload["data"]) for e in es]
                + [jnp.asarray(es[0].payload["data"])] * (Kp - K))
            valid = jnp.stack(
                [jnp.asarray(e.payload["valid"]) for e in es]
                + [jnp.asarray(es[0].payload["valid"])] * (Kp - K))
        vals, counts = wave_drill_stats(data, valid, clip_lo, clip_hi,
                                        pixel_count=pix)
        return (self.ring.put(vals), self.ring.put(counts))

    # -- public enqueue API --------------------------------------------

    def render_byte(self, pool, tables, params16, ctrl, sp,
                    statics: tuple, xla_item, percall,
                    serials=None) -> np.ndarray:
        """Submit one byte-tile render (windows already staged in the
        page pool, ``tables`` PINNED — the wave unpins after enqueue).
        ``xla_item`` is (stack, params11, win, win0) for the race's
        stacked bucketed leg; ``percall`` re-renders this tile alone
        (incident failover).  ``serials`` is the lane's scene-content
        identity (the executor's scene-serial key): the autoplanner
        only superblock-merges lanes whose serials match, so temporal
        waves carrying DIFFERENT timesteps of one layer — identical
        params, different page content — never share a union gather
        table.  Blocks; returns host uint8 (H, W)."""
        from ..resilience import current_token
        e = _Entry("byte", (tuple(statics), id(pool)),
                   {"pool": pool, "tables": np.asarray(tables),
                    "params16": np.asarray(params16),
                    "ctrl": np.asarray(ctrl), "sp": np.asarray(sp),
                    "xla": xla_item,
                    "serials": tuple(serials) if serials else None},
                   percall, current_token(),
                   cleanup=lambda: pool.unpin(tables))
        return self._wait(self._submit(e))

    def render_expr(self, pool, tables, params16, ctrl, sp, consts,
                    statics: tuple, xla_item, percall,
                    serials=None) -> np.ndarray:
        """Submit one fused expression render (`render_byte` contract
        plus ``consts``, the lane's lifted literals (C,) f32).  The
        group key includes the fingerprint (statics[-1]), so lanes
        coalesce exactly when they share structure — mixed expression
        storms still wave within each structure.  Blocks; returns host
        uint8 (H, W)."""
        from ..resilience import current_token
        e = _Entry("expr", (tuple(statics), id(pool)),
                   {"pool": pool, "tables": np.asarray(tables),
                    "params16": np.asarray(params16),
                    "ctrl": np.asarray(ctrl), "sp": np.asarray(sp),
                    "consts": np.asarray(consts, np.float32),
                    "xla": xla_item,
                    "serials": tuple(serials) if serials else None},
                   percall, current_token(),
                   cleanup=lambda: pool.unpin(tables))
        return self._wait(self._submit(e))

    def warp_scored(self, pool, tables, params16, ctrl,
                    statics: tuple, xla_item, percall, serials=None):
        """Submit one scored mosaic (the warp_mosaic_scenes paged
        contract).  Blocks; returns host (canv (n_ns, h, w) f32,
        valid (n_ns, h, w) bool) — the -inf best plane is folded to
        its validity mask on device before readback."""
        from ..resilience import current_token
        e = _Entry("scored", (tuple(statics), id(pool)),
                   {"pool": pool, "tables": np.asarray(tables),
                    "params16": np.asarray(params16),
                    "ctrl": np.asarray(ctrl), "xla": xla_item,
                    "serials": tuple(serials) if serials else None},
                   percall, current_token(),
                   cleanup=lambda: pool.unpin(tables))
        return self._wait(self._submit(e))

    def drill_stats(self, data, valid, clip_lower: float,
                    clip_upper: float, pixel_count: bool, percall):
        """Submit one drill reduction: data/valid (B, N).  Requests
        sharing (shape, clips, mode) stack into one (K, B, N) device
        reduction.  Blocks; returns (vals (B,) f32, counts (B,))."""
        from ..resilience import current_token
        e = _Entry("drill",
                   (tuple(int(d) for d in data.shape),
                    float(clip_lower), float(clip_upper),
                    bool(pixel_count)),
                   {"data": data, "valid": valid},
                   percall, current_token(), cleanup=None)
        return self._wait(self._submit(e))

    # -- lifecycle / introspection -------------------------------------

    def shutdown(self):
        """Stop the threads; leftover pending entries AND staged-but-
        undispatched waves fail over to their per-call legs so no
        request is stranded."""
        with self._lock:
            leftover = self._pending[:]
            self._pending.clear()
        if leftover:
            self._failover(leftover,
                           RuntimeError("wave scheduler shut down"))
        self._stop.set()
        with self._q_cv:
            staged = list(self._staged_q)
            self._staged_q.clear()
            self._q_cv.notify_all()
        for sg in staged:
            self.staging.release(sg.slot)
            self._failover(sg.entries,
                           RuntimeError("wave scheduler shut down"))
        self._kick.set()
        self._readback_q.put(None)
        for t in (self._ticker, self._dispatcher, self._drainer):
            if t is not None and t.is_alive():
                t.join(timeout=2.0)

    def _gap_percentiles(self):  # gskylint: holds-lock
        if not self._gap_ms:
            return 0.0, 0.0
        arr = np.asarray(self._gap_ms)
        return (float(np.percentile(arr, 50)),
                float(np.percentile(arr, 99)))

    def stats(self) -> Dict:
        with self._lock:
            occ = dict(sorted(self.occupancy.items()))
            p50, p99 = self._gap_percentiles()
            busy = self.busy_total_ms
            gap = self.gap_total_ms
            idle = gap / (gap + busy) if (gap + busy) > 0 else 0.0
            out = {"enabled": True,
                   "pipeline": wave_pipeline_enabled(),
                   "wave_max": self._wave_max(),
                   "tick_ms": self._tick_ms if self._tick_ms
                   is not None else wave_tick_ms(),
                   "queue_depth": wave_queue_depth(),
                   "dispatches": self.dispatches,
                   "waves": self.waves,
                   "requests": self.requests,
                   "fallbacks": self.fallbacks,
                   "cancelled": self.cancelled,
                   "occupancy": occ,
                   "assembly_ms_last": round(self.assembly_ms_last,
                                             3),
                   "stage_ms_last": round(self.stage_ms_last, 3),
                   "staged_waves": self.staged_waves,
                   "staged_queue_depth": len(self._staged_q),
                   "gap_ms_p50": round(p50, 3),
                   "gap_ms_p99": round(p99, 3),
                   "gap_samples": len(self._gap_ms),
                   "device_idle_fraction": round(idle, 4),
                   "readback_queue_depth": self._readback_q.qsize(),
                   "readback_depth_max": self.readback_depth_max}
        out["staging"] = self.staging.stats()
        out["ring"] = self.ring.stats()
        return out


# -- module singleton ---------------------------------------------------

_default: Optional[WaveScheduler] = None
_default_lock = threading.Lock()


def default_waves() -> WaveScheduler:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = WaveScheduler()
    return _default


def active_waves() -> Optional[WaveScheduler]:
    """The live scheduler or None — never instantiates (collectors and
    the batcher's delegation probe must not boot threads)."""
    return _default


def wave_stats() -> Dict:
    """Scrape-safe stats: {} until the first wave request."""
    return {} if _default is None else _default.stats()


def reset_waves():
    """Tear down the singleton (tests / config reload)."""
    global _default
    with _default_lock:
        w = _default
        _default = None
    if w is not None:
        w.shutdown()
