"""Wave-level device serving: amortise the per-dispatch host tax
across whole admission waves.

BENCH_r05 (PERF.md) measured the per-dispatch overhead on a real v5e:
a 256px mosaic tile costs ~78.8 ms synchronous against ~12.8 ms
pipelined, and a 1000-point drill ~73.4 ms against ~4.7 ms — the
device is idle most of every request; the ~75 ms is host-side dispatch
tax (upload enqueue, program launch, sync) paid PER CALL.  The ragged
paged kernels (ops/paged.py) already serve any tile shape from one
program, so nothing but the call convention forces tax-per-tile.

This module stops dispatching per tile/drill.  Every scheduler tick,
everything currently eligible — WMS tile renders, drill reductions,
WCS export blocks, mixed — is coalesced into one paged program
invocation per result kind:

- requests enqueue a wave entry (payload + per-request completion
  future) and block on the future, cancellation-aware;
- a ticker thread waits ``GSKY_WAVE_TICK_MS`` for companions, then
  drains up to ``GSKY_WAVE_MAX`` entries (clamped by the brownout
  level under pressure), drops cancelled entries at assembly, groups
  by (kind, statics, pool), and dispatches each group as ONE stacked
  paged program over the PR 8 page pool — page tables and param rows
  stacked exactly like `RenderBatcher._execute_paged`, padding rows
  carrying ns_id -1 so every real row is bit-independent of its wave
  companions;
- results land in an on-device `OutputRing` (donated in/out buffers,
  ops/paged.py) and a readback queue drains them asynchronously on a
  second thread (`device_guard.guarded_readback`), so consumers in
  `tile_stages` / `export` / `drill` never block the NEXT wave's
  dispatch;
- every group dispatch runs under `device_guard.run("dispatch.wave")`
  supervision; an incident fails the wave's requests over
  INDIVIDUALLY (each entry re-renders through its per-call bucketed
  closure), never as a wave.

A tick that carries both tiles and drills dispatches one program per
(kind, statics) group — the mixed wave amortises the tick, admission
and readback machinery; kinds cannot share one XLA program without a
mega-kernel.  ``GSKY_WAVES=0`` restores per-call dispatch
byte-identically: the wave branch sits strictly above the existing
entry points, and the stacked kernels are bit-exact per row (nearest)
against their per-call forms — see tests/test_waves.py.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import CancelledError, Future
from concurrent.futures import TimeoutError as _FutTimeout
from queue import Empty, Queue
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .. import device_guard
from ..obs.metrics import (WAVE_ASSEMBLY_MS, WAVE_DISPATCHES,
                           WAVE_OCCUPANCY)


def waves_enabled() -> bool:
    """Wave dispatch gate: on by default wherever the paged kernels
    serve (GSKY_PAGED + pallas available); GSKY_WAVES=0 restores
    per-call dispatch byte-identically.  Plain-CPU XLA serving keeps
    per-call dispatch — the wave stacking rides the paged programs."""
    from ..ops.paged import paged_enabled
    return os.environ.get("GSKY_WAVES", "1") != "0" and paged_enabled()


def wave_max() -> int:
    """Hard cap on entries per wave (GSKY_WAVE_MAX, default 16) —
    bounds the stacked program's memory footprint and the blast radius
    of one device incident."""
    try:
        v = int(os.environ.get("GSKY_WAVE_MAX", "16"))
    except ValueError:
        v = 16
    return max(1, min(64, v))


def wave_tick_ms() -> float:
    """Coalescing window (GSKY_WAVE_TICK_MS, default 2 ms): how long
    the ticker waits for companions after the first entry arrives.
    Zero dispatches back-to-back (still coalescing whatever queued
    while the previous wave ran)."""
    try:
        v = float(os.environ.get("GSKY_WAVE_TICK_MS", "2"))
    except ValueError:
        v = 2.0
    return max(0.0, min(100.0, v))


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _Entry:
    __slots__ = ("kind", "key", "payload", "fallback", "future",
                 "token", "cleanup", "_cleaned", "t_enq")

    def __init__(self, kind, key, payload, fallback, token, cleanup):
        self.kind = kind
        self.key = key
        self.payload = payload
        self.fallback = fallback
        self.future: Future = Future()
        self.token = token
        self.cleanup = cleanup
        self._cleaned = cleanup is None
        self.t_enq = time.perf_counter()

    def cleanup_once(self):
        if not self._cleaned:
            self._cleaned = True
            try:
                self.cleanup()
            except Exception:   # pragma: no cover - unpin best-effort
                pass


class WaveScheduler:
    """Tick-based wave assembly over the paged kernels.

    Threads start lazily on first submit (a server that never enables
    waves never pays for them) and are daemons: process exit never
    hangs on a drained queue."""

    def __init__(self, max_entries: Optional[int] = None,
                 tick_ms: Optional[float] = None,
                 ring_rows: Optional[int] = None):
        from ..ops.paged import OutputRing
        self._max = max_entries
        self._tick_ms = tick_ms
        self.ring = OutputRing(ring_rows)
        self._lock = threading.Lock()
        self._pending: List[_Entry] = []
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._readback_q: Queue = Queue()
        self._ticker: Optional[threading.Thread] = None
        self._drainer: Optional[threading.Thread] = None
        # counters (under _lock)
        self.dispatches = 0          # device program invocations
        self.waves = 0               # scheduler ticks that dispatched
        self.requests = 0            # entries submitted
        self.fallbacks = 0           # entries served via per-call leg
        self.cancelled = 0           # entries dropped for cancellation
        self.occupancy: Dict[int, int] = {}   # group size -> count
        self.readback_depth_max = 0
        self.assembly_ms_last = 0.0
        from ..obs import tsan
        if tsan.enabled():
            # lockset tracking across the ticker/drainer/request
            # threads (docs/ANALYSIS.md "Race sanitizer")
            tsan.track(self, "WaveScheduler")

    # -- knobs ---------------------------------------------------------

    def _wave_max(self) -> int:
        return self._max if self._max else wave_max()

    def _tick_s(self) -> float:
        ms = self._tick_ms if self._tick_ms is not None \
            else wave_tick_ms()
        return ms / 1e3

    def _effective_max(self) -> int:
        """Brownout/pressure clamp: a degraded device gets smaller
        waves (same shape as the batcher's OOM knee ratchet)."""
        m = self._wave_max()
        try:
            from ..resilience.pressure import brownout_level
            lv = brownout_level()
        except Exception:   # pragma: no cover - pressure optional
            lv = 0
        if lv >= 2:
            return max(1, m // 4)
        if lv == 1:
            return max(1, m // 2)
        return m

    # -- submission ----------------------------------------------------

    def _submit(self, entry: _Entry) -> _Entry:
        self._ensure_threads()
        with self._lock:
            self._pending.append(entry)
            self.requests += 1
        self._kick.set()
        return entry

    @staticmethod
    def _wait(entry: _Entry):
        """Block on the entry's future, cancellation-aware: a request
        whose client disconnected stops waiting within one poll tick
        while its wave still executes for the surviving companions."""
        while True:
            try:
                return entry.future.result(timeout=0.05)
            except _FutTimeout:
                if entry.token is not None:
                    entry.token.check("wave")
            except CancelledError:
                if entry.token is not None:
                    entry.token.check("wave")
                raise

    # -- threads -------------------------------------------------------

    def _ensure_threads(self):
        if self._ticker is not None and self._ticker.is_alive():
            return
        with self._lock:
            if self._ticker is None or not self._ticker.is_alive():
                self._stop.clear()
                self._ticker = threading.Thread(
                    target=self._ticker_loop, name="gsky-wave-ticker",
                    daemon=True)
                self._ticker.start()
            if self._drainer is None or not self._drainer.is_alive():
                self._drainer = threading.Thread(
                    target=self._drain_loop, name="gsky-wave-readback",
                    daemon=True)
                self._drainer.start()

    def _ticker_loop(self):
        while not self._stop.is_set():
            self._kick.wait(timeout=0.25)
            if self._stop.is_set():
                return
            with self._lock:
                if not self._pending:
                    self._kick.clear()
                    continue
            tick = self._tick_s()
            if tick > 0:
                time.sleep(tick)
            try:
                self.run_wave()
            except Exception:   # pragma: no cover - keep ticking
                pass

    def _drain_loop(self):
        while True:
            try:
                item = self._readback_q.get(timeout=0.25)
            except Empty:
                if self._stop.is_set():
                    return
                continue
            if item is None:
                return
            kind, entries, devs, obs = item
            if obs is not None:
                # mesh wave: per-chip shard probe BEFORE the gather —
                # records readiness skew on this (async) thread so the
                # ticker never blocks on a straggler chip
                obs(devs)
            try:
                host = device_guard.guarded_readback(
                    "wave.readback",
                    lambda: tuple(np.asarray(d) for d in devs))
            except Exception as exc:
                self._failover(entries, exc)
                continue
            for i, e in enumerate(entries):
                if e.token is not None and e.token.cancelled():
                    with self._lock:
                        self.cancelled += 1
                    e.future.cancel()
                    continue
                res = host[0][i] if len(host) == 1 \
                    else tuple(h[i] for h in host)
                if not e.future.cancelled():
                    e.future.set_result(res)

    # -- wave assembly -------------------------------------------------

    def run_wave(self) -> int:
        """Assemble and dispatch one wave from the pending queue.
        Returns the number of entries dispatched (tests call this
        directly to step the scheduler deterministically)."""
        t0 = time.perf_counter()
        with self._lock:
            cap = self._effective_max()
            take = self._pending[:cap]
            del self._pending[:cap]
            leftover = bool(self._pending)
        if leftover:
            self._kick.set()
        live: List[_Entry] = []
        for e in take:
            if e.token is not None and e.token.cancelled():
                # cancelled at assembly: release its pages NOW — a
                # dead request must not ride the wave nor hold pins
                e.cleanup_once()
                e.future.cancel()
                with self._lock:
                    self.cancelled += 1
            else:
                live.append(e)
        if not live:
            return 0
        groups: Dict[tuple, List[_Entry]] = {}
        for e in live:
            groups.setdefault((e.kind, e.key), []).append(e)
        dispatched = 0
        # mesh serving (GSKY_MESH=1): every group consults the
        # partition rules; disabled, md is None and the single-chip
        # dispatch below runs byte-identically
        try:
            from ..mesh.dispatch import default_mesh
            md = default_mesh()
        except Exception:   # pragma: no cover - mesh boot failure
            md = None
        for (kind, _key), es in groups.items():
            try:
                if md is not None:
                    devs = device_guard.run(
                        "dispatch.wave",
                        lambda m=md, k=kind, g=es:
                        m.dispatch_wave(self, k, g))
                else:
                    # dataflow autoplanner (GSKY_PLAN): superblock the
                    # group's gathers / pick block shapes BEFORE the
                    # device guard so a planner defect degrades to the
                    # unplanned dispatch, never to a device incident
                    plan = None
                    try:
                        from . import autoplan
                        plan = autoplan.plan_wave_group(kind, es)
                    except Exception:   # planning is an optimisation
                        plan = None
                    devs = device_guard.run(
                        "dispatch.wave",
                        lambda k=kind, g=es, p=plan:
                        self._dispatch_group(k, g, p))
            except Exception as exc:
                # device incident mid-wave: the wave never fails as a
                # unit — each request re-renders per-call
                self._failover(es, exc)
                continue
            dispatched += len(es)
            with self._lock:
                self.dispatches += 1
                n = len(es)
                self.occupancy[n] = self.occupancy.get(n, 0) + 1
            try:
                WAVE_DISPATCHES.labels(kind=kind).inc()
                WAVE_OCCUPANCY.observe(float(len(es)))
            except Exception:  # prom telemetry only
                pass
            self._readback_q.put(
                (kind, es, devs,
                 md.observe_shards if md is not None else None))
            with self._lock:
                self.readback_depth_max = max(
                    self.readback_depth_max, self._readback_q.qsize())
        if dispatched:
            with self._lock:
                self.waves += 1
                self.assembly_ms_last = (time.perf_counter() - t0) * 1e3
            try:
                WAVE_ASSEMBLY_MS.observe(
                    (time.perf_counter() - t0) * 1e3)
            except Exception:  # prom telemetry only
                pass
        return dispatched

    def _failover(self, entries: List[_Entry], exc: Exception):
        for e in entries:
            e.cleanup_once()
            if e.future.cancelled():
                continue
            if e.fallback is None:
                e.future.set_exception(exc)
                continue
            with self._lock:
                self.fallbacks += 1
            try:
                e.future.set_result(e.fallback())
            except Exception as fe:   # pragma: no cover
                if not e.future.done():
                    e.future.set_exception(fe)

    # -- per-kind dispatch ---------------------------------------------

    def _dispatch_group(self, kind: str, es: List[_Entry], plan=None):
        if kind == "byte":
            return self._dispatch_byte(es, plan)
        if kind == "scored":
            return self._dispatch_scored(es, plan)
        if kind == "drill":
            return self._dispatch_drill(es)
        raise ValueError(f"unknown wave kind {kind!r}")

    def _stack_tables(self, es: List[_Entry], Np: int):
        """Shared ragged stacking: granule axis to the wave's LARGEST
        tile, page slots likewise; padding rows carry ns_id -1 + a
        null page table, so they gather nothing and every real row is
        bit-independent of its companions (the parity property the
        GSKY_WAVES=0 escape hatch is tested against)."""
        from ..ops.paged import PARAMS_W
        N = len(es)
        T = max(e.payload["tables"].shape[0] for e in es)
        S = max(e.payload["tables"].shape[1] for e in es)
        tables = np.zeros((Np, T, S), np.int32)
        params = np.zeros((Np, T, PARAMS_W), np.float32)
        params[:, :, 10] = -1.0     # ns_id: padding rows
        for i, e in enumerate(es):
            ti, si = e.payload["tables"].shape
            tables[i, :ti, :si] = e.payload["tables"]
            params[i, :ti] = e.payload["params16"]
        return (jnp.asarray(tables),
                jnp.asarray(params.reshape(Np * T, PARAMS_W)))

    def _dispatch_byte(self, es: List[_Entry], plan=None):
        from ..ops import paged
        from ..ops.paged import render_byte_paged_raced
        pool = es[0].payload["pool"]
        method, n_ns, out_hw, step, auto, colour_scale = es[0].key[0]
        try:
            N = len(es)
            Np = _pow2(N)
            ctrls = np.stack([e.payload["ctrl"] for e in es]
                             + [es[0].payload["ctrl"]] * (Np - N))
            sps = np.stack([e.payload["sp"] for e in es]
                           + [es[0].payload["sp"]] * (Np - N))

            def _xla():
                # per-tile bucketed XLA legs stacked to the wave
                # contract (runs when racing, demoted, or when the
                # planner's byte estimator routed the group here)
                from ..ops.warp import render_scenes_ctrl
                from .executor import _dev_win0    # lazy: avoids cycle
                outs = []
                for e in es:
                    stack, bparams, bwin, bwin0 = e.payload["xla"]
                    outs.append(render_scenes_ctrl(
                        stack, jnp.asarray(e.payload["ctrl"]),
                        jnp.asarray(bparams),
                        jnp.asarray(e.payload["sp"]), method, n_ns,
                        out_hw, step, auto, colour_scale, win=bwin,
                        win0=_dev_win0(bwin0)))
                outs += [outs[0]] * (Np - N)
                return jnp.stack(outs)

            if plan is not None and plan.route == "bucketed":
                # scattered mix: the ragged slot pad would move more
                # HBM bytes than the per-tile pulls (the PR 8 caveat)
                paged.note_gather(plan.bucketed_bytes)
                dev = _xla()
                return (self.ring.put(dev[:N]),)
            blk = plan.blk if plan is not None else None
            sb_of = None
            if plan is not None and plan.route == "superblock":
                tables = jnp.asarray(plan.tables)
                params = jnp.asarray(plan.params)
                sb_of = jnp.asarray(plan.sb_of)
            else:
                tables, params = self._stack_tables(es, Np)
            with pool.locked_pool() as parr:
                dev = render_byte_paged_raced(
                    parr, tables, params, jnp.asarray(ctrls),
                    jnp.asarray(sps), method, n_ns, out_hw, step,
                    auto, colour_scale, _xla, blk=blk, sb_of=sb_of)
            # the wave pad never reaches the ring or the link
            return (self.ring.put(dev[:N]),)
        finally:
            for e in es:
                e.cleanup_once()

    def _dispatch_scored(self, es: List[_Entry], plan=None):
        from ..ops import paged
        from ..ops.paged import warp_scored_paged_raced
        pool = es[0].payload["pool"]
        method, n_ns, out_hw, step = es[0].key[0]
        try:
            N = len(es)
            Np = _pow2(N)
            ctrls = np.stack([e.payload["ctrl"] for e in es]
                             + [es[0].payload["ctrl"]] * (Np - N))

            def _xla():
                from ..ops.warp import warp_scenes_ctrl_scored
                from .executor import _dev_win0    # lazy: avoids cycle
                cs, bs = [], []
                for e in es:
                    stack, bparams, bwin, bwin0 = e.payload["xla"]
                    c, b = warp_scenes_ctrl_scored(
                        stack, jnp.asarray(e.payload["ctrl"]),
                        jnp.asarray(bparams), method, n_ns, out_hw,
                        step, win=bwin, win0=_dev_win0(bwin0))
                    cs.append(c)
                    bs.append(b)
                cs += [cs[0]] * (Np - N)
                bs += [bs[0]] * (Np - N)
                return jnp.stack(cs), jnp.stack(bs)

            if plan is not None and plan.route == "bucketed":
                paged.note_gather(plan.bucketed_bytes)
                canv, best = _xla()
                valid = best > -jnp.inf
                return (self.ring.put(canv[:N]),
                        self.ring.put(valid[:N]))
            blk = plan.blk if plan is not None else None
            sb_of = None
            if plan is not None and plan.route == "superblock":
                tables = jnp.asarray(plan.tables)
                params = jnp.asarray(plan.params)
                sb_of = jnp.asarray(plan.sb_of)
            else:
                tables, params = self._stack_tables(es, Np)
            with pool.locked_pool() as parr:
                canv, best = warp_scored_paged_raced(
                    parr, tables, params, jnp.asarray(ctrls), method,
                    n_ns, out_hw, step, _xla, blk=blk, sb_of=sb_of)
            # fold best -> validity ON DEVICE: the -inf invalid marker
            # must not reach guarded_readback (the integrity probe
            # treats inf as DMA corruption — correctly, everywhere
            # else), and the consumer only ever wants the mask
            valid = best > -jnp.inf
            return (self.ring.put(canv[:N]), self.ring.put(valid[:N]))
        finally:
            for e in es:
                e.cleanup_once()

    def _dispatch_drill(self, es: List[_Entry]):
        from ..ops.paged import wave_drill_stats
        clip_lo, clip_hi, pix = es[0].key[1:]
        K = len(es)
        Kp = _pow2(K)
        # jnp.stack keeps device-resident drill windows on device —
        # the stacked reduction never pulls pixels to host
        data = jnp.stack([jnp.asarray(e.payload["data"]) for e in es]
                         + [jnp.asarray(es[0].payload["data"])]
                         * (Kp - K))
        valid = jnp.stack([jnp.asarray(e.payload["valid"])
                           for e in es]
                          + [jnp.asarray(es[0].payload["valid"])]
                          * (Kp - K))
        vals, counts = wave_drill_stats(data, valid, clip_lo, clip_hi,
                                        pixel_count=pix)
        return (self.ring.put(vals[:K]), self.ring.put(counts[:K]))

    # -- public enqueue API --------------------------------------------

    def render_byte(self, pool, tables, params16, ctrl, sp,
                    statics: tuple, xla_item, percall) -> np.ndarray:
        """Submit one byte-tile render (windows already staged in the
        page pool, ``tables`` PINNED — the wave unpins after enqueue).
        ``xla_item`` is (stack, params11, win, win0) for the race's
        stacked bucketed leg; ``percall`` re-renders this tile alone
        (incident failover).  Blocks; returns host uint8 (H, W)."""
        from ..resilience import current_token
        e = _Entry("byte", (tuple(statics), id(pool)),
                   {"pool": pool, "tables": np.asarray(tables),
                    "params16": np.asarray(params16),
                    "ctrl": np.asarray(ctrl), "sp": np.asarray(sp),
                    "xla": xla_item},
                   percall, current_token(),
                   cleanup=lambda: pool.unpin(tables))
        return self._wait(self._submit(e))

    def warp_scored(self, pool, tables, params16, ctrl,
                    statics: tuple, xla_item, percall):
        """Submit one scored mosaic (the warp_mosaic_scenes paged
        contract).  Blocks; returns host (canv (n_ns, h, w) f32,
        valid (n_ns, h, w) bool) — the -inf best plane is folded to
        its validity mask on device before readback."""
        from ..resilience import current_token
        e = _Entry("scored", (tuple(statics), id(pool)),
                   {"pool": pool, "tables": np.asarray(tables),
                    "params16": np.asarray(params16),
                    "ctrl": np.asarray(ctrl), "xla": xla_item},
                   percall, current_token(),
                   cleanup=lambda: pool.unpin(tables))
        return self._wait(self._submit(e))

    def drill_stats(self, data, valid, clip_lower: float,
                    clip_upper: float, pixel_count: bool, percall):
        """Submit one drill reduction: data/valid (B, N).  Requests
        sharing (shape, clips, mode) stack into one (K, B, N) device
        reduction.  Blocks; returns (vals (B,) f32, counts (B,))."""
        from ..resilience import current_token
        e = _Entry("drill",
                   (tuple(int(d) for d in data.shape),
                    float(clip_lower), float(clip_upper),
                    bool(pixel_count)),
                   {"data": data, "valid": valid},
                   percall, current_token(), cleanup=None)
        return self._wait(self._submit(e))

    # -- lifecycle / introspection -------------------------------------

    def shutdown(self):
        """Stop the threads; leftover pending entries fail over to
        their per-call legs so no request is stranded."""
        with self._lock:
            leftover = self._pending[:]
            self._pending.clear()
        if leftover:
            self._failover(leftover,
                           RuntimeError("wave scheduler shut down"))
        self._stop.set()
        self._kick.set()
        self._readback_q.put(None)
        for t in (self._ticker, self._drainer):
            if t is not None and t.is_alive():
                t.join(timeout=2.0)

    def stats(self) -> Dict:
        with self._lock:
            occ = dict(sorted(self.occupancy.items()))
            return {"enabled": True,
                    "wave_max": self._wave_max(),
                    "tick_ms": self._tick_ms if self._tick_ms
                    is not None else wave_tick_ms(),
                    "dispatches": self.dispatches,
                    "waves": self.waves,
                    "requests": self.requests,
                    "fallbacks": self.fallbacks,
                    "cancelled": self.cancelled,
                    "occupancy": occ,
                    "assembly_ms_last": round(self.assembly_ms_last,
                                              3),
                    "readback_queue_depth": self._readback_q.qsize(),
                    "readback_depth_max": self.readback_depth_max,
                    "ring": self.ring.stats()}


# -- module singleton ---------------------------------------------------

_default: Optional[WaveScheduler] = None
_default_lock = threading.Lock()


def default_waves() -> WaveScheduler:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = WaveScheduler()
    return _default


def active_waves() -> Optional[WaveScheduler]:
    """The live scheduler or None — never instantiates (collectors and
    the batcher's delegation probe must not boot threads)."""
    return _default


def wave_stats() -> Dict:
    """Scrape-safe stats: {} until the first wave request."""
    return {} if _default is None else _default.stats()


def reset_waves():
    """Tear down the singleton (tests / config reload)."""
    global _default
    with _default_lock:
        w = _default
        _default = None
    if w is not None:
        w.shutdown()
