"""GetFeatureInfo: the value under a clicked pixel, per namespace, plus
the contributing files/dates — `processor/feature_info.go:21-130`."""

from __future__ import annotations

import datetime as dt
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..index.client import MASClient
from .tile import TilePipeline
from .types import GeoTileRequest


@dataclass
class FeatureInfo:
    values: Dict[str, Optional[float]]
    files: List[str] = field(default_factory=list)
    dates: List[str] = field(default_factory=list)


def get_feature_info(pipe: TilePipeline, req: GeoTileRequest,
                     x: int, y: int) -> FeatureInfo:
    """Render the request (typically at the tile size the client shows)
    and read pixel (x, y); i/j are 0-based from the top-left, per WMS
    1.3.0."""
    if not (0 <= x < req.width and 0 <= y < req.height):
        raise ValueError(f"i/j ({x},{y}) outside {req.width}x{req.height}")
    granules = pipe.index(req)
    res = pipe.render(req, granules)
    values: Dict[str, Optional[float]] = {}
    for ns in res.namespaces:
        if ns in res.data and bool(res.valid[ns][y, x]):
            values[ns] = float(res.data[ns][y, x])
        else:
            values[ns] = None
    files = sorted({g.path for g in granules})
    dates = sorted({
        dt.datetime.fromtimestamp(g.timestamp, dt.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.000Z")
        for g in granules if g.timestamp})
    return FeatureInfo(values, files, dates)
