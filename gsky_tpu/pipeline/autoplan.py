"""Dataflow autoplanner: shared-halo superblock gathers + cost-model
Pallas block sizing.

The r05 device records (PERF.md) put HBM utilisation at 0.01-3.5%:
after the wave scheduler (PR 12) amortised dispatch tax and the paged
pool (PR 8) deduplicated STAGING, the remaining waste is the GATHER —
every tile in a wave still pulls its own page window pool->VMEM, so an
overlapping pan-walk (adjacent GetMap tiles) or a streamed 4K export
re-reads the same pages N times per dispatch; and the paged/bucketed
kernels tile their output with a fixed 128x128 Pallas block under a
static VMEM gate regardless of window extent, method or granule depth.
Following *Model-Based Warp Overlapped Tiling* (footprints planned
once, halos shared between neighbouring output blocks) and *TileLoom*
(block shapes from a cost model, not a constant), this module is the
planning layer between the wave scheduler and the kernels:

- **Superblock gathers** (`plan_wave_group`): drained wave entries
  whose granule lists match and whose page rects overlap (or sit
  within ``GSKY_PLAN_HALO_MAX`` pages of each other) merge into
  superblocks.  Each superblock's union page region is gathered ONCE —
  the per-tile tables (N, T, S) compact to (G, T, S_u), G <= N, and a
  per-lane ``sb_of`` broadcast hands every output lane its region
  (`ops.paged._paged_scored`).  The planner CONSUMES the footprints
  the wave entries already carry (params slots 11-15, the plan-once
  window spans from `executor._paged_from_group`); it never re-indexes.
  Parity is structural: widening a lane's window to the union changes
  no tap (true-extent oob poisoning runs BEFORE window rebase, and
  every in-extent tap of a lane lies inside its own span by the
  `_granule_bounds` margins), pages are content-keyed so members agree
  on slots, and halo gaps map to the null page.
- **Cost-model block shapes** (`plan_block`): per (output extent,
  n_ns, method, granule depth, page/window geometry) the model scores
  each ``GSKY_PLAN_BLOCKS`` candidate by padded compute + per-grid-step
  overhead under the real VMEM gate, and the verdict persists through
  the kernel ledger (kernel ``plan_block``, the chosen shape encoded
  in the token) so a shape is costed once per process LINEAGE, not per
  process.
- **Ragged-vs-bucketed routing**: the same byte estimator resolves the
  PR 8 caveat — a scattered mix whose ragged slot pad would move more
  bytes than the per-tile bucketed pulls routes to the group's stacked
  bucketed leg instead (``gsky_plan_route_total{path=bucketed}``).

``GSKY_PLAN=0`` disables all three: dispatch shapes, tokens and bytes
are byte-identical to the unplanned path (tests/test_autoplan.py).
Mesh waves plan per shard (`plan_sharded`) so no superblock — and no
halo — ever crosses a chip boundary.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

from ..obs.metrics import (PLAN_BLOCK_SHAPE, PLAN_BYTES_SAVED,
                           PLAN_ROUTE, PLAN_SUPERBLOCKS)


def plan_enabled() -> bool:
    """Autoplanner gate: on by default; GSKY_PLAN=0 restores today's
    independent-window dispatch byte-identically (no superblocks, no
    block-shape overrides, no route changes)."""
    return os.environ.get("GSKY_PLAN", "1") != "0"


def plan_halo_max() -> int:
    """Largest page gap (GSKY_PLAN_HALO_MAX, default 2) two windows
    may leave between them and still merge: 0 merges only overlapping/
    adjacent rects; larger values trade null-page gather waste for
    fewer superblocks."""
    try:
        v = int(os.environ.get("GSKY_PLAN_HALO_MAX", "2"))
    except ValueError:
        v = 2
    return max(0, min(16, v))


# default block-shape ladder: f32 tiling wants rows a multiple of 8 and
# cols a multiple of 128 (the (8, 128) min tile); 128x128 first so cost
# ties keep today's shape
_DEF_BLOCKS = ((128, 128), (256, 128), (128, 256), (256, 256),
               (64, 128))
# modelled per-grid-step overhead in pixel-visit units: grid setup +
# accumulator init/flush per step — what a finer tiling pays for its
# smaller pad waste
_STEP_OVERHEAD = 4096
_TAPS = {"near": 1, "nearest": 1, "bilinear": 4, "cubic": 16}


def plan_blocks():
    """Candidate (block_h, block_w) ladder from GSKY_PLAN_BLOCKS
    ("128x128,256x128,..."); malformed or lane-misaligned entries are
    dropped, an empty result falls back to the default ladder."""
    v = os.environ.get("GSKY_PLAN_BLOCKS", "")
    if not v.strip():
        return _DEF_BLOCKS
    out = []
    for part in v.lower().split(","):
        try:
            bh_s, bw_s = part.strip().split("x")
            bh, bw = int(bh_s), int(bw_s)
        except ValueError:
            continue
        if bh > 0 and bw > 0 and bh % 8 == 0 and bw % 128 == 0:
            out.append((bh, bw))
    return tuple(out) if out else _DEF_BLOCKS


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# ---------------------------------------------------------------------------
# cost-model block sizing (ledger-persisted)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_COSTED: Dict[tuple, tuple] = {}    # key -> (bh, bw) chosen
_SEEDED = False
# plan counters (under _LOCK)
_STATS = {"superblocks": 0, "merged_lanes": 0, "bytes_saved": 0,
          "routes": {"ragged": 0, "bucketed": 0}, "groups_planned": 0,
          "assembly_planned": 0}


def _seed_from_ledger():  # gskylint: holds-lock
    """Replay persisted plan_block verdicts into the in-process memo,
    once: the chosen shape is encoded in the token (the ledger only
    accepts promoted/demoted/failed verdicts), so a costed shape
    survives process restarts without re-deriving."""
    global _SEEDED
    if _SEEDED:
        return
    _SEEDED = True
    try:
        from ..ops import kernel_ledger as kl
        for (name, tok), rec in kl.entries().items():
            if name != "plan_block" or rec.get("verdict") != "promoted":
                continue
            token = kl.decode_token(tok)
            if token is None or not kl.token_version_ok(name, token) \
                    or len(token) != 12:
                continue
            key = tuple(token[1:10])
            _COSTED.setdefault(key, (int(token[10]), int(token[11])))
    except Exception:  # noqa: BLE001 - a bad ledger never blocks planning
        pass


def _block_cost(h: int, w: int, T: int, taps: int, bh: int,
                bw: int) -> int:
    """Modelled work for one lane at block (bh, bw): padded pixel
    visits (pad waste is real compute) + per-grid-step overhead."""
    hp = -(-h // bh) * bh
    wp = -(-w // bw) * bw
    steps = (hp // bh) * (wp // bw) * max(1, T)
    return hp * wp * max(1, T) * taps + steps * _STEP_OVERHEAD


def plan_block(h: int, w: int, n_ns: int, method: str, T: int = 1,
               S: int = 0, pr: int = 0, pc: int = 0, win=None):
    """Cost-model Pallas block shape for an (h, w) output under the
    real VMEM ceiling.  ``S > 0`` gates candidates through the paged
    budget (`ops.paged.paged_vmem_ok`); ``S == 0`` is the bucketed
    kernel, gated on the window extent ``win``.  Returns (bh, bw), or
    None when the default 128x128 wins (so default-path jit keys and
    ledger tokens stay untouched).  Decisions memoise in-process and
    persist through the kernel ledger."""
    if not plan_enabled():
        return None
    from ..ops.paged import paged_vmem_ok
    from ..ops.pallas_tpu import (_WARP_BLK, _WARP_VMEM_BUDGET,
                                  _warp_vmem_bytes)
    key = (int(h), int(w), int(n_ns), str(method), int(T), int(S),
           int(pr), int(pc),
           None if win is None else (int(win[0]), int(win[1])))
    with _LOCK:
        _seed_from_ledger()
        got = _COSTED.get(key)
    if got is None:
        taps = _TAPS.get(str(method), 4)
        best = None
        best_cost = None
        for bh, bw in plan_blocks():
            if S > 0:
                if not paged_vmem_ok(S, n_ns, pr, pc, (bh, bw)):
                    continue
            elif win is not None:
                if _warp_vmem_bytes(int(win[0]), int(win[1]), n_ns,
                                    (bh, bw)) > _WARP_VMEM_BUDGET:
                    continue
            cost = _block_cost(int(h), int(w), int(T), taps, bh, bw)
            if best_cost is None or cost < best_cost:
                best, best_cost = (bh, bw), cost
        if best is None:
            best = (_WARP_BLK, _WARP_BLK)
        with _LOCK:
            got = _COSTED.setdefault(key, best)
        if got is best:
            # first process in the lineage to cost this point: persist
            # (the shape rides the token; verdict is always promoted)
            try:
                from ..ops import kernel_ledger as kl
                kl.record("plan_block", ("pl1",) + key + got, "promoted")
            except Exception:  # noqa: BLE001 - durability is optional
                pass
    try:
        PLAN_BLOCK_SHAPE.labels(shape=f"{got[0]}x{got[1]}").inc()
    except Exception:  # prom telemetry only
        pass
    from ..ops.pallas_tpu import _WARP_BLK as _D
    return None if got == (_D, _D) else got


# ---------------------------------------------------------------------------
# superblock planning over wave groups
# ---------------------------------------------------------------------------

class Plan:
    """One wave group's dispatch plan.  ``route``:

    - ``"superblock"``: dispatch the compacted (tables, params, sb_of)
      through the paged kernel — ``tables`` (Gp, T, S_u) np.int32,
      ``params`` (Np*T, 16) np.float32 (lane windows rewritten to
      their superblock's union), ``sb_of`` (Np,) np.int32;
    - ``"bucketed"``: the ragged slot pad would move more HBM bytes
      than the per-tile bucketed pulls (the PR 8 crossover) — dispatch
      the group's stacked bucketed XLA leg directly;
    - ``"ragged"``: no profitable merge; dispatch unchanged (``blk``
      still applies).
    """

    __slots__ = ("route", "tables", "params", "sb_of", "blk",
                 "superblocks", "naive_bytes", "planned_bytes",
                 "bucketed_bytes", "merged_lanes")

    def __init__(self, route, blk=None, tables=None, params=None,
                 sb_of=None, superblocks=0, naive_bytes=0,
                 planned_bytes=0, bucketed_bytes=None, merged_lanes=0):
        self.route = route
        self.blk = blk
        self.tables = tables
        self.params = params
        self.sb_of = sb_of
        self.superblocks = superblocks
        self.naive_bytes = naive_bytes
        self.planned_bytes = planned_bytes
        self.bucketed_bytes = bucketed_bytes
        self.merged_lanes = merged_lanes


def _entry_rows(e, pr: int, pc: int):
    """Per-granule (page rect, slot row) footprints one wave entry
    already carries: rect recovered from params slots 11-14 (origin
    and extent are page-aligned by construction), slots from the
    pinned table row.  The planner consumes, it doesn't re-index."""
    p16 = np.asarray(e.payload["params16"], np.float32)
    tb = np.asarray(e.payload["tables"], np.int32)
    rows = []
    for t in range(p16.shape[0]):
        i0 = int(round(float(p16[t, 11]) / pr))
        j0 = int(round(float(p16[t, 12]) / pc))
        ni = max(1, int(round(float(p16[t, 13]) / pr)))
        nj = max(1, int(round(float(p16[t, 14]) / pc)))
        rows.append(((i0, i0 + ni - 1, j0, j0 + nj - 1),
                     tb[t, :ni * nj]))
    return rows


def _rect_union(u, r, halo: int):
    """Union of two page rects when they overlap or sit within
    ``halo`` pages on BOTH axes, else None."""
    gi = max(u[0], r[0]) - min(u[1], r[1]) - 1
    gj = max(u[2], r[2]) - min(u[3], r[3]) - 1
    if gi > halo or gj > halo:
        return None
    return (min(u[0], r[0]), max(u[1], r[1]),
            min(u[2], r[2]), max(u[3], r[3]))


def _merge_cluster(idxs: List[int], rows, halo: int, slot_cap: int,
                   vmem_ok):
    """Greedy superblock formation inside one granule-signature
    cluster: lanes sorted by origin, each placed into the first
    superblock whose per-granule unions stay within the halo, the
    page-slot cap and the VMEM gate.  Returns [(member idxs, union
    rects per granule)]."""
    order = sorted(idxs, key=lambda i: (rows[i][0][0][0],
                                        rows[i][0][0][2]))
    sbs: List[list] = []
    for i in order:
        rects_i = [r for r, _s in rows[i]]
        placed = False
        for sb in sbs:
            if len(sb[1]) != len(rects_i):
                continue
            cand = []
            for u, r in zip(sb[1], rects_i):
                nu = _rect_union(u, r, halo)
                if nu is None or ((nu[1] - nu[0] + 1)
                                  * (nu[3] - nu[2] + 1)) > slot_cap:
                    cand = None
                    break
                cand.append(nu)
            if cand is None:
                continue
            if not vmem_ok(max((u[1] - u[0] + 1) * (u[3] - u[2] + 1)
                               for u in cand)):
                continue
            sb[0].append(i)
            sb[1] = cand
            placed = True
            break
        if not placed:
            sbs.append([[i], rects_i])
    return sbs


def _cluster_and_merge(es, rows, n_ns: int, pr: int, pc: int, blk):
    """Cluster lanes by granule signature (identical params[:11]
    blocks — same scenes, same affine, same priorities) and merge each
    cluster into superblocks.  Lanes that merge MUST read identical
    page content at shared positions; the content-keyed pool
    guarantees it for identical granule lists.  The signature
    therefore ALSO carries the lane's scene-serial key when the
    submitter provided one (``payload["serials"]``, executor wave
    lanes): two timesteps of one layer share every param — same
    affine, same priorities — yet hold different pixels, and merging
    them would gather one timestep's pages for both.  Temporal waves
    merge exactly the frames whose requested times resolved to the
    SAME underlying data (WMS-T nearest semantics), which is where the
    animation path's gather amortisation comes from."""
    from ..ops.paged import page_slots, paged_vmem_ok
    halo = plan_halo_max()
    slot_cap = page_slots()
    clusters: Dict[tuple, List[int]] = {}
    for i, e in enumerate(es):
        p16 = np.asarray(e.payload["params16"], np.float32)
        key = (p16.shape[0], p16[:, :11].tobytes(),
               e.payload.get("serials"))
        clusters.setdefault(key, []).append(i)
    sbs = []
    for idxs in clusters.values():
        sbs.extend(_merge_cluster(
            idxs, rows, halo, slot_cap,
            lambda npg: paged_vmem_ok(_pow2(npg), n_ns, pr, pc, blk)))
    return sbs


def _build_superblock_arrays(es, rows, sbs, T: int, Np: int, pr: int,
                             pc: int):
    """Assemble the compacted dispatch arrays from the merge result:
    union tables (Gp, T, S_u) via `pages.union_table`, per-lane params
    with window slots 11-15 rewritten to the lane's superblock union,
    and the lane->superblock broadcast map."""
    from ..ops.paged import PARAMS_W
    from .pages import union_table
    G = len(sbs)
    Gp = _pow2(G)
    S_u = _pow2(max(
        (u[1] - u[0] + 1) * (u[3] - u[2] + 1)
        for _m, rects in sbs for u in rects))
    tables = np.zeros((Gp, T, S_u), np.int32)
    params = np.zeros((Np, T, PARAMS_W), np.float32)
    params[:, :, 10] = -1.0     # ns_id: padding rows gather nothing
    sb_of = np.zeros(Np, np.int32)
    for g, (members, rects) in enumerate(sbs):
        for t, u in enumerate(rects):
            mem = [(rows[i][t][1],) + rows[i][t][0] for i in members]
            u_slots = union_table(mem, *u)
            tables[g, t, :u_slots.shape[0]] = u_slots
        for i in members:
            sb_of[i] = g
            p16 = np.asarray(es[i].payload["params16"], np.float32)
            te = p16.shape[0]
            params[i, :te] = p16
            for t, u in enumerate(rects):
                params[i, t, 11] = u[0] * pr
                params[i, t, 12] = u[2] * pc
                params[i, t, 13] = (u[1] - u[0] + 1) * pr
                params[i, t, 14] = (u[3] - u[2] + 1) * pc
                params[i, t, 15] = u[3] - u[2] + 1
    return tables, params, sb_of, G, Gp, S_u


def _bucketed_bytes(es) -> Optional[int]:
    """Estimated HBM bytes the group's stacked bucketed leg would
    move: per entry, the windowed slice of the scene stack it gathers
    (the whole stack when unwindowed).  None when any entry lacks a
    bucketed payload."""
    total = 0
    try:
        for e in es:
            stack, _p, bwin, _w0 = e.payload["xla"]
            if bwin is not None:
                total += (int(stack.shape[0]) * int(bwin[0])
                          * int(bwin[1]) * stack.dtype.itemsize)
            else:
                total += int(np.prod([int(d) for d in stack.shape])) \
                    * stack.dtype.itemsize
    except Exception:  # noqa: BLE001 - estimator is advisory
        return None
    return total


def _note_route(path: str):
    with _LOCK:
        _STATS["routes"][path] = _STATS["routes"].get(path, 0) + 1
        _STATS["groups_planned"] += 1
    try:
        PLAN_ROUTE.labels(path=path).inc()
    except Exception:  # prom telemetry only
        pass


def union_lane_spans(spans, cap: int, maxnpg: int):
    """Cross-band gather-window merge for one EXPRESSION lane: the
    lane's granules are different bands of the same bbox, so their page
    rects overlap near-totally — unioning them makes every band's
    page-table row the same shape (params16[11:16] identical down the T
    axis), which is the cheapest superblock the planner ever sees: the
    between-lane clusterer then matches expression lanes row for row.

    ``spans`` is `_paged_from_group`'s per-granule (i0, i1, j0, j1)
    list (None = padding/off-scene); all spans in a scene group share
    one bucket shape, so the union of clipped rects stays clipped.
    Returns (merged spans, new maxnpg) — unchanged when merging would
    exceed the page budget or bump the slot pow2 (never trade a bigger
    program for the merge)."""
    live = [s for s in spans if s is not None]
    if len(live) < 2:
        return spans, maxnpg
    i0 = min(s[0] for s in live)
    i1 = max(s[1] for s in live)
    j0 = min(s[2] for s in live)
    j1 = max(s[3] for s in live)
    npg = (i1 - i0 + 1) * (j1 - j0 + 1)
    if npg > cap or _pow2(npg) != _pow2(maxnpg):
        return spans, maxnpg
    u = (i0, i1, j0, j1)
    return [u if s is not None else None for s in spans], npg


def plan_wave_group(kind: str, es, stage: str = "dispatch"
                    ) -> Optional[Plan]:
    """Plan one drained wave group.  Under the synchronous ticker this
    runs just before group dispatch; the pipelined scheduler
    (GSKY_WAVE_PIPELINE, pipeline/waves.py) calls it from the ASSEMBLY
    stage with ``stage="assembly"`` — planning off the dispatch
    critical path, overlapped with the previous wave's execution.  All
    planner state is under ``_LOCK``, so assembly-thread planning may
    race a mesh ``plan_sharded`` on the dispatch thread.  Returns None
    — dispatch exactly as today — when planning is off, the kind has no
    gather, or nothing improves; otherwise a `Plan` whose route the
    dispatcher follows.  Never raises into the wave path: any planner
    defect degrades to the unplanned dispatch."""
    if not plan_enabled() or kind not in ("byte", "scored", "expr") \
            or not es:
        return None
    if stage == "assembly":
        with _LOCK:
            _STATS["assembly_planned"] += 1
    try:
        statics = es[0].key[0]
        method, n_ns, out_hw = statics[0], statics[1], statics[2]
        pool = es[0].payload["pool"]
        pr, pc = int(pool.page_rows), int(pool.page_cols)
        N = len(es)
        Np = _pow2(N)
        T = max(e.payload["tables"].shape[0] for e in es)
        S_in = max(e.payload["tables"].shape[1] for e in es)
        naive = Np * T * S_in * pr * pc * 4
        blk = plan_block(int(out_hw[0]), int(out_hw[1]), int(n_ns),
                         str(method), T=T, S=S_in, pr=pr, pc=pc)
        rows = [_entry_rows(e, pr, pc) for e in es]
        sbs = _cluster_and_merge(es, rows, int(n_ns), pr, pc, blk)
        planned = naive
        built = None
        if len(sbs) < N:
            tables, params, sb_of, G, Gp, S_u = \
                _build_superblock_arrays(es, rows, sbs, T, Np, pr, pc)
            planned = Gp * T * S_u * pr * pc * 4
            built = (tables, params, sb_of, G)
        bucketed = _bucketed_bytes(es)
        if bucketed is not None and bucketed < min(naive, planned):
            _note_route("bucketed")
            return Plan("bucketed", blk=blk, naive_bytes=naive,
                        planned_bytes=planned, bucketed_bytes=bucketed)
        _note_route("ragged")
        if built is not None and planned < naive:
            tables, params, sb_of, G = built
            with _LOCK:
                _STATS["superblocks"] += G
                _STATS["merged_lanes"] += N - G
                _STATS["bytes_saved"] += naive - planned
            try:
                PLAN_SUPERBLOCKS.inc(float(G))
                PLAN_BYTES_SAVED.inc(float(naive - planned))
            except Exception:  # prom telemetry only
                pass
            from ..ops.paged import PARAMS_W
            return Plan("superblock", blk=blk, tables=tables,
                        params=params.reshape(Np * T, PARAMS_W),
                        sb_of=sb_of, superblocks=G, naive_bytes=naive,
                        planned_bytes=planned, bucketed_bytes=bucketed,
                        merged_lanes=N - G)
        if blk is None:
            return None
        return Plan("ragged", blk=blk, naive_bytes=naive,
                    planned_bytes=naive, bucketed_bytes=bucketed)
    except Exception:  # noqa: BLE001 - planning is an optimisation
        return None


def plan_sharded(kind: str, es, n_chips: int, Np: int) -> Optional[Plan]:
    """Mesh variant: plan each chip's lane slice INDEPENDENTLY (chip c
    owns lanes [c*rpc, (c+1)*rpc)), so no superblock — and no halo —
    ever crosses a chip boundary.  Per-chip superblock counts pad to a
    common Gc and the chip tables concatenate to (n_chips*Gc, T, S_u),
    which the wave sharding splits back into Gc rows per chip;
    ``sb_of`` values are chip-LOCAL indices.  Returns None when no
    chip merges anything (the unplanned mesh dispatch runs)."""
    if not plan_enabled() or kind not in ("byte", "scored", "expr") \
            or not es:
        return None
    try:
        statics = es[0].key[0]
        method, n_ns, out_hw = statics[0], statics[1], statics[2]
        pool = es[0].payload["pool"]
        pr, pc = int(pool.page_rows), int(pool.page_cols)
        N = len(es)
        rpc = max(1, Np // max(1, n_chips))
        T = max(e.payload["tables"].shape[0] for e in es)
        S_in = max(e.payload["tables"].shape[1] for e in es)
        naive = Np * T * S_in * pr * pc * 4
        blk = plan_block(int(out_hw[0]), int(out_hw[1]), int(n_ns),
                         str(method), T=T, S=S_in, pr=pr, pc=pc)
        rows = [_entry_rows(e, pr, pc) for e in es]
        chip_sbs = []
        merged_any = False
        for c in range(n_chips):
            lo, hi = c * rpc, min(N, (c + 1) * rpc)
            if lo >= hi:
                chip_sbs.append([])
                continue
            sub = list(range(lo, hi))
            sub_es = [es[i] for i in sub]
            sub_rows = [rows[i] for i in sub]
            sbs = _cluster_and_merge(sub_es, sub_rows, int(n_ns), pr,
                                     pc, blk)
            # re-map member indices back to global lane numbers
            sbs = [[[sub[m] for m in members], rects]
                   for members, rects in sbs]
            if len(sbs) < len(sub):
                merged_any = True
            chip_sbs.append(sbs)
        if not merged_any:
            return None
        from ..ops.paged import PARAMS_W
        from .pages import union_table
        Gc = _pow2(max(1, max(len(s) for s in chip_sbs)))
        S_u = _pow2(max(
            (u[1] - u[0] + 1) * (u[3] - u[2] + 1)
            for sbs in chip_sbs for _m, rects in sbs for u in rects))
        tables = np.zeros((n_chips * Gc, T, S_u), np.int32)
        params = np.zeros((Np, T, PARAMS_W), np.float32)
        params[:, :, 10] = -1.0
        sb_of = np.zeros(Np, np.int32)
        total_sbs = 0
        for c, sbs in enumerate(chip_sbs):
            total_sbs += len(sbs)
            for g, (members, rects) in enumerate(sbs):
                row0 = c * Gc + g
                for t, u in enumerate(rects):
                    mem = [(rows[i][t][1],) + rows[i][t][0]
                           for i in members]
                    u_slots = union_table(mem, *u)
                    tables[row0, t, :u_slots.shape[0]] = u_slots
                for i in members:
                    sb_of[i] = g    # chip-local index
                    p16 = np.asarray(es[i].payload["params16"],
                                     np.float32)
                    params[i, :p16.shape[0]] = p16
                    for t, u in enumerate(rects):
                        params[i, t, 11] = u[0] * pr
                        params[i, t, 12] = u[2] * pc
                        params[i, t, 13] = (u[1] - u[0] + 1) * pr
                        params[i, t, 14] = (u[3] - u[2] + 1) * pc
                        params[i, t, 15] = u[3] - u[2] + 1
        planned = n_chips * Gc * T * S_u * pr * pc * 4
        if planned >= naive:
            return None
        with _LOCK:
            _STATS["superblocks"] += total_sbs
            _STATS["merged_lanes"] += N - total_sbs
            _STATS["bytes_saved"] += naive - planned
        try:
            PLAN_SUPERBLOCKS.inc(float(total_sbs))
            PLAN_BYTES_SAVED.inc(float(naive - planned))
        except Exception:  # prom telemetry only
            pass
        _note_route("ragged")
        return Plan("superblock", blk=blk, tables=tables, params=params,
                    sb_of=sb_of, superblocks=total_sbs,
                    naive_bytes=naive, planned_bytes=planned,
                    merged_lanes=N - total_sbs)
    except Exception:  # noqa: BLE001 - planning is an optimisation
        return None


def plan_stats() -> Dict:
    """The /debug "plan" block: knobs, route split and savings."""
    with _LOCK:
        return {"enabled": plan_enabled(),
                "halo_max": plan_halo_max(),
                "blocks": [f"{bh}x{bw}" for bh, bw in plan_blocks()],
                "costed_shapes": len(_COSTED),
                "superblocks": _STATS["superblocks"],
                "merged_lanes": _STATS["merged_lanes"],
                "gather_bytes_saved": _STATS["bytes_saved"],
                "groups_planned": _STATS["groups_planned"],
                "assembly_planned": _STATS["assembly_planned"],
                "routes": dict(_STATS["routes"])}


def reset_plan_state():
    """Test hook: drop the cost-model memo and counters so knob
    changes (GSKY_PLAN_BLOCKS, ledger path) re-cost."""
    global _SEEDED
    with _LOCK:
        _COSTED.clear()
        _SEEDED = False
        _STATS.update({"superblocks": 0, "merged_lanes": 0,
                       "bytes_saved": 0, "groups_planned": 0,
                       "assembly_planned": 0,
                       "routes": {"ragged": 0, "bucketed": 0}})
