"""The drill pipeline: polygon time-series statistics (WPS Execute).

Reference dataflow: DrillIndexer -> GeoDrillGRPC -> DrillMerger
(`processor/drill_pipeline.go`).  Here:

1. index: MAS ?intersects with the polygon WKT
2. fast path: crawler-precomputed means/sample_counts answer without
   touching files (`processor/drill_grpc.go:70-93`)
3. else per file: rasterize the polygon into the file grid (the
   GDALRasterizeGeometries burn, `worker/gdalprocess/drill.go:275-327`),
   read the masked window, run the banded reductions on device
   (`gsky_tpu.ops.drill`), optionally strided + interpolated
4. merge: per-date weighted means across files (weights = pixel counts,
   `processor/drill_merger.go:54-93`), then band expressions per date
   (`drill_merger.go:110-155`); decile columns become `ns_d1..9`
   namespaces (`drill_pipeline.go:72-83`)
"""

from __future__ import annotations

import math
import xml.etree.ElementTree as ET
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..geo import geometry as geom
from ..geo.crs import EPSG4326, parse_crs
from ..geo.transform import GeoTransform
from ..index.client import Dataset, MASClient
from ..index.store import fmt_time
from ..io.geotiff import GeoTIFF
from ..io.netcdf import NetCDF
from ..ops import drill as D
from ..ops.raster import nodata_mask
from .types import DrillResult, GeoDrillRequest

_BIG = 3.0e38


def split_by_years(req: "GeoDrillRequest", year_step: int):
    """Year-stepped request splitting — the TimeSplitter stage
    (`processor/date_splitter.go:19-31`): yields copies of ``req``
    covering consecutive ``year_step``-year windows of its time range
    (the last window may extend past end_time, as the reference's
    AddDate loop does).  ``year_step <= 0`` yields the request as is."""
    import dataclasses
    import datetime as _dt

    if year_step <= 0 or req.start_time is None or req.end_time is None:
        yield req
        return

    def add_years(ts: float, n: int) -> float:
        d = _dt.datetime.fromtimestamp(ts, _dt.timezone.utc)
        try:
            d = d.replace(year=d.year + n)
        except ValueError:      # Feb 29 -> Mar 1, Go AddDate behaviour
            d = d.replace(year=d.year + n, month=3, day=1)
        return d.timestamp()

    if req.start_time >= req.end_time:
        # point-in-time query: splitting has nothing to window
        yield req
        return
    t = req.start_time
    while t < req.end_time:
        # clamp: unlike the reference (which chunks an already-filtered
        # timestamp list), each window here widens a MAS query, so an
        # unclamped last window would return rows past end_time
        nxt = add_years(t, year_step)
        yield dataclasses.replace(req, start_time=t,
                                  end_time=min(nxt, req.end_time))
        t = nxt


def merge_results(parts: List["DrillResult"]) -> "DrillResult":
    """Concatenate per-window DrillResults (windows from
    `split_by_years` are disjoint, so rows merge by date sort)."""
    parts = [p for p in parts if p.dates]
    if not parts:
        return DrillResult([], {}, {}, [])
    if len(parts) == 1:
        return parts[0]
    names: List[str] = []
    for p in parts:
        for n in p.values:
            if n not in names:
                names.append(n)
    rows = {}
    counts_rows = {}
    for p in parts:
        for i, d in enumerate(p.dates):
            row = rows.setdefault(d, {})
            crow = counts_rows.setdefault(d, {})
            for n in p.values:
                row[n] = p.values[n][i]
                crow[n] = p.counts.get(n, [0] * len(p.dates))[i]
    dates = sorted(rows)
    values = {n: [rows[d].get(n, float("nan")) for d in dates]
              for n in names}
    counts = {n: [counts_rows[d].get(n, 0) for d in dates] for n in names}
    raw = sorted({n for p in parts for n in p.raw_namespaces})
    return DrillResult(dates, values, counts, raw)


class DrillPipeline:
    def __init__(self, mas: MASClient):
        self.mas = mas

    def process_split(self, req: GeoDrillRequest,
                      year_step: int = 0) -> DrillResult:
        """TimeSplitter-wired entry: split the request into year-stepped
        windows, drill each, and merge (`processor/date_splitter.go`)."""
        return merge_results([self.process(w)
                              for w in split_by_years(req, year_step)])

    def index(self, req: GeoDrillRequest) -> List[Dataset]:
        namespaces = list(req.band_exprs.var_list) \
            + [n for n in req.mask_namespaces
               if n not in req.band_exprs.var_list]
        kw = dict(srs="EPSG:4326", wkt=req.geometry_wkt,
                  namespaces=",".join(namespaces))
        if req.start_time is not None:
            kw["time"] = fmt_time(req.start_time)
        if req.end_time is not None:
            kw["until"] = fmt_time(req.end_time)
        return self.mas.intersects(req.collection, **kw)

    def process(self, req: GeoDrillRequest) -> DrillResult:
        # large-polygon tiling (`drill_indexer.go:115-137`): each tiled
        # sub-geometry runs the index + per-file reductions separately,
        # and the (namespace, date) accumulator merges them count-
        # weighted, so memory stays bounded by one tile's window.
        # Known deviation from the untiled result (shared with the
        # reference): adjacent clipped sub-polygons both ALL_TOUCHED-burn
        # the shared boundary row, so edge pixels count in two tiles and
        # the merged mean skews by O(perimeter/area)
        tiles = tiled_geometries(req.geometry_wkt,
                                 req.index_tile_x_size,
                                 req.index_tile_y_size)
        if len(tiles) > 1:
            import dataclasses
            acc: Dict[Tuple[str, float],
                      List[Tuple[float, int]]] = defaultdict(list)
            approx_seen: set = set()
            for wkt in tiles:
                sub = dataclasses.replace(req, geometry_wkt=wkt,
                                          index_tile_x_size=0.0,
                                          index_tile_y_size=0.0)
                self._drill_into(sub, acc, approx_seen)
            return _merge(acc, req)
        acc = defaultdict(list)
        self._drill_into(req, acc)
        return _merge(acc, req)

    def _drill_into(self, req: GeoDrillRequest, acc,
                    approx_seen: Optional[set] = None) -> None:
        datasets = self.index(req)
        g4326 = geom.from_wkt(req.geometry_wkt)

        mask_ds = [d for d in datasets
                   if d.namespace in set(req.mask_namespaces)]
        data_ds = [d for d in datasets if d not in mask_ds]

        for ds in data_ds:
            sel = _selected_times(ds, req)
            if not sel:
                continue
            vrt_xml = None
            if req.vrt_xml:
                # per-granule VRT rendering (`drill_indexer.go:318-346`):
                # exactly ONE temporally co-registered mask granule per
                # requested mask namespace, placed at that namespace's
                # position in req.mask_namespaces so in_ar band order is
                # stable for asymmetric pixel functions
                # (`drill_indexer.go:355-380` places maskGrans[iv] and
                # errors on duplicates)
                from ..io.vrt import render_vrt
                masks = []
                for ns in req.mask_namespaces:
                    cands = [m for m in mask_ds
                             if m.namespace == ns and _times_match(ds, m)]
                    if len(cands) > 1:
                        # the reference's group key is (polygon,
                        # timestamps): spatially tiled mask collections
                        # produce several temporal matches, of which the
                        # co-located tile is the right one
                        same_tile = [m for m in cands
                                     if m.polygon == ds.polygon]
                        if len(same_tile) == 1:
                            cands = same_tile
                    if len(cands) > 1:
                        raise ValueError(
                            f"multiple mask granules for namespace {ns!r} "
                            f"co-registered with {ds.file_path}")
                    if not cands:
                        # count mismatch is an indexer error in the
                        # reference (`drill_indexer.go:309-315`)
                        raise ValueError(
                            f"no mask granule for namespace {ns!r} "
                            f"co-registered with {ds.file_path}")
                    masks.append(cands[0].file_path)
                vrt_xml = render_vrt(req.vrt_xml, ds.file_path, masks)
            elif req.approx and ds.means and ds.sample_counts \
                    and len(ds.means) >= len(ds.timestamps):
                # crawler-stats fast path: no file IO at all.  The stats
                # are WHOLE-FILE aggregates, so under polygon tiling a
                # file spanning several tiles must contribute exactly
                # once or merged means skew toward multi-tile files
                if approx_seen is not None:
                    k = (ds.file_path, ds.ds_name, ds.namespace)
                    if k in approx_seen:
                        continue
                    approx_seen.add(k)
                for ti in sel:
                    date = ds.timestamps[ti] if ds.timestamps else 0.0
                    acc[(ds.namespace, date)].append(
                        (float(ds.means[min(ti, len(ds.means) - 1)]),
                         int(ds.sample_counts[min(ti, len(ds.sample_counts) - 1)])))
                continue
            stats = _drill_file(ds, sel, g4326, req, vrt_xml=vrt_xml)
            if stats is None:
                continue
            values, counts, deciles = stats
            for k, ti in enumerate(sel):
                date = ds.timestamps[ti] if ds.timestamps else 0.0
                acc[(ds.namespace, date)].append(
                    (float(values[k]), int(counts[k])))
                for d in range(req.deciles):
                    acc[(f"{ds.namespace}_d{d + 1}", date)].append(
                        (float(deciles[k, d]), 1))


def _geoloc_drill_mask(ds: Dataset, g4326: geom.Geometry, H: int,
                       W: int):
    """Polygon membership over a CURVILINEAR swath: every sample carries
    its own coordinates, so membership is a vectorised containment test
    on the geolocation arrays — the swath analogue of the affine
    ALL_TOUCHED burn.  Returns (mask (uint8, window-shaped), window
    (c0, r0, c1, r1) in RASTER pixels) or None when nothing matches.

    Handles the details the naive test misses: the geometry is taken in
    the geo_loc record's OWN srs (not ds.srs, which rulesets may
    override); antimeridian-crossing swaths compare on the grid's
    unwrapped longitude branch; a bbox prefilter crops the grid before
    the O(edges x samples) ray cast; geoloc line/pixel offsets+steps map
    grid indices to raster pixels (subsampled geolocation grids); and
    point/line/sub-sample-size geometries fall back to marking the
    samples nearest their vertices, so a tiny drill doesn't silently
    report "no data"."""
    from ..geo.geoloc import load_geoloc_grid
    grid = load_geoloc_grid(ds.file_path, ds.geo_loc)
    if grid is None:
        return None
    gl_srs = ds.geo_loc.get("srs") or "EPSG:4326"
    try:
        gl_crs = parse_crs(gl_srs)
        g = g4326 if gl_crs == EPSG4326 else g4326.transform(
            lambda x, y: EPSG4326.transform_to(gl_crs, x, y))
    except ValueError:
        return None
    if grid._wraps:
        # the grid longitudes live on the unwrapped [180, 360) branch
        g = g.transform(lambda x, y: (np.where(np.asarray(x) < 0.0,
                                               np.asarray(x) + 360.0,
                                               np.asarray(x)), y))

    gh, gw = grid.gx.shape
    inpoly = np.zeros((gh, gw), bool)
    if g.polys:
        b = g.bbox()
        with np.errstate(invalid="ignore"):
            box = ((grid.gx >= b.xmin) & (grid.gx <= b.xmax)
                   & (grid.gy >= b.ymin) & (grid.gy <= b.ymax))
        if box.any():
            rr = np.nonzero(box.any(axis=1))[0]
            cc = np.nonzero(box.any(axis=0))[0]
            sr, er = int(rr[0]), int(rr[-1]) + 1
            sc, ec = int(cc[0]), int(cc[-1]) + 1
            inpoly[sr:er, sc:ec] = geom.contains_mask(
                g, grid.gx[sr:er, sc:ec], grid.gy[sr:er, sc:ec])
    if not inpoly.any():
        # point/line drills and polygons smaller than sample spacing:
        # nearest-sample marking (the ALL_TOUCHED-style floor)
        pts = []
        if g.points is not None:
            pts.append(np.asarray(g.points, np.float64))
        for poly in g.polys:
            for ring in poly:
                if len(ring):
                    pts.append(np.asarray(ring, np.float64))
        if not pts:
            return None
        pts_a = np.concatenate(pts, axis=0)
        col, row = grid.invert(pts_a[:, 0], pts_a[:, 1])
        # invert() returns RASTER pixel coords; back to grid indices
        gj = np.rint((col - 0.5 - grid.pixel_offset)
                     / grid.pixel_step).astype(np.int64)
        gi = np.rint((row - 0.5 - grid.line_offset)
                     / grid.line_step).astype(np.int64)
        ok = (gi >= 0) & (gi < gh) & (gj >= 0) & (gj < gw)
        if not ok.any():
            return None
        inpoly[gi[ok], gj[ok]] = True

    rr = np.nonzero(inpoly.any(axis=1))[0]
    cc = np.nonzero(inpoly.any(axis=0))[0]
    gr0, gr1 = int(rr[0]), int(rr[-1]) + 1
    gc0, gc1 = int(cc[0]), int(cc[-1]) + 1
    # grid indices -> raster pixels via the geoloc offsets/steps; a
    # subsampled geolocation grid (pixel_step > 1) expands each sample
    # to its step-sized block of raster pixels
    ls = max(int(grid.line_step), 1)
    ps = max(int(grid.pixel_step), 1)
    r0 = int(grid.line_offset + ls * gr0)
    c0 = int(grid.pixel_offset + ps * gc0)
    sub = inpoly[gr0:gr1, gc0:gc1]
    mask = np.repeat(np.repeat(sub, ls, axis=0), ps, axis=1)
    r1 = min(r0 + mask.shape[0], H)
    c1 = min(c0 + mask.shape[1], W)
    if r0 >= r1 or c0 >= c1:
        return None
    mask = mask[:r1 - r0, :c1 - c0].astype(np.uint8)
    if not mask.any():
        return None
    return mask, (c0, r0, c1, r1)


def tiled_geometries(wkt: str, step_x: float,
                     step_y: float) -> List[str]:
    """Split an area geometry into index-tile intersections
    (`drill_indexer.go:386-520` getTiledGeometries): a grid of
    (step_x, step_y)-degree tiles over the envelope, each clipped
    against the polygon; non-area geometries and disabled steps pass
    through whole.  Degenerate output falls back to the whole
    geometry (reference behaviour on getTiledGeometries error)."""
    if step_x <= 0.0 and step_y <= 0.0:
        return [wkt]
    try:
        g = geom.from_wkt(wkt)
        if g.kind not in ("Polygon", "MultiPolygon") or g.is_empty:
            return [wkt]
        b = g.bbox()
        sx = step_x if step_x > 0 else (b.xmax - b.xmin) or 1.0
        sy = step_y if step_y > 0 else (b.ymax - b.ymin) or 1.0
        if b.xmax - b.xmin <= sx and b.ymax - b.ymin <= sy:
            return [wkt]
        from ..geo.transform import BBox as _BBox
        # integer tile counts, not float accumulation: stepping x += sx
        # emits ~1e-16-wide sliver tiles when the extent divides evenly,
        # and ALL_TOUCHED burns re-count the whole edge row for them
        nx = max(int(math.ceil((b.xmax - b.xmin) / sx - 1e-9)), 1)
        ny = max(int(math.ceil((b.ymax - b.ymin) / sy - 1e-9)), 1)
        out = []
        for iy in range(ny):
            y1 = b.ymax - iy * sy
            y0 = max(y1 - sy, b.ymin)
            for ix in range(nx):
                x0 = b.xmin + ix * sx
                x1 = min(x0 + sx, b.xmax)
                c = g.clip_bbox(_BBox(x0, y0, x1, y1))
                if not c.is_empty:
                    out.append(c.to_wkt())
        return out or [wkt]
    except Exception:
        return [wkt]


def _times_match(data: Dataset, mask: Dataset) -> bool:
    """A mask granule rides with a data granule when their timestamp
    sets overlap (or either carries none)."""
    if not data.timestamps or not mask.timestamps:
        return True
    return bool(set(data.timestamps) & set(mask.timestamps))


def _selected_times(ds: Dataset, req: GeoDrillRequest) -> List[int]:
    if not ds.timestamps:
        return [0]
    out = []
    for i, t in enumerate(ds.timestamps):
        if req.start_time is not None and t < req.start_time - 1:
            continue
        if req.end_time is not None and t > req.end_time + 1:
            continue
        out.append(i)
    return out


def _drill_file(ds: Dataset, sel: List[int], g4326: geom.Geometry,
                req: GeoDrillRequest, vrt_xml: Optional[str] = None):
    """Masked reductions for the selected bands of one file (or of a
    rendered VRT wrapping it, `drill.go:363-423`)."""
    is_vrt = bool(vrt_xml)
    is_nc = not is_vrt and not ds.ds_name.upper().startswith("GMT:") \
        and (ds.file_path.lower().endswith((".nc", ".nc4"))
             or ds.ds_name.upper().startswith("NETCDF:"))
    try:
        if is_vrt:
            from ..io.vrt import VRTRaster
            h = VRTRaster(vrt_xml)
            H, W = h.height, h.width
        elif is_nc:
            h = NetCDF(ds.file_path)
            var = ds.ds_name.split(":")[-1].strip('"')
            v = h.variables[var]
            H, W = v.shape[-2], v.shape[-1]
        else:
            from ..io.registry import open_raster
            h = open_raster(ds.file_path)
            H, W = h.height, h.width
    except (OSError, ValueError, KeyError, ET.ParseError):
        return None

    try:
        try:
            if is_vrt and h.crs is not None:
                src_crs = h.crs
            else:
                src_crs = parse_crs(ds.srs) if ds.srs else EPSG4326
            gt = h.gt if is_vrt else \
                GeoTransform.from_gdal(ds.geo_transform)
            g = g4326 if src_crs == EPSG4326 else g4326.transform(
                lambda x, y: EPSG4326.transform_to(src_crs, x, y))
        except ValueError:  # unparseable SRS / out-of-domain projection
            return None

        if getattr(ds, "geo_loc", None) and not is_vrt:
            made = _geoloc_drill_mask(ds, g4326, H, W)
            if made is None:
                return None
            mask, (c0, r0, c1, r1) = made
        else:
            # envelope intersect + ALL_TOUCHED mask burn
            b = g.bbox()
            c0, r0 = gt.geo_to_pixel(b.xmin, b.ymax)
            c1, r1 = gt.geo_to_pixel(b.xmax, b.ymin)
            c0, c1 = sorted((c0, c1))
            r0, r1 = sorted((r0, r1))
            c0 = max(int(math.floor(c0)), 0)
            r0 = max(int(math.floor(r0)), 0)
            c1 = min(int(math.ceil(c1)), W)
            r1 = min(int(math.ceil(r1)), H)
            if c0 >= c1 or r0 >= r1:
                return None
            wgt = gt.window(c0, r0)
            mask = geom.rasterize(g, c1 - c0, r1 - r0,
                                  lambda x, y: wgt.geo_to_pixel(x, y),
                                  all_touched=True)
            if not mask.any():
                return None

        # strided band reads with interpolation (`drill.go:119-214`)
        stride = max(req.band_strides, 1)
        read_idx: List[int] = []
        for s in range(0, len(sel), stride):
            e = min(s + stride, len(sel))
            read_idx.append(s)
            if e - 1 != s:
                read_idx.append(e - 1)
        read_idx = sorted(set(read_idx))

        band0 = 1
        if not is_nc and ":" in ds.ds_name \
                and ds.ds_name.rsplit(":", 1)[-1].isdigit():
            band0 = int(ds.ds_name.rsplit(":", 1)[-1])

        # device-resident stack fast path: the whole variable stack
        # lives in HBM (uploaded once per file), the window slice +
        # reductions run on device, and this request ships only the
        # polygon mask + timestep indices — KBs instead of the
        # (B, window) raster through the host link
        if not is_vrt:
            from . import drill_cache as DC
            if DC.enabled():
                try:
                    # async by default: a cold request answers from host
                    # reads while the stack uploads in the background
                    getter = DC.default_drill_cache.get if DC.sync_mode() \
                        else DC.default_drill_cache.get_async
                    st = getter(
                        ds.file_path, is_nc, var if is_nc else "", band0,
                        ds.nodata)
                    dev = _drill_device(st, sel, read_idx, mask,
                                        (c0, r0, c1, r1), req) \
                        if st is not None else None
                except Exception:
                    # any device-path failure (upload OOM, compile)
                    # degrades to host reads, not a failed request
                    dev = None
                if dev is not None:
                    vals, counts, dec = dev
                    return _maybe_interp(vals, counts, dec, read_idx,
                                         sel, stride, req)

        bands_data = []
        for k in read_idx:
            ti = sel[k]
            if is_vrt:
                data = h.read(1, (c0, r0, c1 - c0, r1 - r0),
                              time_index=ti)
                nodata = h.nodata
            elif is_nc:
                data = h.read_slice(var, ti if len(v.shape) > 2 else None,
                                    (c0, r0, c1 - c0, r1 - r0))
                nodata = ds.nodata if ds.nodata is not None else v.nodata
            else:
                # GeoTIFF granules carry one timestamp per file; the band
                # index comes from the crawler's ds_name suffix
                data = h.read(band0, (c0, r0, c1 - c0, r1 - r0))
                nodata = ds.nodata if ds.nodata is not None else h.nodata
            bands_data.append((data.astype(np.float32),
                               nodata_mask(data, nodata)))

        data = np.stack([d for d, _ in bands_data])
        valid = np.stack([m for _, m in bands_data]) & (mask[None] > 0)
        B = data.shape[0]
        vals, counts, dec = _stats_tail(data.reshape(B, -1),
                                        valid.reshape(B, -1), req)
        return _maybe_interp(vals, counts, dec, read_idx, sel, stride,
                             req)
    finally:
        h.close()


def _stats_host(dataf: np.ndarray, validf: np.ndarray,
                req: GeoDrillRequest):
    """The device reductions run in NUMPY for HOST-read window data:
    a cold drill (stack not yet device-resident) must not ship the
    (B, window) block through the device link just to reduce it — the
    reference's reductions are host-side too (`drill.go:128-220`).
    Steady-state requests still reduce on device from the resident
    stack (`_drill_device`).  Same implementation bodies as the device
    path (`ops.drill.*_impl` parameterised on the array namespace), so
    cold and warm responses cannot drift."""
    vals, counts = D.masked_mean_impl(
        dataf, validf, req.clip_lower, req.clip_upper, req.pixel_count,
        np)
    if req.deciles:
        dec = D.deciles_impl(dataf, validf, req.deciles,
                             np).astype(np.float32)
    else:
        dec = np.zeros((dataf.shape[0], 0), np.float32)
    return vals.astype(np.float32), counts.astype(np.int32), dec


def _stats_tail(dataf, validf, req: GeoDrillRequest):
    """Masked mean + deciles over (B, N) data/valid — device or host
    arrays (jnp.asarray is a no-op for resident device buffers; numpy
    inputs reduce in numpy, see `_stats_host`)."""
    if isinstance(dataf, np.ndarray):
        return _stats_host(dataf, validf, req)
    from ..mesh.dispatch import compat_spmd
    spmd = compat_spmd()
    if spmd is not None and not req.deciles:
        # mesh path (GSKY_SPMD=1 compat routing): bands over
        # `granule`, pixels over `x` + psum (deciles need a global
        # sort — those requests stay single-device)
        v, c = spmd.masked_stats(dataf, validf, req.clip_lower,
                                 req.clip_upper, req.pixel_count)
        return (np.asarray(v), np.asarray(c),
                np.zeros((dataf.shape[0], 0), np.float32))
    from ..ops.pallas_tpu import (masked_stats_pallas, pallas_interpret,
                                  run_with_fallback)

    def _via_pallas():
        # VMEM-streamed reduction kernel on TPU backends
        s, c = masked_stats_pallas(
            jnp.asarray(dataf), jnp.asarray(validf),
            req.clip_lower, req.clip_upper,
            interpret=pallas_interpret())
        c = np.asarray(c)
        v = np.where(c > 0, np.asarray(s) / np.maximum(c, 1),
                     0.0).astype(np.float32)
        return v, c

    def _via_xla():
        v, c = D.masked_mean(
            jnp.asarray(dataf), jnp.asarray(validf),
            clip_lower=req.clip_lower, clip_upper=req.clip_upper,
            pixel_count=req.pixel_count)
        return np.asarray(v), np.asarray(c)

    from .waves import default_waves, waves_enabled
    if waves_enabled():
        # wave path: concurrent drills over the same bucketed shape
        # stack into ONE (K, B, N) device reduction per scheduler tick
        # (the reduction is per-row independent, so the stacked result
        # is bit-identical to per-call); the per-call XLA leg is the
        # incident failover
        vals, counts = default_waves().drill_stats(
            dataf, validf, float(req.clip_lower),
            float(req.clip_upper), bool(req.pixel_count), _via_xla)
    elif not req.pixel_count:
        # sync_token engages the fallback guard's first-call speed race
        # too: at deep-stack shapes (1000, 16k) the pallas reduction is
        # the prime suspect for the r5 on-chip warm-drill outlier, and
        # the race demotes it automatically wherever XLA measures
        # faster.  The shape is BUCKETED (`_drill_device` pads the band
        # axis to pow2 and the window to shape buckets), so the token
        # cardinality — and with it the number of races — is bounded
        # plain-int token: the durable ledger round-trips tokens through
        # repr/literal_eval, so numpy ints must not leak in
        vals, counts = run_with_fallback(
            "masked_stats", _via_pallas, _via_xla,
            sync_token=tuple(int(d) for d in dataf.shape))
    else:
        vals, counts = _via_xla()
    if req.deciles:
        dec = np.asarray(D.deciles(jnp.asarray(dataf),
                                   jnp.asarray(validf), req.deciles))
    else:
        dec = np.zeros((dataf.shape[0], 0), np.float32)
    return vals, counts, dec


def _maybe_interp(vals, counts, dec, read_idx, sel, stride,
                  req: GeoDrillRequest):
    """Strided-endpoint interpolation of statistics (`drill.go:119-214`)."""
    if stride > 1 and len(read_idx) < len(sel):
        cols = np.concatenate([vals[:, None], dec], axis=1)
        vi, ci = D.interp_strided(cols, np.tile(counts[:, None],
                                                (1, cols.shape[1])),
                                  np.asarray(read_idx), len(sel))
        vals = vi[:, 0]
        dec = vi[:, 1:]
        counts = ci[:, 0]
    return vals, counts, dec


def _drill_device(st, sel: List[int], read_idx: List[int],
                  mask: np.ndarray, win, req: GeoDrillRequest):
    """Drill one file from its DEVICE-RESIDENT stack: upload the
    rasterized polygon mask + timestep indices (KBs), slice the window
    on device (`ops.drill.window_gather`), reduce in place.  Returns
    (values, counts, deciles) for the read_idx bands, or None when the
    window doesn't fit a padded bucket (caller falls back to host
    reads)."""
    from .executor import _bucket, _bucket_pow2

    c0, r0, c1, r1 = win
    T, H, W = st.shape
    wh, ww = r1 - r0, c1 - c0
    bh = min(_bucket(wh), H)
    bw = min(_bucket(ww), W)
    if bh < wh or bw < ww:
        return None
    # clamp the origin so the padded window stays in bounds; the mask
    # shifts by the clamp offset so pixels keep their identity
    r0c = min(r0, H - bh)
    c0c = min(c0, W - bw)
    mask_p = np.zeros((bh, bw), bool)
    mask_p[r0 - r0c:r0 - r0c + wh, c0 - c0c:c0 - c0c + ww] = mask > 0
    tsel = np.asarray([sel[k] for k in read_idx], np.int32)
    B = len(tsel)
    Bp = _bucket_pow2(B)
    tsel_p = np.pad(tsel, (0, Bp - B), mode="edge")
    # nodata compares in the stack's NATIVE dtype (parity with
    # ops.raster.nodata_mask); a nodata not representable there matches
    # nothing, exactly like the host path's dtype-promoting !=
    dtype = st.dev.dtype
    nd = st.nodata
    if np.isnan(nd):
        use_nd = np.dtype(dtype).kind == "f"
        nd_native = np.zeros((), dtype) if not use_nd \
            else np.asarray(np.nan, dtype)
        if use_nd:
            # NaN nodata: NaN != NaN, so the ~isnan term already covers
            # it — disable the equality term
            use_nd = False
    else:
        nd_native = np.asarray(nd).astype(dtype)
        use_nd = bool(np.asarray(float(nd_native) == float(nd)))
    dataf, validf = D.window_gather(
        st.dev, jnp.asarray(tsel_p), np.int32(r0c), np.int32(c0c),
        jnp.asarray(mask_p), nd_native, np.bool_(use_nd), (bh, bw))
    vals, counts, dec = _stats_tail(dataf, validf, req)
    return vals[:B], counts[:B], dec[:B]


def _merge(acc, req: GeoDrillRequest) -> DrillResult:
    """Weighted means per (namespace, date), then band expressions."""
    dates = sorted({d for (_, d) in acc})
    raw_ns = sorted({n for (n, _) in acc})
    series: Dict[str, List[float]] = {}
    counts: Dict[str, List[int]] = {}
    for ns in raw_ns:
        vs, cs = [], []
        for d in dates:
            items = acc.get((ns, d), [])
            tot = sum(c for _, c in items)
            if tot > 0:
                vs.append(sum(v * c for v, c in items) / tot)
            else:
                vs.append(float("nan"))
            cs.append(tot)
        series[ns] = vs
        counts[ns] = cs

    exprs = req.band_exprs
    out_values: Dict[str, List[float]] = {}
    out_counts: Dict[str, List[int]] = {}
    for ce, name in zip(exprs.expressions, exprs.expr_names):
        if ce._ast[0] == "var" and ce.variables[0] in series:
            out_values[name] = series[ce.variables[0]]
            out_counts[name] = counts[ce.variables[0]]
            continue
        vs, cs = [], []
        for di, d in enumerate(dates):
            env = {}
            ok = True
            cnt = 0
            for var in ce.variables:
                if var not in series or math.isnan(series[var][di]):
                    ok = False
                    break
                env[var] = np.float64(series[var][di])
                cnt = max(cnt, counts[var][di])
            if ok:
                try:
                    vs.append(float(ce(env, xp=np)))
                except ZeroDivisionError:
                    vs.append(float("nan"))
            else:
                vs.append(float("nan"))
            cs.append(cnt if ok else 0)
        out_values[name] = vs
        out_counts[name] = cs
    # decile columns pass through
    for ns in raw_ns:
        if "_d" in ns and ns not in out_values:
            out_values[ns] = series[ns]
            out_counts[ns] = counts[ns]
    return DrillResult(dates, out_values, out_counts, raw_ns)


def drill_csv(res: DrillResult, namespaces: Optional[List[str]] = None) -> str:
    """CSV rows 'date,v1,v2,...' — the WPS template payload format
    (`processor/drill_merger.go:161-171`)."""
    import datetime as dt
    ns = namespaces or list(res.values)
    lines = []
    for i, d in enumerate(res.dates):
        stamp = dt.datetime.fromtimestamp(d, dt.timezone.utc) \
            .strftime("%Y-%m-%d")
        row = [stamp]
        for n in ns:
            v = res.values.get(n, [float("nan")] * len(res.dates))[i]
            row.append("" if math.isnan(v) else f"{v:.4f}")
        lines.append(",".join(row))
    return "\n".join(lines)
