"""Stage-overlapped GetMap/GetTile hot path.

`pipeline/export.py` showed that a bounded decode -> warp -> encode
pipeline keeps every stage busy on different tiles; this module applies
the same architecture to single-tile GetMap requests, where the unit of
overlap is the REQUEST: instead of one opaque worker-thread blob per
request (index + decode + dispatch + blocking readback serialized
end-to-end), each request's render decomposes into

    plan -> index -> decode -> dispatch -> readback

stages with bounded per-stage concurrency (module-level gates sized by
GSKY_TILE_* knobs).  Concurrent requests then overlap like export
tiles do: request A's device output is in flight to the host
(`copy_to_host_async`, issued by the executor's `_prefetch` before the
dispatch gate releases) while request B occupies the dispatch slot and
request C decodes scenes — double-buffering across the request stream.
PNG/JPEG encode runs on `io/png.py`'s sized pool, off the event loop.

Byte identity with the serial path is by construction: the stages call
the SAME prep/dispatch halves (`TilePipeline.composite_prep`/
`composite_dispatch`, `_bands_prep`/`_rgba_try`/`_bands_dispatch`) the
serial fast path runs, in the same order, with the same inputs — only
the thread scheduling and readback timing differ (asserted in
tests/test_tile_pipeline.py).  `GSKY_TILE_PIPELINE=0` is the escape
hatch, read per request like the export engine's GSKY_EXPORT_PIPELINE.

Per-request stage spans land in the ``spans`` dict (seconds per stage +
queue high-water marks) and are folded into /debug's ``tile_stages``
block via `server/metrics.py::record_tile`, mirroring `record_export`.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..obs import record_span, span as obs_span
from ..resilience import check_cancel


def tile_pipeline_enabled() -> bool:
    """GSKY_TILE_PIPELINE=0 escape hatch — read per request so an
    operator can flip a live server without restart."""
    return os.environ.get("GSKY_TILE_PIPELINE", "1") != "0"


def _env_int(name: str, default: int, lo: int = 1, hi: int = 64) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return max(lo, min(hi, v))


class StageGate:
    """Bounded stage admission: a semaphore plus the telemetry the
    /debug `tile_stages` block needs — occupancy high-water (how many
    requests were at the gate when one arrived), cumulative busy
    seconds, entry count.  One gate per stage, shared by every request
    in the process, so the bounds hold across concurrent handlers."""

    def __init__(self, name: str, limit: int):
        self.name = name
        self.limit = limit
        self._sem = threading.Semaphore(limit)
        self._lock = threading.Lock()
        self.waiting = 0          # requests at the gate right now
        self.queue_max = 0        # high-water of `waiting`
        self.busy_s = 0.0
        self.entries = 0

    @contextlib.contextmanager
    def enter(self, spans: Optional[Dict] = None,
              qkey: Optional[str] = None):
        with self._lock:
            self.waiting += 1
            occupancy = self.waiting
            if occupancy > self.queue_max:
                self.queue_max = occupancy
        if spans is not None and qkey:
            # occupancy INCLUDING self, like export's qsize()+1 marks:
            # 1 means uncontended, >1 means the stage actually queued
            spans[qkey] = max(spans.get(qkey, 0), occupancy)
        self._sem.acquire()
        with self._lock:
            self.waiting -= 1
            self.entries += 1
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._sem.release()
            with self._lock:
                self.busy_s += dt

    def stats(self) -> Dict:
        with self._lock:
            return {"limit": self.limit, "waiting": self.waiting,
                    "queue_max": self.queue_max, "entries": self.entries,
                    "busy_s": round(self.busy_s, 6)}


_gates: Dict[str, StageGate] = {}
_gates_lock = threading.Lock()

# stage -> (env knob, default limit).  Decode admits several requests
# (scene loads are IO + host work and the scene cache latches dedup
# concurrent loads of one scene); dispatch stays narrow — the device
# stream is one queue, and two slots give exactly the double-buffer:
# one request's dispatch issues while the previous one's output
# transfer (started under the gate via _prefetch) drains.
_STAGES = {"decode": ("GSKY_TILE_DECODE_WORKERS", 4),
           "dispatch": ("GSKY_TILE_DISPATCH_SLOTS", 2)}


def _gate(name: str) -> StageGate:
    g = _gates.get(name)
    if g is None:
        with _gates_lock:
            g = _gates.get(name)
            if g is None:
                env, default = _STAGES[name]
                g = _gates[name] = StageGate(name, _env_int(env, default))
    return g


def reset_gates() -> None:
    """Drop the process gates so the next request re-reads the sizing
    knobs (tests; never needed on a serving path)."""
    with _gates_lock:
        _gates.clear()


def gate_stats() -> Dict:
    with _gates_lock:
        return {n: g.stats() for n, g in _gates.items()}


def _decode_stage(pipe, req, granules, spans: Dict) -> None:
    """Warm every distinct scene into the device cache under the decode
    gate.  Purely a prefetch: failures are swallowed here because the
    dispatch stage re-resolves each scene through the same cache and
    surfaces (or degrades) errors exactly as the serial path does —
    identical outcomes, just earlier, bounded, and overlapped."""
    from .export import _scene_key
    gate = _gate("decode")
    check_cancel("decode")
    t0 = time.perf_counter()
    with gate.enter(spans, "decode_queue_max"):
        seen = set()
        dst_gt = req.dst_gt()
        for g in granules:
            # per-granule: an abandoned request stops warming scenes
            # and releases the decode slot within one granule
            check_cancel("decode")
            k = _scene_key(g)
            if k in seen:
                continue
            seen.add(k)
            try:
                pipe.executor.warm_scene(g, dst_gt, req.crs,
                                         req.height, req.width)
            except Exception:  # prewarm is advisory - the render path decodes on miss
                pass
    spans["decode_s"] = spans.get("decode_s", 0.0) \
        + time.perf_counter() - t0


def _dispatch_stage(dispatch, spans: Dict):
    """Run one device dispatch under the dispatch gate.  The executor's
    render functions `_prefetch` their outputs (copy_to_host_async)
    before returning, so by the time the gate releases the
    device->host transfer is already in flight — the next request's
    dispatch overlaps this one's readback."""
    from .batcher import batching_enabled
    from .waves import waves_enabled
    from ..ingest import stats as ingest_stats
    check_cancel("dispatch")
    t0 = time.perf_counter()
    try:
        # mark the device-busy window: ranged reads running while ANY
        # dispatch is in flight count as overlapped IO in the
        # gsky_ingest_overlap_ratio gauge
        with ingest_stats.dispatch_inflight(), obs_span("tile.dispatch") as sp:
            try:
                from ..server.prewarm import compile_count
                c0 = compile_count()
            except Exception:
                compile_count, c0 = None, 0
            try:
                if batching_enabled() or waves_enabled():
                    # the batcher/wave scheduler NEEDS concurrent
                    # arrivals to coalesce into one dispatch; a narrow
                    # gate here would serialize them and defeat it, so
                    # both modes keep their own admission (wave size +
                    # brownout clamp for waves)
                    sp.set(batched=batching_enabled(),
                           waved=waves_enabled())
                    return dispatch()
                with _gate("dispatch").enter(spans, "dispatch_queue_max"):
                    # re-check AFTER the gate wait: the client may have
                    # gone away while this request queued for the slot
                    check_cancel("dispatch")
                    return dispatch()
            finally:
                if compile_count is not None:
                    sp.set(fresh_compile=compile_count() > c0)
                sp.set(queue_max=spans.get("dispatch_queue_max", 0))
    finally:
        spans["dispatch_s"] = spans.get("dispatch_s", 0.0) \
            + time.perf_counter() - t0


def _readback(dev, spans: Dict) -> np.ndarray:
    """Complete the in-flight device->host copy.  No gate: the transfer
    was started under the dispatch gate; this just blocks until the
    bytes land, which is exactly the overlap window other requests use.
    The sync runs under the device guard: hang watchdog
    (GSKY_DEVICE_HANG_S), incident classification, and the output
    integrity probe (docs/RESILIENCE.md "Device failures")."""
    check_cancel("readback")
    t0 = time.perf_counter()
    with obs_span("tile.readback") as sp:
        from .. import device_guard
        arr = device_guard.guarded_readback(
            "tile.readback", lambda: np.asarray(dev))
        sp.set(bytes=int(arr.nbytes))
    spans["readback_s"] = spans.get("readback_s", 0.0) \
        + time.perf_counter() - t0
    return arr


def render_staged(pipe, req, n_exprs: int,
                  offset: float = 0.0, scale: float = 0.0,
                  clip: float = 0.0, colour_scale: int = 0,
                  auto: bool = True,
                  stats: Optional[Dict[str, int]] = None,
                  spans: Optional[Dict] = None):
    """The staged GetMap fast path, run inside the request's worker
    thread.  Returns (kind, host_array) with kind in {"composite",
    "rgba", "planes"}, or None when the request doesn't qualify for the
    fused path — callers then fall back to the modular render exactly
    like the serial fast path does.

    Stage structure per request:
      plan      qualification + namespace/selection resolution (host)
      index     the MAS query (timed inside the prep via _timed_index)
      decode    scene warm into the device cache, bounded by the gate
      dispatch  ONE fused device dispatch, bounded; output prefetched
      readback  np.asarray completing the in-flight transfer
    """
    spans = spans if spans is not None else {}
    t0 = time.perf_counter()
    with obs_span("tile.plan") as psp:
        if n_exprs == 1:
            made = pipe.composite_prep(req, stats, spans)
        elif n_exprs == 3:
            made = pipe._bands_prep(req, n_bands=3, stats=stats,
                                    spans=spans)
        else:
            made = pipe._bands_prep(req, stats=stats, spans=spans)
        psp.set(qualified=made is not None)
    # "plan" is the prep minus the index query it contains
    spans["plan_s"] = spans.get("plan_s", 0.0) \
        + max(0.0, time.perf_counter() - t0 - spans.get("index_s", 0.0))
    if spans.get("index_s"):
        # the MAS query ran inside the prep (see _timed_index); surface
        # it as its own span, anchored to where the prep ended
        record_span("tile.index", spans["index_s"])
    if made is None:
        return None

    granules = made[0]
    with obs_span("tile.decode") as dsp:
        _decode_stage(pipe, req, granules, spans)
        dsp.set(granules=len(granules),
                queue_max=spans.get("decode_queue_max", 0))

    if n_exprs == 1:
        dev = _dispatch_stage(
            lambda: pipe.composite_dispatch(req, made, offset, scale,
                                            clip, colour_scale, auto),
            spans)
        kind = "composite"
    elif n_exprs == 3:
        granules, ns_index, out_sel = made
        dev = _dispatch_stage(
            lambda: pipe._rgba_try(req, granules, ns_index, out_sel,
                                   offset, scale, clip, colour_scale,
                                   auto),
            spans)
        kind = "rgba"
        if dev is None:
            dev = _dispatch_stage(
                lambda: pipe._bands_dispatch(req, granules, ns_index,
                                             out_sel, offset, scale,
                                             clip, colour_scale, auto),
                spans)
            kind = "planes"
    else:
        granules, ns_index, out_sel = made
        dev = _dispatch_stage(
            lambda: pipe._bands_dispatch(req, granules, ns_index,
                                         out_sel, offset, scale, clip,
                                         colour_scale, auto),
            spans)
        kind = "planes"
    if dev is None:
        return None
    return kind, _readback(dev, spans)
