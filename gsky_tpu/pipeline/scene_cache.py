"""Device-resident source-scene cache.

The reference amortises IO with a per-process GDAL block cache
(`worker/gdalprocess/warp.go:278-332`); the TPU-native analogue keeps whole
decoded scenes in HBM.  Host->device upload is the scarcest resource when
the accelerator sits behind a network link (measured ~10-40 MB/s with
~90 ms/MB serial latency), while HBM is plentiful — so each (path, band)
source raster is decoded and shipped ONCE — NaN-encoded f32, invalid
pixels pre-baked to NaN so per-dispatch validity is one isnan on the
gathered tap — and every subsequent tile request warps from the cached
device array (`ops.warp.warp_scenes_batch`) with only a ~2 KB
control-grid upload.

Eviction is LRU by device bytes.  Scenes above ``max_scene_px`` are not
cached (a one-off window read is cheaper than shipping the whole raster).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..geo.crs import CRS, parse_crs
from ..geo.transform import GeoTransform
from .types import Granule


_scene_serial = itertools.count(1)


@dataclass
class DeviceScene:
    dev: jax.Array            # (bh, bw) f32, invalid=NaN, bucket-padded
    height: int               # true rows
    width: int                # true cols
    nodata: float             # NaN when absent
    gt: GeoTransform
    crs: CRS
    # monotonic identity: downstream caches key on this instead of
    # id(dev), which can be reused after eviction/GC (stale-stack hazard)
    serial: int = field(default_factory=lambda: next(_scene_serial))

    @property
    def bucket(self) -> Tuple[int, int]:
        return self.dev.shape

    @property
    def dtype(self):
        return self.dev.dtype


def _bucket(n: int, step: int = 256) -> int:
    return max(step, (n + step - 1) // step * step)


def _put_scene(data, serial: int):
    """Shard-aware host->device upload: under mesh per-chip placement
    (GSKY_MESH_PLACE=1) the scene ships straight to its owning chip —
    the chip whose page pool will stage its pages — instead of to
    device 0 and letting jit re-shard.  Single-chip / placement-off
    keeps the plain async `device_put` unchanged."""
    try:
        from ..mesh.pools import staging_device
        dev = staging_device(serial)
    except Exception:   # pragma: no cover - mesh optional at runtime
        dev = None
    if dev is None:
        return jax.device_put(data)
    return jax.device_put(data, dev)


class SceneCache:
    def __init__(self, max_bytes: int = 2 << 30,
                 max_scene_px: int = 64 << 20):
        self._lock = threading.Lock()
        self._scenes: Dict[tuple, DeviceScene] = {}
        self._order: List[tuple] = []
        self._bytes = 0
        self._max_bytes = max_bytes
        self._max_scene_px = max_scene_px
        self._inflight: Dict[tuple, threading.Event] = {}
        self.hits = 0
        self.misses = 0
        # ranged-window routing: decline counts per key (promote-to-
        # residency once a "cold" scene turns out to be hot), plus the
        # running total of requests served through the window path
        self._route_counts: Dict[tuple, int] = {}
        self.window_routed = 0
        self.staged_loads = 0

    def _key(self, g: Granule) -> tuple:
        return (g.path, g.band, g.var_name, g.time_index)

    def _pick_level(self, g: Granule, stride: float) -> int:
        """Decimation level to cache for a request stepping ``stride``
        source pixels per dst pixel: the coarsest GeoTIFF overview that
        fits, or a power-of-two read stride for NetCDF (quantised so a
        zoom sweep shares cache entries instead of one per stride)."""
        if stride < 2.0:
            return 1
        try:
            from .decode import _handles
            h = _handles.get(g.path, g.is_netcdf)
            if g.is_netcdf:
                v = h.variables.get(g.var_name)
                H, W = (v.shape[-2], v.shape[-1]) if v is not None \
                    else (2, 2)
                lv = 1
                while lv * 2 <= stride and H // (lv * 2) >= 2 \
                        and W // (lv * 2) >= 2:
                    lv *= 2
                return lv
            best = 1
            for f, _ in h.overviews:
                if f <= stride:
                    best = f
            return best
        except Exception:
            return 1

    @staticmethod
    def _route_promote() -> int:
        import os
        try:
            return int(os.environ.get("GSKY_INGEST_WINDOW_PROMOTE", 4))
        except (TypeError, ValueError):
            return 4

    def _route_window(self, key: tuple, g: Granule, dst_bbox,
                      dst_crs) -> bool:
        """True when this request should stream through the ranged
        window path instead of forcing whole-scene residency: ingest is
        on, the scene is not (and is not becoming) resident, and the
        request footprint covers less than ``GSKY_INGEST_WINDOW_FRAC``
        of the raster.  After ``GSKY_INGEST_WINDOW_PROMOTE`` declines of
        one key the scene has proven hot and is promoted to residency."""
        try:
            from ..ingest import ingest_enabled, window_route_frac
            if not ingest_enabled():
                return False
            lim = window_route_frac()
            if lim <= 0.0:
                return False
            with self._lock:
                if key in self._scenes or key in self._inflight:
                    return False      # resident scenes always serve
            from .decode import granule_footprint_frac
            frac = granule_footprint_frac(g, dst_bbox, dst_crs)
            if frac is None or frac >= lim:
                return False
            promote = self._route_promote()
            with self._lock:
                n = self._route_counts.get(key, 0) + 1
                self._route_counts[key] = n
                if len(self._route_counts) > 4096:
                    self._route_counts.pop(next(iter(self._route_counts)))
                if 0 < promote <= n:
                    del self._route_counts[key]
                    return False      # hot after all: load it
                self.window_routed += 1
            return True
        except Exception:
            return False

    def get(self, g: Granule, stride: float = 1.0,
            dst_bbox=None, dst_crs=None) -> Optional[DeviceScene]:
        """Cached scene for a granule, decoding + uploading on first use.
        Returns None when the scene is uncacheable (too big / unreadable).
        Concurrent requests for the same scene decode once (per-key
        latch), not once per tile.

        ``stride`` (source px per dst px) selects the cached resolution:
        zoomed-out requests get the overview/decimated level — which also
        makes scenes above ``max_scene_px`` cacheable once the level
        fits (`worker/gdalprocess/warp.go:156-198`).

        ``dst_bbox``/``dst_crs`` (optional) describe the request
        footprint; with ingest on, a non-resident scene barely touched
        by the request is declined (None) so the caller's existing
        uncacheable-scene fallback serves it through ranged window
        decode instead of paying a whole-scene read + upload."""
        level = self._pick_level(g, stride)
        key = self._key(g) + (level,)
        if dst_bbox is not None and dst_crs is not None and \
                self._route_window(key, g, dst_bbox, dst_crs):
            return None
        while True:
            with self._lock:
                hit = self._scenes.get(key)
                if hit is not None:
                    self.hits += 1
                    self._order.remove(key)
                    self._order.append(key)
                    return hit
                ev = self._inflight.get(key)
                if ev is None:
                    self._inflight[key] = threading.Event()
                    self.misses += 1      # under _lock: exact counts
                    break
            ev.wait()

        scene = None
        try:
            scene = self._load(g, level)
            if scene is not None:
                nbytes = int(np.prod(scene.bucket)) * scene.dtype.itemsize
                with self._lock:
                    self._scenes[key] = scene
                    self._order.append(key)
                    self._bytes += nbytes
                    while self._bytes > self._max_bytes and \
                            len(self._order) > 1:
                        old = self._order.pop(0)
                        ev_s = self._scenes.pop(old)
                        self._bytes -= int(np.prod(ev_s.bucket)) \
                            * ev_s.dtype.itemsize
        finally:
            with self._lock:
                self._inflight.pop(key).set()
        return scene

    def clear(self) -> None:
        """Drop every resident scene (chaos/ops hook — forces the next
        request through the full decode path again).  In-flight loads
        are untouched: they re-insert under the lock when they finish."""
        with self._lock:
            self._scenes.clear()
            self._order.clear()
            self._bytes = 0

    def _staging_read(self, h, band: int, W: int, H: int, ovr,
                      nodata):
        """Decode a whole GeoTIFF scene straight into a pooled,
        page-grid-padded f32 staging buffer: one allocation, in-place
        NaN-encode, and `device_put` ships the same memory (zero
        intermediate copies).  Returns (buf, pool) or (None, None) for
        the classic path.  Only sources whose f32 cast is value-exact
        (f32, and int/uint ≤ 16 bit with an f32-exact nodata) stage —
        anything else would change the nodata compare and break the
        GSKY_INGEST=0 byte-identity contract."""
        try:
            from ..ingest import ingest_enabled
            from ..io.geotiff import GeoTIFF
            if not ingest_enabled() or not isinstance(h, GeoTIFF):
                return None, None
            dt = h.dtype
            exact = (dt.kind == "f" and dt.itemsize == 4) or \
                (dt.kind in "iu" and dt.itemsize <= 2)
            if not exact:
                return None, None
            if nodata is not None:
                ndf = float(nodata)
                if not (np.isnan(ndf) or float(np.float32(ndf)) == ndf):
                    return None, None
            from ..ingest.staging import default_staging_pool
            pool = default_staging_pool()
            buf = pool.acquire(_bucket(H), _bucket(W))
            try:
                h.read(band, (0, 0, W, H), ifd=ovr, out=buf[:H, :W])
            except Exception:
                pool.release(buf)
                return None, None
            return buf, pool
        except Exception:
            return None, None

    def _load(self, g: Granule, level: int = 1) -> Optional[DeviceScene]:
        from .decode import _handles
        gt = GeoTransform.from_gdal(g.geo_transform)
        sbuf = spool = None
        try:
            from ..resilience import faults
            faults.inject("decode")
            h = _handles.get(g.path, g.is_netcdf)
            if g.is_netcdf:
                v = h.variables.get(g.var_name)
                if v is None:
                    return None
                H, W = v.shape[-2], v.shape[-1]
                st = level if level > 1 and H // level >= 2 \
                    and W // level >= 2 else 1
                if (H // st) * (W // st) > self._max_scene_px:
                    return None
                Ho, Wo = H // st, W // st
                data = h.read_slice(g.var_name, g.time_index,
                                    (0, 0, Wo * st, Ho * st), step=st)
                if st > 1:
                    gt = gt.decimated(st)
                nodata = g.nodata if g.nodata is not None else v.nodata
            else:
                W, H = h.width, h.height
                ovr = None
                if level > 1 and getattr(h, "overviews", ()):
                    fx, fy, ovr = h.pick_overview(float(level))
                if ovr is not None:
                    gt = gt.scaled(fx, fy)
                    W, H = ovr.width, ovr.height
                if H * W > self._max_scene_px:
                    return None
                nodata = g.nodata if g.nodata is not None else h.nodata
                sbuf, spool = self._staging_read(h, g.band, W, H, ovr,
                                                 nodata)
                if sbuf is not None:
                    data = None
                elif ovr is not None:
                    data = h.read(g.band, (0, 0, W, H), ifd=ovr)
                else:
                    # no ifd kwarg here: the registry read contract is
                    # plain read(band, window) — handles that don't
                    # declare an ifd kwarg (HDF4) raised TypeError into
                    # the except below and were silently uncacheable,
                    # falling back to the window path every render
                    data = h.read(g.band, (0, 0, W, H))
        except Exception as e:
            # "uncacheable" must stay a degradation, never a crash — but
            # it must also be VISIBLE: a signature drift in a handle's
            # read() once hid here as a silent slow path for the format
            import logging
            logging.getLogger("gsky.scene_cache").warning(
                "scene uncacheable, window-path fallback: %s (%s: %s)",
                g.path, type(e).__name__, e)
            return None
        crs = parse_crs(g.srs) if g.srs else None
        if crs is None:
            if sbuf is not None:
                spool.release(sbuf)
            return None
        nd = float(nodata) if nodata is not None else float("nan")
        from ..ingest import stats as _istats
        if sbuf is not None:
            # staged load: the buffer IS the scene — encode in place,
            # ship it, and cool it in the pool until the async upload
            # completes (recycling under an in-flight DMA would corrupt
            # the resident scene)
            from ..ops.raster import nodata_mask
            view = sbuf[:H, :W]
            if not np.isnan(nd):
                valid = nodata_mask(view, nd)
                valid &= np.isfinite(view)
                view[~valid] = np.nan
            serial = next(_scene_serial)
            dev = _put_scene(sbuf, serial)
            spool.release(sbuf, dev)
            _istats.record_whole(H * W * h.dtype.itemsize)
            with self._lock:
                self.staged_loads += 1
            return DeviceScene(dev=dev, height=H, width=W,
                               nodata=float("nan"), gt=gt, crs=crs,
                               serial=serial)
        _istats.record_whole(data.nbytes)
        true_h, true_w = data.shape
        # NaN-encode ONCE at load: invalid pixels (nodata / non-finite)
        # become NaN in an f32 scene, so every later dispatch's validity
        # is a single isnan on the gathered tap — no per-dispatch
        # full-scene dtype cast or nodata compare on any backend.  The
        # f32 precision equals what the kernels always computed in
        # (the old path cast per dispatch); memory is 2x an int16 scene,
        # paid from the same LRU byte budget.
        from ..ops.raster import nodata_mask
        if data.dtype != np.float32 or not np.isnan(nd):
            # (f32 + NaN-nodata sources are already in encoded form —
            # skip three full-scene host passes on that common case)
            valid = nodata_mask(data, nd if not np.isnan(nd) else None)
            data = data.astype(np.float32)
            # inf (incl. f64 overflowing the f32 cast) is invalid too,
            # so the documented "validity == ~isnan" invariant holds
            valid &= np.isfinite(data)
            data[~valid] = np.nan
        bh, bw = _bucket(true_h), _bucket(true_w)
        if (bh, bw) != data.shape:
            pad = np.full((bh, bw), np.nan, np.float32)
            pad[:true_h, :true_w] = data
            data = pad
        # device_put, not jnp.asarray: the async host->device upload
        # returns immediately with the transfer in flight, so the
        # loading thread (the staged tile path's decode stage) moves on
        # to the next scene while DMA drains; the first kernel that
        # consumes the scene synchronizes.  nbytes accounting is exact
        # either way: the cache charges bucket dims x itemsize, which
        # is precisely the committed device allocation.
        serial = next(_scene_serial)
        dev = _put_scene(data, serial)
        return DeviceScene(dev=dev, height=true_h, width=true_w,
                           nodata=float("nan"), gt=gt, crs=crs,
                           serial=serial)


# module-level default (shared across pipelines/requests)
default_scene_cache = SceneCache()
