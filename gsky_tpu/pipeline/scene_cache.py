"""Device-resident source-scene cache.

The reference amortises IO with a per-process GDAL block cache
(`worker/gdalprocess/warp.go:278-332`); the TPU-native analogue keeps whole
decoded scenes in HBM.  Host->device upload is the scarcest resource when
the accelerator sits behind a network link (measured ~10-40 MB/s with
~90 ms/MB serial latency), while HBM is plentiful — so each (path, band)
source raster is decoded and shipped ONCE — NaN-encoded f32, invalid
pixels pre-baked to NaN so per-dispatch validity is one isnan on the
gathered tap — and every subsequent tile request warps from the cached
device array (`ops.warp.warp_scenes_batch`) with only a ~2 KB
control-grid upload.

Eviction is LRU by device bytes.  Scenes above ``max_scene_px`` are not
cached (a one-off window read is cheaper than shipping the whole raster).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..geo.crs import CRS, parse_crs
from ..geo.transform import GeoTransform
from .types import Granule


_scene_serial = itertools.count(1)


@dataclass
class DeviceScene:
    dev: jax.Array            # (bh, bw) f32, invalid=NaN, bucket-padded
    height: int               # true rows
    width: int                # true cols
    nodata: float             # NaN when absent
    gt: GeoTransform
    crs: CRS
    # monotonic identity: downstream caches key on this instead of
    # id(dev), which can be reused after eviction/GC (stale-stack hazard)
    serial: int = field(default_factory=lambda: next(_scene_serial))

    @property
    def bucket(self) -> Tuple[int, int]:
        return self.dev.shape

    @property
    def dtype(self):
        return self.dev.dtype


def _bucket(n: int, step: int = 256) -> int:
    return max(step, (n + step - 1) // step * step)


class SceneCache:
    def __init__(self, max_bytes: int = 2 << 30,
                 max_scene_px: int = 64 << 20):
        self._lock = threading.Lock()
        self._scenes: Dict[tuple, DeviceScene] = {}
        self._order: List[tuple] = []
        self._bytes = 0
        self._max_bytes = max_bytes
        self._max_scene_px = max_scene_px
        self._inflight: Dict[tuple, threading.Event] = {}
        self.hits = 0
        self.misses = 0

    def _key(self, g: Granule) -> tuple:
        return (g.path, g.band, g.var_name, g.time_index)

    def _pick_level(self, g: Granule, stride: float) -> int:
        """Decimation level to cache for a request stepping ``stride``
        source pixels per dst pixel: the coarsest GeoTIFF overview that
        fits, or a power-of-two read stride for NetCDF (quantised so a
        zoom sweep shares cache entries instead of one per stride)."""
        if stride < 2.0:
            return 1
        try:
            from .decode import _handles
            h = _handles.get(g.path, g.is_netcdf)
            if g.is_netcdf:
                v = h.variables.get(g.var_name)
                H, W = (v.shape[-2], v.shape[-1]) if v is not None \
                    else (2, 2)
                lv = 1
                while lv * 2 <= stride and H // (lv * 2) >= 2 \
                        and W // (lv * 2) >= 2:
                    lv *= 2
                return lv
            best = 1
            for f, _ in h.overviews:
                if f <= stride:
                    best = f
            return best
        except Exception:
            return 1

    def get(self, g: Granule,
            stride: float = 1.0) -> Optional[DeviceScene]:
        """Cached scene for a granule, decoding + uploading on first use.
        Returns None when the scene is uncacheable (too big / unreadable).
        Concurrent requests for the same scene decode once (per-key
        latch), not once per tile.

        ``stride`` (source px per dst px) selects the cached resolution:
        zoomed-out requests get the overview/decimated level — which also
        makes scenes above ``max_scene_px`` cacheable once the level
        fits (`worker/gdalprocess/warp.go:156-198`)."""
        level = self._pick_level(g, stride)
        key = self._key(g) + (level,)
        while True:
            with self._lock:
                hit = self._scenes.get(key)
                if hit is not None:
                    self.hits += 1
                    self._order.remove(key)
                    self._order.append(key)
                    return hit
                ev = self._inflight.get(key)
                if ev is None:
                    self._inflight[key] = threading.Event()
                    self.misses += 1      # under _lock: exact counts
                    break
            ev.wait()

        scene = None
        try:
            scene = self._load(g, level)
            if scene is not None:
                nbytes = int(np.prod(scene.bucket)) * scene.dtype.itemsize
                with self._lock:
                    self._scenes[key] = scene
                    self._order.append(key)
                    self._bytes += nbytes
                    while self._bytes > self._max_bytes and \
                            len(self._order) > 1:
                        old = self._order.pop(0)
                        ev_s = self._scenes.pop(old)
                        self._bytes -= int(np.prod(ev_s.bucket)) \
                            * ev_s.dtype.itemsize
        finally:
            with self._lock:
                self._inflight.pop(key).set()
        return scene

    def clear(self) -> None:
        """Drop every resident scene (chaos/ops hook — forces the next
        request through the full decode path again).  In-flight loads
        are untouched: they re-insert under the lock when they finish."""
        with self._lock:
            self._scenes.clear()
            self._order.clear()
            self._bytes = 0

    def _load(self, g: Granule, level: int = 1) -> Optional[DeviceScene]:
        from .decode import _handles
        gt = GeoTransform.from_gdal(g.geo_transform)
        try:
            from ..resilience import faults
            faults.inject("decode")
            h = _handles.get(g.path, g.is_netcdf)
            if g.is_netcdf:
                v = h.variables.get(g.var_name)
                if v is None:
                    return None
                H, W = v.shape[-2], v.shape[-1]
                st = level if level > 1 and H // level >= 2 \
                    and W // level >= 2 else 1
                if (H // st) * (W // st) > self._max_scene_px:
                    return None
                Ho, Wo = H // st, W // st
                data = h.read_slice(g.var_name, g.time_index,
                                    (0, 0, Wo * st, Ho * st), step=st)
                if st > 1:
                    gt = gt.decimated(st)
                nodata = g.nodata if g.nodata is not None else v.nodata
            else:
                W, H = h.width, h.height
                ovr = None
                if level > 1 and getattr(h, "overviews", ()):
                    fx, fy, ovr = h.pick_overview(float(level))
                if ovr is not None:
                    gt = gt.scaled(fx, fy)
                    W, H = ovr.width, ovr.height
                if H * W > self._max_scene_px:
                    return None
                if ovr is not None:
                    data = h.read(g.band, (0, 0, W, H), ifd=ovr)
                else:
                    # no ifd kwarg here: the registry read contract is
                    # plain read(band, window) — handles that don't
                    # declare an ifd kwarg (HDF4) raised TypeError into
                    # the except below and were silently uncacheable,
                    # falling back to the window path every render
                    data = h.read(g.band, (0, 0, W, H))
                nodata = g.nodata if g.nodata is not None else h.nodata
        except Exception as e:
            # "uncacheable" must stay a degradation, never a crash — but
            # it must also be VISIBLE: a signature drift in a handle's
            # read() once hid here as a silent slow path for the format
            import logging
            logging.getLogger("gsky.scene_cache").warning(
                "scene uncacheable, window-path fallback: %s (%s: %s)",
                g.path, type(e).__name__, e)
            return None
        crs = parse_crs(g.srs) if g.srs else None
        if crs is None:
            return None
        nd = float(nodata) if nodata is not None else float("nan")
        true_h, true_w = data.shape
        # NaN-encode ONCE at load: invalid pixels (nodata / non-finite)
        # become NaN in an f32 scene, so every later dispatch's validity
        # is a single isnan on the gathered tap — no per-dispatch
        # full-scene dtype cast or nodata compare on any backend.  The
        # f32 precision equals what the kernels always computed in
        # (the old path cast per dispatch); memory is 2x an int16 scene,
        # paid from the same LRU byte budget.
        from ..ops.raster import nodata_mask
        if data.dtype != np.float32 or not np.isnan(nd):
            # (f32 + NaN-nodata sources are already in encoded form —
            # skip three full-scene host passes on that common case)
            valid = nodata_mask(data, nd if not np.isnan(nd) else None)
            data = data.astype(np.float32)
            # inf (incl. f64 overflowing the f32 cast) is invalid too,
            # so the documented "validity == ~isnan" invariant holds
            valid &= np.isfinite(data)
            data[~valid] = np.nan
        bh, bw = _bucket(true_h), _bucket(true_w)
        if (bh, bw) != data.shape:
            pad = np.full((bh, bw), np.nan, np.float32)
            pad[:true_h, :true_w] = data
            data = pad
        # device_put, not jnp.asarray: the async host->device upload
        # returns immediately with the transfer in flight, so the
        # loading thread (the staged tile path's decode stage) moves on
        # to the next scene while DMA drains; the first kernel that
        # consumes the scene synchronizes.  nbytes accounting is exact
        # either way: the cache charges bucket dims x itemsize, which
        # is precisely the committed device allocation.
        dev = jax.device_put(data)
        return DeviceScene(dev=dev, height=true_h, width=true_w,
                           nodata=float("nan"), gt=gt, crs=crs)


# module-level default (shared across pipelines/requests)
default_scene_cache = SceneCache()
