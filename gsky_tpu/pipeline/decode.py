"""Granule window decoding: the host-side IO stage feeding the TPU.

Plays the role of the reference's GDAL subprocess reads
(`worker/gdalprocess/warp.go:89-101` + block IO `:259-345`): for each
granule, work out which source window the dst tile's gather footprint
touches, read only that window (GeoTIFF tile/strip subset or NetCDF
hyperslab), and hand back float32 + validity.  Reads run in a thread pool
(decode releases the GIL in zlib/h5py) — the analogue of the process pool
(`worker/gdalprocess/pool.go`), without needing crash isolation since
there's no C library state to corrupt.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..geo.crs import CRS, parse_crs
from ..geo.transform import BBox, GeoTransform, transform_bbox
from ..io.geotiff import GeoTIFF
from ..io.netcdf import NetCDF
from ..ops.raster import nodata_mask
from .types import Granule


@dataclass
class DecodedWindow:
    granule: Granule
    data: np.ndarray          # (h, w) float32
    valid: np.ndarray         # (h, w) bool
    window_gt: GeoTransform   # georeferencing of the window
    src_crs: CRS


class _HandleCache:
    """Open-file handle cache (the expensive part of GDAL open that
    band_query exists to avoid is amortised here)."""

    def __init__(self, max_handles: int = 64):
        self._lock = threading.Lock()
        self._handles: Dict[str, object] = {}
        self._order: List[str] = []
        self._opening: Dict[str, threading.Event] = {}
        self._max = max_handles

    def get(self, path: str, is_netcdf: bool):
        # per-path open latch: concurrent callers for the same path wait
        # for the first opener instead of each paying the (expensive)
        # duplicate open and closing the loser afterwards
        while True:
            with self._lock:
                h = self._handles.get(path)
                if h is not None:
                    return h
                ev = self._opening.get(path)
                if ev is None:
                    ev = self._opening[path] = threading.Event()
                    break
            # opener in flight: wait, then re-check (a set() without a
            # cached handle means the open failed — retry it ourselves)
            ev.wait()
        try:
            # non-NetCDF granules resolve through the format registry
            # (GeoTIFF fast path, GMT grids, adapter tier) — the GDALOpen
            # driver-dispatch role (`worker/gdalprocess/warp.go:89-101`)
            from ..io.registry import open_raster
            h = NetCDF(path) if is_netcdf else open_raster(path)
        except BaseException:
            with self._lock:
                self._opening.pop(path, None)
            ev.set()
            raise
        with self._lock:
            self._opening.pop(path, None)
            if path in self._handles:
                # unreachable with the latch, but keeps the invariant
                # under any future insertion path: close the loser
                try:
                    h.close()
                except Exception:  # loser handle may already be closed
                    pass
                h = self._handles[path]
            else:
                self._handles[path] = h
                self._order.append(path)
                while len(self._order) > self._max:
                    old = self._order.pop(0)
                    try:
                        self._handles.pop(old).close()
                    except Exception:  # evicted handle may already be closed
                        pass
        ev.set()
        return h


_handles = _HandleCache()
_geoloc_skips = 0

# cumulative windows actually READ (post intersection/geoloc filtering):
# the export planner's decode-dedup accounting counts these, and the
# one-decode-per-(path, band, window) acceptance test asserts on them
_counter_lock = threading.Lock()
window_reads = 0


def _count_read() -> None:
    global window_reads
    with _counter_lock:
        window_reads += 1


def _ingest_source(path: str):
    """ByteSource for a granule when ranged ingest is on (None → the
    classic whole-file handle read).  Never raises — any source failure
    degrades to the plain path."""
    try:
        from ..ingest import ingest_enabled
        if not ingest_enabled():
            return None
        from ..ingest.source import source_for
        return source_for(path)
    except Exception:
        return None


def _read_tiff(h, band: int, win, ifd, path: str) -> np.ndarray:
    """GeoTIFF window read, ranged when a ByteSource is available.

    The ranged leg reuses the exact decode/assembly code of the plain
    leg (`GeoTIFF.read(source=...)` only swaps how raw block bytes are
    fetched), so output is byte-identical by construction; any ranged
    failure falls back to the handle read and is counted."""
    from ..ingest import stats as _istats
    src = _ingest_source(path) if isinstance(h, GeoTIFF) else None
    if src is not None:
        try:
            out = h.read(band, win, ifd=ifd, source=src)
            _istats.record_ranged_window()
            return out
        except Exception:
            _istats.record_fallback()
    out = h.read(band, win, ifd=ifd) if ifd is not None else h.read(band, win)
    _istats.record_whole(out.nbytes)
    return out


def _read_nc(h, var_name: str, time_index, win, step: int,
             path: str) -> np.ndarray:
    """NetCDF hyperslab read, ranged (NetCDF-3 row byte-ranges) when a
    ByteSource is available; HDF5-backed files always take the handle
    path (h5py owns chunk decode)."""
    from ..ingest import stats as _istats
    src = _ingest_source(path) if getattr(h, "_nc3", None) is not None else None
    if src is not None:
        try:
            out = h.read_slice_source(var_name, src, time_index, win,
                                      step=step)
            _istats.record_ranged_window()
            return out
        except Exception:
            _istats.record_fallback()
    out = h.read_slice(var_name, time_index, win, step=step)
    _istats.record_whole(out.nbytes)
    return out


def granule_footprint_frac(granule: Granule, dst_bbox: BBox,
                           dst_crs: CRS) -> Optional[float]:
    """Fraction of the granule's raster the dst footprint touches
    (0..1), or None when it can't be computed (callers treat None as
    "assume full").  Drives the scene cache's window-vs-residency
    routing: tiny footprints stream through ranged window decode
    instead of forcing a whole-scene load."""
    if granule.geo_loc:
        return None
    try:
        src_crs = parse_crs(granule.srs) if granule.srs else dst_crs
        gt = GeoTransform.from_gdal(granule.geo_transform)
        src_bbox = transform_bbox(dst_bbox, dst_crs, src_crs)
        h = _handles.get(granule.path, granule.is_netcdf)
        if granule.is_netcdf:
            v = h.variables.get(granule.var_name)
            if v is None:
                return None
            H, W = v.shape[-2], v.shape[-1]
        else:
            W, H = h.width, h.height
        if not W or not H:
            return None
        win = _pixel_window(gt, src_bbox, W, H, margin=3)
        if win is None:
            return 0.0
        return (win[2] * win[3]) / float(W * H)
    except Exception:
        return None


def margin_for(resample: str) -> int:
    return {"near": 1, "nearest": 1, "bilinear": 2, "cubic": 3}.get(resample, 2)


def dst_stride_px(gt: GeoTransform, src_bbox: BBox,
                  dst_hw: Optional[Tuple[int, int]]) -> float:
    """Source pixels stepped per destination pixel for this request —
    the quantity GDAL's warper derives to select an overview level
    (`worker/gdalprocess/warp.go:156-198`).  Conservative (min of the
    two axes) so the chosen level always meets the finer axis."""
    if dst_hw is None:
        return 1.0
    th, tw = dst_hw
    if not tw or not th or not gt.dx or not gt.dy:
        return 1.0
    sx = abs(src_bbox.width / gt.dx) / tw
    sy = abs(src_bbox.height / gt.dy) / th
    return max(1.0, min(sx, sy))


def decode_window(granule: Granule, dst_bbox: BBox, dst_crs: CRS,
                  resample: str = "near",
                  dst_hw: Optional[Tuple[int, int]] = None
                  ) -> Optional[DecodedWindow]:
    """Read the source window covering dst_bbox (+ resample margin).
    Returns None when the granule doesn't intersect the tile.

    With ``dst_hw`` = (height, width) of the destination tile, zoomed-out
    requests read from the coarsest sufficient overview (GeoTIFF pyramid
    IFDs) or a strided hyperslab (NetCDF) instead of full resolution —
    `worker/gdalprocess/warp.go:156-198`."""
    if granule.geo_loc:
        # curvilinear granules have no affine pixel grid; they render
        # through the scene path's geolocation-array ctrl inversion
        # (executor._geoloc_ctrl) on every route — fused, modular/mask
        # (tile.render's gl split), and remote (the worker's geoloc
        # warp branch).  Reaching THIS window decode with a geoloc
        # granule means a caller missed that routing; log loudly, the
        # granule degrades to empty
        global _geoloc_skips
        _geoloc_skips += 1
        if _geoloc_skips <= 10 or _geoloc_skips % 1000 == 0:
            import logging
            logging.getLogger("gsky.decode").warning(
                "curvilinear granule %s skipped on the windowed decode "
                "path (renders only via the scene path; skip #%d)",
                granule.path, _geoloc_skips)
        return None
    from ..resilience import faults
    faults.inject("decode")
    src_crs = parse_crs(granule.srs) if granule.srs else dst_crs
    gt = GeoTransform.from_gdal(granule.geo_transform)
    try:
        src_bbox = transform_bbox(dst_bbox, dst_crs, src_crs)
    except ValueError:
        return None

    margin = margin_for(resample)
    h = _handles.get(granule.path, granule.is_netcdf)
    stride = dst_stride_px(gt, src_bbox, dst_hw)
    if granule.is_netcdf:
        v = h.variables.get(granule.var_name)
        if v is None:
            return None
        H, W = v.shape[-2], v.shape[-1]
        st = int(stride) if stride >= 2.0 else 1
        if st > 1 and (H // st < 2 or W // st < 2):
            st = 1
        if st > 1:
            Ho, Wo = H // st, W // st
            gt_ov = gt.decimated(st)
            win = _pixel_window(gt_ov, src_bbox, Wo, Ho, margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = _read_nc(h, granule.var_name, granule.time_index,
                            (c0 * st, r0 * st, w * st, ww * st),
                            st, granule.path)
            gt = gt_ov
            win = (c0, r0, w, ww)
        else:
            win = _pixel_window(gt, src_bbox, W, H, margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = _read_nc(h, granule.var_name, granule.time_index,
                            (c0, r0, w, ww), 1, granule.path)
        nodata = granule.nodata if granule.nodata is not None else v.nodata
    else:
        W, H = h.width, h.height
        fx = fy = 1.0
        ovr = None
        if stride >= 2.0 and h.overviews:
            fx, fy, ovr = h.pick_overview(stride)
        if ovr is not None:
            gt_ov = gt.scaled(fx, fy)
            win = _pixel_window(gt_ov, src_bbox, ovr.width, ovr.height,
                                margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = _read_tiff(h, granule.band, (c0, r0, w, ww), ovr,
                              granule.path)
            gt = gt_ov
        else:
            win = _pixel_window(gt, src_bbox, W, H, margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = _read_tiff(h, granule.band, (c0, r0, w, ww), None,
                              granule.path)
        nodata = granule.nodata if granule.nodata is not None else h.nodata
    window_gt = gt.window(win[0], win[1])
    valid = nodata_mask(data, nodata)
    _count_read()
    return DecodedWindow(granule, data.astype(np.float32), valid,
                         window_gt, src_crs)


def _pixel_window(gt: GeoTransform, bbox: BBox, W: int, H: int,
                  margin: int) -> Optional[Tuple[int, int, int, int]]:
    import math
    c0, r0 = gt.geo_to_pixel(bbox.xmin, bbox.ymax)
    c1, r1 = gt.geo_to_pixel(bbox.xmax, bbox.ymin)
    c0, c1 = sorted((c0, c1))
    r0, r1 = sorted((r0, r1))
    c0 = max(int(math.floor(c0)) - margin, 0)
    r0 = max(int(math.floor(r0)) - margin, 0)
    c1 = min(int(math.ceil(c1)) + margin, W)
    r1 = min(int(math.ceil(r1)) + margin, H)
    if c0 >= c1 or r0 >= r1:
        return None
    return c0, r0, c1 - c0, r1 - r0


def decode_all(granules: List[Granule], dst_bbox: BBox, dst_crs: CRS,
               resample: str = "near", workers: int = 8,
               dst_hw: Optional[Tuple[int, int]] = None,
               errors: Optional[List[Exception]] = None
               ) -> List[Optional[DecodedWindow]]:
    """Decode all granule windows concurrently, preserving order.

    A ``None`` slot means EITHER the granule doesn't intersect the tile
    (normal) OR its decode raised; pass ``errors`` to collect the raised
    exceptions so callers can apply the partial-failure policy
    (``resilience.check_partial``) without conflating the two.
    """
    if not granules:
        return []
    with cf.ThreadPoolExecutor(min(workers, len(granules))) as ex:
        return list(ex.map(
            lambda g: _safe_decode(g, dst_bbox, dst_crs, resample, dst_hw,
                                   errors),
            granules))


def _safe_decode(g, dst_bbox, dst_crs, resample, dst_hw=None, errors=None):
    try:
        return decode_window(g, dst_bbox, dst_crs, resample, dst_hw)
    except Exception as e:
        # failures degrade to an empty granule, not a failed request
        # (EmptyTile sentinel behaviour, `tile_indexer.go:106,211,307`)
        if errors is not None:
            errors.append(e)
        return None
