"""Granule window decoding: the host-side IO stage feeding the TPU.

Plays the role of the reference's GDAL subprocess reads
(`worker/gdalprocess/warp.go:89-101` + block IO `:259-345`): for each
granule, work out which source window the dst tile's gather footprint
touches, read only that window (GeoTIFF tile/strip subset or NetCDF
hyperslab), and hand back float32 + validity.  Reads run in a thread pool
(decode releases the GIL in zlib/h5py) — the analogue of the process pool
(`worker/gdalprocess/pool.go`), without needing crash isolation since
there's no C library state to corrupt.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..geo.crs import CRS, parse_crs
from ..geo.transform import BBox, GeoTransform, transform_bbox
from ..io.geotiff import GeoTIFF
from ..io.netcdf import NetCDF
from ..ops.raster import nodata_mask
from .types import Granule


@dataclass
class DecodedWindow:
    granule: Granule
    data: np.ndarray          # (h, w) float32
    valid: np.ndarray         # (h, w) bool
    window_gt: GeoTransform   # georeferencing of the window
    src_crs: CRS


class _HandleCache:
    """Open-file handle cache (the expensive part of GDAL open that
    band_query exists to avoid is amortised here)."""

    def __init__(self, max_handles: int = 64):
        self._lock = threading.Lock()
        self._handles: Dict[str, object] = {}
        self._order: List[str] = []
        self._max = max_handles

    def get(self, path: str, is_netcdf: bool):
        with self._lock:
            h = self._handles.get(path)
            if h is not None:
                return h
        # non-NetCDF granules resolve through the format registry
        # (GeoTIFF fast path, GMT grids, adapter tier) — the GDALOpen
        # driver-dispatch role (`worker/gdalprocess/warp.go:89-101`)
        from ..io.registry import open_raster
        h = NetCDF(path) if is_netcdf else open_raster(path)
        with self._lock:
            if path in self._handles:
                h.close()
                return self._handles[path]
            self._handles[path] = h
            self._order.append(path)
            while len(self._order) > self._max:
                old = self._order.pop(0)
                try:
                    self._handles.pop(old).close()
                except Exception:
                    pass
        return h


_handles = _HandleCache()
_geoloc_skips = 0

# cumulative windows actually READ (post intersection/geoloc filtering):
# the export planner's decode-dedup accounting counts these, and the
# one-decode-per-(path, band, window) acceptance test asserts on them
_counter_lock = threading.Lock()
window_reads = 0


def _count_read() -> None:
    global window_reads
    with _counter_lock:
        window_reads += 1


def margin_for(resample: str) -> int:
    return {"near": 1, "nearest": 1, "bilinear": 2, "cubic": 3}.get(resample, 2)


def dst_stride_px(gt: GeoTransform, src_bbox: BBox,
                  dst_hw: Optional[Tuple[int, int]]) -> float:
    """Source pixels stepped per destination pixel for this request —
    the quantity GDAL's warper derives to select an overview level
    (`worker/gdalprocess/warp.go:156-198`).  Conservative (min of the
    two axes) so the chosen level always meets the finer axis."""
    if dst_hw is None:
        return 1.0
    th, tw = dst_hw
    if not tw or not th or not gt.dx or not gt.dy:
        return 1.0
    sx = abs(src_bbox.width / gt.dx) / tw
    sy = abs(src_bbox.height / gt.dy) / th
    return max(1.0, min(sx, sy))


def decode_window(granule: Granule, dst_bbox: BBox, dst_crs: CRS,
                  resample: str = "near",
                  dst_hw: Optional[Tuple[int, int]] = None
                  ) -> Optional[DecodedWindow]:
    """Read the source window covering dst_bbox (+ resample margin).
    Returns None when the granule doesn't intersect the tile.

    With ``dst_hw`` = (height, width) of the destination tile, zoomed-out
    requests read from the coarsest sufficient overview (GeoTIFF pyramid
    IFDs) or a strided hyperslab (NetCDF) instead of full resolution —
    `worker/gdalprocess/warp.go:156-198`."""
    if granule.geo_loc:
        # curvilinear granules have no affine pixel grid; they render
        # through the scene path's geolocation-array ctrl inversion
        # (executor._geoloc_ctrl) on every route — fused, modular/mask
        # (tile.render's gl split), and remote (the worker's geoloc
        # warp branch).  Reaching THIS window decode with a geoloc
        # granule means a caller missed that routing; log loudly, the
        # granule degrades to empty
        global _geoloc_skips
        _geoloc_skips += 1
        if _geoloc_skips <= 10 or _geoloc_skips % 1000 == 0:
            import logging
            logging.getLogger("gsky.decode").warning(
                "curvilinear granule %s skipped on the windowed decode "
                "path (renders only via the scene path; skip #%d)",
                granule.path, _geoloc_skips)
        return None
    from ..resilience import faults
    faults.inject("decode")
    src_crs = parse_crs(granule.srs) if granule.srs else dst_crs
    gt = GeoTransform.from_gdal(granule.geo_transform)
    try:
        src_bbox = transform_bbox(dst_bbox, dst_crs, src_crs)
    except ValueError:
        return None

    margin = margin_for(resample)
    h = _handles.get(granule.path, granule.is_netcdf)
    stride = dst_stride_px(gt, src_bbox, dst_hw)
    if granule.is_netcdf:
        v = h.variables.get(granule.var_name)
        if v is None:
            return None
        H, W = v.shape[-2], v.shape[-1]
        st = int(stride) if stride >= 2.0 else 1
        if st > 1 and (H // st < 2 or W // st < 2):
            st = 1
        if st > 1:
            Ho, Wo = H // st, W // st
            gt_ov = gt.decimated(st)
            win = _pixel_window(gt_ov, src_bbox, Wo, Ho, margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = h.read_slice(granule.var_name, granule.time_index,
                                (c0 * st, r0 * st, w * st, ww * st),
                                step=st)
            gt = gt_ov
            win = (c0, r0, w, ww)
        else:
            win = _pixel_window(gt, src_bbox, W, H, margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = h.read_slice(granule.var_name, granule.time_index,
                                (c0, r0, w, ww))
        nodata = granule.nodata if granule.nodata is not None else v.nodata
    else:
        W, H = h.width, h.height
        fx = fy = 1.0
        ovr = None
        if stride >= 2.0 and h.overviews:
            fx, fy, ovr = h.pick_overview(stride)
        if ovr is not None:
            gt_ov = gt.scaled(fx, fy)
            win = _pixel_window(gt_ov, src_bbox, ovr.width, ovr.height,
                                margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = h.read(granule.band, (c0, r0, w, ww), ifd=ovr)
            gt = gt_ov
        else:
            win = _pixel_window(gt, src_bbox, W, H, margin)
            if win is None:
                return None
            c0, r0, w, ww = win
            data = h.read(granule.band, (c0, r0, w, ww))
        nodata = granule.nodata if granule.nodata is not None else h.nodata
    window_gt = gt.window(win[0], win[1])
    valid = nodata_mask(data, nodata)
    _count_read()
    return DecodedWindow(granule, data.astype(np.float32), valid,
                         window_gt, src_crs)


def _pixel_window(gt: GeoTransform, bbox: BBox, W: int, H: int,
                  margin: int) -> Optional[Tuple[int, int, int, int]]:
    import math
    c0, r0 = gt.geo_to_pixel(bbox.xmin, bbox.ymax)
    c1, r1 = gt.geo_to_pixel(bbox.xmax, bbox.ymin)
    c0, c1 = sorted((c0, c1))
    r0, r1 = sorted((r0, r1))
    c0 = max(int(math.floor(c0)) - margin, 0)
    r0 = max(int(math.floor(r0)) - margin, 0)
    c1 = min(int(math.ceil(c1)) + margin, W)
    r1 = min(int(math.ceil(r1)) + margin, H)
    if c0 >= c1 or r0 >= r1:
        return None
    return c0, r0, c1 - c0, r1 - r0


def decode_all(granules: List[Granule], dst_bbox: BBox, dst_crs: CRS,
               resample: str = "near", workers: int = 8,
               dst_hw: Optional[Tuple[int, int]] = None,
               errors: Optional[List[Exception]] = None
               ) -> List[Optional[DecodedWindow]]:
    """Decode all granule windows concurrently, preserving order.

    A ``None`` slot means EITHER the granule doesn't intersect the tile
    (normal) OR its decode raised; pass ``errors`` to collect the raised
    exceptions so callers can apply the partial-failure policy
    (``resilience.check_partial``) without conflating the two.
    """
    if not granules:
        return []
    with cf.ThreadPoolExecutor(min(workers, len(granules))) as ex:
        return list(ex.map(
            lambda g: _safe_decode(g, dst_bbox, dst_crs, resample, dst_hw,
                                   errors),
            granules))


def _safe_decode(g, dst_bbox, dst_crs, resample, dst_hw=None, errors=None):
    try:
        return decode_window(g, dst_bbox, dst_crs, resample, dst_hw)
    except Exception as e:
        # failures degrade to an empty granule, not a failed request
        # (EmptyTile sentinel behaviour, `tile_indexer.go:106,211,307`)
        if errors is not None:
            errors.append(e)
        return None
