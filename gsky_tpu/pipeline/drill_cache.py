"""Device-resident drill stack cache.

The drill hot loop (`worker/gdalprocess/drill.go:128-220`) reads the
polygon window of every selected timestep from disk per request; on a
tunneled TPU the dominant cost is shipping that (B, window) block to the
device — ~64 MB for the 1000-step benchmark, i.e. seconds of link time
per request.  The TPU-native answer mirrors `pipeline.scene_cache`: the
WHOLE variable stack (T, H, W) uploads once in its native dtype and
stays in HBM; each drill request then ships only a rasterized polygon
mask and a timestep index vector (KBs), and the window slice + masked
reductions run on device (`ops.drill.window_gather`).

Eviction is LRU by device bytes.  Stacks above ``max_item_bytes`` are
not cached (one-off window reads through the host path are cheaper than
pinning HBM on them); 64-bit stacks are not cached either, because the
upload would silently downcast (x64 is off in production) and break
nodata parity with the host path.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

_stack_serial = itertools.count(1)


@dataclass
class DeviceStack:
    dev: object               # jax (T, H, W) native dtype
    nodata: float             # NaN when absent
    serial: int = field(default_factory=lambda: next(_stack_serial))

    @property
    def shape(self):
        return self.dev.shape

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.dev.shape)) * self.dev.dtype.itemsize


class DrillStackCache:
    def __init__(self, max_bytes: int = 4 << 30,
                 max_item_bytes: int = 1 << 30,
                 max_negative: int = 4096,
                 max_background_loads: int = 2):
        self._lock = threading.Lock()
        # bound on concurrent get_async loader threads: a cold drill
        # over a many-file collection must not fan out one full-raster
        # load (+ host buffer + upload) per file at once — unscheduled
        # misses stay on the host path and retry on a later request
        self._bg_slots = threading.BoundedSemaphore(max_background_loads)
        self._stacks: Dict[tuple, DeviceStack] = {}
        self._order: List[tuple] = []
        self._bytes = 0
        self._max_bytes = max_bytes
        self._max_item = max_item_bytes
        # permanently-uncacheable keys (too big / wrong dtype), bounded;
        # transient load errors are NOT recorded, so they retry
        self._neg: Dict[tuple, None] = {}
        self._max_neg = max_negative
        self._inflight: Dict[tuple, threading.Event] = {}
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(path: str, var_name: str, band0: int,
             nodata: Optional[float]):
        """(key, mtime) or None when the file can't be stat'd.  NaN
        can't be a dict-key component (NaN != NaN would miss every
        hit); absent/NaN nodata normalises to a sentinel."""
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            return None
        nd_key = "nan" if nodata is None or \
            (isinstance(nodata, float) and np.isnan(nodata)) \
            else float(nodata)
        return (path, mtime, var_name, band0, nd_key), mtime

    def get(self, path: str, is_nc: bool, var_name: str, band0: int,
            nodata: Optional[float]) -> Optional[DeviceStack]:
        """Cached (T, H, W) stack for one file variable/band, uploading
        on first use (BLOCKING until the upload lands).  None when
        uncacheable (too big, 64-bit, or unreadable — unreadable retries
        next request).  Concurrent first requests load once.  ``nodata``
        is part of the identity: two collections indexing the same file
        with different overrides get distinct (correct) masks."""
        made = self._key(path, var_name, band0, nodata)
        if made is None:
            return None
        key, mtime = made
        while True:
            with self._lock:
                hit = self._stacks.get(key)
                if hit is not None:
                    self.hits += 1
                    self._order.remove(key)
                    self._order.append(key)
                    return hit
                if key in self._neg:
                    # a cached negative answer is a hit of the cache's
                    # decision, not an uncounted branch
                    self.hits += 1
                    return None
                ev = self._inflight.get(key)
                if ev is None:
                    self._inflight[key] = threading.Event()
                    self.misses += 1      # under _lock: exact counts
                    break
            ev.wait()
        return self._load_into(key, mtime, path, is_nc, var_name, band0,
                               nodata)

    def get_async(self, path: str, is_nc: bool, var_name: str,
                  band0: int,
                  nodata: Optional[float]) -> Optional[DeviceStack]:
        """Resident stack, or None immediately — scheduling a
        background load on a first miss so a LATER request hits.  The
        cold request then runs at host-read speed instead of blocking
        on a multi-second stack upload through the device link (the
        cfg5 cold-path fix): first drill ~= the CPU baseline, steady
        state on-device."""
        made = self._key(path, var_name, band0, nodata)
        if made is None:
            return None
        key, mtime = made
        with self._lock:
            hit = self._stacks.get(key)
            if hit is not None:
                self.hits += 1
                self._order.remove(key)
                self._order.append(key)
                return hit
            if key in self._neg:
                self.hits += 1
                return None
            if key in self._inflight:
                return None          # load already on its way
            if not self._bg_slots.acquire(blocking=False):
                return None          # loader pool saturated: retry later
            self._inflight[key] = threading.Event()
            self.misses += 1

        def load_and_release():
            try:
                self._load_into(key, mtime, path, is_nc, var_name,
                                band0, nodata)
            finally:
                self._bg_slots.release()

        threading.Thread(target=load_and_release,
                         name="gsky-drill-upload", daemon=True).start()
        return None

    def wait_idle(self, timeout_s: float = 60.0) -> bool:
        """Block until no loads are in flight (benches/tests separating
        cold from warm).  True when idle within the timeout."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                evs = list(self._inflight.values())
            if not evs:
                return True
            for ev in evs:
                if not ev.wait(max(deadline - time.monotonic(), 0.0)):
                    return False

    def clear(self) -> None:
        """Drop every resident stack (bench cold-path measurement)."""
        with self._lock:
            self._stacks.clear()
            self._order.clear()
            self._neg.clear()
            self._bytes = 0

    def _load_into(self, key, mtime, path, is_nc, var_name, band0,
                   nodata) -> Optional[DeviceStack]:
        """Load + insert under the inflight latch taken by the caller."""
        stack = None
        permanent_no = False
        try:
            stack, permanent_no = self._load(path, is_nc, var_name,
                                             band0, nodata)
            with self._lock:
                if stack is not None:
                    # a new mtime supersedes older entries for the file
                    for old in [k for k in self._order
                                if k[0] == path and k[1] != mtime]:
                        self._order.remove(old)
                        self._bytes -= self._stacks.pop(old).nbytes
                    self._stacks[key] = stack
                    self._order.append(key)
                    self._bytes += stack.nbytes
                    while self._bytes > self._max_bytes and \
                            len(self._order) > 1:
                        old = self._order.pop(0)
                        self._bytes -= self._stacks.pop(old).nbytes
                elif permanent_no:
                    if len(self._neg) >= self._max_neg:
                        self._neg.pop(next(iter(self._neg)))
                    self._neg[key] = None
        finally:
            with self._lock:
                self._inflight.pop(key).set()
        return stack

    def _load(self, path: str, is_nc: bool, var_name: str, band0: int,
              nodata: Optional[float]):
        """(stack or None, permanently_uncacheable)."""
        import jax.numpy as jnp

        from .decode import _handles
        try:
            h = _handles.get(path, is_nc)
            if is_nc:
                v = h.variables.get(var_name)
                if v is None:
                    return None, True
                itemsize = np.dtype(v.dtype).itemsize
                if itemsize > 4:
                    return None, True   # would downcast on upload
                if len(v.shape) == 2:
                    T, (H, W) = 1, v.shape
                else:
                    T, H, W = v.shape[0], v.shape[-2], v.shape[-1]
                nd = nodata if nodata is not None else v.nodata
                if T * H * W * itemsize > self._max_item:
                    return None, True
                if len(v.shape) <= 3:
                    data = np.asarray(v[:])
                    if data.ndim == 2:
                        data = data[None]
                else:   # rank 4: (t, level0, y, x) per-timestep reads
                    data = np.stack([
                        h.read_slice(var_name, t, (0, 0, W, H))
                        for t in range(T)])
            else:
                W, H = h.width, h.height
                ifd = getattr(h, "ifd", None)
                if ifd is not None:
                    from ..io.geotiff import T_BITS
                    bits = ifd.arr(T_BITS) or (32,)
                    itemsize = max(int(bits[0]) // 8, 1)
                else:       # registry handle (GMT/adapter)
                    itemsize = np.dtype(
                        getattr(h, "dtype", np.float32)).itemsize
                if itemsize > 4:
                    return None, True
                nd = nodata if nodata is not None else h.nodata
                if H * W * itemsize > self._max_item:
                    return None, True
                data = h.read(band0, (0, 0, W, H))[None]
            if data.dtype.itemsize > 4:
                return None, True
            # the device upload itself stays inside the try: a full HBM
            # (RESOURCE_EXHAUSTED) must degrade to host reads, not kill
            # the request — and must retry later (transient)
            dev = jnp.asarray(data)
        except Exception:
            return None, False
        return DeviceStack(dev=dev,
                           nodata=float(nd) if nd is not None
                           else float("nan")), False


# module-level default (shared across requests); anything CPU-bound can
# disable via GSKY_DRILL_CACHE=0; GSKY_DRILL_CACHE=sync restores the
# blocking first-request upload (deterministic paths for tests)
def enabled() -> bool:
    return os.environ.get("GSKY_DRILL_CACHE", "1") != "0"


def sync_mode() -> bool:
    return os.environ.get("GSKY_DRILL_CACHE", "1") == "sync"


default_drill_cache = DrillStackCache()
