"""The TPU warp executor: shape-bucketed batched gather dispatch.

Replaces the reference's per-granule worker RPC fan-out
(`processor/tile_grpc.go:219-242` + the C warp loop) with one XLA dispatch
per (source-shape bucket, method): source windows are padded up to a small
set of shapes so recompilation is bounded (SURVEY §7 "padded shape
buckets"), coordinates are computed once per (dst grid, src CRS) in f64 on
host and only the cheap affine part is per-granule.
"""

from __future__ import annotations

import math
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..geo.crs import CRS
from ..geo.transform import GeoTransform
from ..ops.paged import PARAMS_W as PAGED_PARAMS_W
from ..ops.paged import paged_enabled
from ..ops.pallas_tpu import render_byte_raced, warp_scored_raced
from ..ops.warp import (combine_scored, render_scenes_bands_ctrl,
                        warp_gather_batch)
from ..mesh.dispatch import compat_spmd
from .decode import DecodedWindow

# padded source-window shape buckets (H and W independently bucketed)
_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096)


def _prefetch(x):
    """Start the device->host copy of a TERMINAL result now, without
    blocking: the caller's eventual np.asarray overlaps with other
    requests' transfers instead of serialising per-buffer (measured on
    the tunneled link: ~80 ms per cold 64 KB pull serial, ~10 ms with
    copies in flight)."""
    try:
        x.copy_to_host_async()
    except Exception:  # backend lacks copy_to_host_async (CPU) - sync pull still works
        pass
    return x


def _bucket_in(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return int(math.ceil(n / 4096) * 4096)


def _bucket(n: int) -> int:
    return _bucket_in(n, _BUCKETS)


def _bucket_pow2(n: int, lo: int = 1) -> int:
    """Next power of two >= n (batch-count and namespace-count padding so
    jit specialisations stay bounded)."""
    b = lo
    while b < n:
        b *= 2
    return b


def _window_mode() -> bool:
    """Gather-window gate (GSKY_WARP_WINDOW): '1' on, '0' off, default
    'auto' = on for TPU-like backends only.  XLA's TPU gather lowering
    costs proportional to the SOURCE extent, so slicing the tile's
    footprint window out of the scene stack before the gather is the
    difference between ~13 ms and ~1 ms per 256-px tile over 2048-px
    scenes; on CPU the gather is a per-tap scalar loop and the slice is
    pure overhead."""
    v = os.environ.get("GSKY_WARP_WINDOW", "auto")
    if v == "0":
        return False
    if v == "1":
        return True
    from ..ops.pallas_tpu import tpu_like_backend
    return tpu_like_backend()


_WIN_MARGIN = 2  # covers cubic's +2 tap and f32-vs-f64 coord rounding

# gather-window sizes get a DENSER bucket list than the decode-path
# shape buckets: a 300-px footprint over a 512-px scene must land in a
# 384 window, not bucket up to the whole scene and decline.  Still a
# bounded set (jit variants per (win_h, win_w) pair), just finer.
_WIN_BUCKETS = (64, 96, 128, 192, 256, 384, 512, 768, 1024, 1536,
                2048, 3072, 4096)


def _win_bucket(n: int) -> int:
    return _bucket_in(n, _WIN_BUCKETS)


def _granule_bounds(p: np.ndarray, cx: np.ndarray, cy: np.ndarray):
    """Raw gather-footprint bounds (r_lo, r_hi, c_lo, c_hi) of ONE
    granule's param row, or None when the granule has no finite coords
    (nothing to gather).  Exactness: the dense device coords are the
    bilinear interpolation of the ctrl-point coords with the affine
    applied — affine commutes with interpolation, so the dense extremes
    are bounded by the affine evaluated at the ctrl points, computed
    here in f64.  The same margin rules serve `_gather_window` (bucketed
    windows) and `_paged_from_group` (page-grid coverage), so the two
    paths gather the same taps."""
    # clamp to the kernel's oob thresholds (coords past the true
    # extent are NaN-poisoned on device and never gathered): a tile
    # straddling a scene edge must not inflate the footprint to its
    # off-scene extent and lose the window
    cols = np.clip(p[0] + p[1] * cx + p[2] * cy - 0.5, -1.0, p[7])
    rows = np.clip(p[3] + p[4] * cx + p[5] * cy - 0.5, -1.0, p[6])
    ok = np.isfinite(rows) & np.isfinite(cols)
    if not ok.any():
        return None
    rmin = float(rows[ok].min())
    rmax = float(rows[ok].max())
    cmin = float(cols[ok].min())
    cmax = float(cols[ok].max())
    r_lo = math.floor(rmin) - _WIN_MARGIN
    c_lo = math.floor(cmin) - _WIN_MARGIN
    # high edge gets one extra pixel: the device recomputes coords in
    # f32, which can land just past the f64 bound and bump floor() by
    # one, pushing cubic's +2 tap one past _WIN_MARGIN
    r_hi = math.floor(rmax) + _WIN_MARGIN + 2
    c_hi = math.floor(cmax) + _WIN_MARGIN + 2
    return r_lo, r_hi, c_lo, c_hi


def _gather_window(params64: np.ndarray, cx: np.ndarray, cy: np.ndarray,
                   bucket_h: int, bucket_w: int):
    """(win, win0) covering every granule's finite gather footprint, or
    None when windowing can't help (footprint ~ scene, or no finite
    coords).

    params64: (B, 11) f64 granule params (ns_id < 0 rows are padding);
    cx/cy: host ctrl coords (gh, gw), possibly NaN."""
    r_lo = c_lo = None
    r_hi = c_hi = None
    for p in params64:
        if p[10] < 0:
            continue
        made = _granule_bounds(p, cx, cy)
        if made is None:
            continue
        if r_lo is None:
            r_lo, r_hi, c_lo, c_hi = made
        else:
            r_lo = min(r_lo, made[0])
            r_hi = max(r_hi, made[1])
            c_lo = min(c_lo, made[2])
            c_hi = max(c_hi, made[3])
    if r_lo is None:
        return None
    made = finish_window(r_lo, r_hi, c_lo, c_hi, bucket_h, bucket_w)
    if made is None:
        return None
    win, win0 = made
    # raw (unpadded, unclamped) bounds ride along so batch flushes can
    # union footprints BEFORE bucketing (unioning padded windows would
    # overshoot a bucket and decline needlessly)
    return win, win0, (r_lo, r_hi, c_lo, c_hi)


def finish_window(r_lo: int, r_hi: int, c_lo: int, c_hi: int,
                  bucket_h: int, bucket_w: int):
    """Bucket raw footprint bounds into (win, win0), or None when the
    window would be the whole stack — the ONE place the bucket /
    decline / origin-clamp rules live (`_gather_window` and the
    batcher's union flush both finish through here)."""
    wr = min(_win_bucket(r_hi - r_lo), bucket_h)
    wc = min(_win_bucket(c_hi - c_lo), bucket_w)
    if wr >= bucket_h and wc >= bucket_w:
        return None
    r0 = min(max(r_lo, 0), bucket_h - wr)
    c0 = min(max(c_lo, 0), bucket_w - wc)
    return (wr, wc), np.array([r0, c0], np.int32)


def _dev_win0(win0):
    return None if win0 is None else jnp.asarray(win0)


def _inv_gt_params(gt: GeoTransform, ox: float, oy: float):
    """Origin-folded inverse geotransform (src-CRS coords relative to
    (ox, oy) -> granule pixel): the 6-tuple every scene kernel takes in
    params[:6] — col = p0 + p1*sx + p2*sy, row = p3 + p4*sx + p5*sy."""
    det = gt.dx * gt.dy - gt.rx * gt.ry
    inv = (gt.dy / det, -gt.rx / det, -gt.ry / det, gt.dx / det)
    a0 = inv[0] * (ox - gt.x0) + inv[1] * (oy - gt.y0)
    a3 = inv[2] * (ox - gt.x0) + inv[3] * (oy - gt.y0)
    return (a0, inv[0], inv[1], a3, inv[2], inv[3])


class WarpExecutor:
    """Batches decoded granule windows into device dispatches."""

    # LRU bounds, not clear-alls: a burst of distinct tiles must evict
    # the oldest entries, not dump the whole working set (a clear causes
    # a recompute/re-upload storm exactly when traffic is heaviest)
    _GEO_CACHE_MAX = 256
    _STACK_CACHE_MAX = 32
    # per-granule scalar strides get their own (much larger) map: one
    # tiny entry per granule geotransform must not flush the multi-MB
    # projection grids out of the 256-slot LRU above
    _STRIDE_CACHE_MAX = 8192

    def __init__(self):
        self._geo_cache: OrderedDict = OrderedDict()
        self._stack_cache: OrderedDict = OrderedDict()
        self._stride_cache: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        # dispatch counters by (path, shape bucket) — the /debug
        # side-door's "where do renders actually go" answer
        self.bucket_stats: Dict[str, int] = {}
        # gather-window engagement (window mode on): groups that got a
        # window vs groups that declined (footprint ~ scene / no coords)
        self.win_engaged = 0
        self.win_declined = 0
        # paged-path engagement (GSKY_PAGED on): dispatches served from
        # the page pool vs declined back to buckets (page budget / pool
        # pressure / multi-CRS)
        self.paged_engaged = 0
        self.paged_declined = 0
        from .batcher import RenderBatcher
        self._batcher = RenderBatcher()
        # a device RESOURCE_EXHAUSTED shrinks the coalesce knee before
        # the guard's one-shot retry (docs/RESILIENCE.md)
        from ..device_guard import register_oom_hook
        register_oom_hook(self._batcher.note_oom)

    def _note_win(self, win) -> None:
        """Engagement telemetry, recorded at the dispatches that
        actually pass ``win`` to a kernel (the batcher branch drops the
        window and must not count as engaged)."""
        if not _window_mode():
            return
        with self._lock:
            if win is not None:
                self.win_engaged += 1
            else:
                self.win_declined += 1

    def _count(self, path: str, bucket=None) -> None:
        key = f"{path}:{bucket}" if bucket is not None else path
        with self._lock:
            self.bucket_stats[key] = self.bucket_stats.get(key, 0) + 1

    def _geo_cache_get(self, key):
        with self._lock:
            hit = self._geo_cache.get(key)
            if hit is not None:
                self._geo_cache.move_to_end(key)
            return hit

    def _geo_cache_put(self, key, value):
        with self._lock:
            self._geo_cache[key] = value
            self._geo_cache.move_to_end(key)
            while len(self._geo_cache) > self._GEO_CACHE_MAX:
                self._geo_cache.popitem(last=False)

    def _dst_geo_coords(self, dst_gt: GeoTransform, dst_crs: CRS,
                        height: int, width: int,
                        src_crs: CRS) -> Tuple[np.ndarray, np.ndarray]:
        """(sx, sy): dst pixel centres projected into src CRS, cached —
        the projection math is shared by every granule in that CRS (the
        expensive part of `coord_grid`)."""
        key = (dst_gt.to_gdal(), dst_crs, height, width, src_crs)
        hit = self._geo_cache_get(key)
        if hit is not None:
            return hit
        c = np.arange(width, dtype=np.float64) + 0.5
        r = np.arange(height, dtype=np.float64) + 0.5
        C, R = np.meshgrid(c, r)
        x, y = dst_gt.pixel_to_geo(C, R, np)
        sx, sy = dst_crs.transform_to(src_crs, x, y, np)
        sx = np.asarray(sx, np.float64)
        sy = np.asarray(sy, np.float64)
        self._geo_cache_put(key, (sx, sy))
        return sx, sy

    def _ctrl_geo_coords(self, dst_gt: GeoTransform, dst_crs: CRS,
                         height: int, width: int, src_crs: CRS,
                         step: int) -> Tuple[np.ndarray, np.ndarray, int]:
        """Sparse control-point grid: dst pixel centres at every
        ``step``-th row/col projected into src CRS (f64, host).  The
        dense grid is reconstructed on device (`ops.warp._bilerp_grid`),
        GDAL-approx-transformer style, so only ~2 KB of coordinates are
        uploaded per tile.

        Like GDAL's approx transformer (0.125 px error bound,
        `worker/gdalprocess/warp.go:219`), the grid is validated once
        per cache entry against exactly projected cell midpoints; the
        step halves until the interpolation error is within bound (so
        strongly nonlinear transforms — polar CRSs — refine instead of
        silently smearing).  Returns (sx, sy, actual_step)."""
        key = ("ctrl", dst_gt.to_gdal(), dst_crs, height, width, src_crs,
               step)
        hit = self._geo_cache_get(key)
        if hit is not None:
            return hit
        while True:
            gh = (height - 1 + step - 1) // step + 1
            gw = (width - 1 + step - 1) // step + 1
            c = np.arange(gw, dtype=np.float64) * step + 0.5
            r = np.arange(gh, dtype=np.float64) * step + 0.5
            C, R = np.meshgrid(c, r)
            x, y = dst_gt.pixel_to_geo(C, R, np)
            sx, sy = dst_crs.transform_to(src_crs, x, y, np)
            sx = np.asarray(sx, np.float64)
            sy = np.asarray(sy, np.float64)
            if step <= 2 or self._ctrl_err_px(
                    sx, sy, dst_gt, dst_crs, src_crs, step) <= 0.125:
                break
            step //= 2
        self._geo_cache_put(key, (sx, sy, step))
        return sx, sy, step

    @staticmethod
    def _ctrl_err_px(sx: np.ndarray, sy: np.ndarray, dst_gt: GeoTransform,
                     dst_crs: CRS, src_crs: CRS, step: int) -> float:
        """Max bilinear-interpolation error of the ctrl grid at cell
        midpoints, in units of local source-coords-per-dst-pixel."""
        gh, gw = sx.shape
        if gh < 2 or gw < 2:
            return 0.0
        c = (np.arange(gw - 1, dtype=np.float64) + 0.5) * step + 0.5
        r = (np.arange(gh - 1, dtype=np.float64) + 0.5) * step + 0.5
        C, R = np.meshgrid(c, r)
        x, y = dst_gt.pixel_to_geo(C, R, np)
        ex, ey = dst_crs.transform_to(src_crs, x, y, np)
        ix = 0.25 * (sx[:-1, :-1] + sx[:-1, 1:] + sx[1:, :-1]
                     + sx[1:, 1:])
        iy = 0.25 * (sy[:-1, :-1] + sy[:-1, 1:] + sy[1:, :-1]
                     + sy[1:, 1:])
        du = np.hypot(sx[:-1, 1:] - sx[:-1, :-1],
                      sy[:-1, 1:] - sy[:-1, :-1]) / step
        dv = np.hypot(sx[1:, :-1] - sx[:-1, :-1],
                      sy[1:, :-1] - sy[:-1, :-1]) / step
        scale = np.maximum(np.maximum(du, dv), 1e-12)
        with np.errstate(invalid="ignore"):
            px = np.hypot(np.asarray(ex) - ix, np.asarray(ey) - iy) / scale
        if not px.size or np.all(np.isnan(px)):
            return 0.0
        return float(np.nanmax(px))

    def _granule_stride(self, g, dst_gt: GeoTransform, dst_crs: CRS,
                        height: int, width: int) -> float:
        """Source pixels stepped per dst pixel for a granule under this
        request — drives overview-level selection in the scene cache
        (`worker/gdalprocess/warp.go:156-198`).  Reuses the cached ctrl
        grid, so the cost after the first call per (dst, src CRS) is a
        few medians."""
        from ..geo.crs import parse_crs
        try:
            key = (dst_gt.to_gdal(), dst_crs, height, width,
                   g.srs, tuple(g.geo_transform or ()))
            with self._lock:
                hit = self._stride_cache.get(key)
                if hit is not None:
                    self._stride_cache.move_to_end(key)
                    return hit
            src_crs = parse_crs(g.srs) if g.srs else None
            if src_crs is None:
                return 1.0
            sx, sy, step = self._ctrl_geo_coords(dst_gt, dst_crs, height,
                                                 width, src_crs, 16)
            ggt = GeoTransform.from_gdal(g.geo_transform)
            col, row = ggt.geo_to_pixel(sx, sy, np)
            with np.errstate(invalid="ignore"):
                dr = np.nanmedian(np.abs(np.diff(row, axis=0))) / step
                dc = np.nanmedian(np.abs(np.diff(col, axis=1))) / step
            stride = min(float(dr), float(dc))
            stride = stride if np.isfinite(stride) and stride > 1.0 \
                else 1.0
            with self._lock:
                self._stride_cache[key] = stride
                while len(self._stride_cache) > self._STRIDE_CACHE_MAX:
                    self._stride_cache.popitem(last=False)
            return stride
        except Exception:
            return 1.0

    def warm_scene(self, g, dst_gt: GeoTransform, dst_crs: CRS,
                   height: int, width: int, cache=None):
        """Decode + upload one granule's scene into the device cache at
        the overview level this destination grid needs, returning the
        `DeviceScene` or None (uncacheable).  The export engine's decode
        stage calls this ahead of the warp stage so `warp_mosaic_scenes`
        hits a warm cache; the stride logic is exactly `_scene_groups`'
        so both pick the same cache level."""
        from .scene_cache import default_scene_cache
        cache = cache or default_scene_cache
        stride = 1.0 if g.geo_loc else self._granule_stride(
            g, dst_gt, dst_crs, height, width)
        return cache.get(g, stride,
                         dst_bbox=dst_gt.bbox(width, height),
                         dst_crs=dst_crs)

    def warp_all(self, windows: Sequence[Optional[DecodedWindow]],
                 dst_gt: GeoTransform, dst_crs: CRS, height: int, width: int,
                 method: str = "near") -> List[Optional[Tuple[np.ndarray, np.ndarray]]]:
        """Warp every decoded window onto the dst grid.  Returns, per
        input, (data (H,W) f32, ok (H,W) bool) or None."""
        jobs: List[Tuple[int, DecodedWindow, np.ndarray, np.ndarray]] = []
        for i, wdw in enumerate(windows):
            if wdw is None:
                continue
            sx, sy = self._dst_geo_coords(dst_gt, dst_crs, height, width,
                                          wdw.src_crs)
            col, row = wdw.window_gt.geo_to_pixel(sx, sy, np)
            jobs.append((i, wdw, (row - 0.5).astype(np.float32),
                         (col - 0.5).astype(np.float32)))

        results: List[Optional[Tuple[np.ndarray, np.ndarray]]] = \
            [None] * len(windows)
        # bucket by padded source shape
        buckets: Dict[Tuple[int, int], List] = {}
        for job in jobs:
            h, w = job[1].data.shape
            buckets.setdefault((_bucket(h), _bucket(w)), []).append(job)

        for (bh, bw), batch in buckets.items():
            B = _bucket_pow2(len(batch))  # pow2 pad: bounded jit variants
            self._count("window_batch", (bh, bw, B))
            src = np.zeros((B, bh, bw), np.float32)
            valid = np.zeros((B, bh, bw), bool)
            rows = np.full((B, height, width), -1e6, np.float32)
            cols = np.full((B, height, width), -1e6, np.float32)
            for k, j in enumerate(batch):
                rows[k] = j[2]
                cols[k] = j[3]
            for k, (_, wdw, _, _) in enumerate(batch):
                h, w = wdw.data.shape
                src[k, :h, :w] = wdw.data
                valid[k, :h, :w] = wdw.valid
            out, ok = warp_gather_batch(
                jnp.asarray(src), jnp.asarray(valid),
                jnp.asarray(rows), jnp.asarray(cols), method)
            # results stay ON DEVICE (lazy per-granule slices); downstream
            # mosaic/expr/scale stages consume them without a host round
            # trip — critical when the device sits behind a network tunnel
            # where every sync costs tens of ms
            for k, (i, _, _, _) in enumerate(batch):
                results[i] = (out[k], ok[k])
        return results


    def warp_mosaic(self, windows: Sequence[DecodedWindow],
                    ns_ids: Sequence[int], prios: Sequence[float],
                    dst_gt: GeoTransform, dst_crs: CRS,
                    height: int, width: int, n_ns: int,
                    method: str = "near"):
        """Fused fast path: warp every window AND mosaic per namespace in
        one device dispatch per source CRS (uploads: padded window stack
        + ~2 KB control grid + per-granule affine params — NOT the dense
        (2, B, H, W) coordinate grids, which cost ~32 MB/tile for deep
        stacks).  The dense dst->src projection happens once per
        (dst grid, src CRS) on host at control points; the device
        reconstructs it bilinearly (0.125 px validated error, as the
        scene path does).

        Returns (canvases (n_ns_pad, H, W) f32 jax, valids bool jax) —
        callers slice the first ``n_ns`` entries.
        """
        by_crs: Dict[CRS, List[int]] = {}
        for i, wdw in enumerate(windows):
            by_crs.setdefault(wdw.src_crs, []).append(i)
        n_pad = _bucket_pow2(n_ns)
        parts = []
        for crs, idxs in by_crs.items():
            sx, sy, step = self._ctrl_geo_coords(dst_gt, dst_crs, height,
                                                 width, crs, 16)
            gs = [windows[i] for i in idxs]
            bh = _bucket(max(g.data.shape[0] for g in gs))
            bw = _bucket(max(g.data.shape[1] for g in gs))
            B = _bucket_pow2(len(gs))
            src = np.full((B, bh, bw), np.nan, np.float32)
            params = np.zeros((B, 11), np.float64)
            params[:, 10] = -1.0
            ox, oy = gs[0].window_gt.x0, gs[0].window_gt.y0
            ctrl = np.stack([sx - ox, sy - oy]).astype(np.float32)
            for k, (i, wdw) in enumerate(zip(idxs, gs)):
                h0, w0 = wdw.data.shape
                src[k, :h0, :w0] = np.where(wdw.valid, wdw.data, np.nan)
                params[k, :6] = _inv_gt_params(wdw.window_gt, ox, oy)
                params[k, 6] = h0
                params[k, 7] = w0
                params[k, 8] = np.nan   # validity is NaN-encoded in src
                params[k, 9] = prios[i]
                params[k, 10] = ns_ids[i]
            parts.append(warp_scored_raced(
                jnp.asarray(src), jnp.asarray(ctrl),
                jnp.asarray(params.astype(np.float32)), method, n_pad,
                (height, width), step))
        if len(parts) == 1:
            canv, best = parts[0]
            return canv, best > -jnp.inf
        canvs = jnp.stack([p[0] for p in parts])
        bests = jnp.stack([p[1] for p in parts])
        return combine_scored(canvs, bests)


    def warp_mosaic_scenes(self, granules, ns_ids: Sequence[int],
                           prios: Sequence[float], dst_gt: GeoTransform,
                           dst_crs: CRS, height: int, width: int,
                           n_ns: int, method: str = "near", cache=None):
        """Fastest path: fused warp+mosaic from device-cached full scenes
        (`ops.warp.warp_scenes_batch`).  Per tile this uploads only the
        shared ~0.5 MB coordinate grid + a (B, 11) param block; scene
        pixels never leave HBM between requests.

        Returns (canvases, valids) jax arrays, or None when the granule
        set is not uniform enough (mixed CRS/dtype/bucket) or a scene is
        uncacheable — callers fall back to the window path.
        """
        groups = self._scene_groups(granules, ns_ids, prios, dst_gt,
                                    dst_crs, height, width, cache)
        if groups is None:
            return None
        n_pad = _bucket_pow2(n_ns)
        if len(groups) == 1:
            stack, _, params, step, _, ctrl_dev, win, win0, *_ = groups[0]
            spmd = compat_spmd()
            if spmd is not None:
                # mesh path (GSKY_SPMD=1 compat routing): granule axis
                # over `granule`, width over `x` — the mesh-owned
                # fused mosaic on 1..N chips (SURVEY §2.8 P5/P6)
                self._count("scene_mosaic_spmd", (stack.shape, win))
                self._note_win(win)
                canv, best = spmd.mosaic_scored(
                    stack, ctrl_dev, params, method, n_pad,
                    (height, width), step, win=win, win0=win0)
                return canv, best > -jnp.inf
            if paged_enabled():
                made_p = self._paged_from_group(groups[0], n_pad)
                if made_p is not None:
                    pool, tables, params16, _ = made_p
                    self._note_paged(True)
                    from .waves import default_waves, waves_enabled
                    if waves_enabled():
                        # wave path: enqueue to the tick scheduler —
                        # this mosaic shares ONE stacked paged program
                        # with whatever else the wave carries
                        self._count("scene_mosaic_wave", tables.shape)
                        ctrl_host = groups[0][1]
                        from .. import device_guard

                        def _percall():
                            # incident failover: this request alone,
                            # through the bucketed per-call leg
                            c, b = device_guard.run(
                                "dispatch.bucketed",
                                lambda: warp_scored_raced(
                                    stack, ctrl_dev,
                                    jnp.asarray(params), method,
                                    n_pad, (height, width), step,
                                    win=win,
                                    win0_dev=_dev_win0(win0)))
                            return (np.asarray(c),
                                    np.asarray(b) > -np.inf)

                        c, v = default_waves().warp_scored(
                            pool, tables, params16, ctrl_host,
                            (method, n_pad, (height, width), step),
                            (stack, params, win, win0), _percall,
                            serials=groups[0][4])
                        return jnp.asarray(c), jnp.asarray(v)
                    self._count("scene_mosaic_paged", tables.shape)
                    from ..ops.paged import warp_scored_paged_raced

                    def _xla():
                        from ..ops.warp import warp_scenes_ctrl_scored
                        c, b = warp_scenes_ctrl_scored(
                            stack, ctrl_dev, jnp.asarray(params),
                            method, n_pad, (height, width), step,
                            win=win, win0=_dev_win0(win0))
                        return c[None], b[None]

                    from .. import device_guard

                    def _dispatch():
                        with pool.locked_pool() as parr:
                            return warp_scored_paged_raced(
                                parr, jnp.asarray(tables[None]),
                                jnp.asarray(params16), ctrl_dev[None],
                                method, n_pad, (height, width), step,
                                _xla)

                    try:
                        canvs, bests = device_guard.run(
                            "dispatch.paged", _dispatch)
                    finally:
                        pool.unpin(tables)
                    return canvs[0], bests[0] > -jnp.inf
                self._note_paged(False)
            self._count("scene_mosaic", (stack.shape, win))
            self._note_win(win)
            from .. import device_guard
            canv, best = device_guard.run(
                "dispatch.bucketed",
                lambda: warp_scored_raced(stack, ctrl_dev,
                                          jnp.asarray(params), method,
                                          n_pad, (height, width), step,
                                          win=win,
                                          win0_dev=_dev_win0(win0)))
            return canv, best > -jnp.inf
        # multi-CRS granule set (e.g. scenes across UTM zones): one
        # scored dispatch per source-CRS group, then a per-pixel
        # priority combine — newest-wins survives the grouping because
        # each partial carries its winners' priorities
        self._count("scene_mosaic_multicrs", len(groups))
        for g in groups:
            self._note_win(g[6])
        parts = [warp_scored_raced(
                    stack, ctrl_dev, jnp.asarray(params),
                    method, n_pad, (height, width), step,
                    win=win, win0_dev=_dev_win0(win0))
                 for stack, _, params, step, _, ctrl_dev, win,
                 win0, *_ in groups]
        canvs = jnp.stack([p[0] for p in parts])
        bests = jnp.stack([p[1] for p in parts])
        return combine_scored(canvs, bests)

    def render_byte_scenes(self, granules, ns_ids: Sequence[int],
                           prios: Sequence[float], dst_gt: GeoTransform,
                           dst_crs: CRS, height: int, width: int,
                           n_ns: int, method: str = "near",
                           offset: float = 0.0, scale: float = 0.0,
                           clip: float = 0.0, colour_scale: int = 0,
                           auto: bool = True, cache=None):
        """Whole-tile fast path: cached scenes -> PNG-ready uint8
        composite, coalesced with concurrent companion requests into one
        vmapped dispatch (`pipeline.batcher.RenderBatcher`).  Returns a
        host uint8 (H, W) array or None (fallback)."""
        made = self._scene_inputs(granules, ns_ids, prios, dst_gt,
                                  dst_crs, height, width, cache)
        if made is None:
            return None
        stack, ctrl, params, step, skey, ctrl_dev, win, win0, win_raw, \
            *_ = made
        sp = np.array([offset, scale, clip], np.float32)
        statics = (method, _bucket_pow2(n_ns), (height, width), step,
                   auto, colour_scale)
        spmd = compat_spmd()
        if spmd is not None:
            self._count("render_byte_spmd", (stack.shape, win))
            self._note_win(win)
            return _prefetch(spmd.render_composite(
                stack, ctrl_dev, params, sp, *statics,
                win=win, win0=win0))
        from .batcher import batching_enabled
        if paged_enabled():
            made_p = self._paged_from_group(made, statics[1])
            if made_p is not None:
                pool, tables, params16, real_pages = made_p
                self._note_paged(True)
                from .waves import default_waves, waves_enabled
                if waves_enabled():
                    # wave path: every eligible request of the tick —
                    # tiles of ANY shape, plus drills — shares the
                    # dispatch; checked before batching because wave
                    # ticks subsume the batcher's flush entirely
                    self._count("render_byte_wave", tables.shape)
                    from .. import device_guard

                    def _percall():
                        out = device_guard.run(
                            "dispatch.bucketed",
                            lambda: render_byte_raced(
                                stack, ctrl_dev, jnp.asarray(params),
                                jnp.asarray(sp), *statics, win=win,
                                win0_dev=_dev_win0(win0)))
                        return np.asarray(out)

                    return default_waves().render_byte(
                        pool, tables, params16, ctrl, sp, statics,
                        (stack, params, win, win0), _percall,
                        serials=skey)
                if batching_enabled():
                    # the paged batch key carries NO stack/shape
                    # identity: tiles over different scene sets and
                    # window sizes coalesce into one ragged dispatch
                    self._count("render_byte_paged_batched",
                                tables.shape)
                    fallback = (stack, params, win, win0)
                    return self._batcher.render_paged(
                        ("paged",) + statics, pool, tables, params16,
                        ctrl, sp, statics, real_pages, fallback)
                self._count("render_byte_paged", tables.shape)
                from ..ops.paged import render_byte_paged_raced

                def _xla():
                    from ..ops.warp import render_scenes_ctrl
                    return render_scenes_ctrl(
                        stack, ctrl_dev, jnp.asarray(params),
                        jnp.asarray(sp), *statics, win=win,
                        win0=_dev_win0(win0))[None]

                from .. import device_guard

                def _dispatch():
                    with pool.locked_pool() as parr:
                        return render_byte_paged_raced(
                            parr, jnp.asarray(tables[None]),
                            jnp.asarray(params16), ctrl_dev[None],
                            jnp.asarray(sp[None]), *statics, _xla)

                try:
                    out = device_guard.run("dispatch.paged", _dispatch)
                finally:
                    pool.unpin(tables)
                return _prefetch(out[0])
            self._note_paged(False)
        if batching_enabled():
            # batched tiles share one dispatch; the batcher unions the
            # per-tile windows at flush (its win_batches/full_batches
            # counters carry the engagement telemetry for this path)
            self._count("render_byte_batched", stack.shape)
            # scene-serial key (not id()): address reuse after eviction
            # must never coalesce a request into another stack's batch
            key = skey + statics
            return self._batcher.render(key, stack, ctrl, params, sp,
                                        statics, win_raw=win_raw)
        self._count("render_byte", (stack.shape, win))
        self._note_win(win)
        from .. import device_guard
        out = device_guard.run(
            "dispatch.bucketed",
            lambda: render_byte_raced(stack, ctrl_dev,
                                      jnp.asarray(params),
                                      jnp.asarray(sp), *statics,
                                      win=win, win0_dev=_dev_win0(win0)))
        return _prefetch(out)

    def render_expr_byte(self, granules, ns_ids: Sequence[int],
                         prios: Sequence[float], dst_gt: GeoTransform,
                         dst_crs: CRS, height: int, width: int,
                         n_slots: int, fp, method: str = "near",
                         offset: float = 0.0, scale: float = 0.0,
                         clip: float = 0.0, colour_scale: int = 0,
                         auto: bool = True, cache=None):
        """Fused band-algebra fast path (GSKY_EXPR_FUSE): cached scenes
        -> one paged program that gathers EVERY referenced band's
        window, interpolates each, evaluates the expression as a traced
        epilogue and scales to byte — no per-band mosaic dispatches, no
        f32 plane round-trips through HBM.

        ``ns_ids`` are fingerprint SLOT indices (variable i of ``fp``
        is mosaic slot i); ``fp`` is the `ops.expr.ExprFingerprint`.
        Returns a uint8 (H, W) array or None — the caller then runs
        the unfused `evaluate_expressions` leg (multi-CRS granule sets,
        page budget, SPMD compat mode)."""
        made = self._scene_inputs(granules, ns_ids, prios, dst_gt,
                                  dst_crs, height, width, cache)
        if made is None:
            return None
        stack, ctrl, params, step, skey, ctrl_dev, win, win0, win_raw, \
            *_ = made
        if compat_spmd() is not None:
            return None     # mesh compat routing has no expr epilogue
        if not paged_enabled():
            return None
        n_pad = _bucket_pow2(n_slots)
        made_p = self._paged_from_group(made, n_pad, lane_union=True)
        if made_p is None:
            self._note_paged(False)
            return None
        pool, tables, params16, real_pages = made_p
        self._note_paged(True)
        sp = np.array([offset, scale, clip], np.float32)
        consts = fp.const_array()
        statics = (method, n_pad, (height, width), step, auto,
                   colour_scale, fp.key)
        from ..ops.paged import expr_epilogue, note_expr_fused

        def _unfused_xla():
            # the race/fallback reference: bucketed scored mosaic +
            # the SAME epilogue + scale — `evaluate_expressions`
            # semantics op for op
            from ..ops.scale import scale_to_byte
            from ..ops.warp import warp_scenes_ctrl_scored
            c, b = warp_scenes_ctrl_scored(
                stack, ctrl_dev, jnp.asarray(params), method, n_pad,
                (height, width), step, win=win, win0=_dev_win0(win0))
            plane, ok = expr_epilogue(c[None], b[None], fp.key,
                                      jnp.asarray(consts[None]))
            return scale_to_byte(plane, ok, offset, scale, clip,
                                 colour_scale, auto)

        from .waves import default_waves, waves_enabled
        if waves_enabled():
            # wave path: expression lanes coalesce with every other
            # lane of the tick that shares (statics, fingerprint, pool)
            self._count("render_expr_wave", tables.shape)
            note_expr_fused("wave")
            from .. import device_guard

            def _percall():
                out = device_guard.run("dispatch.bucketed",
                                       _unfused_xla)
                return np.asarray(out[0])

            return default_waves().render_expr(
                pool, tables, params16, ctrl, sp, consts, statics,
                (stack, params, win, win0), _percall, serials=skey)
        self._count("render_expr_paged", tables.shape)
        note_expr_fused("percall")
        from ..ops.paged import render_expr_paged_raced
        from .. import device_guard

        def _dispatch():
            with pool.locked_pool() as parr:
                return render_expr_paged_raced(
                    parr, jnp.asarray(tables[None]),
                    jnp.asarray(params16), ctrl_dev[None],
                    jnp.asarray(sp[None]), jnp.asarray(consts[None]),
                    method, n_pad, (height, width), step, auto,
                    colour_scale, fp.key, fp.hash, _unfused_xla)

        try:
            out = device_guard.run("dispatch.paged", _dispatch)
        finally:
            pool.unpin(tables)
        return _prefetch(out[0])

    def render_bands_byte(self, granules, ns_ids: Sequence[int],
                          prios: Sequence[float], dst_gt: GeoTransform,
                          dst_crs: CRS, height: int, width: int,
                          n_ns: int, out_sel: Sequence[int],
                          method: str = "near", offset: float = 0.0,
                          scale: float = 0.0, clip: float = 0.0,
                          colour_scale: int = 0, auto: bool = True,
                          cache=None):
        """Multi-band fused fast path (RGB styles): one dispatch from
        cached scenes to per-band uint8 planes
        (`ops.warp.render_scenes_bands_ctrl`).  Returns a device uint8
        (n_out, H, W) array or None (fallback)."""
        made = self._scene_inputs(granules, ns_ids, prios, dst_gt,
                                  dst_crs, height, width, cache)
        if made is None:
            return None
        stack, _, params, step, _, ctrl_dev, win, win0, *_ = made
        self._count("render_bands", (stack.shape, win))
        self._note_win(win)
        sp = jnp.asarray(np.array([offset, scale, clip], np.float32))
        sel = jnp.asarray(np.asarray(out_sel, np.int32))
        return _prefetch(render_scenes_bands_ctrl(
            stack, ctrl_dev, jnp.asarray(params), sp, sel,
            method, _bucket_pow2(n_ns), (height, width), step, auto,
            colour_scale, win=win, win0=_dev_win0(win0)))

    def render_rgba_byte(self, granules, out_sel: Sequence[int],
                         dst_gt: GeoTransform, dst_crs: CRS,
                         height: int, width: int, method: str = "near",
                         offset: float = 0.0, scale: float = 0.0,
                         clip: float = 0.0, colour_scale: int = 0,
                         auto: bool = True, cache=None):
        """Channel-packed RGB fast path: when the request is one RGB
        scene (one temporal granule per output band, all bands sharing
        grid/dtype/nodata — the Sentinel-2 true-colour shape), the three
        band scenes pack into a (sh, sw, 3) device array (cached) and
        `ops.warp.render_rgba_ctrl` renders the PNG-ready (H, W, 4)
        RGBA tile in one dispatch, computing warp indices once for all
        three bands.  Returns a device uint8 (H, W, 4) or None (caller
        falls back to the per-band path)."""
        if len(granules) != 3 or len(out_sel) != 3 \
                or sorted(out_sel) != [0, 1, 2]:
            return None
        g0 = granules[0]
        if g0.geo_loc:
            return None
        for g in granules[1:]:
            if g.geo_loc or g.srs != g0.srs \
                    or g.geo_transform != g0.geo_transform:
                return None
        from ..geo.crs import parse_crs
        from .scene_cache import default_scene_cache
        cache = cache or default_scene_cache
        try:
            src_crs = parse_crs(g0.srs) if g0.srs else None
        except ValueError:
            return None
        if src_crs is None:
            return None
        stride = self._granule_stride(g0, dst_gt, dst_crs, height, width)
        # out_sel maps expression order -> ns index == granule index here
        # (one granule per namespace); channel k comes from the granule
        # whose ns id equals out_sel[k]
        chans = []
        rgba_bbox = dst_gt.bbox(width, height)
        for ns in out_sel:
            s = cache.get(granules[ns], stride,
                          dst_bbox=rgba_bbox, dst_crs=dst_crs)
            if s is None:
                return None
            chans.append(s)
        s0 = chans[0]
        for s in chans[1:]:
            if s.bucket != s0.bucket or s.dtype != s0.dtype \
                    or s.crs != s0.crs \
                    or not (np.isnan(s.nodata) and np.isnan(s0.nodata)
                            or s.nodata == s0.nodata) \
                    or (s.height, s.width) != (s0.height, s0.width):
                return None
        sx, sy, step = self._ctrl_geo_coords(dst_gt, dst_crs, height,
                                             width, s0.crs, 16)
        ox, oy = s0.gt.x0, s0.gt.y0
        dkey = ("ctrldev", dst_gt.to_gdal(), dst_crs, height, width,
                s0.crs, ox, oy)
        ctrl_dev = self._geo_cache_get(dkey)
        if ctrl_dev is None:
            ctrl_dev = jnp.asarray(
                np.stack([sx - ox, sy - oy]).astype(np.float32))
            self._geo_cache_put(dkey, ctrl_dev)
        skey = ("rgb",) + tuple(s.serial for s in chans)
        with self._lock:
            packed = self._stack_cache.get(skey)
            if packed is not None:
                self._stack_cache.move_to_end(skey)
        if packed is None:
            packed = jnp.stack([s.dev for s in chans], axis=-1)
            with self._lock:
                self._stack_cache[skey] = packed
                self._stack_cache.move_to_end(skey)
                while len(self._stack_cache) > self._STACK_CACHE_MAX:
                    self._stack_cache.popitem(last=False)
        inv = _inv_gt_params(s0.gt, ox, oy)
        param = np.array(inv + (s0.height, s0.width, s0.nodata, 0.0, 0.0),
                         np.float32)
        win = win0 = None
        if _window_mode():
            # window bound from the SAME param row the kernel consumes
            # (prio/ns slots are 0, so _gather_window reads it as one
            # non-padding granule)
            made_w = _gather_window(param.astype(np.float64)[None, :],
                                    sx - ox, sy - oy,
                                    int(packed.shape[0]),
                                    int(packed.shape[1]))
            if made_w is not None:
                win, win0, _ = made_w
        from ..ops.warp import render_rgba_ctrl
        self._count("render_rgba", (packed.shape, win))
        self._note_win(win)
        sp = np.array([offset, scale, clip], np.float32)
        return _prefetch(render_rgba_ctrl(
            packed, ctrl_dev, jnp.asarray(param), jnp.asarray(sp),
            method, (height, width), step, auto, colour_scale,
            win=win, win0=_dev_win0(win0)))

    def _note_paged(self, engaged: bool) -> None:
        with self._lock:
            if engaged:
                self.paged_engaged += 1
            else:
                self.paged_declined += 1

    def _paged_from_group(self, group, n_pad: int,
                          lane_union: bool = False):
        """Page tables + 16-wide kernel params for one scene group
        (`_scene_groups` tuple), or None when the paged path can't
        serve it — page budget exceeded, pool full of pinned pages, or
        the page block over VMEM — and the caller keeps the bucketed
        dispatch.

        Returns (pool, tables (T, S) int32, params16 (T, 16) f32,
        real_pages).  Page coverage per granule comes from the SAME
        `_granule_bounds` margins the bucketed window uses, so both
        paths gather identical taps; table slots come back PINNED and
        the caller must `pool.unpin(tables)` once its dispatch is
        enqueued.  ``lane_union`` (expression lanes) merges the
        per-granule page rects across the lane's bands
        (`autoplan.union_lane_spans`) so every band row shares one
        window shape — widened taps stay correct because off-window
        coords are oob-poisoned before the rebase."""
        from ..ops.paged import page_slots, paged_vmem_ok
        from .pages import default_page_pool
        (_, ctrl, _, _, _, _, _, _, _, gs, params64) = group
        pool = default_page_pool()
        if gs:
            # mesh per-chip placement (GSKY_MESH_PLACE=1): the group's
            # pages stage into the pool on the chip that owns its lead
            # scene; wave groups key on the pool object, so per-chip
            # groups dispatch concurrently on their owning chips
            try:
                from ..mesh.pools import staging_pool
                chip_pool = staging_pool(int(gs[0].serial))
            except Exception:   # pragma: no cover - mesh optional
                chip_pool = None
            if chip_pool is not None:
                pool = chip_pool
        pr, pc = pool.page_rows, pool.page_cols
        cx = np.asarray(ctrl[0], np.float64)
        cy = np.asarray(ctrl[1], np.float64)
        T = int(params64.shape[0])
        spans = []
        maxnpg = 1
        cap = page_slots()
        for k in range(T):
            p = params64[k]
            if p[10] < 0 or k >= len(gs):
                spans.append(None)      # batch-padding row
                continue
            made = _granule_bounds(p, cx, cy)
            if made is None:
                spans.append(None)      # nothing to gather
                continue
            r_lo, r_hi, c_lo, c_hi = made
            dev = gs[k].dev
            bh, bw = int(dev.shape[0]), int(dev.shape[1])
            i0 = max(0, r_lo) // pr
            i1 = min(-(-bh // pr) - 1, r_hi // pr)
            j0 = max(0, c_lo) // pc
            j1 = min(-(-bw // pc) - 1, c_hi // pc)
            if i1 < i0 or j1 < j0:
                spans.append(None)      # footprint entirely off-scene
                continue
            npg = (i1 - i0 + 1) * (j1 - j0 + 1)
            if npg > cap:
                return None
            maxnpg = max(maxnpg, npg)
            spans.append((i0, i1, j0, j1))
        if lane_union:
            from .autoplan import union_lane_spans
            spans, maxnpg = union_lane_spans(spans, cap, maxnpg)
        S = _bucket_pow2(maxnpg)
        if not paged_vmem_ok(S, n_pad, pr, pc):
            return None
        tables = np.zeros((T, S), np.int32)
        params16 = np.zeros((T, PAGED_PARAMS_W), np.float32)
        params16[:, :11] = params64[:, :11].astype(np.float32)
        pinned = []
        real_pages = 0
        for k, span in enumerate(spans):
            if span is None:
                # zero-extent row (slots 13/14 stay 0): every tap is
                # out of window, exactly a bucketed all-masked granule
                continue
            i0, i1, j0, j1 = span
            s = gs[k]
            slots = pool.table_for(s.dev, s.serial, i0, i1, j0, j1)
            if slots is None:
                for t in pinned:
                    pool.unpin(t)
                return None
            pinned.append(slots)
            tables[k, :slots.size] = slots
            real_pages += int(slots.size)
            params16[k, 11] = i0 * pr
            params16[k, 12] = j0 * pc
            params16[k, 13] = (i1 - i0 + 1) * pr
            params16[k, 14] = (j1 - j0 + 1) * pc
            params16[k, 15] = j1 - j0 + 1
        return pool, tables, params16, real_pages

    def _scene_inputs(self, granules, ns_ids, prios, dst_gt, dst_crs,
                      height, width, cache=None):
        """Single-group scene inputs; None when the granule set is not
        uniform (the byte fast paths then fall back)."""
        groups = self._scene_groups(granules, ns_ids, prios, dst_gt,
                                    dst_crs, height, width, cache)
        if groups is None or len(groups) != 1:
            return None
        return groups[0]

    def _geoloc_ctrl(self, g, dst_gt: GeoTransform, dst_crs: CRS,
                     height: int, width: int):
        """Control grid for a curvilinear granule: dst ctrl points
        projected to the geolocation CRS, then inverted through the
        geolocation arrays to fractional source PIXEL coords
        (`geo.geoloc.GeolocGrid`) — the kernels consume them with an
        identity affine, exactly like projected grids.  None when the
        geoloc arrays can't be loaded."""
        from ..geo.crs import parse_crs
        from ..geo.geoloc import load_geoloc_grid
        grid = load_geoloc_grid(g.path, g.geo_loc)
        if grid is None:
            return None
        try:
            gl_crs = parse_crs(g.geo_loc.get("srs") or "EPSG:4326")
        except ValueError:
            return None
        key = ("glctrl", g.path, g.geo_loc.get("x_var"),
               dst_gt.to_gdal(), dst_crs, height, width)
        hit = self._geo_cache_get(key)
        if hit is not None:
            return hit
        step = 16
        while True:
            sx, sy, step = self._ctrl_geo_coords(dst_gt, dst_crs, height,
                                                 width, gl_crs, step)
            col, row = grid.invert(sx, sy)
            # the inversion leg needs its own 0.125-px validation (the
            # projection leg's _ctrl_err_px can't see it): compare the
            # on-device bilinear reconstruction at ctrl-cell midpoints
            # against exact inversion there, halving the step for
            # strongly curved swaths
            if step <= 2:
                break
            gh, gw = sx.shape
            if gh < 2 or gw < 2:
                break
            c = (np.arange(gw - 1, dtype=np.float64) + 0.5) * step + 0.5
            r = (np.arange(gh - 1, dtype=np.float64) + 0.5) * step + 0.5
            C, R = np.meshgrid(c, r)
            mx, my = dst_gt.pixel_to_geo(C, R, np)
            ex, ey = dst_crs.transform_to(gl_crs, mx, my, np)
            ecol, erow = grid.invert(np.asarray(ex), np.asarray(ey))
            icol = 0.25 * (col[:-1, :-1] + col[:-1, 1:] + col[1:, :-1]
                           + col[1:, 1:])
            irow = 0.25 * (row[:-1, :-1] + row[:-1, 1:] + row[1:, :-1]
                           + row[1:, 1:])
            with np.errstate(invalid="ignore"):
                err = np.hypot(ecol - icol, erow - irow)
            if not err.size or np.all(np.isnan(err)) \
                    or float(np.nanmax(err)) <= 0.125:
                break
            step //= 2
        out = (np.stack([col, row]).astype(np.float32), step)
        self._geo_cache_put(key, out)
        return out

    def _scene_groups(self, granules, ns_ids, prios, dst_gt, dst_crs,
                      height, width, cache=None):
        """Device inputs for the fused scene kernels, grouped by
        (source CRS, bucket shape, dtype) — curvilinear granules group
        by their geolocation arrays instead: each group gets its own
        (stack, ctrl, params, step); multi-group sets (granules spanning
        UTM zones, or mixing regular and curvilinear grids) combine via
        the scored kernels.  None when any scene is uncacheable."""
        from .scene_cache import default_scene_cache
        cache = cache or default_scene_cache
        scenes = []
        grp_bbox = dst_gt.bbox(width, height)
        for g in granules:
            stride = 1.0 if g.geo_loc else self._granule_stride(
                g, dst_gt, dst_crs, height, width)
            s = cache.get(g, stride, dst_bbox=grp_bbox, dst_crs=dst_crs)
            if s is None:
                return None
            scenes.append(s)
        by_key: Dict[tuple, List[int]] = {}
        for i, s in enumerate(scenes):
            g = granules[i]
            if g.geo_loc:
                key = ("gl", g.path, g.geo_loc.get("x_var"),
                       g.geo_loc.get("y_var"), s.bucket, str(s.dtype))
            else:
                key = (s.crs.name(), s.bucket, str(s.dtype))
            by_key.setdefault(key, []).append(i)

        groups = []
        for gkey, idxs in by_key.items():
            gs = [scenes[i] for i in idxs]
            s0 = gs[0]
            is_gl = gkey[0] == "gl"
            if is_gl:
                made = self._geoloc_ctrl(granules[idxs[0]], dst_gt,
                                         dst_crs, height, width)
                if made is None:
                    return None
                ctrl, step = made
                gl0 = granules[idxs[0]]
                dkey = ("ctrldev", "gl", gl0.path,
                        gl0.geo_loc.get("x_var"), gl0.geo_loc.get("y_var"),
                        dst_gt.to_gdal(), dst_crs, height, width)
            else:
                sx, sy, step = self._ctrl_geo_coords(
                    dst_gt, dst_crs, height, width, s0.crs, 16)
                ox, oy = s0.gt.x0, s0.gt.y0
                ctrl = np.stack([sx - ox, sy - oy]).astype(np.float32)
                dkey = ("ctrldev", dst_gt.to_gdal(), dst_crs, height,
                        width, s0.crs, ox, oy)
            # the ~2 KB ctrl grid re-uploads on every render otherwise;
            # tile servers see heavy repeats, so keep the DEVICE copy in
            # the same LRU as the host grids.  The HOST array stays the
            # group's ctrl: the batcher np.stacks ctrl grids, and a
            # device array there would force a sync + download per
            # queued tile — consumers pick the device copy up by dkey
            ctrl_dev = self._geo_cache_get(dkey)
            if ctrl_dev is None:
                ctrl_dev = jnp.asarray(ctrl)
                self._geo_cache_put(dkey, ctrl_dev)

            B = _bucket_pow2(len(gs))
            params = np.zeros((B, 11), np.float64)
            params[:, 10] = -1.0
            for k, (i, s) in enumerate(zip(idxs, gs)):
                if is_gl:
                    # ctrl already carries pixel coords: identity affine
                    params[k, :6] = (0.0, 1.0, 0.0, 0.0, 0.0, 1.0)
                else:
                    params[k, :6] = _inv_gt_params(s.gt, ox, oy)
                params[k, 6] = s.height
                params[k, 7] = s.width
                params[k, 8] = s.nodata
                params[k, 9] = prios[i]
                params[k, 10] = ns_ids[i]

            skey = tuple(s.serial for s in gs) + (B,)
            with self._lock:
                stack = self._stack_cache.get(skey)
                if stack is not None:
                    self._stack_cache.move_to_end(skey)
            if stack is None:
                devs = [s.dev for s in gs]
                devs += [devs[0]] * (B - len(devs))
                stack = jnp.stack(devs)
                with self._lock:
                    self._stack_cache[skey] = stack
                    self._stack_cache.move_to_end(skey)
                    while len(self._stack_cache) > self._STACK_CACHE_MAX:
                        self._stack_cache.popitem(last=False)
            win = win0 = win_raw = None
            if _window_mode():
                made_w = _gather_window(
                    params, np.asarray(ctrl[0], np.float64),
                    np.asarray(ctrl[1], np.float64),
                    int(stack.shape[1]), int(stack.shape[2]))
                if made_w is not None:
                    win, win0, win_raw = made_w
            # trailing members (scenes + f64 params) feed the paged
            # dispatch (`_paged_from_group`); consumers of the bucketed
            # 9-prefix unpack with `*_`
            groups.append((stack, ctrl, params.astype(np.float32), step,
                           skey, ctrl_dev, win, win0, win_raw, gs,
                           params))
        return groups


# module-level default executor (compile cache shared across requests)
default_executor = WarpExecutor()
