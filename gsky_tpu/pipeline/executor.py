"""The TPU warp executor: shape-bucketed batched gather dispatch.

Replaces the reference's per-granule worker RPC fan-out
(`processor/tile_grpc.go:219-242` + the C warp loop) with one XLA dispatch
per (source-shape bucket, method): source windows are padded up to a small
set of shapes so recompilation is bounded (SURVEY §7 "padded shape
buckets"), coordinates are computed once per (dst grid, src CRS) in f64 on
host and only the cheap affine part is per-granule.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..geo.crs import CRS
from ..geo.transform import GeoTransform
from ..ops.warp import warp_gather_batch
from .decode import DecodedWindow

# padded source-window shape buckets (H and W independently bucketed)
_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096)


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return int(math.ceil(n / 4096) * 4096)


class WarpExecutor:
    """Batches decoded granule windows into device dispatches."""

    def __init__(self):
        self._geo_cache: Dict[tuple, Tuple[np.ndarray, np.ndarray]] = {}
        self._lock = threading.Lock()

    def _dst_geo_coords(self, dst_gt: GeoTransform, dst_crs: CRS,
                        height: int, width: int,
                        src_crs: CRS) -> Tuple[np.ndarray, np.ndarray]:
        """(sx, sy): dst pixel centres projected into src CRS, cached —
        the projection math is shared by every granule in that CRS (the
        expensive part of `coord_grid`)."""
        key = (dst_gt.to_gdal(), dst_crs, height, width, src_crs)
        with self._lock:
            hit = self._geo_cache.get(key)
        if hit is not None:
            return hit
        c = np.arange(width, dtype=np.float64) + 0.5
        r = np.arange(height, dtype=np.float64) + 0.5
        C, R = np.meshgrid(c, r)
        x, y = dst_gt.pixel_to_geo(C, R, np)
        sx, sy = dst_crs.transform_to(src_crs, x, y, np)
        sx = np.asarray(sx, np.float64)
        sy = np.asarray(sy, np.float64)
        with self._lock:
            if len(self._geo_cache) > 256:
                self._geo_cache.clear()
            self._geo_cache[key] = (sx, sy)
        return sx, sy

    def warp_all(self, windows: Sequence[Optional[DecodedWindow]],
                 dst_gt: GeoTransform, dst_crs: CRS, height: int, width: int,
                 method: str = "near") -> List[Optional[Tuple[np.ndarray, np.ndarray]]]:
        """Warp every decoded window onto the dst grid.  Returns, per
        input, (data (H,W) f32, ok (H,W) bool) or None."""
        jobs: List[Tuple[int, DecodedWindow, np.ndarray, np.ndarray]] = []
        for i, wdw in enumerate(windows):
            if wdw is None:
                continue
            sx, sy = self._dst_geo_coords(dst_gt, dst_crs, height, width,
                                          wdw.src_crs)
            col, row = wdw.window_gt.geo_to_pixel(sx, sy, np)
            jobs.append((i, wdw, (row - 0.5).astype(np.float32),
                         (col - 0.5).astype(np.float32)))

        results: List[Optional[Tuple[np.ndarray, np.ndarray]]] = \
            [None] * len(windows)
        # bucket by padded source shape
        buckets: Dict[Tuple[int, int], List] = {}
        for job in jobs:
            h, w = job[1].data.shape
            buckets.setdefault((_bucket(h), _bucket(w)), []).append(job)

        for (bh, bw), batch in buckets.items():
            B = len(batch)
            src = np.zeros((B, bh, bw), np.float32)
            valid = np.zeros((B, bh, bw), bool)
            rows = np.stack([j[2] for j in batch])
            cols = np.stack([j[3] for j in batch])
            for k, (_, wdw, _, _) in enumerate(batch):
                h, w = wdw.data.shape
                src[k, :h, :w] = wdw.data
                valid[k, :h, :w] = wdw.valid
            out, ok = warp_gather_batch(
                jnp.asarray(src), jnp.asarray(valid),
                jnp.asarray(rows), jnp.asarray(cols), method)
            out = np.asarray(out)
            ok = np.asarray(ok)
            for k, (i, _, _, _) in enumerate(batch):
                results[i] = (out[k], ok[k])
        return results


# module-level default executor (compile cache shared across requests)
default_executor = WarpExecutor()
