"""Dataset -> granule expansion: the axis-intersection odometer.

Port of the tile indexer's generalised N-D axis selection
(`processor/tile_indexer.go:459-531,590-813`): for each MAS dataset,
intersect the request's time range / axis selectors with the dataset's
axes, then emit one granule per (file, band/axis-combination), suffixing
namespaces with ``var#axis=value`` when an axis expands into multiple
values.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

from ..index.client import Dataset
from .types import AxisSelector, Granule


def _select_time_indices(timestamps: Sequence[float],
                         start: Optional[float],
                         end: Optional[float]) -> List[int]:
    """Indices of timestamps within [start, end] (end exclusive when a
    range is given, matching `doSelectionByRange`'s t >= start && t < end;
    a point query start==end selects exact matches)."""
    if not timestamps:
        return []
    if start is None:
        return list(range(len(timestamps)))
    out = []
    for i, t in enumerate(timestamps):
        if end is None or end == start:
            if abs(t - start) < 1.0:
                out.append(i)
        elif start <= t < end:
            out.append(i)
    return out


def expand_granules(datasets: Sequence[Dataset],
                    start_time: Optional[float],
                    end_time: Optional[float],
                    axes: Sequence[AxisSelector] = ()) -> List[Granule]:
    """One granule per (dataset, selected time, selected extra-axis
    combination)."""
    out: List[Granule] = []
    axsel = {a.name: a for a in axes}
    for ds in datasets:
        up = ds.ds_name.upper()
        # GMT grids share the .nc extension but are flat one-band
        # rasters — they route through the registry, not the NetCDF
        # variable model
        is_nc = not up.startswith("GMT:") and (
            up.startswith("NETCDF:")
            or ds.file_path.lower().endswith((".nc", ".nc4")))
        var_name = ""
        if is_nc:
            var_name = ds.ds_name.split(":")[-1].strip('"')
        # band number recorded by the crawler for multiband GeoTIFFs
        band0 = 1
        if not is_nc and ":" in ds.ds_name \
                and ds.ds_name.rsplit(":", 1)[-1].isdigit():
            band0 = int(ds.ds_name.rsplit(":", 1)[-1])

        # time selection
        tsel = axsel.get("time")
        if tsel is not None and tsel.start is not None:
            tidx = _select_time_indices(ds.timestamps, tsel.start, tsel.end)
        else:
            tidx = _select_time_indices(ds.timestamps, start_time, end_time)
        if not ds.timestamps:
            tidx = [-1]  # untimed dataset: single granule

        # extra axes (odometer over value selections)
        extra = [a for a in ds.axes if a.name != "time"]
        combos: List[List[tuple]] = [[]]
        for ax in extra:
            sel = axsel.get(ax.name)
            values = list(ax.params)
            idxs = list(range(len(values)))
            if sel is not None:
                if sel.in_values:
                    idxs = [i for i, v in enumerate(values)
                            if any(abs(v - w) < 1e-9 for w in sel.in_values)]
                elif sel.start is not None:
                    hi = sel.end if sel.end is not None else sel.start
                    if hi == sel.start:
                        idxs = [i for i, v in enumerate(values)
                                if abs(v - sel.start) < 1e-9]
                    else:
                        idxs = [i for i, v in enumerate(values)
                                if sel.start <= v < hi]
                elif sel.idx_start is not None:
                    stop = sel.idx_end + 1 if sel.idx_end is not None \
                        else len(values)
                    idxs = list(range(sel.idx_start, min(stop, len(values)),
                                      max(sel.idx_step, 1)))
            elif len(values) > 1:
                idxs = idxs[:1]  # unselected multi-value axis: first value
            combos = [c + [(ax, i)] for c in combos for i in idxs]

        for ti in tidx:
            for combo in combos:
                ns = ds.namespace
                band = band0
                time_index = ti if ti >= 0 else None
                if is_nc and ti >= 0:
                    band = ti + 1
                # apply extra-axis strides to the band index and suffix
                # namespaces (`tile_indexer.go:493-516`)
                for ax, i in combo:
                    if ax.strides:
                        band += ax.strides[0] * i
                    val = ax.params[i] if i < len(ax.params) else i
                    ns = f"{ns}#{ax.name}={val:g}"
                ts = ds.timestamps[ti] if ti >= 0 else 0.0
                out.append(Granule(
                    path=ds.file_path,
                    ds_name=ds.ds_name,
                    namespace=ns,
                    base_namespace=ds.namespace,
                    band=band,
                    time_index=time_index,
                    timestamp=ts,
                    srs=ds.srs,
                    geo_transform=list(ds.geo_transform or ()),
                    nodata=ds.nodata,
                    array_type=ds.array_type,
                    is_netcdf=is_nc,
                    var_name=var_name,
                    geo_loc=ds.geo_loc,
                    polygon=ds.polygon,
                ))
    # dedup (the gRPC stage dedups granules, `tile_grpc.go:78-83`)
    seen = set()
    uniq = []
    for g in out:
        key = (g.path, g.namespace, g.band, g.timestamp)
        if key not in seen:
            seen.add(key)
            uniq.append(g)
    return uniq
