"""WCS auto-size: suggested reprojection extent over the matched files.

Port of `processor/tile_extent.go:19-165` + the worker's
`ComputeReprojectExtent` (`worker/gdalprocess/warp.go:433-487`): for each
matched dataset, suggest the dst pixel size that preserves source
resolution, and take the max over files.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..geo.crs import CRS, parse_crs
from ..geo.transform import BBox, GeoTransform, suggest_output_size
from ..index.client import MASClient
from ..index.store import fmt_time
from .types import GeoTileRequest


def compute_reprojection_extent(mas: MASClient, req: GeoTileRequest,
                                max_size: int = 65536) -> Tuple[int, int]:
    """(width, height) suggestion for the request bbox; (0, 0) when no
    files match."""
    kw = dict(srs=req.crs.name(), wkt=req.bbox.to_polygon_wkt(),
              namespaces=",".join(req.band_exprs.var_list),
              nseg=req.polygon_segments)
    if req.start_time is not None:
        kw["time"] = fmt_time(req.start_time)
    if req.end_time is not None:
        kw["until"] = fmt_time(req.end_time)
    datasets = mas.intersects(req.collection, **kw)
    best_w = best_h = 0
    for ds in datasets:
        if not ds.geo_transform or not ds.srs:
            continue
        try:
            src_crs = parse_crs(ds.srs)
        except ValueError:
            continue
        gt = GeoTransform.from_gdal(ds.geo_transform)
        # estimate source size from the footprint polygon bbox
        from ..geo import geometry as geom
        try:
            b = geom.from_wkt(ds.polygon).bbox()
        except ValueError:
            continue
        c0, r0 = gt.geo_to_pixel(b.xmin, b.ymax)
        c1, r1 = gt.geo_to_pixel(b.xmax, b.ymin)
        w = abs(int(round(c1 - c0)))
        h = abs(int(round(r1 - r0)))
        if w < 2 or h < 2:
            continue
        try:
            dst_bbox, sw, sh = suggest_output_size(gt, w, h, src_crs,
                                                   req.crs, max_size)
        except ValueError:
            continue
        # scale to the requested bbox share of the suggested extent
        if dst_bbox.width <= 0 or dst_bbox.height <= 0:
            continue
        fw = req.bbox.width / dst_bbox.width
        fh = req.bbox.height / dst_bbox.height
        best_w = max(best_w, min(int(round(sw * fw)), max_size))
        best_h = max(best_h, min(int(round(sh * fh)), max_size))
    return best_w, best_h
