"""Cross-request render batching — SURVEY §2.8 P1's "async server in
front of a batching TPU executor", realised.

Measured on a tunneled v5e, a fused single-tile render costs ~5 serial
device-stream operations (uploads, execution, pull) at ~2.5 ms each;
request concurrency cannot overlap them because the device stream is one
queue.  This batcher coalesces concurrent tile renders that share a
scene stack + static config into ONE vmapped dispatch
(`ops.warp.render_scenes_ctrl_many`), amortising the round trips N ways.

A request waits at most ``max_wait_s`` (default 3 ms) for companions.
Batches are padded to the next power of two (clamped to ``max_batch``,
which should itself be a power of two), so a key compiles at most
log2(max_batch)+1 specialisations while half-full batches don't pull
double their bytes.

**Default OFF** (`GSKY_RENDER_BATCH=1` enables): batching trades
transfer granularity for round-trip count, which wins when the
host<->device link is latency-bound (PCIe-attached TPU: ~10 us
round trips) but loses when it is bandwidth-bound — over the tunneled
dev link (~10 MB/s, ~90 ms/MB) a padded 16-tile pull moves more bytes
than the tiles it serves, measured 4x slower end-to-end.  The
single-tile fused path already saturates that link.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from ..obs import span as obs_span
from ..obs.metrics import BATCH_FLUSHES
from ..ops.warp import render_scenes_ctrl_many

_MAX_BATCH = 16

# EMA weight of the newest per-tile latency sample; ~5 samples to
# converge, enough inertia to ride out scheduler noise
_EMA_ALPHA = 0.3
# a padded size is past the knee when its per-tile latency exceeds the
# best smaller size by this factor (BENCH_r05: x8 batches measured
# 2.26x the single-tile per-tile cost on a bandwidth-bound link)
_KNEE_RATIO = 1.25


def batching_enabled() -> bool:
    return os.environ.get("GSKY_RENDER_BATCH", "0") == "1"


def _knee_cap() -> int:
    """Static coalesce cap (GSKY_RENDER_BATCH_MAX): operators who have
    already measured their link can pin the knee instead of waiting for
    the adaptive ratchet to find it."""
    try:
        v = int(os.environ.get("GSKY_RENDER_BATCH_MAX", _MAX_BATCH))
    except ValueError:
        return _MAX_BATCH
    return max(1, min(_MAX_BATCH, v))


class RenderBatcher:
    def __init__(self, max_batch: int = _MAX_BATCH,
                 max_wait_s: float = 0.003):
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self._lock = threading.Lock()
        # key -> (stack, [(ctrl, params, sp, win_raw, Future), ...], Timer)
        self._groups: Dict[tuple, Tuple[object, List, object]] = {}
        # batches dispatched with / without a union gather window
        # (engagement telemetry, mirroring WarpExecutor.win_engaged)
        self.win_batches = 0
        self.full_batches = 0
        # ragged paged flushes (GSKY_PAGED batching path) and the
        # running padding bill: bytes moved (uploads + pull + staged
        # gather source) that served pow2/bucket padding instead of
        # payload.  The paged path exists to shrink this figure; the
        # split is surfaced in /debug and as Prometheus gauges
        self.paged_batches = 0
        self.pad_waste_bytes = 0
        # adaptive throughput knee: coalescing amortises device round
        # trips, but past some batch size the padded pull's BYTES cost
        # more than the round trips saved (render_mosaic_256_x8
        # regression: 9.29 ms/tile batched vs 4.10 single in
        # BENCH_r05).  Per padded-size EMAs of measured per-tile
        # latency feed a ratchet that caps the flush threshold at the
        # largest size still pulling its weight.
        self.knee = min(max_batch, _knee_cap())
        self._tile_ms: Dict[int, float] = {}   # padded size -> EMA ms
        self._tile_n: Dict[int, int] = {}      # samples per size
        from ..obs import tsan
        if tsan.enabled():
            # lockset tracking across flush timers / request threads
            # (docs/ANALYSIS.md "Race sanitizer")
            tsan.track(self, "RenderBatcher")

    def _observe(self, np_size: int, n_tiles: int, ms: float) -> None:
        """Fold one executed batch's per-tile latency into the EMA for
        its padded size and ratchet the knee down when this size
        measures slower than a smaller one.  The FIRST sample at each
        size is discarded: it carries the jit compile."""
        with self._lock:
            seen = self._tile_n.get(np_size, 0)
            self._tile_n[np_size] = seen + 1
            if seen == 0:
                return
            per_tile = ms / max(1, n_tiles)
            ema = self._tile_ms.get(np_size)
            self._tile_ms[np_size] = per_tile if ema is None else \
                (1 - _EMA_ALPHA) * ema + _EMA_ALPHA * per_tile
            if np_size <= 1:
                return
            smaller = [v for k, v in self._tile_ms.items()
                       if k < np_size]
            if smaller and self._tile_ms[np_size] > \
                    _KNEE_RATIO * min(smaller):
                self.knee = min(self.knee, max(1, np_size // 2))

    def note_oom(self) -> None:
        """Device-guard OOM relief hook (device_guard.register_oom_hook):
        halve the coalesce knee so the post-relief retry — and every
        later wave — dispatches smaller batches.  Like the latency
        ratchet this only moves down: a device that has proven it can
        exhaust HBM at a batch size should not be offered it again."""
        with self._lock:
            self.knee = max(1, self.knee // 2)

    def stats(self) -> Dict:
        """/debug `gather_window` payload: where the knee sits, the
        evidence (per padded-size per-tile EMA ms) behind it, batch
        engagement counters, and the cumulative padding bill."""
        with self._lock:
            return {"batch_knee": self.knee,
                    "tile_ms": {k: round(v, 3)
                                for k, v in sorted(self._tile_ms.items())},
                    "win_batches": self.win_batches,
                    "full_batches": self.full_batches,
                    "paged_batches": self.paged_batches,
                    "pad_waste_bytes": self.pad_waste_bytes}

    @staticmethod
    def _wait(fut: Future):
        """Block on a batch future, cancellation-aware: a request whose
        client disconnected stops waiting within one poll tick and
        unwinds (releasing its admission permit / stage slot) while the
        batch itself still executes for its surviving companions —
        cancelling one tile must never fail a shared flush."""
        from ..resilience import current_token
        tok = current_token()
        if tok is None:
            return fut.result()
        while True:
            try:
                return fut.result(timeout=0.05)
            except _FutTimeout:
                tok.check("batch")

    def render(self, key: tuple, stack, ctrl, params, sp,
               statics: tuple, win_raw=None) -> np.ndarray:
        """Submit one tile; blocks until its batch executes.  ``key``
        must capture everything that makes tiles batchable together:
        the scene-stack identity plus all static kernel parameters.
        win_raw: this tile's RAW footprint bounds (r_lo, r_hi, c_lo,
        c_hi) from `executor._gather_window` (or None); the flush
        unions them into one batch-wide bucketed window when every tile
        has bounds.  Returns the uint8 (H, W) tile as host numpy."""
        fut: Future = Future()
        flush_now = None
        with self._lock:
            entry = self._groups.get(key)
            if entry is None:
                timer = threading.Timer(self.max_wait_s,
                                        self._flush_key, (key, statics))
                timer.daemon = True
                self._groups[key] = (stack,
                                     [(ctrl, params, sp, win_raw, fut)],
                                     timer)
                timer.start()
            else:
                entry[1].append((ctrl, params, sp, win_raw, fut))
                if len(entry[1]) >= min(self.max_batch, self.knee):
                    flush_now = self._groups.pop(key)
        if flush_now is not None:
            # the pending wait timer would still fire, take the lock and
            # pop nothing — cancel it with the batch already claimed
            flush_now[2].cancel()
            self._execute(flush_now, statics, trigger="size")
        return self._wait(fut)

    def _union_window(self, items, stack):
        """One (win, win0) covering every tile's RAW footprint bounds,
        bucketed once — or (None, None) when any tile has no bounds or
        the union grows to the whole stack.  Coalesced tiles come from
        one map view, so the union is normally barely larger than a
        single tile's footprint."""
        if any(it[3] is None for it in items):
            return None, None
        from .executor import finish_window   # lazy: avoids cycle
        made = finish_window(
            min(it[3][0] for it in items),
            max(it[3][1] for it in items),
            min(it[3][2] for it in items),
            max(it[3][3] for it in items),
            int(stack.shape[1]), int(stack.shape[2]))
        return (None, None) if made is None else made

    def _flush_key(self, key: tuple, statics: tuple):
        with self._lock:
            entry = self._groups.pop(key, None)
        if entry is not None:
            self._execute(entry, statics, trigger="timer")

    def _execute(self, entry, statics: tuple, trigger: str = "size"):
        stack, items = entry[0], entry[1]
        method, n_ns, out_hw, step, auto, colour_scale = statics
        try:
            N = len(items)
            # pad to the next power of two (<= max_batch): bounded jit
            # specialisations per key (log2(max_batch) of them) while
            # keeping the padded PULL close to the real batch — padding
            # always to max_batch doubles transfer bytes for half-full
            # batches, and the pull is the expensive part of the link
            Np = 1
            while Np < N:
                Np *= 2
            Np = min(Np, self.max_batch)
            ctrls = np.stack([it[0] for it in items]
                             + [items[0][0]] * (Np - N))
            params = np.stack([it[1] for it in items]
                              + [items[0][1]] * (Np - N))
            sps = np.stack([it[2] for it in items]
                           + [items[0][2]] * (Np - N))
            win, win0 = self._union_window(items, stack)
            # padding bill (approximate, documented in docs/KERNELS.md):
            # pow2 batch-pad replicas of the uploads + the padded uint8
            # pull, plus the window-bucket overshoot of the gathered
            # source over the raw union footprint
            h, w = out_hw
            waste = (Np - N) * (h * w + ctrls[0].nbytes
                                + params[0].nbytes + sps[0].nbytes)
            if win is not None:
                raw = (max(it[3][1] for it in items)
                       - min(it[3][0] for it in items)) * \
                      (max(it[3][3] for it in items)
                       - min(it[3][2] for it in items))
                waste += max(0, win[0] * win[1] - raw) * 4 \
                    * int(stack.shape[0])
            with self._lock:
                if win is not None:
                    self.win_batches += 1
                else:
                    self.full_batches += 1
                self.pad_waste_bytes += int(waste)
            try:
                BATCH_FLUSHES.labels(
                    kind="windowed" if win is not None else "full").inc()
            except Exception:  # prom counter is telemetry only
                pass
            t0 = time.perf_counter()
            # traced only when flushed from a request thread (the timer
            # thread carries no request context — counters still count)
            with obs_span("batch.flush", trigger=trigger) as bsp:
                out = np.asarray(render_scenes_ctrl_many(
                    stack, jnp.asarray(ctrls), jnp.asarray(params),
                    jnp.asarray(sps), method, n_ns, out_hw, step, auto,
                    colour_scale, win=win,
                    win0=None if win is None else jnp.asarray(win0)))
                bsp.set(tiles=N, padded=Np, windowed=win is not None)
            self._observe(Np, N, (time.perf_counter() - t0) * 1e3)
            for i, it in enumerate(items):
                it[4].set_result(out[i])
        except Exception as e:  # pragma: no cover - propagate to callers
            for it in items:
                if not it[4].done():
                    it[4].set_exception(e)

    # -- ragged paged batching (GSKY_PAGED, ops/paged.py) -------------

    def render_paged(self, key: tuple, pool, tables, params16, ctrl,
                     sp, statics: tuple, real_pages: int,
                     fallback) -> np.ndarray:
        """Submit one tile whose gather windows are already staged in
        the page pool; blocks until its batch executes.  Unlike
        `render`, ``key`` carries NO scene-stack or window-shape
        identity — only the statics — so HETEROGENEOUS concurrent
        tiles (different scene sets, scene counts and window sizes)
        coalesce into one ragged dispatch; the flush pads the granule
        and page-slot axes to the batch maxima instead of shape
        buckets.  ``tables`` arrives PINNED (executor's
        `_paged_from_group`); the flush unpins after enqueue.
        ``fallback`` is (stack, params11, win, win0) for the race's
        per-tile bucketed XLA leg.

        Wave subsumption (GSKY_WAVES, pipeline/waves.py): when the
        wave scheduler is live, batcher flushes are subsumed by wave
        ticks — the executor routes eligible tiles to the wave path
        before the batching check, and a direct caller landing here
        joins the current wave instead of opening a batcher group
        (same ragged stacking, same unpin contract, plus the wave's
        cross-KIND coalescing and async readback)."""
        from .waves import active_waves, waves_enabled
        w = active_waves() if waves_enabled() else None
        if w is not None:
            def _percall():
                from .. import device_guard
                from ..ops.warp import render_scenes_ctrl
                from .executor import _dev_win0    # lazy: avoids cycle
                stack, bparams, bwin, bwin0 = fallback
                return np.asarray(device_guard.run(
                    "dispatch.bucketed",
                    lambda: render_scenes_ctrl(
                        stack, jnp.asarray(ctrl), jnp.asarray(bparams),
                        jnp.asarray(sp), *statics, win=bwin,
                        win0=_dev_win0(bwin0))))

            return w.render_byte(pool, tables, params16, ctrl, sp,
                                 statics, fallback, _percall)
        fut: Future = Future()
        item = (pool, tables, params16, ctrl, sp, int(real_pages),
                fallback, fut)
        flush_now = None
        with self._lock:
            entry = self._groups.get(key)
            if entry is None:
                timer = threading.Timer(self.max_wait_s,
                                        self._flush_key_paged,
                                        (key, statics))
                timer.daemon = True
                self._groups[key] = (None, [item], timer)
                timer.start()
            else:
                entry[1].append(item)
                if len(entry[1]) >= min(self.max_batch, self.knee):
                    flush_now = self._groups.pop(key)
        if flush_now is not None:
            flush_now[2].cancel()
            self._execute_paged(flush_now[1], statics, trigger="size")
        return self._wait(fut)

    def _flush_key_paged(self, key: tuple, statics: tuple):
        with self._lock:
            entry = self._groups.pop(key, None)
        if entry is not None:
            self._execute_paged(entry[1], statics, trigger="timer")

    def _execute_paged(self, items, statics: tuple,
                       trigger: str = "size"):
        method, n_ns, out_hw, step, auto, colour_scale = statics
        h, w = out_hw
        pool = items[0][0]
        try:
            from ..ops.paged import PARAMS_W, render_byte_paged_raced
            N = len(items)
            Np = 1
            while Np < N:
                Np *= 2
            Np = min(Np, self.max_batch)
            # ragged pad: granule axis to the batch's LARGEST tile
            # (per-item T is already pow2, so the max is too), page
            # slots likewise — no shape buckets, one compiled program
            # per (statics, T, S) point regardless of window shapes
            T = max(it[1].shape[0] for it in items)
            S = max(it[1].shape[1] for it in items)
            tables = np.zeros((Np, T, S), np.int32)
            params = np.zeros((Np, T, PARAMS_W), np.float32)
            params[:, :, 10] = -1.0     # ns_id: padding rows
            for i, it in enumerate(items):
                ti, si = it[1].shape
                tables[i, :ti, :si] = it[1]
                params[i, :ti] = it[2]
            ctrls = np.stack([it[3] for it in items]
                             + [items[0][3]] * (Np - N))
            sps = np.stack([it[4] for it in items]
                           + [items[0][4]] * (Np - N))
            real_pages = sum(it[5] for it in items)
            page_bytes = pool.page_rows * pool.page_cols * 4
            waste = (Np - N) * (h * w + ctrls[0].nbytes
                                + T * PARAMS_W * 4 + sps[0].nbytes) \
                + (Np * T * S - real_pages) * page_bytes
            with self._lock:
                self.paged_batches += 1
                self.pad_waste_bytes += int(waste)
            try:
                BATCH_FLUSHES.labels(kind="paged").inc()
            except Exception:  # prom counter is telemetry only
                pass

            def _xla():
                # per-tile bucketed XLA legs, stacked to the paged
                # output contract (runs only when racing or demoted)
                from ..ops.warp import render_scenes_ctrl
                from .executor import _dev_win0    # lazy: avoids cycle
                outs = []
                for it in items:
                    stack, bparams, bwin, bwin0 = it[6]
                    outs.append(render_scenes_ctrl(
                        stack, jnp.asarray(it[3]), jnp.asarray(bparams),
                        jnp.asarray(it[4]), method, n_ns, out_hw, step,
                        auto, colour_scale, win=bwin,
                        win0=_dev_win0(bwin0)))
                outs += [outs[0]] * (Np - N)
                return jnp.stack(outs)

            t0 = time.perf_counter()
            with obs_span("batch.flush", trigger=trigger) as bsp:
                with pool.locked_pool() as parr:
                    dev = render_byte_paged_raced(
                        parr, jnp.asarray(tables),
                        jnp.asarray(params.reshape(Np * T, PARAMS_W)),
                        jnp.asarray(ctrls), jnp.asarray(sps), method,
                        n_ns, out_hw, step, auto, colour_scale, _xla)
                # slice off the batch pad BEFORE the pull: the padded
                # tiles never cross the link
                out = np.asarray(dev[:N])
                bsp.set(tiles=N, padded=Np, paged=True)
            self._observe(Np, N, (time.perf_counter() - t0) * 1e3)
            for i, it in enumerate(items):
                it[7].set_result(out[i])
        except Exception as e:  # pragma: no cover - propagate to callers
            for it in items:
                if not it[7].done():
                    it[7].set_exception(e)
        finally:
            for it in items:
                try:
                    pool.unpin(it[1])
                except Exception:   # pragma: no cover
                    pass
