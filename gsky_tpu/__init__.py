"""gsky_tpu — a TPU-native distributed geospatial data server.

A from-scratch rebuild of the capabilities of GSKY (NCI's distributed,
scalable geospatial data server): OGC WMS / WCS / WPS / DAP4 service over
large archives of GeoTIFF / NetCDF raster data, with the per-pixel raster
compute (reprojection/warping, temporal mosaicing, band math, colour
scaling, polygon drill statistics) executed on TPU via JAX/XLA/Pallas.

Package layout
--------------
- ``gsky_tpu.geo``       coordinate reference systems, affine transforms and
                         geometry — all projection math is jax-traceable so
                         coordinate transforms fuse into device kernels.
- ``gsky_tpu.ops``       the TPU compute kernels: warp (reprojection
                         resampling), temporal mosaic, colour scaling,
                         palettes, band-expression compiler, drill
                         reductions.
- ``gsky_tpu.io``        raster IO: GeoTIFF codec, NetCDF (h5py + classic),
                         PNG, DAP4 encoding.  Native C++ fast paths.
- ``gsky_tpu.index``     the metadata index (MAS equivalent): sqlite store,
                         masapi-compatible HTTP API, crawler.
- ``gsky_tpu.pipeline``  request pipelines: tile (WMS/WCS), drill (WPS),
                         extent, feature info.
- ``gsky_tpu.server``    the OWS HTTP front-end, config system, templates,
                         metrics.
- ``gsky_tpu.worker``    the RPC compute worker boundary: gRPC service,
                         batching TPU executor, process supervision.
- ``gsky_tpu.parallel``  device-mesh sharding for multi-chip rendering.
"""

__version__ = "0.1.0"
