"""Geolocation-array (curvilinear grid) support.

The reference warps curvilinear products (e.g. Himawari swaths) through
GDAL's geolocation transformer (`worker/gdalprocess/warp.go:52-67`): the
file carries 2-D per-sample longitude/latitude arrays instead of an
affine geotransform, and the warp inverts that mapping per pixel.

The TPU-native equivalent inverts the geolocation arrays ONLY at the
~hundreds of host-side control points of the approx transformer
(`pipeline.executor._ctrl_geo_coords`); the control grid then carries
fractional source PIXEL coordinates with an identity affine, and the
device reconstructs the dense map bilinearly exactly as it does for
projected grids — the fused warp kernels never know the grid was
curvilinear.

Inversion: a coarse scatter-filled backmap gives the initial guess
(GDAL's GDALCreateGeoLocTransformer builds the same structure), then
damped Newton iterations on the bilinear surface refine to sub-0.1-px.
Out-of-domain queries extrapolate linearly from the nearest edge cell,
so coordinates fall naturally outside [0, W) and the kernels' bounds
checks reject them per-pixel (no NaN fringe at swath edges).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np


class GeolocGrid:
    """gx/gy: (gh, gw) geolocation arrays — the geographic coordinates
    of raster samples; raster pixel (col, row) maps to array index
    (j, i) via col = pixel_offset + pixel_step * j (GDAL GEOLOCATION
    metadata convention, offsets/steps from the crawler's geo_loc
    record)."""

    def __init__(self, gx: np.ndarray, gy: np.ndarray,
                 line_offset: float = 0.0, pixel_offset: float = 0.0,
                 line_step: float = 1.0, pixel_step: float = 1.0,
                 backmap_size: int = 64):
        self.gx = np.asarray(gx, np.float64)
        self.gy = np.asarray(gy, np.float64)
        if self.gx.shape != self.gy.shape or self.gx.ndim != 2:
            raise ValueError("geolocation arrays must be matching 2-D")
        self.line_offset = float(line_offset)
        self.pixel_offset = float(pixel_offset)
        self.line_step = float(line_step)
        self.pixel_step = float(pixel_step)
        # antimeridian-crossing swaths: adjacent samples jumping ~360°
        # would make the bilinear surface non-invertible at the seam;
        # unwrap to a continuous +[180, 360) branch (queries shift onto
        # the same branch in invert())
        self._wraps = False
        with np.errstate(invalid="ignore"):
            jumps = max(
                float(np.nanmax(np.abs(np.diff(self.gx, axis=0)))
                      if self.gx.shape[0] > 1 else 0.0),
                float(np.nanmax(np.abs(np.diff(self.gx, axis=1)))
                      if self.gx.shape[1] > 1 else 0.0))
        if jumps > 180.0:
            self._wraps = True
            self.gx = np.where(self.gx < 0.0, self.gx + 360.0, self.gx)
        self._build_backmap(backmap_size)

    # -- backmap --------------------------------------------------------

    def _build_backmap(self, n: int):
        gh, gw = self.gx.shape
        finite = np.isfinite(self.gx) & np.isfinite(self.gy)
        if not finite.any():
            raise ValueError("geolocation arrays are all-invalid")
        self.x0 = float(np.nanmin(np.where(finite, self.gx, np.nan)))
        self.x1 = float(np.nanmax(np.where(finite, self.gx, np.nan)))
        self.y0 = float(np.nanmin(np.where(finite, self.gy, np.nan)))
        self.y1 = float(np.nanmax(np.where(finite, self.gy, np.nan)))
        self._bn = n
        sx = (self.x1 - self.x0) or 1.0
        sy = (self.y1 - self.y0) or 1.0
        bi = np.full((n, n), -1.0)
        bj = np.full((n, n), -1.0)
        ii, jj = np.nonzero(finite)
        bx = np.clip(((self.gx[ii, jj] - self.x0) / sx * (n - 1)), 0,
                     n - 1).astype(np.int64)
        by = np.clip(((self.gy[ii, jj] - self.y0) / sy * (n - 1)), 0,
                     n - 1).astype(np.int64)
        # last write wins per bin — any sample in the bin is a fine seed
        bi[by, bx] = ii
        bj[by, bx] = jj
        # hole-fill by nearest-neighbour dilation so every bin seeds
        for _ in range(2 * n):
            holes = bi < 0
            if not holes.any():
                break
            for dy, dx in ((0, 1), (0, -1), (1, 0), (-1, 0)):
                src_i = np.roll(bi, (dy, dx), (0, 1))
                src_j = np.roll(bj, (dy, dx), (0, 1))
                take = holes & (src_i >= 0)
                bi[take] = src_i[take]
                bj[take] = src_j[take]
                holes = bi < 0
        self._bi = bi
        self._bj = bj

    # -- bilinear sample with linear extrapolation ----------------------

    def _sample(self, arr: np.ndarray, i: np.ndarray, j: np.ndarray):
        """Bilinear value + partials at fractional (i, j); cells clamp to
        the grid so out-of-bounds queries extend the edge cell
        linearly."""
        gh, gw = arr.shape
        i0 = np.clip(np.floor(i).astype(np.int64), 0, gh - 2)
        j0 = np.clip(np.floor(j).astype(np.int64), 0, gw - 2)
        ti = i - i0
        tj = j - j0
        a00 = arr[i0, j0]
        a01 = arr[i0, j0 + 1]
        a10 = arr[i0 + 1, j0]
        a11 = arr[i0 + 1, j0 + 1]
        v = (a00 * (1 - ti) * (1 - tj) + a01 * (1 - ti) * tj
             + a10 * ti * (1 - tj) + a11 * ti * tj)
        dvi = (a10 - a00) * (1 - tj) + (a11 - a01) * tj
        dvj = (a01 - a00) * (1 - ti) + (a11 - a10) * ti
        return v, dvi, dvj

    # -- inversion ------------------------------------------------------

    def invert(self, x, y, iters: int = 12) -> Tuple[np.ndarray,
                                                     np.ndarray]:
        """Geographic (x, y) -> fractional raster pixel coords
        (col, row), corner-based (sample j's centre is at col j + 0.5),
        ready for the warp kernels' identity-affine control grids.
        Out-of-domain points extrapolate past the grid edge and land
        outside [0, size) where the kernel bounds checks reject them."""
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        if self._wraps:
            x = np.where(x < 0.0, x + 360.0, x)
        shape = x.shape
        xf = x.ravel()
        yf = y.ravel()
        gh, gw = self.gx.shape
        n = self._bn
        sx = (self.x1 - self.x0) or 1.0
        sy = (self.y1 - self.y0) or 1.0
        bxi = np.clip(((xf - self.x0) / sx * (n - 1)), 0,
                      n - 1)
        byi = np.clip(((yf - self.y0) / sy * (n - 1)), 0, n - 1)
        with np.errstate(invalid="ignore"):
            bxi = np.nan_to_num(bxi).astype(np.int64)
            byi = np.nan_to_num(byi).astype(np.int64)
        i = self._bi[byi, bxi].astype(np.float64)
        j = self._bj[byi, bxi].astype(np.float64)
        for _ in range(iters):
            vx, dxi, dxj = self._sample(self.gx, i, j)
            vy, dyi, dyj = self._sample(self.gy, i, j)
            rx = vx - xf
            ry = vy - yf
            det = dxj * dyi - dxi * dyj
            det = np.where(np.abs(det) < 1e-30, 1e-30, det)
            dj = (rx * dyi - ry * dxi) / det
            di = (ry * dxj - rx * dyj) / det
            # damped + bounded step: keeps the iteration stable across
            # backmap-seed jumps while still allowing edge extrapolation
            step = np.maximum(gh, gw) * 0.5
            i = i - np.clip(di, -step, step)
            j = j - np.clip(dj, -step, step)
            i = np.clip(i, -2.0, gh + 1.0)
            j = np.clip(j, -2.0, gw + 1.0)
        bad = ~(np.isfinite(xf) & np.isfinite(yf))
        i = np.where(bad, np.nan, i)
        j = np.where(bad, np.nan, j)
        col = self.pixel_offset + self.pixel_step * j + 0.5
        row = self.line_offset + self.line_step * i + 0.5
        return col.reshape(shape), row.reshape(shape)


# -- loading ------------------------------------------------------------

_grid_cache: Dict[tuple, GeolocGrid] = {}
_grid_cache_lock = threading.Lock()


def load_geoloc_grid(path: str, geo_loc: Dict) -> Optional[GeolocGrid]:
    """GeolocGrid for a granule's geo_loc record (crawler schema:
    x_var/y_var + offsets/steps), cached per file+vars.  None when the
    arrays can't be read."""
    key = (path, geo_loc.get("x_var"), geo_loc.get("y_var"))
    with _grid_cache_lock:
        hit = _grid_cache.get(key)
    if hit is not None:
        return hit
    try:
        from ..io.netcdf import NetCDF
        with NetCDF(path) as nc:
            gx = np.asarray(nc.variables[geo_loc["x_var"]][:], np.float64)
            gy = np.asarray(nc.variables[geo_loc["y_var"]][:], np.float64)
        grid = GeolocGrid(
            gx, gy,
            line_offset=float(geo_loc.get("line_offset", 0.0)),
            pixel_offset=float(geo_loc.get("pixel_offset", 0.0)),
            line_step=float(geo_loc.get("line_step", 1.0)),
            pixel_step=float(geo_loc.get("pixel_step", 1.0)))
    except Exception:
        return None
    # eviction + insert under one lock: two racing loaders must not both
    # pop the same key (the loser's KeyError used to fail the request)
    with _grid_cache_lock:
        while len(_grid_cache) > 16:
            _grid_cache.pop(next(iter(_grid_cache)))
        _grid_cache[key] = grid
    return grid
