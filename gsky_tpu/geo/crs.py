"""Coordinate reference systems with jax-traceable projection math.

The reference server delegates every coordinate transform to GDAL/OSR on the
host (e.g. the per-row transform loop feeding the warp kernel,
``worker/gdalprocess/warp.go:261-345``, and the canonical-bbox transform,
``utils/wms.go:487-522``).  Here each projection's forward/inverse formulas
are written against an array module (``numpy`` or ``jax.numpy``) so the full
dst-pixel -> dst-CRS -> lon/lat -> src-CRS -> src-pixel chain is elementwise
array math that XLA fuses straight into the warp gather on TPU — no host
round-trip, no per-row loop.

Formulas follow Snyder, *Map Projections — A Working Manual* (USGS PP 1395).
Supported projections cover the datasets GSKY serves (Landsat UTM, MODIS
sinusoidal, Australian Albers EPSG:3577, Web Mercator tiles, lat/lon grids,
Himawari-8 geostationary):

- geographic (EPSG:4326 and friends)
- pseudo/web mercator (EPSG:3857)
- transverse mercator / UTM (EPSG:326xx, 327xx, 28349-28356 GDA94 MGA)
- albers equal area (EPSG:3577 Australian Albers, EPSG:102008 ...)
- lambert conformal conic
- sinusoidal (MODIS, spherical)
- geostationary (Himawari-8 full disk)

A CRS is a hashable frozen dataclass, safe to close over in ``jit``.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Ellipsoids
# ---------------------------------------------------------------------------

WGS84_A = 6378137.0
WGS84_F = 1.0 / 298.257223563
GRS80_F = 1.0 / 298.257222101
MODIS_SPHERE_R = 6371007.181  # radius of the authalic sphere used by MODIS


@dataclass(frozen=True)
class Ellipsoid:
    a: float = WGS84_A
    f: float = WGS84_F

    @property
    def b(self) -> float:
        return self.a * (1.0 - self.f)

    @property
    def e2(self) -> float:
        return self.f * (2.0 - self.f)

    @property
    def e(self) -> float:
        return math.sqrt(self.e2)

    @property
    def ep2(self) -> float:  # second eccentricity squared
        e2 = self.e2
        return e2 / (1.0 - e2)


WGS84 = Ellipsoid(WGS84_A, WGS84_F)
GRS80 = Ellipsoid(WGS84_A, GRS80_F)
SPHERE = Ellipsoid(MODIS_SPHERE_R, 0.0)

_ELLIPSOIDS = {
    "WGS84": WGS84,
    "GRS80": GRS80,
    "GRS67": Ellipsoid(6378160.0, 1 / 298.247167427),
    "WGS72": Ellipsoid(6378135.0, 1 / 298.26),
    "bessel": Ellipsoid(6377397.155, 1 / 299.1528128),
    "clrk66": Ellipsoid(6378206.4, 1 / 294.9786982),
    "clrk80": Ellipsoid(6378249.145, 1 / 293.465),
    "intl": Ellipsoid(6378388.0, 1 / 297.0),
    "krass": Ellipsoid(6378245.0, 1 / 298.3),
    "aust_SA": Ellipsoid(6378160.0, 1 / 298.25),
    "sphere": Ellipsoid(6370997.0, 0.0),
}


# ---------------------------------------------------------------------------
# Projection kernels (Snyder).  Each takes/returns radians-free degrees for
# lon/lat and metres for x/y.  ``xp`` is numpy or jax.numpy.
# ---------------------------------------------------------------------------

def _rad(deg, xp):
    return deg * (math.pi / 180.0)


def _deg(rad, xp):
    return rad * (180.0 / math.pi)


# -- mercator (ellipsoidal, Snyder 7-7..7-10) -------------------------------

def _merc_fwd(lon, lat, p, xp):
    a, e = p.ellps.a, p.ellps.e
    lat = xp.clip(lat, -89.5, 89.5)
    phi = _rad(lat, xp)
    x = a * p.k0 * _rad(lon - p.lon0, xp)
    esin = e * xp.sin(phi)
    y = a * p.k0 * xp.log(xp.tan(math.pi / 4 + phi / 2)
                          * ((1 - esin) / (1 + esin)) ** (e / 2))
    return x + p.x0, y + p.y0


def _merc_inv(x, y, p, xp):
    a, e = p.ellps.a, p.ellps.e
    lon = p.lon0 + _deg((x - p.x0) / (a * p.k0), xp)
    t = xp.exp(-(y - p.y0) / (a * p.k0))
    phi = math.pi / 2 - 2 * xp.arctan(t)
    for _ in range(6):
        esin = e * xp.sin(phi)
        phi = math.pi / 2 - 2 * xp.arctan(
            t * ((1 - esin) / (1 + esin)) ** (e / 2))
    return lon, _deg(phi, xp)


# -- web mercator (spherical formulas on the WGS84 semi-major axis) ---------

def _webmerc_fwd(lon, lat, p, xp):
    a = p.ellps.a
    x = a * _rad(lon - p.lon0, xp) + p.x0
    lat = xp.clip(lat, -85.06, 85.06)
    y = a * xp.log(xp.tan(math.pi / 4.0 + _rad(lat, xp) / 2.0)) + p.y0
    return x, y


def _webmerc_inv(x, y, p, xp):
    a = p.ellps.a
    lon = p.lon0 + _deg((x - p.x0) / a, xp)
    lat = _deg(2.0 * xp.arctan(xp.exp((y - p.y0) / a)) - math.pi / 2.0, xp)
    return lon, lat


# -- transverse mercator (ellipsoidal, Snyder 8-12..8-17 / 8-18..8-25) ------

def _tm_M(phi, e2, a, xp):
    e4 = e2 * e2
    e6 = e4 * e2
    return a * (
        (1 - e2 / 4 - 3 * e4 / 64 - 5 * e6 / 256) * phi
        - (3 * e2 / 8 + 3 * e4 / 32 + 45 * e6 / 1024) * xp.sin(2 * phi)
        + (15 * e4 / 256 + 45 * e6 / 1024) * xp.sin(4 * phi)
        - (35 * e6 / 3072) * xp.sin(6 * phi)
    )


def _tmerc_fwd(lon, lat, p, xp):
    a, e2 = p.ellps.a, p.ellps.e2
    ep2 = p.ellps.ep2
    k0, lon0, lat0 = p.k0, p.lon0, p.lat0
    phi = _rad(lat, xp)
    lam = _rad(lon - lon0, xp)
    sphi, cphi = xp.sin(phi), xp.cos(phi)
    N = a / xp.sqrt(1 - e2 * sphi * sphi)
    T = (sphi / cphi) ** 2
    C = ep2 * cphi * cphi
    A = lam * cphi
    M = _tm_M(phi, e2, a, xp)
    M0 = _tm_M(math.radians(lat0), e2, a, np)
    A2, A3 = A * A, A * A * A
    x = k0 * N * (A + (1 - T + C) * A3 / 6
                  + (5 - 18 * T + T * T + 72 * C - 58 * ep2) * A2 * A3 / 120)
    y = k0 * (M - M0 + N * (sphi / cphi) * (
        A2 / 2 + (5 - T + 9 * C + 4 * C * C) * A2 * A2 / 24
        + (61 - 58 * T + T * T + 600 * C - 330 * ep2) * A3 * A3 / 720))
    return x + p.x0, y + p.y0


def _tmerc_inv(x, y, p, xp):
    a, e2 = p.ellps.a, p.ellps.e2
    ep2 = p.ellps.ep2
    k0, lon0, lat0 = p.k0, p.lon0, p.lat0
    x = x - p.x0
    y = y - p.y0
    M0 = _tm_M(math.radians(lat0), e2, a, np)
    M = M0 + y / k0
    e4, e6 = e2 * e2, e2 * e2 * e2
    mu = M / (a * (1 - e2 / 4 - 3 * e4 / 64 - 5 * e6 / 256))
    e1 = (1 - math.sqrt(1 - e2)) / (1 + math.sqrt(1 - e2))
    phi1 = mu + (3 * e1 / 2 - 27 * e1 ** 3 / 32) * xp.sin(2 * mu) \
        + (21 * e1 ** 2 / 16 - 55 * e1 ** 4 / 32) * xp.sin(4 * mu) \
        + (151 * e1 ** 3 / 96) * xp.sin(6 * mu) \
        + (1097 * e1 ** 4 / 512) * xp.sin(8 * mu)
    sphi, cphi = xp.sin(phi1), xp.cos(phi1)
    C1 = ep2 * cphi * cphi
    T1 = (sphi / cphi) ** 2
    N1 = a / xp.sqrt(1 - e2 * sphi * sphi)
    R1 = a * (1 - e2) / (1 - e2 * sphi * sphi) ** 1.5
    D = x / (N1 * k0)
    D2 = D * D
    phi = phi1 - (N1 * sphi / cphi / R1) * (
        D2 / 2 - (5 + 3 * T1 + 10 * C1 - 4 * C1 * C1 - 9 * ep2) * D2 * D2 / 24
        + (61 + 90 * T1 + 298 * C1 + 45 * T1 * T1 - 252 * ep2 - 3 * C1 * C1)
        * D2 * D2 * D2 / 720)
    lam = (D - (1 + 2 * T1 + C1) * D * D2 / 6
           + (5 - 2 * C1 + 28 * T1 - 3 * C1 * C1 + 8 * ep2 + 24 * T1 * T1)
           * D * D2 * D2 / 120) / cphi
    return lon0 + _deg(lam, xp), _deg(phi, xp)


# -- albers equal area (ellipsoidal, Snyder 14-1..14-21) --------------------

def _aea_qm(sphi, e, e2):
    """q for scalar sinphi with python floats (setup constants)."""
    if e == 0.0:
        return 2.0 * sphi
    return (1 - e2) * (sphi / (1 - e2 * sphi * sphi)
                       - (1 / (2 * e)) * math.log((1 - e * sphi) / (1 + e * sphi)))


def _aea_q(sphi, e, e2, xp):
    if e == 0.0:
        return 2.0 * sphi
    return (1 - e2) * (sphi / (1 - e2 * sphi * sphi)
                       - (1 / (2 * e)) * xp.log((1 - e * sphi) / (1 + e * sphi)))


def _aea_consts(p):
    e, e2, a = p.ellps.e, p.ellps.e2, p.ellps.a
    phi1, phi2 = math.radians(p.lat1), math.radians(p.lat2)
    phi0 = math.radians(p.lat0)
    m1 = math.cos(phi1) / math.sqrt(1 - e2 * math.sin(phi1) ** 2)
    m2 = math.cos(phi2) / math.sqrt(1 - e2 * math.sin(phi2) ** 2)
    q0 = _aea_qm(math.sin(phi0), e, e2)
    q1 = _aea_qm(math.sin(phi1), e, e2)
    q2 = _aea_qm(math.sin(phi2), e, e2)
    if abs(phi1 - phi2) < 1e-10:
        n = math.sin(phi1)
    else:
        n = (m1 * m1 - m2 * m2) / (q2 - q1)
    C = m1 * m1 + n * q1
    rho0 = a * math.sqrt(max(C - n * q0, 0.0)) / n
    return n, C, rho0


def _aea_fwd(lon, lat, p, xp):
    e, e2, a = p.ellps.e, p.ellps.e2, p.ellps.a
    n, C, rho0 = _aea_consts(p)
    phi = _rad(lat, xp)
    q = _aea_q(xp.sin(phi), e, e2, xp)
    rho = a * xp.sqrt(xp.maximum(C - n * q, 0.0)) / n
    theta = n * _rad(lon - p.lon0, xp)
    x = rho * xp.sin(theta) + p.x0
    y = rho0 - rho * xp.cos(theta) + p.y0
    return x, y


def _aea_inv(x, y, p, xp):
    e, e2, a = p.ellps.e, p.ellps.e2, p.ellps.a
    n, C, rho0 = _aea_consts(p)
    x = x - p.x0
    y = rho0 - (y - p.y0)
    rho = xp.sqrt(x * x + y * y)
    theta = xp.arctan2(xp.sign(n) * x, xp.sign(n) * y)
    q = (C - (rho * n / a) ** 2) / n
    lon = p.lon0 + _deg(theta / n, xp)
    if e == 0.0:
        phi = xp.arcsin(xp.clip(q / 2.0, -1.0, 1.0))
        return lon, _deg(phi, xp)
    # iterate Snyder 3-16; fixed iteration count keeps it jax-traceable
    phi = xp.arcsin(xp.clip(q / 2.0, -1.0, 1.0))
    for _ in range(6):
        sphi = xp.sin(phi)
        t = 1 - e2 * sphi * sphi
        phi = phi + (t * t / (2 * xp.cos(phi))) * (
            q / (1 - e2)
            - sphi / t
            + (1 / (2 * e)) * xp.log((1 - e * sphi) / (1 + e * sphi)))
    return lon, _deg(phi, xp)


# -- lambert conformal conic (ellipsoidal, Snyder 15-1..15-11) --------------

def _lcc_tm(phi, e):
    return math.tan(math.pi / 4 - phi / 2) / (
        (1 - e * math.sin(phi)) / (1 + e * math.sin(phi))) ** (e / 2)


def _lcc_t(phi, e, xp):
    return xp.tan(math.pi / 4 - phi / 2) / (
        (1 - e * xp.sin(phi)) / (1 + e * xp.sin(phi))) ** (e / 2)


def _lcc_consts(p):
    e, e2 = p.ellps.e, p.ellps.e2
    phi1, phi2 = math.radians(p.lat1), math.radians(p.lat2)
    phi0 = math.radians(p.lat0)
    m1 = math.cos(phi1) / math.sqrt(1 - e2 * math.sin(phi1) ** 2)
    t1 = _lcc_tm(phi1, e)
    if abs(phi1 - phi2) < 1e-10:
        n = math.sin(phi1)
    else:
        m2 = math.cos(phi2) / math.sqrt(1 - e2 * math.sin(phi2) ** 2)
        t2 = _lcc_tm(phi2, e)
        n = (math.log(m1) - math.log(m2)) / (math.log(t1) - math.log(t2))
    F = m1 / (n * t1 ** n)
    rho0 = p.ellps.a * F * _lcc_tm(phi0, e) ** n
    return n, F, rho0


def _lcc_fwd(lon, lat, p, xp):
    e, a = p.ellps.e, p.ellps.a
    n, F, rho0 = _lcc_consts(p)
    phi = _rad(lat, xp)
    t = _lcc_t(phi, e, xp)
    rho = a * F * t ** n
    theta = n * _rad(lon - p.lon0, xp)
    x = rho * xp.sin(theta) + p.x0
    y = rho0 - rho * xp.cos(theta) + p.y0
    return x, y


def _lcc_inv(x, y, p, xp):
    e, a = p.ellps.e, p.ellps.a
    n, F, rho0 = _lcc_consts(p)
    x = x - p.x0
    y = rho0 - (y - p.y0)
    rho = xp.sign(n) * xp.sqrt(x * x + y * y)
    theta = xp.arctan2(xp.sign(n) * x, xp.sign(n) * y)
    t = (rho / (a * F)) ** (1.0 / n)
    # Snyder 7-9 iteration, fixed count
    phi = math.pi / 2 - 2 * xp.arctan(t)
    for _ in range(6):
        sphi = xp.sin(phi)
        phi = math.pi / 2 - 2 * xp.arctan(
            t * ((1 - e * sphi) / (1 + e * sphi)) ** (e / 2))
    lon = p.lon0 + _deg(theta / n, xp)
    return lon, _deg(phi, xp)


# -- sinusoidal (spherical; MODIS grid) -------------------------------------

def _sinu_fwd(lon, lat, p, xp):
    R = p.ellps.a
    phi = _rad(lat, xp)
    x = R * _rad(lon - p.lon0, xp) * xp.cos(phi) + p.x0
    y = R * phi + p.y0
    return x, y


def _sinu_inv(x, y, p, xp):
    R = p.ellps.a
    phi = (y - p.y0) / R
    cphi = xp.cos(phi)
    cphi = xp.where(xp.abs(cphi) < 1e-12, 1e-12, cphi)
    lon = p.lon0 + _deg((x - p.x0) / (R * cphi), xp)
    return lon, _deg(phi, xp)


# -- geostationary (Himawari-8/AHI, GOES; sweep axis y; CGMS LRIT/HRIT) -----

def _geos_fwd(lon, lat, p, xp):
    """PROJ's geos algorithm, sweep=y (Himawari/MSG convention), working in
    units of the semi-major axis."""
    a, e2 = p.ellps.a, p.ellps.e2
    radius_p = math.sqrt(1 - e2)        # b/a
    radius_g = 1.0 + p.h / a            # satellite distance from centre
    radius_g_1 = p.h / a
    lam = _rad(lon - p.lon0, xp)
    phi = xp.arctan(radius_p * radius_p * xp.tan(_rad(lat, xp)))
    r = radius_p / xp.hypot(radius_p * xp.cos(phi), xp.sin(phi))
    vx = r * xp.cos(lam) * xp.cos(phi)
    vy = r * xp.sin(lam) * xp.cos(phi)
    vz = r * xp.sin(phi)
    tmp = radius_g - vx
    # visibility: points on the far side of the earth are not imageable;
    # NaN there so warps resolve them to nodata instead of wrong gathers
    visible = ((radius_g - vx) * vx - vy * vy
               - vz * vz / (radius_p * radius_p)) >= 0.0
    nan = xp.asarray(float("nan"))
    x = xp.where(visible, radius_g_1 * xp.arctan(vy / tmp), nan)
    y = xp.where(visible, radius_g_1 * xp.arctan(vz / xp.hypot(vy, tmp)), nan)
    return a * x + p.x0, a * y + p.y0


def _geos_inv(x, y, p, xp):
    a, e2 = p.ellps.a, p.ellps.e2
    radius_p = math.sqrt(1 - e2)
    radius_p2 = 1 - e2
    radius_p_inv2 = 1.0 / (1 - e2)
    radius_g = 1.0 + p.h / a
    radius_g_1 = p.h / a
    xs = (x - p.x0) / a
    ys = (y - p.y0) / a
    vx = -xp.ones_like(xs * 1.0)
    vy = xp.tan(xs / radius_g_1)
    vz = xp.tan(ys / radius_g_1) * xp.hypot(xp.ones_like(vy), vy)
    av = vz / radius_p
    aq = vy * vy + av * av + vx * vx
    bq = 2 * radius_g * vx
    det = xp.maximum(bq * bq - 4 * aq * (radius_g * radius_g - 1.0), 0.0)
    k = (-bq - xp.sqrt(det)) / (2 * aq)
    vx2 = radius_g + k * vx
    vy2 = k * vy
    vz2 = k * vz
    lam = xp.arctan2(vy2, vx2)
    phi = xp.arctan(vz2 * xp.cos(lam) / vx2)
    phi = xp.arctan(radius_p_inv2 * xp.tan(phi))
    return p.lon0 + _deg(lam, xp), _deg(phi, xp)


_KERNELS = {
    "longlat": (None, None),
    "merc": (_merc_fwd, _merc_inv),
    "webmerc": (_webmerc_fwd, _webmerc_inv),
    "tmerc": (_tmerc_fwd, _tmerc_inv),
    "aea": (_aea_fwd, _aea_inv),
    "lcc": (_lcc_fwd, _lcc_inv),
    "sinu": (_sinu_fwd, _sinu_inv),
    "geos": (_geos_fwd, _geos_inv),
}


# ---------------------------------------------------------------------------
# CRS dataclass
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CRS:
    """A coordinate reference system.

    ``proj`` selects the projection kernel; parameters mirror proj4 names.
    Hashable + frozen so it can be closed over in jitted functions and used
    as a compile-cache key.
    """

    proj: str  # longlat | webmerc | tmerc | aea | lcc | sinu | geos
    ellps: Ellipsoid = WGS84
    lon0: float = 0.0
    lat0: float = 0.0
    lat1: float = 0.0  # 1st standard parallel (aea/lcc)
    lat2: float = 0.0  # 2nd standard parallel (aea/lcc)
    k0: float = 1.0
    x0: float = 0.0
    y0: float = 0.0
    h: float = 0.0  # satellite height (geos)
    epsg: Optional[int] = None  # authority code if known

    # -- transforms ---------------------------------------------------------

    @property
    def is_geographic(self) -> bool:
        return self.proj == "longlat"

    def to_lonlat(self, x, y, xp=np):
        """Projected coords (m) -> lon/lat degrees."""
        if self.proj == "longlat":
            return x, y
        return _KERNELS[self.proj][1](x, y, self, xp)

    def from_lonlat(self, lon, lat, xp=np):
        """lon/lat degrees -> projected coords (m)."""
        if self.proj == "longlat":
            return lon, lat
        return _KERNELS[self.proj][0](lon, lat, self, xp)

    def transform_to(self, other: "CRS", x, y, xp=np):
        """Coordinates in this CRS -> coordinates in ``other``."""
        if self == other:
            return x, y
        lon, lat = self.to_lonlat(x, y, xp)
        return other.from_lonlat(lon, lat, xp)

    # -- descriptions -------------------------------------------------------

    def name(self) -> str:
        if self.epsg is not None:
            return f"EPSG:{self.epsg}"
        return f"+proj={self.proj}"

    def to_wkt(self) -> str:
        """Minimal well-known-text, sufficient for our own round-trip and
        for GeoTIFF/NetCDF metadata emission."""
        if self.proj == "longlat":
            return (
                'GEOGCS["WGS 84",DATUM["WGS_1984",SPHEROID["WGS 84",'
                f'{self.ellps.a},{1.0 / self.ellps.f if self.ellps.f else 0}]],'
                'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433],'
                f'AUTHORITY["EPSG","{self.epsg or 4326}"]]'
            )
        inv_f = 1.0 / self.ellps.f if self.ellps.f else 0.0
        proj_names = {
            "merc": "Mercator_1SP",
            "webmerc": "Mercator_1SP",
            "tmerc": "Transverse_Mercator",
            "aea": "Albers_Conic_Equal_Area",
            "lcc": "Lambert_Conformal_Conic_2SP",
            "sinu": "Sinusoidal",
            "geos": "Geostationary_Satellite",
        }
        params = [
            ("central_meridian", self.lon0),
            ("latitude_of_origin", self.lat0),
            ("standard_parallel_1", self.lat1),
            ("standard_parallel_2", self.lat2),
            ("scale_factor", self.k0),
            ("false_easting", self.x0),
            ("false_northing", self.y0),
        ]
        if self.proj == "geos":
            params.append(("satellite_height", self.h))
        pstr = ",".join(f'PARAMETER["{k}",{v}]' for k, v in params)
        auth = f',AUTHORITY["EPSG","{self.epsg}"]' if self.epsg else ""
        return (
            f'PROJCS["{self.name()}",GEOGCS["WGS 84",DATUM["WGS_1984",'
            f'SPHEROID["WGS 84",{self.ellps.a},{inv_f}]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
            f'PROJECTION["{proj_names[self.proj]}"],{pstr},'
            f'UNIT["metre",1]{auth}]'
        )

    def to_proj4(self) -> str:
        e = self.ellps
        if e.f == 0.0:
            ell = f"+R={e.a}"
        else:
            name = next((n for n, el in _ELLIPSOIDS.items() if el == e), None)
            ell = f"+ellps={name}" if name else f"+a={e.a} +rf={1.0 / e.f}"
        base = {
            "longlat": f"+proj=longlat {ell}",
            "merc": (f"+proj=merc +lon_0={self.lon0} +k={self.k0} "
                     f"+x_0={self.x0} +y_0={self.y0} {ell}"),
            "webmerc": (f"+proj=merc +a={e.a} +b={e.a} +lon_0={self.lon0} "
                        f"+x_0={self.x0} +y_0={self.y0}"),
            "tmerc": (f"+proj=tmerc +lat_0={self.lat0} +lon_0={self.lon0} "
                      f"+k={self.k0} +x_0={self.x0} +y_0={self.y0} {ell}"),
            "aea": (f"+proj=aea +lat_1={self.lat1} +lat_2={self.lat2} "
                    f"+lat_0={self.lat0} +lon_0={self.lon0} "
                    f"+x_0={self.x0} +y_0={self.y0} {ell}"),
            "lcc": (f"+proj=lcc +lat_1={self.lat1} +lat_2={self.lat2} "
                    f"+lat_0={self.lat0} +lon_0={self.lon0} "
                    f"+x_0={self.x0} +y_0={self.y0} {ell}"),
            "sinu": f"+proj=sinu +lon_0={self.lon0} +x_0={self.x0} +y_0={self.y0} {ell}",
            "geos": (f"+proj=geos +h={self.h} +lon_0={self.lon0} "
                     f"+x_0={self.x0} +y_0={self.y0} {ell}"),
        }[self.proj]
        return base + " +units=m +no_defs" if self.proj != "longlat" else base + " +no_defs"


# ---------------------------------------------------------------------------
# Registry / parsing
# ---------------------------------------------------------------------------

EPSG4326 = CRS("longlat", WGS84, epsg=4326)
EPSG3857 = CRS("webmerc", WGS84, epsg=3857)

# Australian Albers (GDA94) — GSKY's home projection for Landsat/geoglam.
EPSG3577 = CRS("aea", GRS80, lon0=132.0, lat0=0.0, lat1=-18.0, lat2=-36.0,
               x0=0.0, y0=0.0, epsg=3577)
# MODIS sinusoidal
CRS_SINU_MODIS = CRS("sinu", SPHERE, lon0=0.0, epsg=None)
# Himawari-8 full disk
CRS_HIMAWARI = CRS("geos", WGS84, lon0=140.7, h=35785863.0, epsg=None)

_STATIC_EPSG = {
    4326: EPSG4326,
    4283: CRS("longlat", GRS80, epsg=4283),  # GDA94 geographic
    3857: EPSG3857,
    900913: CRS("webmerc", WGS84, epsg=900913),
    3577: EPSG3577,
    102008: CRS("aea", GRS80, lon0=-96.0, lat0=40.0, lat1=20.0, lat2=60.0,
                epsg=102008),  # North America Albers
    6974: CRS_SINU_MODIS,  # SR-ORG:6974 style MODIS sinusoidal
}


def _epsg_lookup(code: int) -> CRS:
    if code in _STATIC_EPSG:
        return _STATIC_EPSG[code]
    # UTM WGS84: 326xx north / 327xx south
    if 32601 <= code <= 32660:
        zone = code - 32600
        return CRS("tmerc", WGS84, lon0=zone * 6 - 183, lat0=0.0, k0=0.9996,
                   x0=500000.0, y0=0.0, epsg=code)
    if 32701 <= code <= 32760:
        zone = code - 32700
        return CRS("tmerc", WGS84, lon0=zone * 6 - 183, lat0=0.0, k0=0.9996,
                   x0=500000.0, y0=10000000.0, epsg=code)
    # GDA94 MGA zones 49-56 (EPSG:28349-28356)
    if 28348 <= code <= 28358:
        zone = code - 28300
        return CRS("tmerc", GRS80, lon0=zone * 6 - 183, lat0=0.0, k0=0.9996,
                   x0=500000.0, y0=10000000.0, epsg=code)
    raise ValueError(f"unsupported EPSG code {code}")


_NUM = r"[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?"


def _parse_proj4(s: str) -> CRS:
    kv = {}
    for tok in s.split():
        tok = tok.lstrip("+")
        if "=" in tok:
            k, v = tok.split("=", 1)
            kv[k] = v
        else:
            kv[tok] = True
    proj = kv.get("proj", "longlat")
    if kv.get("R"):
        ellps = Ellipsoid(float(kv["R"]), 0.0)
    elif kv.get("a") and kv.get("b"):
        a, b = float(kv["a"]), float(kv["b"])
        ellps = Ellipsoid(a, (a - b) / a)
    elif kv.get("ellps"):
        name = str(kv["ellps"])
        if name not in _ELLIPSOIDS:
            raise ValueError(f"unsupported ellipsoid {name!r}")
        ellps = _ELLIPSOIDS[name]
    else:
        ellps = WGS84
    def f(name, default=0.0):
        return float(kv.get(name, default))
    if proj == "longlat":
        return CRS("longlat", ellps)
    if proj == "merc":
        # spherical (web) mercator only when explicitly spherical: +R, or
        # +a == +b; otherwise full ellipsoidal mercator
        if ellps.f == 0.0 or (kv.get("a") is not None and kv.get("a") == kv.get("b")):
            return CRS("webmerc", Ellipsoid(ellps.a, 0.0), lon0=f("lon_0"),
                       x0=f("x_0"), y0=f("y_0"))
        return CRS("merc", ellps, lon0=f("lon_0"), k0=f("k", f("k_0", 1.0)),
                   x0=f("x_0"), y0=f("y_0"))
    if proj in ("tmerc", "utm"):
        if proj == "utm":
            zone = int(kv["zone"])
            south = "south" in kv
            return CRS("tmerc", ellps, lon0=zone * 6 - 183, k0=0.9996,
                       x0=500000.0, y0=10000000.0 if south else 0.0)
        return CRS("tmerc", ellps, lon0=f("lon_0"), lat0=f("lat_0"),
                   k0=f("k", f("k_0", 1.0)), x0=f("x_0"), y0=f("y_0"))
    if proj == "aea":
        return CRS("aea", ellps, lon0=f("lon_0"), lat0=f("lat_0"),
                   lat1=f("lat_1"), lat2=f("lat_2"), x0=f("x_0"), y0=f("y_0"))
    if proj == "lcc":
        return CRS("lcc", ellps, lon0=f("lon_0"), lat0=f("lat_0"),
                   lat1=f("lat_1"), lat2=f("lat_2", f("lat_1")),
                   x0=f("x_0"), y0=f("y_0"))
    if proj == "sinu":
        return CRS("sinu", ellps if ellps.f == 0 else SPHERE, lon0=f("lon_0"),
                   x0=f("x_0"), y0=f("y_0"))
    if proj == "geos":
        return CRS("geos", ellps, lon0=f("lon_0"), h=f("h"),
                   x0=f("x_0"), y0=f("y_0"))
    raise ValueError(f"unsupported proj4 projection {proj!r}")


def _wkt_param(wkt: str, name: str, default: float = 0.0) -> float:
    m = re.search(rf'PARAMETER\["{name}",\s*({_NUM})\]', wkt, re.I)
    return float(m.group(1)) if m else default


def _parse_wkt(wkt: str) -> CRS:
    m = re.search(r'AUTHORITY\["EPSG","(\d+)"\]\s*\]\s*$', wkt)
    if m:
        try:
            return _epsg_lookup(int(m.group(1)))
        except ValueError:
            pass
    sp = re.search(rf'SPHEROID\["[^"]*",\s*({_NUM}),\s*({_NUM})', wkt, re.I)
    if sp:
        a = float(sp.group(1))
        inv_f = float(sp.group(2))
        ellps = Ellipsoid(a, 1.0 / inv_f if inv_f else 0.0)
    else:
        ellps = WGS84
    if not re.search(r"PROJCS", wkt, re.I):
        return CRS("longlat", ellps)
    pm = re.search(r'PROJECTION\["([^"]+)"\]', wkt, re.I)
    pname = (pm.group(1) if pm else "").lower()
    lon0 = _wkt_param(wkt, "central_meridian", _wkt_param(wkt, "longitude_of_center"))
    lat0 = _wkt_param(wkt, "latitude_of_origin", _wkt_param(wkt, "latitude_of_center"))
    lat1 = _wkt_param(wkt, "standard_parallel_1")
    lat2 = _wkt_param(wkt, "standard_parallel_2", lat1)
    k0 = _wkt_param(wkt, "scale_factor", 1.0)
    x0 = _wkt_param(wkt, "false_easting")
    y0 = _wkt_param(wkt, "false_northing")
    if "transverse_mercator" in pname:
        return CRS("tmerc", ellps, lon0=lon0, lat0=lat0, k0=k0, x0=x0, y0=y0)
    if "albers" in pname:
        return CRS("aea", ellps, lon0=lon0, lat0=lat0, lat1=lat1, lat2=lat2,
                   x0=x0, y0=y0)
    if "lambert_conformal" in pname:
        return CRS("lcc", ellps, lon0=lon0, lat0=lat0, lat1=lat1, lat2=lat2,
                   x0=x0, y0=y0)
    if "sinusoidal" in pname:
        return CRS("sinu", Ellipsoid(ellps.a, 0.0), lon0=lon0, x0=x0, y0=y0)
    if "mercator" in pname:
        # EPSG:3857-style WKT declares Mercator_1SP on the WGS84 spheroid but
        # is actually spherical ("Pseudo-Mercator"); detect it by name.
        if ellps.f == 0.0 or "pseudo-mercator" in wkt.lower() \
                or "popular visualisation" in wkt.lower():
            return CRS("webmerc", Ellipsoid(ellps.a, 0.0), lon0=lon0,
                       x0=x0, y0=y0)
        return CRS("merc", ellps, lon0=lon0, k0=k0, x0=x0, y0=y0)
    if "geostationary" in pname:
        return CRS("geos", ellps, lon0=lon0,
                   h=_wkt_param(wkt, "satellite_height"), x0=x0, y0=y0)
    raise ValueError(f"unsupported WKT projection {pname!r}")


def parse_crs(s) -> CRS:
    """Parse an EPSG code ('EPSG:3857', 'epsg:4326', 3857), a proj4 string,
    or a WKT string into a CRS."""
    if isinstance(s, CRS):
        return s
    if isinstance(s, int):
        return _epsg_lookup(s)
    s = s.strip()
    m = re.match(r"^(?:urn:ogc:def:crs:)?EPSG:{1,2}(\d+)$", s, re.I)
    if m:
        return _epsg_lookup(int(m.group(1)))
    if s.upper() in ("CRS:84", "WGS84", "WGS:84"):
        return EPSG4326
    if s.startswith("+"):
        return _parse_proj4(s)
    if s.upper().startswith(("GEOGCS", "PROJCS", "GEOGCRS", "PROJCRS")):
        return _parse_wkt(s)
    raise ValueError(f"cannot parse CRS {s!r}")
