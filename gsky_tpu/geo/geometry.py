"""Geometry: WKT / GeoJSON parsing, predicates, area, rasterization.

Replaces the reference's OGR/geos usage: polygon area for the WPS request
limit (`utils/wps.go:245`), geometry normalisation for metrics
(`metrics/metrics.go:156-210`), MAS's Douglas-Peucker simplification
(`mas/api/mas.sql:424-432`), and the drill mask burn
(`worker/gdalprocess/drill.go:275-327` — GDALRasterizeGeometries with
ALL_TOUCHED=TRUE), all with no native geometry library.
"""

from __future__ import annotations

import json
import math
import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .transform import BBox

Ring = np.ndarray  # (N, 2) float64, closed (first == last) not required


@dataclass
class Geometry:
    """Point / LineString / Polygon / MultiPolygon.

    ``polys`` is a list of polygons; each polygon is a list of rings
    (first exterior, rest holes); each ring an (N,2) array of x,y.
    Points/lines are stored in ``points``.
    """

    kind: str  # Point | MultiPoint | LineString | Polygon | MultiPolygon
    polys: List[List[Ring]] = field(default_factory=list)
    points: Optional[np.ndarray] = None  # (N,2) for point/line kinds

    # -- constructors -------------------------------------------------------

    @classmethod
    def point(cls, x: float, y: float) -> "Geometry":
        return cls("Point", points=np.array([[x, y]], dtype=np.float64))

    @classmethod
    def polygon(cls, rings: Sequence[Sequence[Tuple[float, float]]]) -> "Geometry":
        return cls("Polygon", polys=[[np.asarray(r, dtype=np.float64) for r in rings]])

    @classmethod
    def bbox_polygon(cls, b: BBox) -> "Geometry":
        return cls.polygon([[(b.xmin, b.ymin), (b.xmax, b.ymin),
                             (b.xmax, b.ymax), (b.xmin, b.ymax),
                             (b.xmin, b.ymin)]])

    # -- basics -------------------------------------------------------------

    def bbox(self) -> BBox:
        arrs = []
        if self.points is not None:
            arrs.append(self.points)
        for poly in self.polys:
            arrs.extend(poly)
        pts = np.concatenate(arrs, axis=0)
        return BBox(float(pts[:, 0].min()), float(pts[:, 1].min()),
                    float(pts[:, 0].max()), float(pts[:, 1].max()))

    def transform(self, fn) -> "Geometry":
        """Apply fn(x_array, y_array) -> (x, y) to every vertex."""
        def t(a):
            x, y = fn(a[:, 0], a[:, 1])
            return np.stack([np.asarray(x), np.asarray(y)], axis=1)
        return Geometry(
            self.kind,
            polys=[[t(r) for r in poly] for poly in self.polys],
            points=t(self.points) if self.points is not None else None,
        )

    def area(self) -> float:
        """Planar area (units of the coordinate system squared)."""
        total = 0.0
        for poly in self.polys:
            for i, ring in enumerate(poly):
                a = abs(_shoelace(ring))
                total += a if i == 0 else -a
        return total

    # -- predicates ---------------------------------------------------------

    def contains_point(self, x: float, y: float) -> bool:
        for poly in self.polys:
            if _point_in_ring(poly[0], x, y):
                if not any(_point_in_ring(h, x, y) for h in poly[1:]):
                    return True
        return False

    def intersects_bbox(self, b: BBox) -> bool:
        """Accurate polygon/bbox intersection test (used by the MAS index in
        place of PostGIS ST_Intersects for bbox queries)."""
        if not self.bbox().intersects(b):
            return False
        if self.kind in ("Point", "MultiPoint"):
            return any(b.xmin <= p[0] <= b.xmax and b.ymin <= p[1] <= b.ymax
                       for p in self.points)
        if self.kind == "LineString":
            return _segments_cross_bbox(self.points, b)
        # any bbox corner (or its centre) inside the polygon?
        if _bbox_corner_hits(self, b):
            return True
        if self.contains_point((b.xmin + b.xmax) / 2, (b.ymin + b.ymax) / 2):
            return True
        for poly in self.polys:
            for ring in poly:  # exterior AND holes: a hole boundary crossing
                # the bbox means polygon material enters it too
                inside = ((ring[:, 0] >= b.xmin) & (ring[:, 0] <= b.xmax)
                          & (ring[:, 1] >= b.ymin) & (ring[:, 1] <= b.ymax))
                if inside.any() and ring is poly[0]:
                    return True
                if _segments_cross_bbox(ring, b):
                    # an edge passes through the bbox; for holes this still
                    # implies polygon material in the bbox (hole boundary is
                    # adjacent to material)
                    return True
        return False

    # -- simplification (Douglas-Peucker, cf. mas.sql:424-432) --------------

    def simplify(self, tol: float) -> "Geometry":
        def simp(r):
            s = _douglas_peucker(r, tol)
            return s if len(s) >= 4 else r
        return Geometry(self.kind,
                        polys=[[simp(r) for r in poly] for poly in self.polys],
                        points=self.points)

    def segmentize(self, max_len: float) -> "Geometry":
        """Insert vertices so no segment exceeds max_len (PostGIS
        ST_Segmentize, used before lossy reprojection in mas.sql)."""
        def seg(r):
            out = [r[0]]
            for i in range(1, len(r)):
                p0, p1 = r[i - 1], r[i]
                d = math.hypot(p1[0] - p0[0], p1[1] - p0[1])
                n = max(1, int(math.ceil(d / max_len)))
                for k in range(1, n + 1):
                    out.append(p0 + (p1 - p0) * (k / n))
            return np.asarray(out)
        return Geometry(self.kind,
                        polys=[[seg(r) for r in poly] for poly in self.polys],
                        points=self.points)

    # -- antimeridian handling (ST_SplitDatelineWGS84, mas.sql:13-84) -------

    def clip_bbox(self, b: BBox) -> "Geometry":
        """Polygon intersection with an axis-aligned box (four
        Sutherland-Hodgman half-plane passes per ring) — the drill
        indexer's OGR_G_Intersection-with-tile equivalent.  Polygons
        whose exterior clips away entirely drop; holes clip with their
        polygon."""
        def clip_ring(r):
            c = r
            for axis, bound, keep_le in ((0, b.xmin, False),
                                         (0, b.xmax, True),
                                         (1, b.ymin, False),
                                         (1, b.ymax, True)):
                if not len(c):
                    break
                c = _clip_ring_halfplane(c, axis, bound, keep_le)
            return c

        polys = []
        for rings in self.polys:
            ext = clip_ring(rings[0]) if rings else np.zeros((0, 2))
            # drop degenerate output (same >=4-point rule as
            # split_dateline): S-H clipping of concave subjects can emit
            # sliver rings that an ALL_TOUCHED burn would wrongly count
            if len(ext) < 4:
                continue
            keep = [ext]
            for hole in rings[1:]:
                h = clip_ring(hole)
                if len(h) >= 4:
                    keep.append(h)
            polys.append(keep)
        kind = "MultiPolygon" if len(polys) > 1 else "Polygon"
        return Geometry(kind, polys=polys)

    @property
    def is_empty(self) -> bool:
        return not self.polys or all(
            not rings or not len(rings[0]) for rings in self.polys)

    def split_dateline(self) -> "Geometry":
        """Split polygons whose longitudes span the antimeridian into a
        MultiPolygon with parts on both sides of ±180 — without this, a
        zone-60/zone-1 footprint transformed to WGS84 (mixed ±179.x
        vertices) reads as a sliver wrapped the wrong way around the
        planet and point/bbox predicates mis-answer on both sides.
        Reference: `mas/api/mas.sql:13-84` (shift east, clip at the
        hemisphere boundary, translate the western part back)."""
        if self.kind not in ("Polygon", "MultiPolygon"):
            return self
        out_polys: List[List[Ring]] = []
        changed = False
        for poly in self.polys:
            ext = poly[0]
            lons = ext[:, 0]
            if lons.max() - lons.min() <= 180.0:
                out_polys.append(poly)
                continue
            changed = True
            # ST_ShiftLongitude: extend into 0..360
            shifted = [r.copy() for r in poly]
            for r in shifted:
                r[:, 0] = np.where(r[:, 0] < 0, r[:, 0] + 360.0, r[:, 0])
            east = [_clip_ring_halfplane(r, 0, 180.0, keep_le=True) for r in shifted]
            west = [_clip_ring_halfplane(r, 0, 180.0, keep_le=False) for r in shifted]
            east = [r for r in east if len(r) >= 4]
            west = [r for r in west if len(r) >= 4]
            # wide-but-not-crossing footprints (e.g. a rule-driven
            # whole-world bbox with vertices AT ±180) collapse under the
            # shift: -180 and +180 land on the same meridian, the
            # shifted exterior has ~zero area, and the clip yields
            # slivers.  A genuinely crossing footprint unwraps to a
            # small-but-real area instead — so a degenerate SHIFTED
            # exterior means "wasn't crossing": keep the polygon whole.
            # EXACT zero, not a relative epsilon: an ultra-thin but
            # genuinely-crossing sliver has a tiny REAL shifted area and
            # must still split; only the all-vertices-on-one-meridian
            # collapse (the +/-180 world-footprint case) shifts to an
            # exactly degenerate exterior
            if abs(_shoelace(shifted[0])) == 0.0:
                out_polys.append(poly)
                continue
            if east:
                out_polys.append(east)
            if west:
                for r in west:
                    r[:, 0] -= 360.0
                out_polys.append(west)
        if not changed:
            return self
        if len(out_polys) == 1:
            return Geometry("Polygon", polys=out_polys)
        return Geometry("MultiPolygon", polys=out_polys)

    # -- serialisation ------------------------------------------------------

    def to_wkt(self, ndigits: int = 8) -> str:
        def fmt(v):
            s = f"{v:.{ndigits}f}".rstrip("0").rstrip(".")
            return s if s not in ("-0", "") else "0"

        def ring_wkt(r):
            pts = list(r)
            if len(pts) and (pts[0][0] != pts[-1][0] or pts[0][1] != pts[-1][1]):
                pts.append(pts[0])
            return "(" + ",".join(f"{fmt(p[0])} {fmt(p[1])}" for p in pts) + ")"

        if self.kind == "Point":
            p = self.points[0]
            return f"POINT({fmt(p[0])} {fmt(p[1])})"
        if self.kind in ("LineString", "MultiPoint"):
            body = ",".join(f"{fmt(p[0])} {fmt(p[1])}" for p in self.points)
            return f"{self.kind.upper()}({body})"
        if self.kind == "Polygon":
            return "POLYGON(" + ",".join(ring_wkt(r) for r in self.polys[0]) + ")"
        if self.kind == "MultiPolygon":
            return "MULTIPOLYGON(" + ",".join(
                "(" + ",".join(ring_wkt(r) for r in poly) + ")"
                for poly in self.polys) + ")"
        raise ValueError(self.kind)

    def to_geojson(self) -> dict:
        def ring(r):
            pts = [[float(p[0]), float(p[1])] for p in r]
            if pts and pts[0] != pts[-1]:
                pts.append(pts[0])
            return pts
        if self.kind == "Point":
            return {"type": "Point",
                    "coordinates": [float(self.points[0][0]), float(self.points[0][1])]}
        if self.kind in ("LineString", "MultiPoint"):
            return {"type": self.kind,
                    "coordinates": [[float(p[0]), float(p[1])] for p in self.points]}
        if self.kind == "Polygon":
            return {"type": "Polygon",
                    "coordinates": [ring(r) for r in self.polys[0]]}
        if self.kind == "MultiPolygon":
            return {"type": "MultiPolygon",
                    "coordinates": [[ring(r) for r in poly] for poly in self.polys]}
        raise ValueError(self.kind)


# ---------------------------------------------------------------------------
# internal helpers
# ---------------------------------------------------------------------------

def _clip_ring_halfplane(ring: Ring, axis: int, bound: float,
                         keep_le: bool) -> Ring:
    """Sutherland-Hodgman clip of a ring against an axis-aligned
    half-plane (coord[axis] <= bound or >= bound), closing the result."""
    def inside(p):
        return p[axis] <= bound if keep_le else p[axis] >= bound

    def cross(p0, p1):
        t = (bound - p0[axis]) / (p1[axis] - p0[axis])
        q = p0 + t * (np.asarray(p1, np.float64) - p0)
        q[axis] = bound
        return q

    pts = [np.asarray(p, np.float64) for p in ring]
    if len(pts) and np.array_equal(pts[0], pts[-1]):
        pts = pts[:-1]
    out: List[np.ndarray] = []
    for i, p1 in enumerate(pts):
        p0 = pts[i - 1]
        if inside(p1):
            if not inside(p0):
                out.append(cross(p0, p1))
            out.append(np.asarray(p1, np.float64))
        elif inside(p0):
            out.append(cross(p0, p1))
    if len(out) < 3:
        return np.zeros((0, 2))
    out.append(out[0])
    return np.asarray(out, np.float64)


def _shoelace(ring: Ring) -> float:
    x, y = ring[:, 0], ring[:, 1]
    return 0.5 * float(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y))


def contains_mask(g: "Geometry", xs: np.ndarray,
                  ys: np.ndarray) -> np.ndarray:
    """Vectorised `contains_point` over coordinate arrays — the
    polygon-membership test for CURVILINEAR sample grids, where every
    sample carries its own (lon, lat) and an affine rasterize cannot
    apply (the drill's swath-mask analogue of the ALL_TOUCHED burn).
    Same even-odd ray-cast convention as `_point_in_ring`."""
    xs = np.asarray(xs, np.float64)
    ys = np.asarray(ys, np.float64)
    inside = np.zeros(xs.shape, bool)

    def ray(ring, px, py):
        x, y = ring[:, 0], ring[:, 1]
        x2, y2 = np.roll(x, -1), np.roll(y, -1)
        cnt = np.zeros(px.shape, np.int64)
        with np.errstate(divide="ignore", invalid="ignore"):
            for i in range(len(x)):
                if y[i] == y2[i]:
                    continue
                cond = (y[i] > py) != (y2[i] > py)
                xint = x[i] + (py - y[i]) * (x2[i] - x[i]) \
                    / (y2[i] - y[i])
                cnt += (cond & (px < xint)).astype(np.int64)
        return cnt % 2 == 1

    for poly in g.polys:
        if not poly or not len(poly[0]):
            continue
        acc = ray(poly[0], xs, ys)
        for hole in poly[1:]:
            if len(hole):
                acc &= ~ray(hole, xs, ys)
        inside |= acc
    return inside


def _point_in_ring(ring: Ring, px: float, py: float) -> bool:
    x, y = ring[:, 0], ring[:, 1]
    x2, y2 = np.roll(x, -1), np.roll(y, -1)
    cond = (y > py) != (y2 > py)
    with np.errstate(divide="ignore", invalid="ignore"):
        xint = x + (py - y) * (x2 - x) / (y2 - y)
    crossings = np.count_nonzero(cond & (px < xint))
    return bool(crossings % 2)


def _bbox_corner_hits(g: "Geometry", b: BBox) -> bool:
    return any(g.contains_point(cx, cy) for cx, cy in
               ((b.xmin, b.ymin), (b.xmax, b.ymin), (b.xmax, b.ymax), (b.xmin, b.ymax)))


def _segments_cross_bbox(pts: np.ndarray, b: BBox) -> bool:
    # Cohen–Sutherland-ish: a segment crosses the bbox iff its clipped
    # parametric interval is non-empty.
    p0 = pts[:-1]
    p1 = pts[1:]
    d = p1 - p0
    t0 = np.zeros(len(p0))
    t1 = np.ones(len(p0))
    ok = np.ones(len(p0), dtype=bool)
    for axis, lo, hi in ((0, b.xmin, b.xmax), (1, b.ymin, b.ymax)):
        dv = d[:, axis]
        pv = p0[:, axis]
        with np.errstate(divide="ignore", invalid="ignore"):
            tl = (lo - pv) / dv
            th = (hi - pv) / dv
        tlo = np.where(dv >= 0, tl, th)
        thi = np.where(dv >= 0, th, tl)
        par = dv == 0
        inside_par = (pv >= lo) & (pv <= hi)
        t0 = np.where(par, t0, np.maximum(t0, tlo))
        t1 = np.where(par, t1, np.minimum(t1, thi))
        ok &= np.where(par, inside_par, True)
    return bool(np.any(ok & (t0 <= t1)))


def _douglas_peucker(ring: Ring, tol: float) -> Ring:
    n = len(ring)
    if n < 3:
        return ring
    keep = np.zeros(n, dtype=bool)
    keep[0] = keep[-1] = True
    stack = [(0, n - 1)]
    while stack:
        i0, i1 = stack.pop()
        if i1 <= i0 + 1:
            continue
        p0, p1 = ring[i0], ring[i1]
        seg = p1 - p0
        L = math.hypot(seg[0], seg[1])
        pts = ring[i0 + 1:i1]
        if L == 0:
            d = np.hypot(pts[:, 0] - p0[0], pts[:, 1] - p0[1])
        else:
            d = np.abs(np.cross(seg, pts - p0)) / L
        imax = int(np.argmax(d))
        if d[imax] > tol:
            k = i0 + 1 + imax
            keep[k] = True
            stack.append((i0, k))
            stack.append((k, i1))
    return ring[keep]


# ---------------------------------------------------------------------------
# Rasterization — the drill mask burn
# ---------------------------------------------------------------------------

def rasterize(geom: Geometry, width: int, height: int,
              geo_to_pixel, all_touched: bool = True) -> np.ndarray:
    """Burn a geometry into a (height, width) uint8 mask.

    ``geo_to_pixel(x_arr, y_arr) -> (col, row)`` maps geometry coordinates to
    fractional pixel coords.  ``all_touched=True`` matches the reference's
    GDALRasterizeGeometries ALL_TOUCHED=TRUE burn
    (`worker/gdalprocess/drill.go:309-316`): any pixel touched by the polygon
    boundary or interior is set.
    """
    mask = np.zeros((height, width), dtype=np.uint8)
    if geom.kind in ("Point", "MultiPoint"):
        c, r = geo_to_pixel(geom.points[:, 0], geom.points[:, 1])
        c = np.floor(np.asarray(c)).astype(int)
        r = np.floor(np.asarray(r)).astype(int)
        ok = (c >= 0) & (c < width) & (r >= 0) & (r < height)
        mask[r[ok], c[ok]] = 1
        return mask
    if geom.kind == "LineString":
        c, r = geo_to_pixel(geom.points[:, 0], geom.points[:, 1])
        px = np.stack([np.asarray(c, dtype=np.float64),
                       np.asarray(r, dtype=np.float64)], axis=1)
        _burn_lines(mask, px)
        return mask

    for poly in geom.polys:
        rings_px = []
        for ring in poly:
            c, r = geo_to_pixel(ring[:, 0], ring[:, 1])
            rings_px.append(np.stack([np.asarray(c, dtype=np.float64),
                                      np.asarray(r, dtype=np.float64)], axis=1))
        _fill_polygon(mask, rings_px, all_touched)
    return mask


def _fill_polygon(mask: np.ndarray, rings: List[np.ndarray], all_touched: bool):
    height, width = mask.shape
    # Scanline fill with even-odd rule at pixel centres (row + 0.5),
    # vectorised over edges: for each edge find its active row span, compute
    # all its scanline x-intersections at once, then sort crossings per row.
    def close(r):
        if len(r) and (r[0][0] != r[-1][0] or r[0][1] != r[-1][1]):
            return np.vstack([r, r[:1]])
        return r

    rings = [close(r) for r in rings]
    ey0, ey1, ex0, eslope = [], [], [], []
    for ring in rings:
        pts = ring
        if len(pts) < 3:
            continue
        x0, y0 = pts[:-1, 0], pts[:-1, 1]
        x1, y1 = pts[1:, 0], pts[1:, 1]
        nz = y0 != y1
        x0, y0, x1, y1 = x0[nz], y0[nz], x1[nz], y1[nz]
        swap = y0 > y1
        x0s = np.where(swap, x1, x0)
        y0s = np.where(swap, y1, y0)
        x1s = np.where(swap, x0, x1)
        y1s = np.where(swap, y0, y1)
        ey0.append(y0s)
        ey1.append(y1s)
        ex0.append(x0s)
        eslope.append((x1s - x0s) / (y1s - y0s))
    if not ey0:
        return
    y0 = np.concatenate(ey0)
    y1 = np.concatenate(ey1)
    x0 = np.concatenate(ex0)
    slope = np.concatenate(eslope)
    # active row range per edge: rows with y0 <= row+0.5 < y1
    r0 = np.maximum(np.ceil(y0 - 0.5).astype(np.int64), 0)
    r1 = np.minimum(np.ceil(y1 - 0.5).astype(np.int64), height)  # exclusive
    counts = np.maximum(r1 - r0, 0)
    total = int(counts.sum())
    if total:
        # expand to one (row, x) crossing per active edge-row
        eidx = np.repeat(np.arange(len(y0)), counts)
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        rows = r0[eidx] + (np.arange(total) - starts)
        xs = x0[eidx] + (rows + 0.5 - y0[eidx]) * slope[eidx]
        # sort by (row, x) and pair consecutive crossings per row
        order = np.lexsort((xs, rows))
        rows, xs = rows[order], xs[order]
        row_start = np.searchsorted(rows, np.arange(height), side="left")
        row_end = np.searchsorted(rows, np.arange(height), side="right")
        for row in range(height):
            s, e = row_start[row], row_end[row]
            if s >= e:
                continue
            rxs = xs[s:e]
            for i in range(0, len(rxs) - 1, 2):
                c0 = int(math.ceil(rxs[i] - 0.5))
                c1 = int(math.floor(rxs[i + 1] - 0.5))
                if c1 >= 0 and c0 < width:
                    mask[row, max(c0, 0):min(c1, width - 1) + 1] = 1
    if all_touched:
        # also burn every pixel the (closed) boundary passes through
        for ring in rings:
            _burn_lines(mask, ring)


def _burn_lines(mask: np.ndarray, ring: np.ndarray):
    height, width = mask.shape
    pts = ring
    for i in range(len(pts) - 1):
        x0, y0 = pts[i]
        x1, y1 = pts[i + 1]
        n = int(max(abs(x1 - x0), abs(y1 - y0)) * 2) + 1
        t = np.linspace(0.0, 1.0, n + 1)
        cx = np.floor(x0 + (x1 - x0) * t).astype(int)
        cy = np.floor(y0 + (y1 - y0) * t).astype(int)
        ok = (cx >= 0) & (cx < width) & (cy >= 0) & (cy < height)
        mask[cy[ok], cx[ok]] = 1


# ---------------------------------------------------------------------------
# Parsing
# ---------------------------------------------------------------------------

_WKT_NUM = r"[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?"


def _parse_ring_text(t: str) -> np.ndarray:
    pts = []
    for pair in t.split(","):
        xy = pair.split()
        pts.append((float(xy[0]), float(xy[1])))
    return np.asarray(pts, dtype=np.float64)


def _split_parens(t: str) -> List[str]:
    """Extract the contents of each top-level parenthesised group:
    '(a),(b (c))' -> ['a', 'b (c)']."""
    out, depth, cur = [], 0, []
    for ch in t:
        if ch == "(":
            depth += 1
            if depth == 1:
                cur = []
                continue
        elif ch == ")":
            depth -= 1
            if depth == 0:
                out.append("".join(cur))
                continue
        if depth >= 1:
            cur.append(ch)
    return out


def from_wkt(wkt: str) -> Geometry:
    s = wkt.strip()
    m = re.match(r"^\s*(\w+)\s*\((.*)\)\s*$", s, re.S)
    if not m:
        raise ValueError(f"bad WKT: {wkt[:80]!r}")
    kind = m.group(1).upper()
    body = m.group(2)
    if kind == "POINT":
        xy = body.split()
        return Geometry.point(float(xy[0]), float(xy[1]))
    if kind == "LINESTRING":
        return Geometry("LineString", points=_parse_ring_text(body))
    if kind == "POLYGON":
        rings = [_parse_ring_text(r) for r in _split_parens(body)]
        return Geometry("Polygon", polys=[rings])
    if kind == "MULTIPOLYGON":
        polys = []
        for poly_txt in _split_parens(body):
            rings = [_parse_ring_text(r) for r in _split_parens(poly_txt)]
            polys.append(rings)
        return Geometry("MultiPolygon", polys=polys)
    raise ValueError(f"unsupported WKT type {kind}")


def from_geojson(obj) -> Geometry:
    """Parse a GeoJSON geometry / Feature / FeatureCollection (first feature),
    matching the WPS input handling (`ows.go:1280-1304`)."""
    if isinstance(obj, (str, bytes)):
        obj = json.loads(obj)
    t = obj.get("type")
    if t == "FeatureCollection":
        feats = obj.get("features") or []
        if not feats:
            raise ValueError("empty FeatureCollection")
        return from_geojson(feats[0])
    if t == "Feature":
        return from_geojson(obj["geometry"])
    coords = obj.get("coordinates")
    if t == "Point":
        return Geometry.point(float(coords[0]), float(coords[1]))
    if t == "LineString":
        return Geometry("LineString", points=np.asarray(coords, dtype=np.float64))
    if t == "Polygon":
        return Geometry("Polygon",
                        polys=[[np.asarray(r, dtype=np.float64) for r in coords]])
    if t == "MultiPolygon":
        return Geometry("MultiPolygon",
                        polys=[[np.asarray(r, dtype=np.float64) for r in poly]
                               for poly in coords])
    raise ValueError(f"unsupported GeoJSON type {t}")
