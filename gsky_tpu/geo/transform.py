"""Affine geotransforms, bounding boxes and tile grids.

Mirrors the coordinate plumbing the reference scatters across
``utils/wms.go:487-532`` (canonical bbox / pixel resolution),
``worker/gdalprocess/warp.go:103-155`` (geotransform handling) and the WMS
tile conventions — rebuilt as small pure functions over numpy/jax arrays.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np

from .crs import CRS, EPSG3857, EPSG4326

# Web-mercator world extent (what WMS EPSG:3857 tiles address).
MERC_ORIGIN = 20037508.342789244


@dataclass(frozen=True)
class BBox:
    """Axis-aligned bounding box in some CRS: (xmin, ymin, xmax, ymax)."""

    xmin: float
    ymin: float
    xmax: float
    ymax: float

    def as_tuple(self) -> Tuple[float, float, float, float]:
        return (self.xmin, self.ymin, self.xmax, self.ymax)

    @property
    def width(self) -> float:
        return self.xmax - self.xmin

    @property
    def height(self) -> float:
        return self.ymax - self.ymin

    def intersects(self, other: "BBox") -> bool:
        return not (self.xmax <= other.xmin or other.xmax <= self.xmin
                    or self.ymax <= other.ymin or other.ymax <= self.ymin)

    def intersection(self, other: "BBox") -> "BBox":
        return BBox(max(self.xmin, other.xmin), max(self.ymin, other.ymin),
                    min(self.xmax, other.xmax), min(self.ymax, other.ymax))

    def union(self, other: "BBox") -> "BBox":
        return BBox(min(self.xmin, other.xmin), min(self.ymin, other.ymin),
                    max(self.xmax, other.xmax), max(self.ymax, other.ymax))

    def is_empty(self) -> bool:
        return self.xmax <= self.xmin or self.ymax <= self.ymin

    def buffer(self, d: float) -> "BBox":
        return BBox(self.xmin - d, self.ymin - d, self.xmax + d, self.ymax + d)

    def to_polygon_wkt(self) -> str:
        return (f"POLYGON(({self.xmin} {self.ymin},{self.xmax} {self.ymin},"
                f"{self.xmax} {self.ymax},{self.xmin} {self.ymax},"
                f"{self.xmin} {self.ymin}))")


@dataclass(frozen=True)
class GeoTransform:
    """GDAL-style affine geotransform.

    ``x = x0 + col*dx + row*rx``, ``y = y0 + col*ry + row*dy`` where
    (x0, y0) is the outer corner of pixel (0, 0) and pixel coordinates are
    measured at pixel centres as (col + 0.5, row + 0.5).
    Matches the 6-tuple used throughout the reference
    (`worker/gdalprocess/warp.go:118-131`).
    """

    x0: float
    dx: float
    rx: float  # row rotation/shear term for x
    y0: float
    ry: float  # column rotation/shear term for y
    dy: float

    @classmethod
    def from_gdal(cls, g: Sequence[float]) -> "GeoTransform":
        return cls(g[0], g[1], g[2], g[3], g[4], g[5])

    def to_gdal(self) -> Tuple[float, ...]:
        return (self.x0, self.dx, self.rx, self.y0, self.ry, self.dy)

    @classmethod
    def from_bbox(cls, bbox: BBox, width: int, height: int) -> "GeoTransform":
        """North-up transform covering bbox with width x height pixels."""
        return cls(bbox.xmin, bbox.width / width, 0.0,
                   bbox.ymax, 0.0, -bbox.height / height)

    # -- pixel <-> geo ------------------------------------------------------

    def pixel_to_geo(self, col, row, xp=np):
        """(col,row) pixel coords (fractional, origin at corner) -> (x,y)."""
        x = self.x0 + col * self.dx + row * self.rx
        y = self.y0 + col * self.ry + row * self.dy
        return x, y

    def geo_to_pixel(self, x, y, xp=np):
        """(x,y) -> fractional (col,row)."""
        det = self.dx * self.dy - self.rx * self.ry
        inv_dx = self.dy / det
        inv_rx = -self.rx / det
        inv_ry = -self.ry / det
        inv_dy = self.dx / det
        dxv = x - self.x0
        dyv = y - self.y0
        col = inv_dx * dxv + inv_rx * dyv
        row = inv_ry * dxv + inv_dy * dyv
        return col, row

    def bbox(self, width: int, height: int) -> BBox:
        xs, ys = [], []
        for c, r in ((0, 0), (width, 0), (0, height), (width, height)):
            x, y = self.pixel_to_geo(c, r)
            xs.append(x)
            ys.append(y)
        return BBox(min(xs), min(ys), max(xs), max(ys))

    @property
    def is_north_up(self) -> bool:
        return self.rx == 0.0 and self.ry == 0.0

    def resolution(self) -> Tuple[float, float]:
        return (math.hypot(self.dx, self.ry), math.hypot(self.rx, self.dy))

    def window(self, col0: int, row0: int) -> "GeoTransform":
        """Transform for a sub-window starting at pixel (col0, row0)."""
        x0, y0 = self.pixel_to_geo(col0, row0)
        return GeoTransform(x0, self.dx, self.rx, y0, self.ry, self.dy)

    def scaled(self, fx: float, fy: float) -> "GeoTransform":
        """Transform for the same extent at resolution scaled by (fx, fy)
        (fx > 1 means coarser pixels)."""
        return GeoTransform(self.x0, self.dx * fx, self.rx * fy,
                            self.y0, self.ry * fx, self.dy * fy)

    def decimated(self, st: int) -> "GeoTransform":
        """Transform for a [::st, ::st] strided sampling of this grid:
        decimated pixel k holds the VALUE of full-res pixel k*st, so the
        origin shifts back by (st-1)/2 pixels to keep sample centres
        honest (unlike `scaled`, which models extent-preserving
        block-reduced overviews)."""
        return GeoTransform(
            self.x0 - (st - 1) / 2 * (self.dx + self.rx),
            self.dx * st, self.rx * st,
            self.y0 - (st - 1) / 2 * (self.ry + self.dy),
            self.ry * st, self.dy * st)


# ---------------------------------------------------------------------------
# Reprojection of extents
# ---------------------------------------------------------------------------

def transform_bbox(bbox: BBox, src: CRS, dst: CRS, densify: int = 21) -> BBox:
    """Reproject a bbox by densified edge sampling (the robust way GDAL's
    transformer approximates reprojected extents; cf. `utils/wms.go:498-521`
    which samples the 4 corners via OSR)."""
    if src == dst:
        return bbox
    t = np.linspace(0.0, 1.0, densify)
    xs = bbox.xmin + t * bbox.width
    ys = bbox.ymin + t * bbox.height
    ex = np.concatenate([xs, xs, np.full_like(t, bbox.xmin), np.full_like(t, bbox.xmax)])
    ey = np.concatenate([np.full_like(t, bbox.ymin), np.full_like(t, bbox.ymax), ys, ys])
    ox, oy = src.transform_to(dst, ex, ey)
    ok = np.isfinite(ox) & np.isfinite(oy)
    if not ok.any():
        raise ValueError("bbox does not transform into destination CRS")
    return BBox(float(np.min(ox[ok])), float(np.min(oy[ok])),
                float(np.max(ox[ok])), float(np.max(oy[ok])))


def canonical_bbox(bbox: BBox, crs: CRS) -> BBox:
    """Canonicalise a request bbox into EPSG:3857, mirroring
    `utils/wms.go:487-522` (used for zoom-level / overview decisions)."""
    return transform_bbox(bbox, crs, EPSG3857)


def pixel_resolution(bbox: BBox, crs: CRS, width: int, height: int) -> float:
    """EPSG:3857 metres/pixel of a request, cf. `utils/wms.go:524-532`."""
    c = canonical_bbox(bbox, crs)
    return max(c.width / width, c.height / height)


def suggest_output_size(src_gt: GeoTransform, src_w: int, src_h: int,
                        src_crs: CRS, dst_crs: CRS,
                        max_size: int = 65536) -> Tuple[BBox, int, int]:
    """Suggest a destination extent + pixel size that roughly preserves source
    resolution — the role of GDALSuggestedWarpOutput in the reference's extent
    op (`worker/gdalprocess/warp.go:433-487`)."""
    src_bbox = src_gt.bbox(src_w, src_h)
    dst_bbox = transform_bbox(src_bbox, src_crs, dst_crs)
    # estimate dst resolution by transforming the pixel diagonal at centre
    cx = (src_bbox.xmin + src_bbox.xmax) / 2
    cy = (src_bbox.ymin + src_bbox.ymax) / 2
    rx, ry = src_gt.resolution()
    x2, y2 = src_crs.transform_to(dst_crs, np.array([cx, cx + rx]), np.array([cy, cy + ry]))
    dres = max(min(abs(float(x2[1] - x2[0])), abs(float(y2[1] - y2[0]))), 1e-9)
    w = max(1, min(max_size, int(round(dst_bbox.width / dres))))
    h = max(1, min(max_size, int(round(dst_bbox.height / dres))))
    return dst_bbox, w, h


# ---------------------------------------------------------------------------
# Tile maths
# ---------------------------------------------------------------------------

def split_bbox(bbox: BBox, width: int, height: int,
               tile_w: int, tile_h: int):
    """Split an output raster into tiles, yielding
    (tile_bbox, off_x, off_y, tw, th) — the WCS large-output decomposition
    (`ows.go:815-833`)."""
    gt = GeoTransform.from_bbox(bbox, width, height)
    out = []
    for row0 in range(0, height, tile_h):
        th = min(tile_h, height - row0)
        for col0 in range(0, width, tile_w):
            tw = min(tile_w, width - col0)
            x0, y0 = gt.pixel_to_geo(col0, row0)
            x1, y1 = gt.pixel_to_geo(col0 + tw, row0 + th)
            out.append((BBox(min(x0, x1), min(y0, y1), max(x0, x1), max(y0, y1)),
                        col0, row0, tw, th))
    return out


def xyz_tile_bbox(z: int, x: int, y: int) -> BBox:
    """EPSG:3857 bbox of a slippy-map tile (origin top-left)."""
    n = 1 << z
    size = 2 * MERC_ORIGIN / n
    xmin = -MERC_ORIGIN + x * size
    ymax = MERC_ORIGIN - y * size
    return BBox(xmin, ymax - size, xmin + size, ymax)
