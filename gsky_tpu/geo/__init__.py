from .crs import CRS, EPSG4326, EPSG3857, parse_crs
from .transform import GeoTransform, BBox
from . import geometry

__all__ = [
    "CRS",
    "EPSG4326",
    "EPSG3857",
    "parse_crs",
    "GeoTransform",
    "BBox",
    "geometry",
]
