"""The distributed worker boundary (§2.4 of the reference survey).

``gsky-rpc``-equivalent gRPC service + supervised decode-subprocess pool
+ OOM monitor + client-side fan-out.  The compute inside the boundary is
the TPU executor; the pool isolates codec IO crashes the way the
reference isolates GDAL (`worker/gdalprocess/`).
"""

from .client import ConcLimiter, WorkerClient  # noqa: F401
from .oom import OOMMonitor  # noqa: F401
from .pool import PoolFullError, ProcessPool  # noqa: F401
from .server import WorkerService, make_grpc_server  # noqa: F401
