"""Client-side fan-out to worker nodes.

Role of the reference's `processor/tile_grpc.go`: a shuffled connection
pool over ``worker_nodes`` with round-robin dispatch
(`tile_grpc.go:99-125`), a concurrency limiter of
``GrpcConcLimit x nodes`` (`tile_grpc.go:222`), per-granule warp RPCs,
and worker-metrics accumulation (`tile_grpc.go:262-272`).
"""

from __future__ import annotations

import concurrent.futures as cf
import itertools
import logging
import random
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..geo.crs import CRS
from ..geo.transform import GeoTransform
from ..pipeline.types import GeoTileRequest, Granule
from ..resilience import (BackendUnavailable, BreakerOpen, clamp_timeout,
                          faults, get_breaker, registry)
from . import gskyrpc_pb2 as pb
from .serialize import granule_to_pb, unpack_raster
from .server import METHOD

log = logging.getLogger("gsky.worker.client")

DEFAULT_CONC_PER_NODE = 16


class ConcLimiter:
    """Semaphore-style fan-out limiter (`processor/conc_limiter.go`)."""

    def __init__(self, n: int):
        self._sem = threading.Semaphore(max(n, 1))

    def __enter__(self):
        self._sem.acquire()
        return self

    def __exit__(self, *exc):
        self._sem.release()
        return False


class WorkerClient:
    """Round-robin gRPC client over a shuffled node list."""

    def __init__(self, nodes: Sequence[str],
                 conc_per_node: int = DEFAULT_CONC_PER_NODE,
                 max_msg: int = 64 << 20, timeout: float = 130.0):
        import grpc

        if not nodes:
            raise ValueError("no worker nodes")
        nodes = list(nodes)
        random.shuffle(nodes)          # `tile_grpc.go:99-104`
        opts = [("grpc.max_receive_message_length", max_msg),
                ("grpc.max_send_message_length", max_msg)]
        self._channels = [grpc.insecure_channel(n, options=opts)
                          for n in nodes]
        self._stubs = [ch.unary_unary(
            METHOD, request_serializer=pb.Task.SerializeToString,
            response_deserializer=pb.Result.FromString)
            for ch in self._channels]
        self._rr = itertools.count()
        # one breaker per node, shared process-wide by address so a
        # rebuilt client (SIGHUP reload) keeps the node's health history
        self._breakers = [get_breaker(f"worker:{n}") for n in nodes]
        self.limiter = ConcLimiter(conc_per_node * len(nodes))
        self.timeout = timeout
        self.nodes = nodes
        self._max_msg = max_msg
        # persistent fan-out pool: sized to the RPC concurrency cap so
        # per-request thread churn stays off the GetMap hot path
        self._fanout = cf.ThreadPoolExecutor(
            max_workers=conc_per_node * len(nodes),
            thread_name_prefix="gsky-warp-rpc")

    def autosize(self) -> int:
        """Size the RPC concurrency cap from the workers' actual pool
        sizes (`getGrpcPoolSize`, `utils/config.go:1124-1187`): the
        fan-out limit becomes sum(pool_size) across nodes.  Returns the
        new cap; keeps the configured default when the query fails."""
        try:
            total = sum(i.pool_size for i in self.worker_info()
                        if i.pool_size > 0)
        except Exception:
            return self.limiter._sem._value if hasattr(
                self.limiter, "_sem") else 0
        if total > 0:
            self.limiter = ConcLimiter(total)
            self._fanout.shutdown(wait=False)
            self._fanout = cf.ThreadPoolExecutor(
                max_workers=total, thread_name_prefix="gsky-warp-rpc")
        return total

    def process(self, task: pb.Task) -> pb.Result:
        """Dispatch with per-node health tracking and failover.

        Starts at the round-robin position, skips nodes whose breaker is
        open, and on transport failure records it against that node and
        fails over to the next stub — ejecting a sick node costs one
        failed RPC, not a request.  Only when every node has failed (or
        is circuit-open) does the error surface, as
        :class:`BackendUnavailable`.
        """
        with self.limiter:
            n = len(self._stubs)
            start = next(self._rr)
            last: Optional[Exception] = None
            for k in range(n):
                i = (start + k) % n
                br = self._breakers[i]
                if not br.allow():
                    continue
                try:
                    faults.inject("worker")
                    res = self._stubs[i](task,
                                         timeout=clamp_timeout(self.timeout))
                except Exception as e:
                    br.record_failure()
                    last = e
                    if k + 1 < n:
                        registry.count_retry("worker")
                    continue
                br.record_success()
                return res
        if last is None:
            raise BreakerOpen("all worker nodes circuit-open",
                              site="worker")
        registry.count_exhausted("worker")
        raise BackendUnavailable(
            f"all {n} worker node(s) failed (last: {last})",
            site="worker") from last

    # -- high-level ops ------------------------------------------------------

    def worker_info(self, timeout: float = 10.0) -> List[pb.WorkerInfo]:
        """Pool info from every reachable node (`getGrpcPoolSize`,
        `utils/config.go:1124-1187`).  Nodes are queried concurrently
        and unreachable ones are logged + flagged on their breaker and
        skipped — a dead node costs one timeout in parallel with the
        live queries, not a serial 10s stall each at startup."""
        def one(arg):
            node, stub, br = arg
            try:
                r = stub(pb.Task(operation="worker_info"), timeout=timeout)
            except Exception as e:
                br.record_failure()
                log.warning("worker_info: node %s unreachable: %s", node, e)
                return None
            br.record_success()
            return r.worker
        infos = list(self._fanout.map(
            one, zip(self.nodes, self._stubs, self._breakers)))
        return [i for i in infos if i is not None]

    def warp(self, granule: Granule, dst_gt: GeoTransform, dst_crs: CRS,
             width: int, height: int,
             resample: str = "near") -> Optional[Tuple[np.ndarray, np.ndarray]]:
        task = pb.Task(operation="warp")
        task.granule.CopyFrom(granule_to_pb(granule))
        task.dst.srs = dst_crs.name()
        task.dst.geo_transform.extend(dst_gt.to_gdal())
        task.dst.width = width
        task.dst.height = height
        task.dst.resample = resample
        res = self.process(task)
        if res.error:
            raise RuntimeError(res.error)
        return unpack_raster(res)

    def _sub_tile_grid(self, req: GeoTileRequest) -> Tuple[int, int]:
        """P2(c): dst sub-tile bounds for the RPC fan-out
        (`tile_grpc.go:143-198`).  Config values <= 1.0 are fractions of
        the dst size, > 1 absolute pixels, 0 off — but a response whose
        raster would break the gRPC recv cap is ALWAYS sharded (the
        reference relies on operators setting GrpcTileXSize; here a
        4096^2 WCS tile must not 64 MB-bomb the channel by default)."""
        def bound(cfg: float, full: int) -> int:
            if cfg <= 0.0:
                m = full
            elif cfg <= 1.0:
                m = int(full * cfg)
            else:
                m = int(cfg)
            return max(min(m, full), 1)

        mx = bound(req.grpc_tile_x_size, req.width)
        my = bound(req.grpc_tile_y_size, req.height)
        # auto-shard: warped response = w*h*(4B data + 1B mask) + slack.
        # The budget must stay clear of the recv cap itself (a floor
        # above 3/4*max_msg would shard to a size the channel still
        # rejects — a deterministic self-inflicted outage)
        budget = min(max(self._max_msg // 4, 1 << 20),
                     max(self._max_msg * 3 // 4, 5 * 64 * 64))
        while mx * my * 5 > budget and (mx > 64 or my > 64):
            if mx >= my:
                mx = max(mx // 2, 64)
            else:
                my = max(my // 2, 64)
        return mx, my

    def warp_many(self, granules: Sequence[Granule], req: GeoTileRequest,
                  resample: str) -> List[Optional[Tuple[np.ndarray, np.ndarray]]]:
        """Concurrent per-granule warps, order-preserving; failures become
        empty granules (EmptyTile sentinel semantics).  Large dst tiles
        shard into sub-tile RPCs per granule (P2(c),
        `tile_grpc.go:143-198`) and reassemble here."""
        if not granules:
            return []
        dst_gt = req.dst_gt()
        failures: List[Exception] = []
        mx, my = self._sub_tile_grid(req)

        # granule footprint in dst pixel space, for sub-tile pruning:
        # a granule touching one sub-tile must not cost an RPC per
        # sub-tile (`tile_grpc.go` computes granule windows per tile)
        def dst_px_bbox(g: Granule):
            if not g.polygon or (mx >= req.width and my >= req.height):
                return None
            try:
                from ..geo import geometry as geom
                from ..geo.crs import parse_crs
                from ..geo.transform import transform_bbox
                src_bbox = geom.from_wkt(g.polygon).bbox()
                dbox = transform_bbox(src_bbox, parse_crs(g.srs), req.crs)
                gt = dst_gt
                c0, r0 = gt.geo_to_pixel(dbox.xmin, dbox.ymax)
                c1, r1 = gt.geo_to_pixel(dbox.xmax, dbox.ymin)
                c0, c1 = sorted((c0, c1))
                r0, r1 = sorted((r0, r1))
                return (c0 - 2, r0 - 2, c1 + 2, r1 + 2)
            except Exception:
                return None

        jobs = []                 # (granule idx, ox, oy, tw, th)
        for i, g in enumerate(granules):
            pb_ = dst_px_bbox(g)
            touched = False
            for oy in range(0, req.height, my):
                for ox in range(0, req.width, mx):
                    tw = min(mx, req.width - ox)
                    th = min(my, req.height - oy)
                    if pb_ is not None and (
                            ox + tw < pb_[0] or ox > pb_[2]
                            or oy + th < pb_[1] or oy > pb_[3]):
                        continue
                    jobs.append((i, ox, oy, tw, th))
                    touched = True
            if not touched:
                # disjoint granule: keep one tiny probe RPC so the
                # result slot stays a real (empty) raster, not None-by-
                # accident if the footprint estimate was wrong
                jobs.append((i, 0, 0, min(mx, req.width),
                             min(my, req.height)))

        def one(job):
            i, ox, oy, tw, th = job
            try:
                return self.warp(granules[i], dst_gt.window(ox, oy),
                                 req.crs, tw, th, resample)
            except Exception as e:
                failures.append(e)
                return None

        parts = list(self._fanout.map(one, jobs))
        # an explicit flag, NOT a job-count comparison: footprint
        # pruning can leave exactly one sub-tile per granule, and those
        # sub-rasters must still assemble into full-tile canvases
        sharded = mx < req.width or my < req.height
        if not sharded:                       # one whole-tile RPC each
            out: List[Optional[Tuple[np.ndarray, np.ndarray]]] = parts
        else:
            out = [None] * len(granules)
            for (i, ox, oy, tw, th), part in zip(jobs, parts):
                if part is None:
                    continue
                if out[i] is None:
                    out[i] = (np.zeros((req.height, req.width),
                                       np.float32),
                              np.zeros((req.height, req.width), bool))
                d, v = part
                out[i][0][oy:oy + th, ox:ox + tw] = np.asarray(d)
                out[i][1][oy:oy + th, ox:ox + tw] = np.asarray(v)
        if failures:
            log.warning("%d/%d warp RPCs failed (first: %s)",
                        len(failures), len(jobs), failures[0])
            if len(failures) < len(jobs):
                from ..resilience import mark_degraded
                mark_degraded("worker")
            # outage visibility: a dead fleet must not look like "no
            # data" — per-granule failures degrade to empty granules,
            # total failure becomes an error response upstream
            if len(failures) == len(jobs):
                if isinstance(failures[0], BackendUnavailable):
                    raise failures[0]
                raise RuntimeError(
                    f"all {len(jobs)} warp RPCs failed "
                    f"(first: {failures[0]})")
        return out

    def extent(self, granule: Granule, dst_crs: CRS) -> Tuple[int, int]:
        task = pb.Task(operation="extent")
        task.granule.CopyFrom(granule_to_pb(granule))
        task.dst.srs = dst_crs.name()
        res = self.process(task)
        if res.error:
            raise RuntimeError(res.error)
        return res.extent_width, res.extent_height

    def info(self, path: str) -> str:
        res = self.process(pb.Task(operation="info", path=path))
        if res.error:
            raise RuntimeError(res.error)
        return res.info_json

    def close(self):
        self._fanout.shutdown(wait=False)
        for ch in self._channels:
            ch.close()
