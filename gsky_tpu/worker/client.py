"""Client-side fleet dispatch to worker nodes.

Role of the reference's `processor/tile_grpc.go` — a connection pool
over ``worker_nodes`` with per-granule warp RPCs, a concurrency limiter
of ``GrpcConcLimit x nodes`` (`tile_grpc.go:222`) and worker-metrics
accumulation — upgraded from static round-robin to fleet routing
(see docs/FLEET.md):

- tasks carrying a route key ride the consistent-hash ring, so repeat
  requests for one tile land on the shard whose scene cache, kernel
  ledger and XLA cache are already warm for it;
- node health (phi-accrual over heartbeats fed from real RPC traffic
  plus active ``worker_info`` probes) gates the candidate order, and a
  breaker trip reports the node dead immediately;
- stragglers are hedged onto the next ring node past an adaptive p99
  delay, inside a token-bucket hedge budget;
- nodes answering ``backpressure:`` / ``draining:`` are failed over
  without breaker penalty (they are alive), and an all-busy fleet
  surfaces as the *retryable* :class:`NodeBusy` so the retry policy's
  jittered backoff applies instead of an instant hard failure.
"""

from __future__ import annotations

import concurrent.futures as cf
import contextvars
import itertools
import json
import logging
import random
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..fleet import DRAINING, FleetRouter, hedged_call, tile_route_key
from ..obs import (adopt_spans, current_trace_id, event as obs_event,
                   span as obs_span, traceparent)
from ..obs.metrics import RPC_SECONDS, TRACE_EVENTS
from ..geo.crs import CRS
from ..geo.transform import GeoTransform
from ..pipeline.types import GeoTileRequest, Granule
from ..resilience import (BackendUnavailable, BreakerOpen, RetryPolicy,
                          call_with_retry, clamp_timeout, faults,
                          get_breaker, registry)
from . import gskyrpc_pb2 as pb
from .serialize import granule_to_pb, unpack_raster
from .server import METHOD

log = logging.getLogger("gsky.worker.client")

DEFAULT_CONC_PER_NODE = 16

# ops whose Result.info_json is free for the span-backhaul envelope
# ("info" / "worker_info" already carry their payloads there)
_SPAN_OPS = ("warp", "drill", "extent")


def _note(kind: str, **attrs) -> None:
    """Cross-cutting trace event + prom counter; never raises."""
    try:
        TRACE_EVENTS.labels(kind=kind).inc()
        obs_event(kind, **attrs)
    except Exception:  # telemetry must never fail the RPC path
        pass


def _rpc_observe(op: str, outcome: str, dur_s: float) -> None:
    try:
        RPC_SECONDS.labels(op=op, outcome=outcome).observe(dur_s)
    except Exception:  # telemetry must never fail the RPC path
        pass


class NodeBusy(BackendUnavailable):
    """Every candidate node answered "queue full": the fleet is alive
    but saturated.  Retryable — the queues drain at pool speed, so a
    jittered backoff usually lands — unlike its parent, which means the
    fleet could not answer at all."""

    retryable = True

    def __init__(self, message: str, site: str = "worker"):
        super().__init__(message, site=site, retry_after=1.0)


class ConcLimiter:
    """Semaphore-style fan-out limiter (`processor/conc_limiter.go`)."""

    def __init__(self, n: int):
        self._sem = threading.Semaphore(max(n, 1))

    def __enter__(self):
        self._sem.acquire()
        return self

    def __exit__(self, *exc):
        self._sem.release()
        return False

    def try_acquire(self) -> bool:
        """Non-blocking acquire — hedges take a *spare* permit or none:
        a hedge must never queue behind primaries for a slot."""
        return self._sem.acquire(blocking=False)

    def release(self) -> None:
        self._sem.release()


class WorkerClient:
    """Fleet-routed gRPC client over a worker node set."""

    def __init__(self, nodes: Sequence[str],
                 conc_per_node: int = DEFAULT_CONC_PER_NODE,
                 max_msg: int = 64 << 20, timeout: float = 130.0):
        import grpc

        if not nodes:
            raise ValueError("no worker nodes")
        nodes = list(nodes)
        random.shuffle(nodes)          # `tile_grpc.go:99-104`
        opts = [("grpc.max_receive_message_length", max_msg),
                ("grpc.max_send_message_length", max_msg),
                # a node that dies and revives must be re-dialled within
                # a couple of health-probe beats, not after gRPC's
                # default reconnect backoff (which grows to 2 minutes)
                ("grpc.max_reconnect_backoff_ms", 3000)]
        self._grpc_opts = opts
        self._conc_per_node = conc_per_node
        self._channels = [grpc.insecure_channel(n, options=opts)
                          for n in nodes]
        self._stubs = [ch.unary_unary(
            METHOD, request_serializer=pb.Task.SerializeToString,
            response_deserializer=pb.Result.FromString)
            for ch in self._channels]
        self._rr = itertools.count()
        # one breaker per node, shared process-wide by address so a
        # rebuilt client (SIGHUP reload) keeps the node's health history
        self._breakers = [get_breaker(f"worker:{n}") for n in nodes]
        self.limiter = ConcLimiter(conc_per_node * len(nodes))
        self.timeout = timeout
        self.nodes = nodes
        self._index = {n: i for i, n in enumerate(nodes)}
        self._max_msg = max_msg
        self._closed = False
        self._close_lock = threading.Lock()
        # guards membership swaps (elastic scale/replace); dispatch
        # reads one consistent snapshot instead of holding it
        self._membership_lock = threading.Lock()
        self._listened: set = set()
        # jittered backoff for an all-nodes-busy fleet (NodeBusy): the
        # work queues drain in tens of ms, so short delays suffice
        self._busy_policy = RetryPolicy(max_attempts=3, base_delay=0.05,
                                        max_delay=0.5)
        # fleet routing state: ring + health + hedge over this node set
        self.fleet = FleetRouter(nodes, name="worker", probe=self._probe)
        for i, br in enumerate(self._breakers):
            br.add_listener(self._make_breaker_listener(nodes[i]))
            self._listened.add(nodes[i])
        if len(nodes) > 1 and self.fleet.monitor.interval_s > 0:
            self.fleet.monitor.start()
        # persistent fan-out pool: sized to the RPC concurrency cap so
        # per-request thread churn stays off the GetMap hot path
        self._fanout = cf.ThreadPoolExecutor(
            max_workers=conc_per_node * len(nodes),
            thread_name_prefix="gsky-warp-rpc")

    # -- fleet plumbing ------------------------------------------------------

    def _snapshot(self):
        """One consistent (nodes, stubs, breakers, index) view: the
        lists are rebuilt wholesale on membership change, never mutated
        in place, so a snapshot taken here stays internally aligned for
        the whole dispatch even while the elastic fleet rewires."""
        with self._membership_lock:
            return self.nodes, self._stubs, self._breakers, self._index

    def set_nodes(self, addrs: Sequence[str]) -> None:
        """Rewire membership live (elastic fleet scale-up/down/replace,
        docs/FLEET.md "Elastic fleet"): dial channels for new nodes,
        retire departed ones, and reconcile the ring + health monitor —
        purging the departed nodes' router state so churn cannot grow
        unbounded maps.  In-flight RPCs on a retired channel surface as
        transport failures and fail over like any node death."""
        import grpc

        addrs = list(dict.fromkeys(addrs))
        if not addrs:
            raise ValueError("no worker nodes")
        added: List[str] = []
        removed: List = []
        with self._membership_lock:
            if self._closed or set(addrs) == set(self.nodes):
                return
            keep = set(addrs)
            old_index = self._index
            nodes: List[str] = []
            channels, stubs, breakers = [], [], []
            for n in addrs:
                i = old_index.get(n)
                if i is not None:
                    channels.append(self._channels[i])
                    stubs.append(self._stubs[i])
                    breakers.append(self._breakers[i])
                else:
                    ch = grpc.insecure_channel(n, options=self._grpc_opts)
                    channels.append(ch)
                    stubs.append(ch.unary_unary(
                        METHOD,
                        request_serializer=pb.Task.SerializeToString,
                        response_deserializer=pb.Result.FromString))
                    breakers.append(get_breaker(f"worker:{n}"))
                    added.append(n)
                nodes.append(n)
            removed = [(n, self._channels[i])
                       for n, i in old_index.items() if n not in keep]
            self.nodes = nodes
            self._channels = channels
            self._stubs = stubs
            self._breakers = breakers
            self._index = {n: i for i, n in enumerate(nodes)}
            for n in added:
                # breakers are shared process-wide by address: only the
                # first membership of a node hooks this client's listener
                if n not in self._listened:
                    self._listened.add(n)
                    breakers[self._index[n]].add_listener(
                        self._make_breaker_listener(n))
        self.fleet.set_nodes(addrs)
        for _, ch in removed:
            try:
                ch.close()
            except Exception:  # channel already closed
                pass
        if len(addrs) > 1 and self.fleet.monitor.interval_s > 0:
            self.fleet.monitor.start()   # idempotent
        log.info("fleet membership: %d node(s) (+%d/-%d), generation %d",
                 len(addrs), len(added), len(removed),
                 self.fleet.ring.generation)

    def _make_breaker_listener(self, node: str):
        def on_change(br, old, new):
            # an OPEN breaker is an immediate dead-node report for the
            # router; a close (successful probe) is a heartbeat
            if new == br.OPEN:
                self.fleet.monitor.record_failure(node, fatal=True)
            elif new == br.CLOSED and old != br.CLOSED:
                self.fleet.monitor.record_heartbeat(node)
        return on_change

    def _probe(self, node: str):
        """Active health probe: one worker_info RPC.  Returns the
        DRAINING sentinel when the node answered only to say goodbye —
        or when its device supervisor reports suspect/reinitializing
        (alive, rebuilding: keep the beat history warm, route nothing
        new at it until a later probe sees it healthy).  A dead device
        or a tripped crash-loop breaker is an explicit fatal report."""
        if self._closed:
            return False
        _, stubs, _, index = self._snapshot()
        i = index.get(node)
        if i is None:
            return False     # departed between probe list and now
        try:
            res = stubs[i](pb.Task(operation="worker_info"),
                           timeout=5.0)
        except Exception:
            return False
        info = self._info(res)
        dev = info.get("device") or {}
        crash = (info.get("pool") or {}).get("crash_loop") or {}
        if dev.get("state") == "dead" or crash.get("tripped"):
            self.fleet.monitor.record_failure(node, fatal=True)
            return False
        if info.get("draining"):
            return DRAINING
        if dev.get("state") in ("suspect", "reinitializing"):
            return DRAINING
        return True

    @staticmethod
    def _info(res: pb.Result) -> dict:
        """The worker's free-form info_json envelope (drain handshake +
        device supervisor + pool crash-loop state), or {}."""
        if not res.info_json:
            return {}
        try:
            doc = json.loads(res.info_json)
        except (ValueError, AttributeError):
            return {}
        return doc if isinstance(doc, dict) else {}

    @classmethod
    def _draining(cls, res: pb.Result) -> bool:
        return bool(cls._info(res).get("draining"))

    @staticmethod
    def _is_fatal(e: Exception) -> bool:
        """Does this transport error mean the *node* is gone (connection
        refused / unreachable), not just this call?"""
        if isinstance(e, faults.InjectedFault):
            return False
        try:
            import grpc
            if isinstance(e, grpc.RpcError):
                return e.code() == grpc.StatusCode.UNAVAILABLE
        except Exception:  # grpc absent - fall through to the socket-error check
            pass
        return isinstance(e, (ConnectionError, OSError))

    def autosize(self) -> int:
        """Size the RPC concurrency cap from the workers' actual pool
        sizes (`getGrpcPoolSize`, `utils/config.go:1124-1187`): the
        fan-out limit becomes sum(pool_size) across nodes.  Returns the
        new cap; keeps the configured default when the query fails."""
        try:
            total = sum(i.pool_size for i in self.worker_info()
                        if i.pool_size > 0)
        except Exception:
            return self.limiter._sem._value if hasattr(
                self.limiter, "_sem") else 0
        if total > 0:
            self.limiter = ConcLimiter(total)
            self._fanout.shutdown(wait=False)
            self._fanout = cf.ThreadPoolExecutor(
                max_workers=total, thread_name_prefix="gsky-warp-rpc")
        return total

    # -- dispatch ------------------------------------------------------------

    def process(self, task: pb.Task,
                route_key: Optional[str] = None) -> pb.Result:
        """Dispatch with fleet routing, health tracking and failover.

        With a ``route_key``, candidates come from the hash ring
        (healthy-first, bounded-load, deterministic spill order) and the
        first attempt may hedge onto the second candidate; without one,
        the legacy round-robin order applies.  On transport failure the
        node's breaker and health record it and the task fails over to
        the next candidate — ejecting a sick node costs one failed RPC,
        not a request.  Nodes answering ``backpressure:`` / ``draining:``
        are alive: they fail over without breaker penalty.  Exhaustion
        surfaces as :class:`NodeBusy` (every node busy — retryable),
        :class:`BreakerOpen` (every node circuit-open) or
        :class:`BackendUnavailable`.
        """
        if self._closed:
            raise BackendUnavailable("worker client is closed",
                                     site="worker")
        with self.limiter:
            return self._dispatch(task, route_key)

    def _dispatch(self, task: pb.Task, route_key: Optional[str]) -> pb.Result:
        nodes_l, stubs, breakers, index = self._snapshot()
        n = len(stubs)
        keyed = (route_key is not None and self.fleet.enabled and n > 1)
        if keyed:
            order = [index[m]
                     for m in self.fleet.candidates(route_key)
                     if m in index]
        else:
            start = next(self._rr)
            order = [(start + k) % n for k in range(n)]
        timeout = clamp_timeout(self.timeout)
        # one metadata tuple per dispatch: the trace context crosses the
        # process boundary as gRPC metadata (x-gsky-trace: "tid-sid")
        tp = traceparent()
        md = (("x-gsky-trace", tp),) if tp else None
        op = task.operation
        busy = 0
        last: Optional[Exception] = None
        last_busy = ""
        from ..resilience import current_token
        tok = current_token()
        for pos, i in enumerate(order):
            if tok is not None:
                # a cancelled request must not start (or fail over to)
                # another RPC attempt
                tok.check("rpc")
            br = breakers[i]
            if not br.allow():
                continue
            node = nodes_l[i]
            started = node        # in-flight load is per dispatch target
            self.fleet.task_started(started)
            try:
                faults.inject("worker")
                t0 = time.monotonic()
                with obs_span("rpc.worker", node=node, op=op,
                              attempt=pos) as rsp:
                    if (pos == 0 and keyed and self.fleet.hedge_enabled
                            and len(order) > 1):
                        res, hedge_won = self._call_hedged(
                            task, i, order[1], timeout, md,
                            nodes_l, stubs, breakers)
                        if hedge_won:
                            i = order[1]
                            br = breakers[i]
                            node = nodes_l[i]
                            rsp.set(node=node, hedge_won=True)
                            _note("hedge_won", node=node)
                    else:
                        res = self._call_cancellable(stubs[i], task,
                                                     timeout, md, tok)
                dt = time.monotonic() - t0
            except Exception as e:
                br.record_failure()
                self.fleet.node_result(node, ok=False,
                                       fatal=self._is_fatal(e))
                _rpc_observe(op, "transport", time.monotonic() - t0)
                last = e
                if pos + 1 < len(order):
                    registry.count_retry("worker")
                    if keyed:
                        self.fleet.record_reroute()
                        _note("reroute", node=node, reason="failure")
                continue
            finally:
                self.fleet.task_finished(started)
            err = res.error or ""
            if err.startswith("backpressure:"):
                # alive, just saturated: no breaker penalty, fail over
                br.record_success()
                self.fleet.node_result(node, ok=True)
                _rpc_observe(op, "busy", dt)
                rsp.set(outcome="busy")
                busy += 1
                last_busy = err
                if keyed:
                    self.fleet.record_reroute()
                    _note("reroute", node=node, reason="busy")
                continue
            if err.startswith("draining:"):
                # alive, leaving: deregister from routing, fail over
                br.record_success()
                self.fleet.node_result(node, ok=True, draining=True)
                _rpc_observe(op, "draining", dt)
                rsp.set(outcome="draining")
                if keyed:
                    self.fleet.record_reroute()
                    _note("reroute", node=node, reason="draining")
                continue
            if err.startswith("device:"):
                # alive, but its device is mid-incident (hang/crash/OOM/
                # corruption — the supervisor is rebuilding it): no
                # breaker penalty, route around it like a draining node;
                # the next healthy worker_info probe restores it
                br.record_success()
                self.fleet.node_result(node, ok=True, draining=True)
                _rpc_observe(op, "device", dt)
                rsp.set(outcome="device")
                if pos + 1 < len(order):
                    registry.count_retry("worker")
                if keyed:
                    self.fleet.record_reroute()
                    _note("reroute", node=node, reason="device")
                last = RuntimeError(err)
                continue
            # a real answer (success or semantic error): the node lives
            br.record_success()
            self.fleet.node_result(node, ok=True, latency_s=dt)
            outcome = "error" if err else "ok"
            _rpc_observe(op, outcome, dt)
            rsp.set(outcome=outcome)
            if keyed:
                self.fleet.record_locality(route_key, node)
            else:
                self.fleet.record_rr()
            if md is not None and op in _SPAN_OPS and res.info_json:
                # the worker's child spans ride back on the free-form
                # info_json channel; stitch them into the live trace
                try:
                    env = json.loads(res.info_json)
                    if isinstance(env, dict):
                        adopt_spans(env.get("spans"))
                except ValueError:
                    pass
            return res
        if busy:
            raise NodeBusy(
                f"all worker nodes at capacity ({last_busy or 'busy'})")
        if last is None:
            raise BreakerOpen("all worker nodes circuit-open",
                              site="worker")
        registry.count_exhausted("worker")
        raise BackendUnavailable(
            f"all {n} worker node(s) failed (last: {last})",
            site="worker") from last

    def _call_cancellable(self, stub, task: pb.Task, timeout: float,
                          md, tok) -> pb.Result:
        """One RPC that honours the request's cancel token end-to-end:
        the token fires ``fut.cancel()``, gRPC propagates the abort to
        the server (whose handler polls ``ctx.is_active()`` and stops
        decoding/warping for the dead client), and the caller unwinds
        as :class:`RequestCancelled` — a BaseException, so the breaker
        records neither success nor failure for work WE abandoned."""
        if tok is None:
            return stub(task, timeout=timeout, metadata=md)
        import grpc
        fut = stub.future(task, timeout=timeout, metadata=md)
        unhook = tok.on_cancel(lambda: fut.cancel())
        try:
            return fut.result()
        except grpc.FutureCancelledError:
            tok.check("rpc")    # raises RequestCancelled when fired
            raise               # cancelled by someone else: propagate
        finally:
            unhook()

    def _call_hedged(self, task: pb.Task, i: int, j: int,
                     timeout: float, md=None,
                     nodes_l=None, stubs=None, breakers=None
                     ) -> Tuple[pb.Result, bool]:
        """First-candidate dispatch with a straggler hedge onto node
        ``j``.  The hedge consumes a *spare* limiter permit (or does not
        fire), spends one hedge-budget token, and whichever copy loses
        is cancelled — its permit freed immediately."""
        if stubs is None:
            nodes_l, stubs, breakers, _ = self._snapshot()
        fl = self.fleet
        permit = [False]

        def primary():
            fl.hedge.on_primary()
            return stubs[i].future(task, timeout=timeout, metadata=md)

        def hedge():
            # raising here just means "no hedge" to hedged_call
            if self._closed:
                raise RuntimeError("client closed")
            if not breakers[j].allow():
                raise RuntimeError("hedge target circuit-open")
            if not fl.hedge.try_hedge():
                raise RuntimeError("hedge budget exhausted")
            if not self.limiter.try_acquire():
                raise RuntimeError("no spare permit for hedge")
            permit[0] = True
            _note("hedge", node=nodes_l[j])
            try:
                return stubs[j].future(task, timeout=timeout,
                                       metadata=md)
            except Exception:
                permit[0] = False
                self.limiter.release()
                raise

        def on_hedge_cancelled():
            if permit[0]:
                permit[0] = False
                self.limiter.release()

        try:
            res, hedge_won = hedged_call(
                primary, hedge, fl.hedge.delay_s(), timeout,
                on_hedge_cancelled=on_hedge_cancelled)
            if hedge_won:
                fl.hedge.record_win()
            return res, hedge_won
        finally:
            # hedge won (or both settled without a fut2 cancel): the
            # extra permit still held covers a future that has finished
            if permit[0]:
                permit[0] = False
                self.limiter.release()

    # -- high-level ops ------------------------------------------------------

    def worker_info(self, timeout: float = 10.0) -> List[pb.WorkerInfo]:
        """Pool info from every reachable node (`getGrpcPoolSize`,
        `utils/config.go:1124-1187`).  Nodes are queried concurrently
        and unreachable ones are logged + flagged on their breaker and
        skipped — a dead node costs one timeout in parallel with the
        live queries, not a serial 10s stall each at startup.  Every
        answer doubles as a fleet heartbeat (and drain handshake)."""
        def one(arg):
            node, stub, br = arg
            try:
                r = stub(pb.Task(operation="worker_info"), timeout=timeout)
            except Exception as e:
                br.record_failure()
                self.fleet.node_result(node, ok=False,
                                       fatal=self._is_fatal(e))
                log.warning("worker_info: node %s unreachable: %s", node, e)
                return None
            br.record_success()
            self.fleet.node_result(node, ok=True,
                                   draining=self._draining(r))
            return r.worker
        nodes_l, stubs, breakers, _ = self._snapshot()
        infos = list(self._fanout.map(
            one, zip(nodes_l, stubs, breakers)))
        return [i for i in infos if i is not None]

    def warp(self, granule: Granule, dst_gt: GeoTransform, dst_crs: CRS,
             width: int, height: int, resample: str = "near",
             route_key: Optional[str] = None,
             ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        task = pb.Task(operation="warp")
        task.granule.CopyFrom(granule_to_pb(granule))
        task.dst.srs = dst_crs.name()
        task.dst.geo_transform.extend(dst_gt.to_gdal())
        task.dst.width = width
        task.dst.height = height
        task.dst.resample = resample
        # NodeBusy (all queues full) gets jittered backoff — the fleet
        # is alive, its queues drain in tens of ms; everything else
        # (semantic errors, dead fleet) re-raises unchanged
        res = call_with_retry(
            lambda: self.process(task, route_key=route_key),
            self._busy_policy, site="worker-busy",
            retryable=lambda e: isinstance(e, NodeBusy))
        if res.error:
            raise RuntimeError(res.error)
        return unpack_raster(res)

    def _sub_tile_grid(self, req: GeoTileRequest) -> Tuple[int, int]:
        """P2(c): dst sub-tile bounds for the RPC fan-out
        (`tile_grpc.go:143-198`).  Config values <= 1.0 are fractions of
        the dst size, > 1 absolute pixels, 0 off — but a response whose
        raster would break the gRPC recv cap is ALWAYS sharded (the
        reference relies on operators setting GrpcTileXSize; here a
        4096^2 WCS tile must not 64 MB-bomb the channel by default)."""
        def bound(cfg: float, full: int) -> int:
            if cfg <= 0.0:
                m = full
            elif cfg <= 1.0:
                m = int(full * cfg)
            else:
                m = int(cfg)
            return max(min(m, full), 1)

        mx = bound(req.grpc_tile_x_size, req.width)
        my = bound(req.grpc_tile_y_size, req.height)
        # auto-shard: warped response = w*h*(4B data + 1B mask) + slack.
        # The budget must stay clear of the recv cap itself (a floor
        # above 3/4*max_msg would shard to a size the channel still
        # rejects — a deterministic self-inflicted outage)
        budget = min(max(self._max_msg // 4, 1 << 20),
                     max(self._max_msg * 3 // 4, 5 * 64 * 64))
        while mx * my * 5 > budget and (mx > 64 or my > 64):
            if mx >= my:
                mx = max(mx // 2, 64)
            else:
                my = max(my // 2, 64)
        return mx, my

    def warp_many(self, granules: Sequence[Granule], req: GeoTileRequest,
                  resample: str) -> List[Optional[Tuple[np.ndarray, np.ndarray]]]:
        """Concurrent per-granule warps, order-preserving; failures become
        empty granules (EmptyTile sentinel semantics).  Large dst tiles
        shard into sub-tile RPCs per granule (P2(c),
        `tile_grpc.go:143-198`) and reassemble here.  Each sub-tile is
        routed by its canonical tile key, so a repeat of the same
        request re-lands every sub-tile on the shard that warped it
        before (warm scene + kernel caches), while the sub-tiles of one
        large request still spread across the ring."""
        if not granules:
            return []
        dst_gt = req.dst_gt()
        failures: List[Exception] = []
        mx, my = self._sub_tile_grid(req)

        def route_key(ox: int, oy: int, tw: int, th: int) -> str:
            b = dst_gt.window(ox, oy).bbox(tw, th)
            return tile_route_key(req.collection, req.crs.name(),
                                  (b.xmin, b.ymin, b.xmax, b.ymax),
                                  tw, th)

        # granule footprint in dst pixel space, for sub-tile pruning:
        # a granule touching one sub-tile must not cost an RPC per
        # sub-tile (`tile_grpc.go` computes granule windows per tile)
        def dst_px_bbox(g: Granule):
            if not g.polygon or (mx >= req.width and my >= req.height):
                return None
            try:
                from ..geo import geometry as geom
                from ..geo.crs import parse_crs
                from ..geo.transform import transform_bbox
                src_bbox = geom.from_wkt(g.polygon).bbox()
                dbox = transform_bbox(src_bbox, parse_crs(g.srs), req.crs)
                gt = dst_gt
                c0, r0 = gt.geo_to_pixel(dbox.xmin, dbox.ymax)
                c1, r1 = gt.geo_to_pixel(dbox.xmax, dbox.ymin)
                c0, c1 = sorted((c0, c1))
                r0, r1 = sorted((r0, r1))
                return (c0 - 2, r0 - 2, c1 + 2, r1 + 2)
            except Exception:
                return None

        jobs = []                 # (granule idx, ox, oy, tw, th)
        for i, g in enumerate(granules):
            pb_ = dst_px_bbox(g)
            touched = False
            for oy in range(0, req.height, my):
                for ox in range(0, req.width, mx):
                    tw = min(mx, req.width - ox)
                    th = min(my, req.height - oy)
                    if pb_ is not None and (
                            ox + tw < pb_[0] or ox > pb_[2]
                            or oy + th < pb_[1] or oy > pb_[3]):
                        continue
                    jobs.append((i, ox, oy, tw, th))
                    touched = True
            if not touched:
                # disjoint granule: keep one tiny probe RPC so the
                # result slot stays a real (empty) raster, not None-by-
                # accident if the footprint estimate was wrong
                jobs.append((i, 0, 0, min(mx, req.width),
                             min(my, req.height)))

        def one(job):
            i, ox, oy, tw, th = job
            try:
                return self.warp(granules[i], dst_gt.window(ox, oy),
                                 req.crs, tw, th, resample,
                                 route_key=route_key(ox, oy, tw, th))
            except Exception as e:
                failures.append(e)
                return None

        def one_bound(arg):
            # the fan-out pool's threads start from an empty Context;
            # each job gets its own copy of the caller's (a single
            # Context cannot be entered from two threads at once)
            ctx, job = arg
            return ctx.run(one, job)

        parts = list(self._fanout.map(
            one_bound,
            [(contextvars.copy_context(), j) for j in jobs]))
        # an explicit flag, NOT a job-count comparison: footprint
        # pruning can leave exactly one sub-tile per granule, and those
        # sub-rasters must still assemble into full-tile canvases
        sharded = mx < req.width or my < req.height
        if not sharded:                       # one whole-tile RPC each
            out: List[Optional[Tuple[np.ndarray, np.ndarray]]] = parts
        else:
            out = [None] * len(granules)
            for (i, ox, oy, tw, th), part in zip(jobs, parts):
                if part is None:
                    continue
                if out[i] is None:
                    out[i] = (np.zeros((req.height, req.width),
                                       np.float32),
                              np.zeros((req.height, req.width), bool))
                d, v = part
                out[i][0][oy:oy + th, ox:ox + tw] = np.asarray(d)
                out[i][1][oy:oy + th, ox:ox + tw] = np.asarray(v)
        if failures:
            log.warning("%d/%d warp RPCs failed (first: %s) trace=%s",
                        len(failures), len(jobs), failures[0],
                        current_trace_id() or "-")
            if len(failures) < len(jobs):
                from ..resilience import mark_degraded
                mark_degraded("worker")
            # outage visibility: a dead fleet must not look like "no
            # data" — per-granule failures degrade to empty granules,
            # total failure becomes an error response upstream
            if len(failures) == len(jobs):
                if isinstance(failures[0], BackendUnavailable):
                    raise failures[0]
                raise RuntimeError(
                    f"all {len(jobs)} warp RPCs failed "
                    f"(first: {failures[0]})")
        return out

    def extent(self, granule: Granule, dst_crs: CRS) -> Tuple[int, int]:
        task = pb.Task(operation="extent")
        task.granule.CopyFrom(granule_to_pb(granule))
        task.dst.srs = dst_crs.name()
        res = self.process(task)
        if res.error:
            raise RuntimeError(res.error)
        return res.extent_width, res.extent_height

    def info(self, path: str) -> str:
        res = self.process(pb.Task(operation="info", path=path))
        if res.error:
            raise RuntimeError(res.error)
        return res.info_json

    def page_fetch(self, keys, max_bytes: Optional[int] = None,
                   route_key: Optional[str] = None) -> dict:
        """Batched cache-fabric page fetch (docs/FABRIC.md): ask a
        worker for content-keyed ``(serial, pi, pj)`` pages; returns
        ``{key: (PR, PC) float32 page}`` with CRC-failed pages already
        dropped.  Routed like any other op — a ``route_key`` (e.g. the
        serialized page key) lands the ask on the ring-preferred node."""
        from ..fabric import pagerpc
        task = pb.Task(operation="page_fetch",
                       path=pagerpc.encode_request(keys, max_bytes))
        res = self.process(task, route_key=route_key)
        if res.error:
            raise RuntimeError(res.error)
        return pagerpc.decode_result(res.info_json, res.raster)

    def close(self):
        """Idempotent shutdown.  The closed flag flips *first*, so any
        dispatch racing the teardown is rejected up front with
        :class:`BackendUnavailable` instead of hitting a half-closed
        channel mid-RPC."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self.fleet.close()
        self._fanout.shutdown(wait=False)
        for ch in self._channels:
            try:
                ch.close()
            except Exception:  # channel already closed
                pass
