"""The decode subprocess: crash-isolated file IO for the worker server.

Role of the reference's `gsky-gdal-process` (`gdal-process/main.go`):
a single-threaded accept loop over a unix socket, one task per
connection, with

- a per-task wall-clock timeout that hard-exits the process (`os.Exit(2)`
  after 120 s, `gdal-process/main.go:57-68`) so a wedged read can't hold
  a pool slot, and
- a planned exit after ``max_tasks`` tasks so codec/file-handle leaks are
  bounded (`worker/gdalprocess/process.go:154-159`).

Ops handled here are the IO-bound, crash-prone ones: ``decode`` (granule
window read), ``extent`` (open + suggested warp output size) and ``info``
(metadata extraction).  Device compute (warp/drill math) stays in the
server process, which owns the TPU executor — the TPU-first split of the
reference's all-in-subprocess design.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import traceback

# The decode subprocess is host-IO only by design — it must never claim
# an accelerator (N pool children each grabbing a TPU seat would starve
# the executor, and a wedged device link would hang child startup).  The
# container sitecustomize registers the TPU backend at interpreter start
# regardless of env vars, but backends initialise lazily, so pinning the
# platform here (before any jax use) keeps the child CPU-only.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:  # pragma: no cover - jax-less minimal installs
    pass

import numpy as np

from . import gskyrpc_pb2 as pb
from .ipc import recv_task, send_msg
from .serialize import granule_from_pb, pack_raster

EXIT_TIMEOUT = 2
EXIT_RECYCLED = 3


def _do_decode(task: pb.Task) -> pb.Result:
    from ..geo.crs import parse_crs
    from ..geo.transform import GeoTransform
    from ..pipeline.decode import decode_window

    g = granule_from_pb(task.granule)
    d = task.dst
    dst_gt = GeoTransform.from_gdal(list(d.geo_transform))
    dst_bbox = dst_gt.bbox(d.width, d.height)
    dst_crs = parse_crs(d.srs)
    res = pb.Result()
    w = decode_window(g, dst_bbox, dst_crs, d.resample or "near",
                      dst_hw=(d.height, d.width))
    if w is None:
        return res
    pack_raster(res, w.data, w.valid)
    res.window_gt.extend(w.window_gt.to_gdal())
    res.src_srs = w.src_crs.name()
    res.metrics.bytes_read = w.data.nbytes
    return res


def _do_extent(task: pb.Task) -> pb.Result:
    from ..geo.crs import parse_crs
    from ..geo.transform import GeoTransform, suggest_output_size
    from ..io.geotiff import GeoTIFF
    from ..io.netcdf import NetCDF

    g = granule_from_pb(task.granule)
    res = pb.Result()
    if g.is_netcdf:
        h = NetCDF(g.path)
        try:
            v = h.variables.get(g.var_name)
            if v is None:
                res.error = f"no variable {g.var_name}"
                return res
            H, W = v.shape[-2], v.shape[-1]
        finally:
            h.close()
    else:
        h = GeoTIFF(g.path)
        try:
            H, W = h.height, h.width
        finally:
            h.close()
    src_gt = GeoTransform.from_gdal(g.geo_transform)
    src_crs = parse_crs(g.srs)
    dst_crs = parse_crs(task.dst.srs)
    _, sw, sh = suggest_output_size(src_gt, W, H, src_crs, dst_crs)
    res.extent_width = sw
    res.extent_height = sh
    return res


def _do_info(task: pb.Task) -> pb.Result:
    import json

    from ..index.crawler import extract

    res = pb.Result()
    res.info_json = json.dumps(extract(task.path, approx_stats=False))
    return res


_OPS = {"decode": _do_decode, "extent": _do_extent, "info": _do_info}


def handle(task: pb.Task) -> pb.Result:
    fn = _OPS.get(task.operation)
    if fn is None:
        return pb.Result(error=f"unknown operation {task.operation!r}")
    try:
        return fn(task)
    except Exception as e:  # failure -> error result, not a crash
        return pb.Result(error=f"{type(e).__name__}: {e}")


def serve(sock_path: str, max_tasks: int = 20000,
          task_timeout: float = 120.0) -> None:
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(8)

    def on_alarm(signum, frame):
        sys.stderr.write("task timeout, exiting\n")
        os._exit(EXIT_TIMEOUT)

    signal.signal(signal.SIGALRM, on_alarm)

    done = 0
    while True:
        conn, _ = srv.accept()
        try:
            task = recv_task(conn)
            timeout = task.timeout_s or task_timeout
            signal.setitimer(signal.ITIMER_REAL, timeout)
            try:
                res = handle(task)
            finally:
                signal.setitimer(signal.ITIMER_REAL, 0)
            send_msg(conn, res)
        except ConnectionError:
            pass
        except Exception:
            traceback.print_exc()
        finally:
            conn.close()
        done += 1
        if max_tasks and done >= max_tasks:
            os._exit(EXIT_RECYCLED)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="gsky-decode-process")
    ap.add_argument("-sock", required=True)
    ap.add_argument("-max_tasks", type=int, default=20000)
    ap.add_argument("-timeout", type=float, default=120.0)
    a = ap.parse_args(argv)
    serve(a.sock, a.max_tasks, a.timeout)


if __name__ == "__main__":
    main()
