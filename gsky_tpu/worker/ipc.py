"""Protobuf-over-unix-socket framing between the worker server and its
decode subprocesses.

Same IPC shape as the reference's `worker/gdalprocess/process.go:109-159`
/ `gdal-process/main.go:35-88`: a 4-byte big-endian length prefix, one
protobuf message each way, one connection per task.
"""

from __future__ import annotations

import socket
import struct

from . import gskyrpc_pb2 as pb

_LEN = struct.Struct(">I")
MAX_MSG = 1 << 30


def send_msg(sock: socket.socket, msg) -> None:
    payload = msg.SerializeToString()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return bytes(buf)


def recv_task(sock: socket.socket) -> pb.Task:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > MAX_MSG:
        raise ConnectionError(f"oversized message ({n} bytes)")
    t = pb.Task()
    t.ParseFromString(_recv_exact(sock, n))
    return t


def recv_result(sock: socket.socket) -> pb.Result:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > MAX_MSG:
        raise ConnectionError(f"oversized message ({n} bytes)")
    r = pb.Result()
    r.ParseFromString(_recv_exact(sock, n))
    return r


def call_subprocess(sock_path: str, task: pb.Task,
                    timeout: float = 130.0) -> pb.Result:
    """One task round-trip: connect, send, receive, close."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(sock_path)
        send_msg(s, task)
        return recv_result(s)
    finally:
        s.close()
