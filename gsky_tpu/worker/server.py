"""The worker node: gRPC front door + device executor + decode pool.

Role of the reference's `grpc-server/main.go` (binary ``gsky-rpc``): a
gRPC service exposing ``rpc Process(Task) returns (Result)`` with
operations

- ``worker_info`` — answered inline (`grpc-server/main.go:31-33`),
- ``warp``       — decode in the subprocess pool, then warp on the TPU
                   executor owned by this process (the reference does the
                   whole thing in a GDAL subprocess, `warp.go:82-410`),
- ``drill``      — decode + rasterized-mask reductions on device
                   (`worker/gdalprocess/drill.go`),
- ``extent`` / ``info`` — pure IO, delegated to the pool.

The pool gives crash isolation for codec IO; the OOM monitor SIGKILLs the
fattest child under memory pressure (§5.3 semantics).
"""

from __future__ import annotations

import argparse
import concurrent.futures as cf
import json
import logging
import math
import os
import signal
import threading
import time
import weakref
from typing import Optional

import numpy as np

from ..device_guard import DeviceGuardError
from ..fleet import DrainController, Draining
from ..obs import current_trace_id, remote_trace, span as obs_span
from ..resilience import faults
from . import gskyrpc_pb2 as pb
from .oom import OOMMonitor
from .pool import PoolFullError, ProcessPool
from .serialize import granule_from_pb, pack_raster, unpack_raster

log = logging.getLogger("gsky.worker.server")

SERVICE = "gskyrpc.GDAL"
METHOD = f"/{SERVICE}/Process"


def _compile_probe():
    """Pre-dispatch compile-counter sample (None when the probe is
    unavailable); paired with :func:`_device_attrs`."""
    try:
        from ..server.prewarm import compile_count
        return compile_count()
    except Exception:
        return None


def _device_attrs(sp, c0) -> None:
    """Device-side dispatch-span attributes: did THIS dispatch trigger a
    fresh XLA compile, and is the fused pallas kernel in play (the race
    verdict ledger's gate) — both cheap probes, both best-effort."""
    if c0 is not None:
        try:
            from ..server.prewarm import compile_count
            sp.set(fresh_compile=compile_count() > c0)
        except Exception:  # compile probe is best-effort telemetry
            pass
    try:
        from ..ops.pallas_tpu import use_pallas
        sp.set(pallas=bool(use_pallas()))
    except Exception:  # pallas gate probe is best-effort telemetry
        pass


class WorkerService:
    """Op dispatch shared by the gRPC wrapper and in-process tests."""

    def __init__(self, pool: Optional[ProcessPool] = None,
                 pool_size: Optional[int] = None,
                 task_timeout: float = 120.0):
        self.pool = pool or ProcessPool(size=pool_size,
                                        task_timeout=task_timeout)
        self.drain = DrainController("worker-node")
        from ..pipeline.executor import WarpExecutor
        self.executor = WarpExecutor()
        # elastic-fleet lifecycle (fleet/elastic.py): preemption state
        # + warm-handoff bookkeeping.  advertise_addr is how THIS node
        # names itself to peers (set by main() / GSKY_ELASTIC_SELF).
        self.advertise_addr: Optional[str] = \
            os.environ.get("GSKY_ELASTIC_SELF") or None
        self._preempt_lock = threading.Lock()
        self.preempted = False
        self.preempt_exit = None        # graceful: unpark main()
        self.preempt_exit_hard = None   # nograce: take the process
        self._handoff = {"entries": 0, "filled": 0, "cold": 0,
                         "active": 0}
        self._warm_cache = (0.0, None)  # (monotonic ts, journal want)
        # a node:preempt fault is delivered through the real protocol,
        # not a bespoke test path; weakref so a dropped in-process
        # service doesn't live on inside the faults module
        ref = weakref.ref(self)

        def _on_preempt(grace_s: float, graceful: bool) -> None:
            svc = ref()
            if svc is not None:
                svc.begin_preemption(grace_s, graceful=graceful)

        faults.set_preempt_handler(_on_preempt)

    # -- ops -----------------------------------------------------------------

    def process(self, task: pb.Task, ctx=None) -> pb.Result:
        """``ctx`` is the gRPC ServicerContext (None from in-process
        callers): its ``x-gsky-trace`` metadata continues the gateway's
        trace here, and the child spans ride back on ``info_json`` for
        ops that leave that channel free."""
        op = task.operation
        header = None
        if ctx is not None:
            try:
                for k, v in ctx.invocation_metadata():
                    if k == "x-gsky-trace":
                        header = v
                        break
            except Exception:
                header = None
        with remote_trace(header, f"worker.{op}") as wtrace:
            res = self._process(task, op, ctx)
            if wtrace is not None and not res.info_json \
                    and op in ("warp", "drill", "extent"):
                try:
                    res.info_json = json.dumps(
                        {"spans": wtrace.span_dicts()})
                except Exception:  # span attachment is advisory telemetry
                    pass
            return res

    def _process(self, task: pb.Task, op: str, ctx=None) -> pb.Result:
        try:
            # node-level chaos (GSKY_FAULTS="node:kill:..." etc.) hits
            # every RPC including health probes — a killed node just dies
            faults.inject("node")
            if op == "worker_info":
                # answered even while draining: this IS the drain
                # handshake the fleet health monitor reads
                return self._worker_info()
            if op == "preempt":
                # control plane, answered inline: the notice must land
                # on a node that is busy (that's the point)
                return self._preempt(task)
            if op == "journal_handoff":
                # likewise: a successor may be receiving while its own
                # admission picture is grim — inheritance is not work
                return self._journal_handoff(task)
            if op == "page_fetch":
                # outside the drain gate deliberately: a draining
                # (preempted) node serving its resident pages to the
                # successor during the grace window IS the warm
                # handoff — refusing it would force a cold restage
                return self._page_fetch(task)
            with self.drain.track():
                if op == "warp":
                    return self._warp(task, ctx)
                if op == "drill":
                    return self._drill(task)
                if op in ("extent", "info", "decode"):
                    return self.pool.submit(task)
                return pb.Result(error=f"unknown operation {op!r}")
        except Draining as e:
            return pb.Result(error=f"draining: {e}")
        except PoolFullError as e:
            return pb.Result(error=f"backpressure: {e}")
        except DeviceGuardError as e:
            # retryable device incident (hang/crash/OOM/corruption or
            # mid-reinit): the "device:" prefix tells the client to fail
            # over to another node without charging this one a breaker
            # penalty — the supervisor is already rebuilding it
            return pb.Result(error=f"device: {e}")
        except Exception as e:
            log.exception("op %s failed trace=%s", op,
                          current_trace_id() or "-")
            return pb.Result(error=f"{type(e).__name__}: {e}")

    def _worker_info(self) -> pb.Result:
        import jax
        r = pb.Result()
        r.worker.pool_size = self.pool.size
        r.worker.queue_cap = self.pool.queue.maxsize
        r.worker.platform = jax.default_backend()
        # WorkerInfo has no spare proto field; the drain handshake rides
        # the free-form info_json channel instead.  The device
        # supervisor's state and the decode pool's crash-loop breaker
        # ride along so the fleet health monitor can mark a node
        # degraded (suspect/reinitializing) or fatal (dead/crash-loop)
        # from the same probe.
        info = dict(self.drain.stats())
        try:
            from .. import device_guard
            info["device"] = device_guard.default_supervisor().stats()
        except Exception:  # device guard absent - health still reports drain stats
            pass
        try:
            info["pool"] = self.pool.stats()
        except Exception:  # pool stats optional in the health probe
            pass
        try:
            from ..pipeline import pages
            if pages._default is not None:
                # page-pool residency rides the same probe so the soak
                # (and operators) can see peer fills vs cold stages
                info["pages"] = pages._default.stats()
        except Exception:  # no page pool in this build
            pass
        try:
            info["elastic"] = self._elastic_info()
        except Exception:  # readiness is advisory; the probe still answers
            pass
        r.info_json = json.dumps(info)
        return r

    # -- elastic lifecycle (fleet/elastic.py; docs/FLEET.md) -----------------

    def _elastic_info(self) -> dict:
        """Readiness + handoff block of the ``worker_info`` probe: the
        autoscaler's join gate reads ``ready``; ``warm_fraction`` is
        the share of the journal's hot set already resident in this
        node's page pool (1.0 when there is nothing to warm)."""
        from ..fleet import elastic
        from ..pipeline import pages
        pool = pages._default
        want = self._journal_want()
        resident = 0
        capacity = 0
        if pool is not None:
            try:
                st = pool.stats()
                resident = int(st.get("resident", 0))
                capacity = int(st.get("capacity", 0))
            except Exception:  # pool mid-teardown: report cold
                pass
        if want <= 0:
            warm = 1.0
        else:
            goal = min(want, capacity) if capacity else want
            warm = min(1.0, resident / max(goal, 1))
        from .. import fabric
        can_warm = fabric.pages_enabled()
        ready = (not can_warm) or warm >= elastic.warm_fraction_target()
        with self._preempt_lock:
            handoff = dict(self._handoff)
            preempted = self.preempted
        return {"ready": bool(ready),
                "warm_fraction": round(warm, 4),
                "prewarm_done": True,
                "preempted": preempted,
                "handoff": handoff}

    def _journal_want(self) -> int:
        """Journal hot-set size, cached a few seconds — the probe fires
        every heartbeat and replay() re-reads the whole file."""
        now = time.monotonic()
        ts, cached = self._warm_cache
        if cached is not None and now - ts < 5.0:
            return cached
        want = 0
        try:
            from ..device_guard import journal
            if journal.journal_enabled():
                want = len(journal.replay())
        except Exception:
            want = 0
        self._warm_cache = (now, want)
        return want

    def _preempt(self, task: pb.Task) -> pb.Result:
        """The preemption notice (autoscaler scale-down, or the soak
        playing the cloud's spot reclaim): start the drain + warm
        journal handoff under the grace deadline.  Idempotent."""
        try:
            doc = json.loads(task.path or "{}")
        except ValueError:
            doc = {}
        grace = doc.get("grace_s")
        from ..fleet import elastic
        grace_s = float(grace) if grace is not None \
            else elastic.preempt_grace_s()
        self.begin_preemption(
            grace_s, graceful=bool(doc.get("graceful", True)),
            successor=doc.get("successor") or None,
            peers=[p for p in (doc.get("peers") or [])
                   if isinstance(p, str)])
        r = pb.Result()
        r.info_json = json.dumps({"ok": True, "grace_s": grace_s})
        return r

    def begin_preemption(self, grace_s: float, graceful: bool = True,
                         successor: Optional[str] = None,
                         peers=()) -> bool:
        """First notice wins; later notices (a retried RPC, a second
        fault roll) are no-ops.  Returns True when this call started
        the preemption."""
        with self._preempt_lock:
            if self.preempted:
                return False
            self.preempted = True
        threading.Thread(
            target=self._run_preemption,
            args=(max(float(grace_s), 0.0), graceful, successor,
                  list(peers)),
            daemon=True, name="gsky-preempt").start()
        return True

    def _run_preemption(self, grace_s, graceful, successor, peers):
        from ..fleet import elastic
        deadline = time.monotonic() + grace_s
        elastic.note_preemption(graceful and grace_s > 0)
        if not graceful or grace_s <= 0:
            # zero grace: flush what a local restart can use, then go
            log.warning("preemption (no grace): flushing journal")
            self._flush_pool_journal()
            hard = self.preempt_exit_hard or self.preempt_exit
            if hard is not None:
                hard()
            return
        log.info("preemption notice: grace=%.1fs successor=%s",
                 grace_s, successor or "-")
        self.drain.start_drain()
        self._ship_journal(successor, peers,
                           timeout=max(min(grace_s * 0.5, 5.0), 0.5))
        left = deadline - time.monotonic() - 0.25
        ok = self.drain.wait_drained(max(left, 0.0))
        if not ok:
            # hard grace deadline: fail over the stragglers explicitly
            # (counted; their callers see a transport failure, which
            # the fleet router retries on another node)
            n = self.drain.abandon_inflight()
            log.warning("preemption grace expired with %d in flight; "
                        "failing them over", n)
        self._flush_pool_journal()
        st = self.drain.stats()
        log.info("preemption drain done: completed=%d refused=%d "
                 "abandoned=%d", st["completed"], st["refused"],
                 st["abandoned"])
        # hold until the grace deadline even when the drain finished
        # early: the successor is still pulling our pages over
        # page_fetch, and the fleet's health probes need at least one
        # beat of the draining state to classify this departure as a
        # preemption rather than a crash
        left = deadline - time.monotonic() - 0.1
        if left > 0:
            time.sleep(left)
        if self.preempt_exit is not None:
            self.preempt_exit()

    def _ship_journal(self, successor, peers, timeout: float) -> None:
        """Ship this node's hot-set journal (heat scores included) to
        its ring successor so the pages can be pulled from our HBM
        while the grace window keeps us alive."""
        from ..fleet import elastic
        try:
            from ..device_guard import journal
            entries = journal.export_hot(elastic.handoff_max())
        except Exception:
            entries = []
        if successor is None and self.advertise_addr:
            successor = elastic.successor_for(self.advertise_addr, peers)
        if not entries or not successor:
            return
        doc = {"v": 1, "source": self.advertise_addr,
               "peers": [p for p in peers if p != successor],
               "entries": [[s, pi, pj, round(score, 3)]
                           for s, pi, pj, score in entries]}
        try:
            elastic.control_rpc(successor, "journal_handoff", doc,
                                timeout=timeout)
            elastic.note_handoff_shipped(len(entries), True)
            log.info("journal handoff: %d entries -> %s",
                     len(entries), successor)
        except Exception:
            elastic.note_handoff_shipped(len(entries), False)
            log.warning("journal handoff to %s failed", successor)

    def _flush_pool_journal(self) -> None:
        """Dump the pool's in-memory heat to the journal (the teardown
        path already writes heat lines) so even an abandoned exit
        leaves a replayable hot set behind."""
        try:
            from ..pipeline import pages
            if pages._default is not None:
                pages._default.teardown()
        except Exception:
            log.exception("journal flush on preemption failed")

    def _journal_handoff(self, task: pb.Task) -> pb.Result:
        """Successor half of the warm handoff: merge the preempted
        node's scored hot set into our journal, then pull the pages
        hottest-first from its still-alive HBM (and the other peers)
        over the page RPC — in the background; the notice must return
        within the sender's grace window."""
        from ..device_guard import journal
        from ..fleet import elastic
        try:
            doc = json.loads(task.path or "{}")
        except ValueError:
            return pb.Result(error="elastic: malformed handoff")
        entries = []
        for e in doc.get("entries") or []:
            try:
                s, pi, pj = int(e[0]), int(e[1]), int(e[2])
                score = float(e[3]) if len(e) > 3 else 1.0
            except (TypeError, ValueError, IndexError):
                continue
            if pi < 0 or pj < 0:      # same guard as merge_scored
                continue
            entries.append((s, pi, pj, score))
        entries = entries[:elastic.handoff_max()]
        journal.merge_scored(entries)
        self._warm_cache = (0.0, None)   # hot set just grew
        source = doc.get("source") or None
        peers = [p for p in (doc.get("peers") or [])
                 if isinstance(p, str) and p != self.advertise_addr]
        with self._preempt_lock:
            self._handoff["entries"] += len(entries)
            self._handoff["active"] += 1
        threading.Thread(
            target=self._handoff_fill, args=(entries, source, peers),
            daemon=True, name="gsky-handoff-fill").start()
        r = pb.Result()
        r.info_json = json.dumps({"accepted": len(entries)})
        return r

    def _handoff_fill(self, entries, source, peers):
        from .. import fabric
        from ..fleet import elastic
        filled = 0
        keys = [(s, pi, pj) for s, pi, pj, _ in entries]
        try:
            if fabric.pages_enabled() and keys:
                from ..fabric import pagerpc
                from ..pipeline.pages import default_page_pool
                pool = default_page_pool()
                missing = [k for k in keys if not pool.has_page(*k)]
                already = len(keys) - len(missing)
                fill_peers = [p for p in ([source] + peers) if p]
                filled = already + pagerpc.fill_from_peers(
                    pool, missing, peers=fill_peers, prefer=source)
        except Exception:
            log.exception("handoff fill failed")
        cold = len(keys) - filled
        elastic.note_handoff_pages("peer", filled)
        elastic.note_handoff_pages("cold", cold)
        with self._preempt_lock:
            self._handoff["filled"] += filled
            self._handoff["cold"] += cold
            self._handoff["active"] -= 1
        log.info("handoff fill: %d/%d pages from peers", filled,
                 len(keys))

    def _page_fetch(self, task: pb.Task) -> pb.Result:
        """Cache-fabric page RPC (docs/FABRIC.md): read requested
        resident pages back to host and ship them content-keyed with
        per-page CRCs.  Refused when the worker page tier is off."""
        from .. import fabric
        if not fabric.pages_enabled():
            return pb.Result(error="fabric: page peering disabled")
        from ..fabric import pagerpc
        from ..pipeline import pages
        res = pb.Result()
        pool = pages._default
        try:
            doc = json.loads(task.path or "{}")
        except ValueError:
            return pb.Result(error="fabric: malformed page_fetch request")
        if pool is None:
            res.info_json = json.dumps(
                {"v": 1, "page_shape": [0, 0], "pages": []})
            return res
        manifest, blob = pagerpc.serve_page_fetch(pool, doc)
        res.raster = blob
        res.info_json = json.dumps(manifest)
        return res

    def _warp(self, task: pb.Task, ctx=None) -> pb.Result:
        from ..geo.crs import parse_crs
        from ..geo.transform import GeoTransform
        from ..pipeline.decode import DecodedWindow

        # the gateway's cancel token propagates here as a gRPC
        # cancellation; ctx.is_active() goes False the moment the
        # client aborts, so poll it at the expensive boundaries and
        # stop decoding/warping for a response nobody will receive
        def _gone() -> bool:
            try:
                return ctx is not None and not ctx.is_active()
            except Exception:
                return False

        d = task.dst
        res = pb.Result()
        if _gone():
            return pb.Result(error="cancelled: client departed")
        g = granule_from_pb(task.granule)
        if g.geo_loc:
            # curvilinear granules have no affine window to decode; warp
            # straight from the device scene cache through the
            # geolocation ctrl-grid path (executor._geoloc_ctrl).  This
            # read happens in-process rather than through the decode
            # pool: the scene must land in THIS process's HBM cache
            # anyway, and the NetCDF read path here is Python/h5py (the
            # crash-prone native codec is the TIFF path) — the pool's
            # isolation buys little for the cost of a second full-scene
            # copy over IPC.
            dst_gt = GeoTransform.from_gdal(list(d.geo_transform))
            c0 = _compile_probe()
            with obs_span("worker.dispatch", curvilinear=True,
                          shape=[d.height, d.width]) as wsp:
                sc = self.executor.warp_mosaic_scenes(
                    [g], [0], [1.0], dst_gt, parse_crs(d.srs), d.height,
                    d.width, 1, d.resample or "near")
            _device_attrs(wsp, c0)
            if sc is None:
                # parity with the local path's loud degradation: a
                # blank remote tile must not look like absent data
                log.warning("curvilinear granule %s uncacheable; "
                            "warp RPC returns empty trace=%s", g.path,
                            current_trace_id() or "-")
                return res
            canv, vals = sc
            with obs_span("worker.readback") as rb:
                from .. import device_guard
                a = device_guard.guarded_readback(
                    "worker.readback", lambda: np.asarray(canv[0]))
                v = np.asarray(vals[0])
                rb.set(bytes=int(a.nbytes + v.nbytes))
            pack_raster(res, a, v)
            b = dst_gt.bbox(d.width, d.height)
            res.bbox.extend([b.xmin, b.ymin, b.xmax, b.ymax])
            res.dtype = "Float32"
            res.metrics.bytes_read = int(
                np.asarray(canv[0]).nbytes)
            return res
        decode = pb.Task()
        decode.CopyFrom(task)
        decode.operation = "decode"
        with obs_span("worker.decode") as dsp:
            dres = self.pool.submit(decode)
            dsp.set(bytes_read=int(dres.metrics.bytes_read))
        if dres.error:
            return dres
        if _gone():
            # decoded bytes for a departed client: stop before the
            # device dispatch, the costliest remaining step
            return pb.Result(error="cancelled: client departed")
        win = unpack_raster(dres)
        if win is None:  # granule doesn't touch the tile -> empty result
            return res
        data, valid = win
        wdw = DecodedWindow(
            granule=g, data=data, valid=valid,
            window_gt=GeoTransform.from_gdal(list(dres.window_gt)),
            src_crs=parse_crs(dres.src_srs))
        dst_gt = GeoTransform.from_gdal(list(d.geo_transform))
        c0 = _compile_probe()
        with obs_span("worker.dispatch",
                      shape=[d.height, d.width]) as wsp:
            out = self.executor.warp_all([wdw], dst_gt, parse_crs(d.srs),
                                         d.height, d.width,
                                         d.resample or "near")[0]
        _device_attrs(wsp, c0)
        if out is None:
            return res
        with obs_span("worker.readback") as rb:
            from .. import device_guard
            a = device_guard.guarded_readback(
                "worker.readback", lambda: np.asarray(out[0]))
            v = np.asarray(out[1])
            rb.set(bytes=int(a.nbytes + v.nbytes))
        pack_raster(res, a, v)
        b = dst_gt.bbox(d.width, d.height)
        res.bbox.extend([b.xmin, b.ymin, b.xmax, b.ymax])
        res.dtype = "Float32"
        res.metrics.CopyFrom(dres.metrics)
        return res

    def _drill(self, task: pb.Task) -> pb.Result:
        from ..geo import geometry as geom
        from ..index.client import Dataset
        from ..pipeline.drill import _drill_file
        from ..pipeline.types import GeoDrillRequest

        g = task.granule
        sp = task.drill
        ds = Dataset(
            file_path=g.path, ds_name=g.ds_name, namespace=g.namespace,
            array_type=g.array_type or "Float32", srs=g.srs,
            geo_transform=list(g.geo_transform),
            timestamps=[], timestamps_iso=[], polygon="",
            nodata=g.nodata if g.has_nodata else 0.0)
        req = GeoDrillRequest(
            collection="", bands=[g.namespace or "b1"],
            geometry_wkt=sp.geometry_wkt,
            band_strides=max(int(sp.stride), 1),
            deciles=9 if sp.deciles else 0,
            pixel_count=sp.pixel_count,
            clip_lower=sp.clip_lower if sp.has_clip else -3.0e38,
            clip_upper=sp.clip_upper if sp.has_clip else 3.0e38)
        sel = list(sp.time_indices) or [0]
        # sp.vrt_xml arrives RENDERED (the client renders per granule,
        # `drill_indexer.go:340`); drill through the VRT when present
        out = _drill_file(ds, sel, geom.from_wkt(sp.geometry_wkt), req,
                          vrt_xml=sp.vrt_xml or None)
        res = pb.Result()
        if out is None:
            return res
        vals, counts, dec = out
        res.series.means.extend(float(v) if math.isfinite(v) else 0.0
                                for v in np.asarray(vals).ravel())
        res.series.counts.extend(int(c) for c in np.asarray(counts).ravel())
        res.series.deciles.extend(float(v) for v in np.asarray(dec).ravel())
        return res

    def close(self):
        self.pool.close()


# ---------------------------------------------------------------------------
# gRPC wiring (generic handler; stubs aren't generated without grpcio-tools)
# ---------------------------------------------------------------------------


def make_grpc_server(service: WorkerService, address: str = "[::]:11429",
                     max_workers: int = 32, max_msg: int = 64 << 20):
    import grpc

    handler = grpc.method_handlers_generic_handler(SERVICE, {
        "Process": grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: service.process(req, ctx),
            request_deserializer=pb.Task.FromString,
            response_serializer=pb.Result.SerializeToString),
    })
    server = grpc.server(
        cf.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", max_msg),
                 ("grpc.max_send_message_length", max_msg),
                 ("grpc.so_reuseport", 1)])
    server.add_generic_rpc_handlers((handler,))
    server.add_insecure_port(address)
    return server


def main(argv=None):
    ap = argparse.ArgumentParser(prog="gsky-rpc")
    ap.add_argument("-p", "--port", type=int, default=11429)
    ap.add_argument("-host", default="[::]",
                    help="listen address ([::] needs a dual-stack host; "
                         "use 127.0.0.1 on IPv4-only ones)")
    ap.add_argument("-n", "--pool", type=int, default=0,
                    help="decode pool size (default: cpu count)")
    ap.add_argument("-max_tasks", type=int, default=20000)
    ap.add_argument("-timeout", type=float, default=120.0)
    ap.add_argument("-oom_threshold", type=int, default=1536,
                    help="MemAvailable floor in MiB (0 disables)")
    a = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from ..device import ensure_platform
    plat = ensure_platform()
    if plat["fallback"]:
        log.warning("accelerator unreachable after %d probe(s); "
                    "computing on CPU", plat["probe_attempts"])

    svc = WorkerService(pool_size=a.pool or None, task_timeout=a.timeout)
    if not svc.advertise_addr:
        # how peers reach us for the page RPC / journal handoff; wildcard
        # listen addresses advertise loopback (single-host fleets)
        host = "127.0.0.1" if a.host in ("[::]", "0.0.0.0") else a.host
        svc.advertise_addr = f"{host}:{a.port}"
    monitor = None
    if a.oom_threshold:
        def _oom_killed(pid: int) -> None:
            # a defensive kill IS a host-memory OOM incident: count it
            # on the supervisor and shed node-wide pressure so the next
            # victim isn't immediately re-grown
            from .. import device_guard
            from ..resilience.pressure import default_monitor
            device_guard.default_supervisor().record_oom(
                "worker.oom", RuntimeError(f"killed decode pid {pid}"))
            default_monitor().escalate()

        monitor = OOMMonitor(svc.pool.child_pids,
                             threshold_bytes=a.oom_threshold << 20,
                             on_kill=_oom_killed)
        monitor.start()
    server = make_grpc_server(svc, f"{a.host}:{a.port}")
    server.start()
    log.info("gsky-rpc listening on %s:%d (pool=%d)",
             a.host, a.port, svc.pool.size)

    try:
        from .. import fabric
        if fabric.pages_enabled() and fabric.page_peer_addrs():
            # cache-fabric warm boot (docs/FABRIC.md): pull the
            # journal's hot set from ring-adjacent peers instead of
            # cold-staging it request by request.  Backgrounded: the
            # node serves (and cold-stages) normally while it warms.
            from ..pipeline.pages import default_page_pool

            def _warm_boot():
                try:
                    n = default_page_pool().rehydrate()
                    log.info("fabric: warm boot restored %d pages", n)
                except Exception:
                    log.exception("fabric: warm boot failed")

            threading.Thread(target=_warm_boot, daemon=True,
                             name="gsky-fabric-warm").start()
    except Exception:  # fabric optional; a worker must boot without it
        log.exception("fabric: warm boot setup failed")

    # graceful drain: SIGTERM/SIGINT closes the accept gate (new ops
    # answer "draining:", worker_info keeps answering with the draining
    # flag so the fleet deregisters us), in-flight ops run to completion,
    # then the server exits.  A supervisor that can't wait will SIGKILL
    # after its own grace period; GSKY_DRAIN_TIMEOUT_S bounds ours.
    stop = threading.Event()
    # preemption notices (the `preempt` RPC or a node:preempt fault)
    # exit through the same park-loop as a signal drain; a no-grace
    # preemption takes the process the way the reclaim would
    svc.preempt_exit = stop.set
    svc.preempt_exit_hard = lambda: os._exit(1)

    def _drain():
        svc.drain.start_drain()
        timeout = float(os.environ.get("GSKY_DRAIN_TIMEOUT_S", "30") or 30)
        ok = svc.drain.wait_drained(timeout)
        if not ok:
            # grace deadline: fail over the stragglers explicitly
            # (counted) instead of silent in-flight loss, and flush
            # the page journal so the restart replays warm
            n = svc.drain.abandon_inflight()
            log.warning("drain timed out with %d in flight; "
                        "failing them over", n)
            svc._flush_pool_journal()
        st = svc.drain.stats()
        log.info("drain %s: completed=%d refused=%d inflight=%d "
                 "abandoned=%d",
                 "complete" if ok else "TIMED OUT",
                 st["completed"], st["refused"], st["inflight"],
                 st["abandoned"])
        stop.set()

    def _on_term(signum, frame):
        log.info("signal %d: draining worker node", signum)
        threading.Thread(target=_drain, daemon=True,
                         name="gsky-drain").start()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    try:
        # park until a signal-triggered drain completes; the gRPC
        # server keeps serving from its own threads meanwhile
        while not stop.wait(0.5):
            pass
    finally:
        server.stop(grace=5).wait()
        if monitor:
            monitor.stop()
        svc.close()


if __name__ == "__main__":
    main()
