"""Granule/raster <-> protobuf conversion for the worker RPC boundary."""

from __future__ import annotations

import json
import math
from typing import Optional, Tuple

import numpy as np

from ..pipeline.types import Granule
from . import gskyrpc_pb2 as pb


def granule_to_pb(g: Granule) -> pb.Granule:
    m = pb.Granule(
        path=g.path, ds_name=g.ds_name, var_name=g.var_name,
        band=int(g.band),
        time_index=-1 if g.time_index is None else int(g.time_index),
        timestamp=float(g.timestamp), srs=g.srs,
        array_type=g.array_type, is_netcdf=bool(g.is_netcdf),
        namespace=g.namespace, base_namespace=g.base_namespace)
    m.geo_transform.extend(float(v) for v in (g.geo_transform or []))
    if g.nodata is not None and not (isinstance(g.nodata, float)
                                     and math.isnan(g.nodata)):
        m.nodata = float(g.nodata)
        m.has_nodata = True
    if g.geo_loc:
        m.geo_loc_json = json.dumps(g.geo_loc)
    if g.polygon:
        m.polygon = g.polygon
    return m


def granule_from_pb(m: pb.Granule) -> Granule:
    return Granule(
        path=m.path, ds_name=m.ds_name, namespace=m.namespace,
        base_namespace=m.base_namespace, band=m.band,
        time_index=None if m.time_index < 0 else m.time_index,
        timestamp=m.timestamp, srs=m.srs,
        geo_transform=list(m.geo_transform),
        nodata=m.nodata if m.has_nodata else None,
        array_type=m.array_type or "Float32",
        is_netcdf=m.is_netcdf, var_name=m.var_name,
        geo_loc=json.loads(m.geo_loc_json) if m.geo_loc_json else None,
        polygon=m.polygon)


def pack_raster(result: pb.Result, data: np.ndarray,
                valid: np.ndarray) -> None:
    """float32 raster + packed-bit validity into a Result in place."""
    h, w = data.shape
    result.raster = np.ascontiguousarray(data, np.float32).tobytes()
    result.valid = np.packbits(
        np.ascontiguousarray(valid, bool), axis=None).tobytes()
    del result.shape[:]
    result.shape.extend([h, w])


def unpack_raster(result: pb.Result) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    if len(result.shape) != 2 or not result.raster:
        return None
    h, w = result.shape
    data = np.frombuffer(result.raster, np.float32).reshape(h, w)
    bits = np.unpackbits(np.frombuffer(result.valid, np.uint8),
                         count=h * w)
    return data.copy(), bits.astype(bool).reshape(h, w)
