"""Supervised decode-subprocess pool.

The fault-tolerance semantics of the reference's worker layer
(`worker/gdalprocess/pool.go` + `process.go`):

- N subprocesses share one bounded task queue; enqueue on a full queue is
  rejected immediately (queue cap 200/process, `pool.go:19-25`).
- A crashed or wedged subprocess is SIGKILLed and replaced; its task is
  retried up to 5 times (`process.go:189-198`, `pool.go:40-63`).
- Each subprocess is recycled after ``max_tasks`` tasks, jittered per
  process so the pool doesn't recycle in lockstep (`pool.go:29-33`,
  `process.go:154-159`).
- Children die with the parent (Pdeathsig equivalent via
  ``prctl(PR_SET_PDEATHSIG)`` in the child preexec, `process.go:63`).
"""

from __future__ import annotations

import ctypes
import logging
import os
import queue
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import List, Optional

from ..resilience import faults
from . import gskyrpc_pb2 as pb
from .ipc import call_subprocess

log = logging.getLogger("gsky.worker.pool")

MAX_RETRIES = 5
QUEUE_CAP_PER_PROCESS = 200

# consecutive-spawn-failure backoff: exponential with full jitter so a
# pool of slots all failing against the same broken dependency doesn't
# hammer it in lockstep
RESPAWN_BACKOFF_BASE_S = 0.5
RESPAWN_BACKOFF_CAP_S = 15.0

# crash-loop breaker: this many unexpected respawns (crashes or spawn
# failures — NOT planned max_tasks recycles) inside the sliding window
# and the node stops pretending restarts will fix it
CRASH_LOOP_MAX = 5
CRASH_LOOP_WINDOW_S = 60.0


def _respawn_backoff(failures: int, rand=random.random) -> float:
    """Delay before the next spawn attempt after `failures` consecutive
    failures: min(cap, base * 2^failures) with full jitter."""
    raw = min(RESPAWN_BACKOFF_CAP_S,
              RESPAWN_BACKOFF_BASE_S * (2 ** min(failures, 16)))
    return raw * (0.5 + rand())


class CrashLoopBreaker:
    """Sliding-window respawn counter that latches `tripped`.

    A subprocess crash is survivable — the supervisor replaces it and
    retries the task.  A CRASH LOOP is not: N unexpected respawns inside
    the window means something environmental (bad install, exhausted
    node, poisoned input wedging every child) that one more restart
    won't fix.  Tripping doesn't stop the pool — it keeps limping, which
    still beats refusing everything — but the state is folded into the
    worker's info block so the fleet health monitor marks the node fatal
    and routers stop sending it fresh work (docs/RESILIENCE.md)."""

    def __init__(self, max_crashes: int = CRASH_LOOP_MAX,
                 window_s: float = CRASH_LOOP_WINDOW_S,
                 clock=time.monotonic):
        self.max_crashes = max(1, int(max_crashes))
        self.window_s = float(window_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._times: List[float] = []
        self.total = 0
        self.tripped = False

    def record(self) -> bool:
        """Count one unexpected respawn; returns the (possibly newly)
        tripped state."""
        with self._lock:
            now = self.clock()
            self.total += 1
            self._times.append(now)
            cutoff = now - self.window_s
            self._times = [t for t in self._times if t >= cutoff]
            if len(self._times) >= self.max_crashes and not self.tripped:
                self.tripped = True
                log.error(
                    "crash-loop breaker tripped: %d respawns in %.0fs; "
                    "reporting node fatal to fleet health",
                    len(self._times), self.window_s)
            return self.tripped

    def stats(self) -> dict:
        with self._lock:
            return {"tripped": self.tripped, "respawns": self.total,
                    "recent": len(self._times),
                    "max_crashes": self.max_crashes,
                    "window_s": self.window_s}


def _recycle_threshold(max_tasks: int, size: int,
                       rand=random.randrange) -> int:
    """Jittered per-process recycle threshold, proportional to the
    recycle period so a pool draining one shared queue doesn't restart
    in lockstep (the reference jitters by pool size, `pool.go:29-33`;
    our children block ~tens of seconds on startup imports, so the
    spread must be much wider than a few tasks)."""
    if size <= 1:
        return max_tasks
    return max_tasks + rand(max(size, max_tasks // 10))

_PR_SET_PDEATHSIG = 1


def _set_pdeathsig():
    try:
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(_PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:  # non-glibc platform - pdeathsig is a linux nicety
        pass


class _Task:
    __slots__ = ("task", "event", "result", "attempts", "trace_id")

    def __init__(self, task: pb.Task):
        self.task = task
        self.event = threading.Event()
        self.result: Optional[pb.Result] = None
        self.attempts = 0
        # captured at submit: the feeder thread that logs a crash has no
        # request context of its own
        try:
            from ..obs import current_trace_id
            self.trace_id = current_trace_id() or "-"
        except Exception:
            self.trace_id = "-"


class PoolFullError(RuntimeError):
    """Task queue full: pure backpressure, not a broken pool.

    ``retryable`` so :func:`resilience.retry.call_with_retry` backs off
    and re-enqueues instead of failing the request outright — the queue
    drains at pool speed, so a jittered retry usually lands."""

    retryable = True


class Process:
    """One supervised subprocess + the worker thread that feeds it."""

    def __init__(self, pool: "ProcessPool", idx: int):
        self.pool = pool
        self.idx = idx
        self.sock_path = os.path.join(
            pool.tmp_dir, f"gsky_decode_{os.getpid()}_{idx}.sock")
        self.max_tasks = _recycle_threshold(pool.max_tasks, pool.size)
        self.proc: Optional[subprocess.Popen] = None
        self.tasks_done = 0
        self.spawn_failures = 0   # consecutive; drives the backoff
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=f"gsky-pool-{idx}")
        self.thread.start()

    # -- child lifecycle -----------------------------------------------------

    def _spawn(self):
        self.tasks_done = 0
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass
        proc = subprocess.Popen(
            [sys.executable, "-m", "gsky_tpu.worker.subproc",
             "-sock", self.sock_path,
             "-max_tasks", str(self.max_tasks),
             "-timeout", str(self.pool.task_timeout)],
            preexec_fn=_set_pdeathsig,
            stderr=subprocess.DEVNULL if self.pool.quiet else None)
        self.proc = proc
        # give the child time for its first imports (jax is heavy)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not self.pool.closed:
            if os.path.exists(self.sock_path):
                return
            if proc.poll() is not None:
                break
            time.sleep(0.01)
        if self.pool.closed:
            return
        raise RuntimeError(f"decode subprocess {self.idx} failed to start")

    def _kill(self):
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        self.proc = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    # -- task loop -----------------------------------------------------------

    def _respawn(self, crashed: bool = False) -> bool:
        """Spawn with the feeder thread kept alive on failure — a slot
        that can't start a child keeps retrying instead of dying.
        `crashed` marks an UNEXPECTED replacement (child died or wedged,
        vs a planned max_tasks recycle) and feeds the pool's crash-loop
        breaker; spawn failures always do.  Consecutive failures back
        off exponentially with jitter so a broken dependency isn't
        hammered in lockstep by every slot."""
        if crashed:
            self.pool.breaker.record()
        try:
            self._spawn()
            self.spawn_failures = 0
            return True
        except (RuntimeError, OSError) as e:
            log.error("subprocess %d spawn failed: %s", self.idx, e)
            self._kill()
            self.pool.breaker.record()
            delay = _respawn_backoff(self.spawn_failures)
            self.spawn_failures += 1
            deadline = time.monotonic() + delay
            while time.monotonic() < deadline and not self.pool.closed:
                time.sleep(0.05)
            return False

    def _run(self):
        self._respawn()
        while not self.pool.closed:
            if self.proc is None or self.proc.poll() is not None:
                # crashed, recycled, or never started: replace it.  A
                # child that EXITED on its own (proc present, poll set)
                # counts as a crash; a slot still failing to spawn
                # (proc None) already counted when the spawn failed.
                if not self._respawn(crashed=self.proc is not None):
                    continue
            try:
                item = self.pool.queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                break
            try:
                # injected "pool" faults raise a ConnectionError subclass
                # here, driving the REAL kill/respawn/retry path below —
                # no test-only branches in the recovery logic
                faults.inject("pool")
                res = call_subprocess(
                    self.sock_path, item.task,
                    timeout=self.pool.task_timeout + 10.0)
                item.result = res
                item.event.set()
                self.tasks_done += 1
                if self.tasks_done >= self.max_tasks:
                    self._kill()
                    self._respawn()
            except (ConnectionError, OSError) as e:
                # crash/wedge: kill + replace + retry (`process.go:189-198`)
                log.warning("subprocess %d task failed (%s); restarting "
                            "trace=%s", self.idx, e, item.trace_id)
                self._kill()
                self._respawn(crashed=True)
                item.attempts += 1
                if item.attempts >= MAX_RETRIES:
                    item.result = pb.Result(
                        error=f"task failed after {item.attempts} attempts")
                    item.event.set()
                else:
                    try:
                        self.pool.queue.put_nowait(item)
                    except queue.Full:
                        item.result = pb.Result(error="queue full on retry")
                        item.event.set()
        self._kill()


class ProcessPool:
    """N supervised subprocesses sharing one bounded queue."""

    def __init__(self, size: Optional[int] = None, max_tasks: int = 20000,
                 task_timeout: float = 120.0, tmp_dir: Optional[str] = None,
                 quiet: bool = False):
        self.size = size or max(os.cpu_count() or 2, 2)
        self.max_tasks = max_tasks
        self.task_timeout = task_timeout
        self.tmp_dir = tmp_dir or tempfile.mkdtemp(prefix="gsky_pool_")
        self.quiet = quiet
        self.closed = False
        self.breaker = CrashLoopBreaker()
        self.queue: "queue.Queue[Optional[_Task]]" = queue.Queue(
            maxsize=QUEUE_CAP_PER_PROCESS * self.size)
        self.processes: List[Process] = [
            Process(self, i) for i in range(self.size)]

    def submit(self, task: pb.Task) -> pb.Result:
        """Run one task; raises PoolFullError on backpressure
        (`pool.go:19-25`)."""
        if self.closed:
            raise RuntimeError("pool closed")
        item = _Task(task)
        try:
            self.queue.put_nowait(item)
        except queue.Full:
            raise PoolFullError("worker task queue full")
        # IO timeout is enforced by the subprocess itself + call timeout;
        # the extra margin covers queueing delay under load.
        if not item.event.wait(self.task_timeout * MAX_RETRIES + 60.0):
            return pb.Result(error="task timed out in queue")
        return item.result

    def child_pids(self) -> List[int]:
        return [p.pid for p in self.processes if p.pid is not None]

    def stats(self) -> dict:
        """Folded into the worker's info block (_worker_info) so the
        client-side fleet health monitor sees crash-loop state."""
        return {"size": self.size, "queue_depth": self.queue.qsize(),
                "crash_loop": self.breaker.stats()}

    def close(self):
        self.closed = True
        # fail queued tasks immediately so blocked submitters wake up
        while True:
            try:
                item = self.queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item.result = pb.Result(error="pool closed")
                item.event.set()
        for _ in self.processes:
            try:
                self.queue.put_nowait(None)
            except queue.Full:
                pass  # feeders also exit via the closed-flag poll
        for p in self.processes:
            p.thread.join(timeout=10)
            p._kill()
