"""Adaptive OOM monitor for the worker's decode subprocesses.

Role of the reference's `worker/gdalprocess/oom_monitor.go`: poll
``/proc/meminfo`` at an interval adapted to the memory fill rate
(`getPollInterval`, `oom_monitor.go:154-174`), and when available memory
drops below the threshold, SIGKILL the largest-RSS decode subprocess so
the pool's supervisor replaces it — a controlled casualty instead of a
kernel OOM-kill of the whole worker (`oom_monitor.go:176-234`).
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Callable, List, Optional

log = logging.getLogger("gsky.worker.oom")

MIN_POLL_S = 0.05
MAX_POLL_S = 2.0


def mem_available_bytes(meminfo_path: str = "/proc/meminfo") -> Optional[int]:
    try:
        with open(meminfo_path) as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        return None
    return None


def rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError):
        return 0
    return 0


class OOMMonitor:
    """Watches available memory; kills the biggest child below threshold."""

    def __init__(self, child_pids: Callable[[], List[int]],
                 threshold_bytes: int = 1536 << 20,
                 meminfo_path: str = "/proc/meminfo",
                 kill: Callable[[int], None] = None,
                 on_kill: Callable[[int], None] = None):
        self.child_pids = child_pids
        self.threshold = threshold_bytes
        self.meminfo_path = meminfo_path
        self.kill = kill or (lambda pid: os.kill(pid, signal.SIGKILL))
        # fired after a successful defensive kill: the server wires this
        # to the device supervisor (count the OOM) and the pressure
        # monitor (escalate + shed caches) so the whole node backs off,
        # not just the one replaced child
        self.on_kill = on_kill
        self.kills = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_avail: Optional[int] = None
        self._last_t = 0.0

    # -- polling cadence -----------------------------------------------------

    def poll_interval(self, avail: int) -> float:
        """Faster polling as memory fills faster and headroom shrinks
        (`oom_monitor.go:154-174`)."""
        now = time.monotonic()
        headroom = max(avail - self.threshold, 0)
        fill_rate = 0.0
        if self._last_avail is not None and now > self._last_t:
            fill_rate = (self._last_avail - avail) / (now - self._last_t)
        self._last_avail = avail
        self._last_t = now
        if fill_rate <= 0:
            return MAX_POLL_S
        # time until the threshold at the current fill rate, sampled 4x
        eta = headroom / fill_rate
        return min(max(eta / 4.0, MIN_POLL_S), MAX_POLL_S)

    # -- the check -----------------------------------------------------------

    def check_once(self) -> Optional[int]:
        """Returns the killed pid, if any."""
        avail = mem_available_bytes(self.meminfo_path)
        if avail is None or avail >= self.threshold:
            return None
        victims = [(rss_bytes(pid), pid) for pid in self.child_pids()]
        victims = [v for v in victims if v[0] > 0]
        if not victims:
            return None
        rss, pid = max(victims)
        log.warning("OOM defence: %d bytes available < %d threshold; "
                    "killing pid %d (rss %d)", avail, self.threshold, pid, rss)
        try:
            self.kill(pid)
        except OSError:
            return None
        self.kills += 1
        if self.on_kill is not None:
            try:
                self.on_kill(pid)
            except Exception:   # the defence must outlive its observers
                log.exception("on_kill callback failed")
        return pid

    def _run(self):
        while not self._stop.is_set():
            avail = mem_available_bytes(self.meminfo_path)
            if avail is not None and avail < self.threshold:
                self.check_once()
            interval = self.poll_interval(avail) if avail is not None \
                else MAX_POLL_S
            self._stop.wait(interval)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gsky-oom-monitor")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
