"""Popularity-weighted replication of hot pages across shards.

The page-peering tier (`fabric/pagerpc.py`) makes any resident page
fetchable — but a page resident on exactly one worker still dies with
that worker, and serving traffic is Zipf-shaped: losing the head of
the distribution is a fleet-wide miss storm, losing the tail is
nothing.  This module turns the pool journal's heat ranking
(`device_guard/journal.py::replay_scored`) into a replication plan:

* every page gets a deterministic replica set — the first ``r`` nodes
  of its consistent-hash preference walk (`fleet/ring.py`), where
* ``r`` scales with popularity: the hottest page gets the full
  ``GSKY_FABRIC_REPLICAS`` copies, a page at a fraction ``f`` of the
  top score gets ``1 + round(f * (R - 1))`` — Zipf-head content
  survives any single node, tail content costs one slot.

A worker runs :func:`replicate_to_pool` opportunistically (after a
rehydrate, or from an operator/cron poke): it stages — via the normal
page-fetch RPC — every page whose replica set includes this node but
which is not yet resident locally.  Replication is pull-based and
idempotent; there is no coordinator and nothing to fail over.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import page_peer_addrs, replicate_enabled
from ..fleet.ring import HashRing

Key = Tuple[int, int, int]

_lock = threading.Lock()
_replica_pages = 0
_rounds = 0


def replica_count() -> int:
    """Target copies for the hottest content (``GSKY_FABRIC_REPLICAS``,
    default 2 — survive any one node)."""
    try:
        return max(1, int(os.environ.get("GSKY_FABRIC_REPLICAS", 2)))
    except (TypeError, ValueError):
        return 2


def replicas_for(score: float, top_score: float, replicas: int) -> int:
    """Popularity-weighted copy count: linear in the page's share of
    the top heat score, floored at one copy."""
    if top_score <= 0 or replicas <= 1:
        return 1
    frac = max(0.0, min(1.0, float(score) / float(top_score)))
    return 1 + int(round(frac * (replicas - 1)))


def replication_targets(ring: HashRing, key: Key,
                        n: int) -> List[str]:
    """The deterministic replica set: first ``n`` distinct nodes of the
    key's preference walk."""
    return ring.preference(json.dumps([int(k) for k in key]), n)


def plan(scored: Sequence[Tuple[int, int, int, float]],
         nodes: Sequence[str], self_node: str,
         replicas: Optional[int] = None,
         budget_pages: Optional[int] = None) -> List[Key]:
    """Pages ``self_node`` should hold, hottest first.

    ``scored`` is `journal.replay_scored()` output (hottest-first).
    ``budget_pages`` caps the plan so replication never floods a pool
    past its own working set."""
    nodes = sorted(set(nodes))
    if self_node not in nodes or not scored:
        return []
    ring = HashRing(nodes, vnodes=32)
    r = replica_count() if replicas is None else max(1, int(replicas))
    top = max(s for _, _, _, s in scored)
    out: List[Key] = []
    for serial, pi, pj, score in scored:
        key = (int(serial), int(pi), int(pj))
        n = replicas_for(score, top, r)
        if self_node in replication_targets(ring, key, n):
            out.append(key)
            if budget_pages is not None and len(out) >= budget_pages:
                break
    return out


def replicate_to_pool(pool, self_node: str,
                      peers: Optional[List[str]] = None,
                      fetch: Optional[Callable] = None) -> int:
    """Pull this node's planned replicas into ``pool`` via the page
    RPC.  Pages already resident are free; everything else is fetched
    from ring-adjacent peers.  Returns pages newly staged."""
    global _replica_pages, _rounds
    if not replicate_enabled():
        return 0
    from ..device_guard import journal
    scored = journal.replay_scored()
    if not scored:
        return 0
    peers = list(peers if peers is not None else page_peer_addrs())
    nodes = sorted({self_node, *peers})
    # replicate at most half the pool: warmth insurance must not evict
    # the locally-earned working set
    budget = max(1, pool.capacity // 2)
    wanted = plan(scored, nodes, self_node, budget_pages=budget)
    missing = [k for k in wanted if not pool.has_page(*k)]
    held = len(wanted) - len(missing)
    filled = 0
    if missing and peers:
        from . import pagerpc
        filled = pagerpc.fill_from_peers(pool, missing, peers=peers,
                                         fetch=fetch)
    with _lock:
        _replica_pages = held + filled
        _rounds += 1
    return filled


def stats() -> Dict:
    with _lock:
        return {"replica_pages": _replica_pages, "rounds": _rounds,
                "replicas": replica_count()}


def reset_stats() -> None:
    """Test hook."""
    global _replica_pages, _rounds
    with _lock:
        _replica_pages = 0
        _rounds = 0
