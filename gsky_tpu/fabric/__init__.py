"""Cache fabric: lateral peer sharing between gateways and workers.

Two tiers, both default-off behind ``GSKY_FABRIC``:

* **Gateway tier** (`fabric/replay.py`) — on a response-cache miss the
  consistent-hash ring (`fleet/ring.py`) designates an owner gateway;
  non-owners issue a bounded peer-replay RPC and replay the encoded
  bytes instead of paying a full render.  Misses in one gateway become
  hits fleet-wide.
* **Worker tier** (`fabric/pagerpc.py`, `fabric/replicate.py`) — pages
  are content-keyed ``(serial, pi, pj)`` and the pool journal records
  per-page heat, so a worker filling its pool asks ring-adjacent peers
  for hot pages hottest-first over a batched page-fetch RPC instead of
  re-decoding from storage; replicate.py spreads Zipf-head pages across
  shards so hot content survives any single node.

Peer HBM/host memory is an order of magnitude closer than object
storage (see PAPERS.md, cloud-to-GPU throughput tiering): the fabric
fills misses laterally before falling back to the cold tier.  Every
peer interaction is deadline-clamped, breaker-guarded and falls back
per-entry to the local render / cold-stage path — a dead peer costs
one bounded probe, never a 5xx.
"""

from __future__ import annotations

import os
from typing import Dict, List


def _on(name: str, dflt: str = "0") -> bool:
    return os.environ.get(name, dflt).strip().lower() not in (
        "0", "false", "off", "no", "")


def fabric_enabled() -> bool:
    """Master gate: ``GSKY_FABRIC=0`` (the default) keeps every fabric
    code path dormant — byte-identical to a fabric-less build."""
    return _on("GSKY_FABRIC")


def replay_enabled() -> bool:
    """Gateway peer-replay tier (needs the master gate too)."""
    return fabric_enabled() and _on("GSKY_FABRIC_REPLAY", "1")


def pages_enabled() -> bool:
    """Worker page-peering tier (needs the master gate too)."""
    return fabric_enabled() and _on("GSKY_FABRIC_PAGES", "1")


def replicate_enabled() -> bool:
    """Popularity-weighted hot-page replication (worker tier)."""
    return fabric_enabled() and _on("GSKY_FABRIC_REPLICATE", "1")


def self_addr() -> str:
    """This gateway's advertised base URL on the replay ring."""
    return os.environ.get("GSKY_FABRIC_SELF", "").strip()


def peer_addrs() -> List[str]:
    """Peer gateway base URLs (comma-separated, order-insensitive:
    membership is a ring, not a list)."""
    raw = os.environ.get("GSKY_FABRIC_PEERS", "")
    return [p.strip() for p in raw.split(",") if p.strip()]


def page_peer_addrs() -> List[str]:
    """Peer worker gRPC addresses for the page-fetch RPC."""
    raw = os.environ.get("GSKY_FABRIC_PAGE_PEERS", "")
    return [p.strip() for p in raw.split(",") if p.strip()]


def fabric_timeout_s() -> float:
    """Upper bound on any single peer RPC; always further clamped by
    the request deadline (`resilience.clamp_timeout`)."""
    try:
        return float(os.environ.get("GSKY_FABRIC_TIMEOUT_S", 2.0))
    except (TypeError, ValueError):
        return 2.0


def fabric_stats(replay_fabric=None) -> Dict:
    """One dict for the /debug ``fabric`` block; cheap when off."""
    doc: Dict = {"enabled": fabric_enabled(),
                 "replay_enabled": replay_enabled(),
                 "pages_enabled": pages_enabled(),
                 "replicate_enabled": replicate_enabled()}
    if replay_fabric is not None:
        doc["replay"] = replay_fabric.stats()
    try:
        from . import pagerpc, replicate
        doc["pages"] = pagerpc.stats()
        doc["replicate"] = replicate.stats()
    except Exception:  # stats must never take /debug down
        pass
    return doc
