"""Gateway tier: peer replay of fully-encoded responses.

A ``ReplayFabric`` places every gateway (self + ``GSKY_FABRIC_PEERS``)
on the consistent-hash ring from `fleet/ring.py`.  For each canonical
response key the ring designates an *owner* gateway; a non-owner that
misses its local `serving.ResponseCache` asks the owner (then, if that
fails, the next ring candidate) for the encoded bytes over a tiny HTTP
GET before paying a full render.  Because owners concentrate the first
render of each key, one gateway's miss becomes every gateway's hit.

Wire format (``GET {peer}/fabric/replay?key={sha1}``)::

    200  body = entry bytes, plus
         Content-Type:            entry content type
         ETag:                    "sha256[:32]" of the body
         X-Gsky-Fabric-Status:    origin HTTP status (always 200)
         X-Gsky-Fabric-Age:       seconds the entry has been cached
         X-Gsky-Fabric-Max-Age:   origin TTL in seconds
         X-Gsky-Fabric-Ns/-Layer/-Fp: cache identity (namespace, layer,
                                  layer config fingerprint)
         X-Gsky-Fabric-Keep:      JSON of extra replay headers
    404  peer has no fresh entry (or fabric off / brownout shedding)

Validators on receipt: the ETag must match a recomputed digest of the
body (content integrity), and ``max_age - age`` must leave positive
remaining TTL — the rebuilt entry expires at the *origin* deadline, so
Age keeps accumulating across hops exactly as RFC 9111 wants.  Peers
never serve stale or degraded entries (those are marked no-store at
origin and refused here); a brownout peer answers 404 and sheds.

Every fetch is deadline-clamped (`resilience.clamp_timeout`),
singleflight-deduped per key, and guarded by a per-peer breaker
(``fabric:{peer}``).  All failure modes return ``None`` — the caller
falls back to its local render; the fabric can only ever remove work.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from . import fabric_timeout_s, replay_enabled
from ..fleet.ring import HashRing
from ..resilience import (BreakerOpen, DeadlineExceeded, clamp_timeout,
                          get_breaker)
from ..serving.response_cache import CachedResponse, make_entry
from ..serving.singleflight import SingleFlight

_H = "X-Gsky-Fabric"

# fetch outcomes, mirrored into gsky_fabric_replay_total{outcome}
OUTCOMES = ("hit", "miss", "error", "deadline", "breaker_open",
            "owner_local", "disabled")


def _note(outcome: str) -> None:
    try:
        from ..obs import metrics as _m
        _m.FABRIC_REPLAY.labels(outcome=outcome).inc()
    except Exception:  # obs is best-effort, never on the serving path
        pass


def encode_entry(ent: CachedResponse) -> Tuple[Dict[str, str], bytes]:
    """Headers + body for serving ``ent`` to a peer."""
    age = max(0, int(ent.max_age - (ent.expires - time.monotonic())))
    headers = {
        "ETag": ent.etag,
        f"{_H}-Status": str(ent.status),
        f"{_H}-Age": str(age),
        f"{_H}-Max-Age": str(ent.max_age),
        f"{_H}-Ns": ent.namespace,
        f"{_H}-Layer": ent.layer,
        f"{_H}-Fp": ent.layer_fp,
    }
    if ent.headers:
        headers[f"{_H}-Keep"] = json.dumps(list(ent.headers))
    return headers, ent.body


def entry_from_response(status: int, headers: Dict[str, str],
                        body: bytes) -> Optional[CachedResponse]:
    """Validate + rebuild a peer response into a cacheable entry.

    Returns ``None`` for anything unusable: non-200, missing fabric
    headers, ETag/body digest mismatch, or no remaining TTL.
    """
    if status != 200 or not body:
        return None
    hdr = {k.lower(): v for k, v in headers.items()}
    if hdr.get(f"{_H}-NoStore".lower()):
        return None
    try:
        origin_status = int(hdr.get(f"{_H}-Status".lower(), "0"))
        age = int(hdr.get(f"{_H}-Age".lower(), "0"))
        max_age = int(hdr.get(f"{_H}-Max-Age".lower(), "0"))
    except (TypeError, ValueError):
        return None
    if origin_status != 200:
        return None
    remaining = max_age - max(0, age)
    if remaining <= 0:
        return None
    etag = hdr.get("etag", "")
    if etag != '"' + hashlib.sha256(body).hexdigest()[:32] + '"':
        return None          # bytes corrupted or truncated in transit
    keep: Tuple[Tuple[str, str], ...] = ()
    raw_keep = hdr.get(f"{_H}-Keep".lower())
    if raw_keep:
        try:
            keep = tuple((str(k), str(v))
                         for k, v in json.loads(raw_keep))
        except (ValueError, TypeError):
            keep = ()
    ent = make_entry(
        body=body,
        content_type=hdr.get("content-type", "application/octet-stream"),
        status=origin_status,
        namespace=hdr.get(f"{_H}-Ns".lower(), ""),
        layer=hdr.get(f"{_H}-Layer".lower(), ""),
        layer_fp=hdr.get(f"{_H}-Fp".lower(), ""),
        max_age=max_age, headers=keep)
    # expire at the origin deadline, not ours: Age must keep accruing
    ent.expires = time.monotonic() + remaining
    return ent


def _http_fetch(url: str, timeout: float
                ) -> Tuple[int, Dict[str, str], bytes]:
    """Default transport: one blocking stdlib GET (run in a thread)."""
    req = urllib.request.Request(url, method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers.items()), resp.read()
    except urllib.error.HTTPError as exc:
        return exc.code, dict(exc.headers.items() if exc.headers
                              else []), b""


class ReplayFabric:
    """Per-gateway handle on the replay ring.

    ``transport`` is injectable for tests: a callable
    ``(url, timeout) -> (status, headers, body)`` run off-loop.
    """

    def __init__(self, self_addr: str, peers: List[str],
                 timeout_s: Optional[float] = None,
                 transport: Optional[Callable] = None,
                 max_attempts: int = 2):
        self.self_addr = self_addr
        members = sorted({self_addr, *peers})
        self.ring = HashRing(members, vnodes=32)
        self._timeout_s = timeout_s
        self.transport = transport or _http_fetch
        self.flight = SingleFlight()
        self.max_attempts = max_attempts
        self._lock = threading.Lock()
        self.outcomes: Dict[str, int] = {}
        self._ewma_ms: Dict[str, float] = {}   # per-peer RPC latency

    # -- membership --------------------------------------------------

    def set_peers(self, peers: List[str]) -> None:
        """Reconfigure ring membership (bumps ``ring.generation`` when
        it actually changes, instantly re-homing every key)."""
        self.ring.set_nodes(sorted({self.self_addr, *peers}))

    def owner(self, key: str) -> Optional[str]:
        return self.ring.owner(key)

    def is_owner(self, key: str) -> bool:
        return self.owner(key) == self.self_addr

    def candidates(self, key: str) -> List[str]:
        """Ring preference walk for ``key``, minus self, bounded."""
        walk = self.ring.preference(key, self.max_attempts + 1)
        return [p for p in walk if p != self.self_addr][:self.max_attempts]

    # -- bookkeeping -------------------------------------------------

    def _count(self, outcome: str) -> None:
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        _note(outcome)

    def _latency(self, peer: str, ms: float) -> None:
        with self._lock:
            prev = self._ewma_ms.get(peer)
            self._ewma_ms[peer] = ms if prev is None \
                else 0.8 * prev + 0.2 * ms

    def stats(self) -> Dict:
        with self._lock:
            return {"self": self.self_addr,
                    "members": list(self.ring.nodes),
                    "generation": self.ring.generation,
                    "outcomes": dict(self.outcomes),
                    "peer_ewma_ms": {p: round(v, 3) for p, v
                                     in self._ewma_ms.items()}}

    # -- fetch path --------------------------------------------------

    async def fetch(self, key: str) -> Optional[CachedResponse]:
        """Best-effort peer replay for ``key``; never raises.

        Owners return ``None`` immediately (they *are* the authority —
        their render seeds the fleet).  Non-owners walk the ring
        preference, one bounded breaker-guarded probe per candidate.
        """
        if not replay_enabled():
            self._count("disabled")
            return None
        if self.is_owner(key):
            self._count("owner_local")
            return None
        peers = self.candidates(key)
        if not peers:
            self._count("miss")
            return None

        async def _fetch_all():
            for peer in peers:
                ent = await self._fetch_one(peer, key)
                if ent is not None:
                    return ent
            return None

        try:
            ent, _joined = await self.flight.do(f"fabric:{key}",
                                                _fetch_all)
        except DeadlineExceeded:
            self._count("deadline")
            return None
        except Exception:   # transport bugs must not surface as 5xx
            self._count("error")
            return None
        self._count("hit" if ent is not None else "miss")
        return ent

    async def _fetch_one(self, peer: str,
                         key: str) -> Optional[CachedResponse]:
        brk = get_breaker(f"fabric:{peer}")
        if not brk.allow():
            self._count("breaker_open")
            return None
        # no budget left: abort the whole candidate walk, not just
        # this peer — DeadlineExceeded propagates to fetch()
        timeout = clamp_timeout(self._timeout_s
                                if self._timeout_s is not None
                                else fabric_timeout_s())
        url = (peer.rstrip("/") + "/fabric/replay?key="
               + urllib.parse.quote(key, safe=""))
        t0 = time.monotonic()
        try:
            status, headers, body = await asyncio.to_thread(
                self.transport, url, timeout)
        except BreakerOpen:
            self._count("breaker_open")
            return None
        except Exception:
            brk.record_failure()
            self._count("error")
            return None
        self._latency(peer, (time.monotonic() - t0) * 1000.0)
        if status >= 500:
            brk.record_failure()
            self._count("error")
            return None
        brk.record_success()
        return entry_from_response(status, headers, body)


def default_fabric() -> Optional["ReplayFabric"]:
    """Build a fabric from env (``GSKY_FABRIC_SELF`` +
    ``GSKY_FABRIC_PEERS``); ``None`` when not configured."""
    from . import peer_addrs, self_addr
    me, peers = self_addr(), peer_addrs()
    if not me or not peers:
        return None
    return ReplayFabric(me, peers)
