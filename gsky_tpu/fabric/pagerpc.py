"""Worker tier: batched content-keyed page fetch between pools.

Pages are content-keyed ``(serial, pi, pj)`` (pipeline/pages.py), so a
page staged on any worker is byte-equivalent to the same page staged
anywhere else — which makes peer HBM a legitimate fill source.  A
worker rebuilding its pool (cold start, post-preemption
``rehydrate()``) asks ring-adjacent peers for the journal's hot set
hottest-first instead of re-decoding scenes from storage.

Wire format — one worker-RPC round trip (``operation="page_fetch"``
on the existing ``/gskyrpc.GDAL/Process`` method):

* request, in ``Task.path``::

      {"v": 1, "pages": [[serial, pi, pj], ...], "max_bytes": N}

* response: ``Result.raster`` holds the concatenated float32 page
  bytes; ``Result.info_json`` holds the manifest::

      {"v": 1, "page_shape": [PR, PC],
       "pages": [{"serial": s, "pi": i, "pj": j,
                  "off": byte_offset, "len": byte_len, "crc": crc32},
                 ...]}

  Pages the peer doesn't hold are simply absent.  Every page carries a
  stage-side CRC32; the receiver recomputes it before staging and
  drops mismatches — a truncated or corrupted page must never enter a
  pool under a content key it doesn't match.

Batches are capped by ``GSKY_FABRIC_PAGE_BATCH_MB`` per RPC so one
fetch can never message-size-bomb the channel; per-peer breakers
(``fabric-page:{addr}``) stop a dead peer from stalling recovery.
Everything degrades to the cold path: a failed fetch just leaves those
pages for the scene-cache / storage loop.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import fabric_timeout_s, page_peer_addrs
from ..fleet.ring import HashRing
from ..resilience import get_breaker

Key = Tuple[int, int, int]

_lock = threading.Lock()
_stats: Dict[str, float] = {"fills": 0, "served": 0, "rpc_errors": 0,
                            "integrity_drops": 0, "breaker_skips": 0}
_ewma_ms: Dict[str, float] = {}


def _count(name: str, n: int = 1) -> None:
    with _lock:
        _stats[name] = _stats.get(name, 0) + n


def _latency(peer: str, ms: float) -> None:
    with _lock:
        prev = _ewma_ms.get(peer)
        _ewma_ms[peer] = ms if prev is None else 0.8 * prev + 0.2 * ms


def stats() -> Dict:
    with _lock:
        return {**{k: int(v) for k, v in _stats.items()},
                "peer_ewma_ms": {p: round(v, 3)
                                 for p, v in _ewma_ms.items()}}


def batch_bytes() -> int:
    try:
        mb = float(os.environ.get("GSKY_FABRIC_PAGE_BATCH_MB", 8))
    except (TypeError, ValueError):
        mb = 8.0
    return max(1 << 20, int(mb * (1 << 20)))


# -- wire codec -------------------------------------------------------

def encode_request(keys: Sequence[Key],
                   max_bytes: Optional[int] = None) -> str:
    return json.dumps({
        "v": 1,
        "pages": [[int(s), int(pi), int(pj)] for s, pi, pj in keys],
        "max_bytes": int(max_bytes if max_bytes is not None
                         else batch_bytes())})


def serve_page_fetch(pool, doc: Dict) -> Tuple[Dict, bytes]:
    """Serving half: read requested resident pages back to host.

    Returns ``(manifest, blob)``; unknown pages are omitted, the byte
    budget in the request is honoured request-order (the requester
    sends hottest-first, so truncation drops the coldest tail)."""
    budget = int(doc.get("max_bytes") or batch_bytes())
    chunks: List[bytes] = []
    entries: List[Dict] = []
    off = 0
    for item in doc.get("pages") or []:
        try:
            serial, pi, pj = (int(item[0]), int(item[1]), int(item[2]))
        except (TypeError, ValueError, IndexError):
            continue
        page = pool.read_page(serial, pi, pj)
        if page is None:
            continue
        raw = np.ascontiguousarray(page, np.float32).tobytes()
        if off + len(raw) > budget and entries:
            break
        entries.append({"serial": serial, "pi": pi, "pj": pj,
                        "off": off, "len": len(raw),
                        "crc": zlib.crc32(raw)})
        chunks.append(raw)
        off += len(raw)
    _count("served", len(entries))
    manifest = {"v": 1,
                "page_shape": [pool.page_rows, pool.page_cols],
                "pages": entries}
    return manifest, b"".join(chunks)


def decode_result(info_json: str, blob: bytes
                  ) -> Dict[Key, np.ndarray]:
    """Client half: manifest + blob -> {key: (PR, PC) float32 page}.

    CRC failures and malformed extents are dropped (and counted), not
    raised — the content-key contract says a page either matches its
    key exactly or does not exist."""
    try:
        manifest = json.loads(info_json or "{}")
    except ValueError:
        return {}
    try:
        pr, pc = (int(manifest["page_shape"][0]),
                  int(manifest["page_shape"][1]))
    except (KeyError, TypeError, ValueError, IndexError):
        return {}
    want = pr * pc * 4
    out: Dict[Key, np.ndarray] = {}
    for ent in manifest.get("pages") or []:
        try:
            key = (int(ent["serial"]), int(ent["pi"]), int(ent["pj"]))
            off, ln, crc = int(ent["off"]), int(ent["len"]), int(ent["crc"])
        except (KeyError, TypeError, ValueError):
            continue
        raw = blob[off:off + ln]
        if ln != want or len(raw) != ln or zlib.crc32(raw) != crc:
            _count("integrity_drops")
            continue
        out[key] = np.frombuffer(raw, np.float32).reshape(pr, pc)
    return out


# -- transport --------------------------------------------------------

def _grpc_fetch(peer: str, keys: Sequence[Key], max_bytes: int,
                timeout: float) -> Dict[Key, np.ndarray]:
    """One page-fetch RPC against one peer worker; raises on transport
    or peer error (the caller's breaker records it)."""
    import grpc

    from ..worker import gskyrpc_pb2 as pb
    from ..worker.server import METHOD
    opts = [("grpc.max_receive_message_length",
             max_bytes + (1 << 20)),
            ("grpc.max_send_message_length", 4 << 20)]
    ch = grpc.insecure_channel(peer, options=opts)
    try:
        call = ch.unary_unary(
            METHOD, request_serializer=pb.Task.SerializeToString,
            response_deserializer=pb.Result.FromString)
        task = pb.Task(operation="page_fetch",
                       path=encode_request(keys, max_bytes))
        res = call(task, timeout=timeout)
        if res.error:
            raise RuntimeError(res.error)
        return decode_result(res.info_json, res.raster)
    finally:
        ch.close()


def fetch_pages(peer: str, keys: Sequence[Key],
                max_bytes: Optional[int] = None,
                timeout: Optional[float] = None,
                fetch: Optional[Callable] = None
                ) -> Dict[Key, np.ndarray]:
    """Breaker-guarded fetch of ``keys`` from ``peer``; empty dict on
    any failure (never raises)."""
    brk = get_breaker(f"fabric-page:{peer}")
    if not brk.allow():
        _count("breaker_skips")
        return {}
    mb = int(max_bytes if max_bytes is not None else batch_bytes())
    t0 = time.monotonic()
    try:
        got = (fetch or _grpc_fetch)(
            peer, keys, mb,
            timeout if timeout is not None else fabric_timeout_s())
    except Exception:   # any peer failure degrades to the cold path
        brk.record_failure()
        _count("rpc_errors")
        return {}
    brk.record_success()
    _latency(peer, (time.monotonic() - t0) * 1000.0)
    return got


# -- pool fill --------------------------------------------------------

def _batches(keys: List[Key], page_bytes: int,
             cap: int) -> List[List[Key]]:
    per = max(1, cap // max(1, page_bytes))
    return [keys[i:i + per] for i in range(0, len(keys), per)]


def fill_from_peers(pool, entries: Sequence[Key],
                    peers: Optional[List[str]] = None,
                    fetch: Optional[Callable] = None,
                    prefer: Optional[str] = None) -> int:
    """Fill ``pool`` from ring-adjacent peers, hottest-first.

    ``entries`` is the journal's hottest-first page list; each key is
    asked of its ring-preferred peer first (so a stable fleet converges
    on who serves what), then of the next candidate for whatever the
    first round missed.  ``prefer`` names one peer to ask for *every*
    key before the ring walk — the warm-handoff path sets it to the
    preempting node, whose HBM provably holds the shipped hot set for
    as long as its grace window lasts.  Returns pages actually staged."""
    peers = list(peers if peers is not None else page_peer_addrs())
    if not peers and prefer:
        peers = [prefer]
    if not peers or not entries:
        return 0
    ring = HashRing(peers, vnodes=32)
    page_bytes = pool.page_rows * pool.page_cols * 4
    cap = batch_bytes()
    want: List[Key] = [(int(s), int(pi), int(pj))
                       for s, pi, pj in entries]
    filled = 0
    if prefer:
        missing: List[Key] = []
        got_any: Dict[Key, np.ndarray] = {}
        for batch in _batches(want, page_bytes, cap):
            got_any.update(fetch_pages(prefer, batch, cap, fetch=fetch))
        for key in want:
            page = got_any.get(key)
            if page is not None and pool.stage_page(*key, page):
                filled += 1
            else:
                missing.append(key)
        want = missing
        if not want:
            _count("fills", filled)
            return filled
    for rnd in (0, 1):          # preference walk: owner, then next
        missing: List[Key] = []
        by_peer: Dict[str, List[Key]] = {}
        for key in want:
            pref = ring.preference(json.dumps(key), rnd + 1)
            if len(pref) <= rnd:
                continue
            by_peer.setdefault(pref[rnd], []).append(key)
        for peer, keys in by_peer.items():
            got_any: Dict[Key, np.ndarray] = {}
            for batch in _batches(keys, page_bytes, cap):
                got_any.update(fetch_pages(peer, batch, cap,
                                           fetch=fetch))
            for key in keys:
                page = got_any.get(key)
                if page is not None and pool.stage_page(*key, page):
                    filled += 1
                else:
                    missing.append(key)
        want = missing
        if not want:
            break
    _count("fills", filled)
    return filled
