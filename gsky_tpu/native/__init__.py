"""Native (C++) decode kernels, loaded via ctypes.

Build with ``make -C gsky_tpu/native``; every consumer falls back to the
pure-Python implementations when the shared library is absent.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libgskycodec.so")

import numpy as np

_lib: Optional[ctypes.CDLL] = None
if os.path.exists(_LIB_PATH):
    _lib = ctypes.CDLL(_LIB_PATH)
    _lib.lzw_decode.restype = ctypes.c_long
    _lib.lzw_decode.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                ctypes.c_void_p, ctypes.c_long]
    _lib.packbits_decode.restype = ctypes.c_long
    _lib.packbits_decode.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                     ctypes.c_void_p, ctypes.c_long]
    for name in ("unpredict_h8", "unpredict_h16", "unpredict_h32"):
        fn = getattr(_lib, name)
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p, ctypes.c_long, ctypes.c_long,
                       ctypes.c_long]
    _lib.unpredict_fp.restype = None
    _lib.unpredict_fp.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                  ctypes.c_long, ctypes.c_long,
                                  ctypes.c_long, ctypes.c_long]


class codec:
    """Namespace mirroring the pure-Python codec helpers."""

    @staticmethod
    def lzw_decode(data: bytes, expected: int) -> bytes:
        buf = ctypes.create_string_buffer(expected)
        n = _lib.lzw_decode(data, len(data), buf, expected)
        if n < 0:
            raise ValueError("corrupt LZW stream")
        return buf.raw[:n]

    @staticmethod
    def packbits_decode(data: bytes, expected: int) -> bytes:
        buf = ctypes.create_string_buffer(expected)
        n = _lib.packbits_decode(data, len(data), buf, expected)
        return buf.raw[:n]

    @staticmethod
    def unpredict_h(arr: "np.ndarray") -> bool:
        """In-place horizontal predictor undo on a C-contiguous
        (rows, cols, samples) array of 1/2/4-byte integers."""
        fn = {1: _lib.unpredict_h8, 2: _lib.unpredict_h16,
              4: _lib.unpredict_h32}.get(arr.dtype.itemsize)
        if fn is None or not arr.flags.c_contiguous:
            return False
        rows, cols, samples = arr.shape
        fn(arr.ctypes.data, rows, cols, samples)
        return True

    @staticmethod
    def unpredict_fp(data: bytes, rows: int, cols: int, samples: int,
                     itemsize: int) -> bytes:
        buf = ctypes.create_string_buffer(len(data))
        _lib.unpredict_fp(data, buf, rows, cols, samples, itemsize)
        return buf.raw


if _lib is None:
    codec = None  # type: ignore  # geotiff.py falls back to pure Python
