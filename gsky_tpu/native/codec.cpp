// Native decode kernels for the GeoTIFF codec hot path.
//
// The reference keeps its IO layer native (the forked GSKY_netCDF GDAL
// driver, libs/gdal/frmts/gsky_netcdf/) because decode throughput gates
// the warp workers.  Here the same role is played by this small library:
// TIFF-variant LZW, PackBits, and the horizontal/floating-point
// predictors, callable from Python via ctypes (deflate stays on zlib,
// which is already native).
//
// Build: make -C gsky_tpu/native

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// TIFF LZW: MSB-first codes, Clear=256, EOI=257, early code-width change.
// Returns bytes written, or -1 on corrupt input.
long lzw_decode(const uint8_t* src, long src_len, uint8_t* dst, long dst_len) {
    // table entries reference previous output: store (prev, first, len, tail)
    struct Entry { int32_t prev; uint8_t first; uint8_t tail; int32_t len; };
    std::vector<Entry> table(4096);
    for (int i = 0; i < 256; i++) {
        table[i] = {-1, (uint8_t)i, (uint8_t)i, 1};
    }
    int next_code = 258;
    int width = 9;
    long out = 0;
    long bitpos = 0;
    const long nbits = src_len * 8;
    int prev_code = -1;

    auto emit = [&](int code) -> bool {
        // write the expansion of `code` at dst+out (backwards fill);
        // per-byte `w < dst_len` guard below handles truncation
        long end = out + table[code].len;
        long w = end - 1;
        int c = code;
        while (c >= 0 && w >= out) {
            if (w < dst_len) dst[w] = table[c].tail;
            c = table[c].prev;
            w--;
        }
        out = end > dst_len ? dst_len : end;
        return true;
    };

    while (bitpos + width <= nbits && out < dst_len) {
        long byte0 = bitpos >> 3;
        uint32_t chunk = ((uint32_t)src[byte0] << 16);
        if (byte0 + 1 < src_len) chunk |= ((uint32_t)src[byte0 + 1] << 8);
        if (byte0 + 2 < src_len) chunk |= (uint32_t)src[byte0 + 2];
        int shift = 24 - (int)(bitpos & 7) - width;
        int code = (int)((chunk >> shift) & ((1u << width) - 1));
        bitpos += width;

        if (code == 256) {  // clear
            next_code = 258;
            width = 9;
            prev_code = -1;
            continue;
        }
        if (code == 257) break;  // EOI

        if (prev_code < 0) {
            if (code >= 256) return -1;
            emit(code);
            prev_code = code;
        } else {
            if (code < next_code) {
                // new entry: prev + first(code)
                if (next_code < 4096) {
                    table[next_code] = {prev_code, table[prev_code].first,
                                        table[code].first,
                                        table[prev_code].len + 1};
                    next_code++;
                }
                emit(code);
            } else if (code == next_code) {
                if (next_code >= 4096) return -1;
                table[next_code] = {prev_code, table[prev_code].first,
                                    table[prev_code].first,
                                    table[prev_code].len + 1};
                next_code++;
                emit(code);
            } else {
                return -1;
            }
            prev_code = code;
        }
        // early change
        if (next_code + 1 >= (1 << width) && width < 12) width++;
    }
    return out;
}

long packbits_decode(const uint8_t* src, long src_len, uint8_t* dst,
                     long dst_len) {
    long i = 0, out = 0;
    while (i < src_len && out < dst_len) {
        int8_t n = (int8_t)src[i++];
        if (n >= 0) {
            long cnt = n + 1;
            if (i + cnt > src_len) cnt = src_len - i;
            if (out + cnt > dst_len) cnt = dst_len - out;
            memcpy(dst + out, src + i, cnt);
            i += n + 1;
            out += cnt;
        } else if (n != -128) {
            long cnt = 1 - n;
            if (out + cnt > dst_len) cnt = dst_len - out;
            memset(dst + out, src[i], cnt);
            i++;
            out += cnt;
        }
    }
    return out;
}

// Horizontal predictor (TIFF predictor 2), in place.
// stride = cols*samples elements per row; sample-interleaved deltas.
void unpredict_h8(uint8_t* data, long rows, long cols, long samples) {
    long stride = cols * samples;
    for (long r = 0; r < rows; r++) {
        uint8_t* p = data + r * stride;
        for (long i = samples; i < stride; i++) p[i] += p[i - samples];
    }
}

void unpredict_h16(uint16_t* data, long rows, long cols, long samples) {
    long stride = cols * samples;
    for (long r = 0; r < rows; r++) {
        uint16_t* p = data + r * stride;
        for (long i = samples; i < stride; i++) p[i] += p[i - samples];
    }
}

void unpredict_h32(uint32_t* data, long rows, long cols, long samples) {
    long stride = cols * samples;
    for (long r = 0; r < rows; r++) {
        uint32_t* p = data + r * stride;
        for (long i = samples; i < stride; i++) p[i] += p[i - samples];
    }
}

// Floating-point predictor (TIFF predictor 3): byte rows are
// significance-plane separated (big-endian order) and delta-coded.
// in: raw row-major buffer rows x (cols*samples*itemsize) bytes
// out: native little-endian sample stream.
void unpredict_fp(const uint8_t* in, uint8_t* out, long rows, long cols,
                  long samples, long itemsize) {
    long rowlen = cols * samples * itemsize;
    long n = cols * samples;
    std::vector<uint8_t> acc(rowlen);
    for (long r = 0; r < rows; r++) {
        const uint8_t* src = in + r * rowlen;
        uint8_t* dstrow = out + r * rowlen;
        uint8_t run = 0;
        for (long i = 0; i < rowlen; i++) {
            run = (uint8_t)(run + src[i]);
            acc[i] = run;
        }
        // plane p holds byte p (big-endian); emit little-endian
        for (long e = 0; e < n; e++) {
            for (long b = 0; b < itemsize; b++) {
                dstrow[e * itemsize + b] = acc[(itemsize - 1 - b) * n + e];
            }
        }
    }
}

}  // extern "C"
