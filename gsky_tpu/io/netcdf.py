"""NetCDF reading: NetCDF-4 (HDF5) via h5py, NetCDF-3 classic via a
built-in parser.  CF-convention georeferencing.

This is the TPU-era stand-in for the reference's forked GSKY_netCDF GDAL
driver (`libs/gdal/frmts/gsky_netcdf/netcdfdataset.cpp`).  The fork exists
to make single-band opens of huge time-series files cheap (`band_query`
open option, `netcdfdataset.cpp:6994`) and to skip metadata scans
(`md_query`).  Both fall out naturally here: h5py/our parser open lazily
and `read_slice` reads exactly one (time, y, x) hyperslab.

CF support: coordinate variables -> GeoTransform (regular grids),
`grid_mapping` attributes or embedded `spatial_ref`/`crs_wkt` -> CRS,
`time` units parsing ("<unit> since <epoch>"), `_FillValue`/
`missing_value` -> nodata.
"""

from __future__ import annotations

import datetime as dt
import os
import re
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..geo.crs import CRS, EPSG4326, Ellipsoid, parse_crs
from ..geo.transform import GeoTransform

try:
    import h5py
except Exception:  # pragma: no cover
    h5py = None


# ---------------------------------------------------------------------------
# CF time
# ---------------------------------------------------------------------------

_UNIT_SECONDS = {
    "second": 1.0, "seconds": 1.0, "sec": 1.0, "secs": 1.0, "s": 1.0,
    "minute": 60.0, "minutes": 60.0, "min": 60.0, "mins": 60.0,
    "hour": 3600.0, "hours": 3600.0, "h": 3600.0, "hr": 3600.0, "hrs": 3600.0,
    "day": 86400.0, "days": 86400.0, "d": 86400.0,
}

_EPOCH = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)


def parse_cf_time_units(units: str) -> Tuple[float, float]:
    """'days since 2000-01-01 00:00:0.0' -> (seconds_per_unit,
    epoch_unix_seconds)."""
    m = re.match(
        r"\s*(\w+)\s+since\s+(\d{1,4})-(\d{1,2})-(\d{1,2})"
        r"(?:[T ](\d{1,2}):(\d{1,2}):(\d{1,2}(?:\.\d*)?))?",
        units)
    if not m:
        raise ValueError(f"cannot parse CF time units {units!r}")
    mult = _UNIT_SECONDS.get(m.group(1).lower())
    if mult is None:
        raise ValueError(f"unsupported CF time unit {m.group(1)!r}")
    sec = float(m.group(7) or 0)
    base = dt.datetime(int(m.group(2)), int(m.group(3)), int(m.group(4)),
                       int(m.group(5) or 0), int(m.group(6) or 0),
                       int(sec), int((sec % 1) * 1e6),
                       tzinfo=dt.timezone.utc)
    return mult, (base - _EPOCH).total_seconds()


def cf_times_to_unix(values: np.ndarray, units: str) -> np.ndarray:
    mult, epoch = parse_cf_time_units(units)
    return np.asarray(values, np.float64) * mult + epoch


# ---------------------------------------------------------------------------
# CF grid mapping -> CRS
# ---------------------------------------------------------------------------

def crs_from_cf(attrs: Dict[str, object]) -> CRS:
    """Build a CRS from a CF grid-mapping variable's attributes (the logic
    GSKY's fork implements in `netcdfdataset.cpp` SetProjectionFromVar,
    plus the GDAL `spatial_ref` shortcut)."""
    for key in ("spatial_ref", "crs_wkt"):
        wkt = attrs.get(key)
        if isinstance(wkt, bytes):
            wkt = wkt.decode("latin-1")
        if isinstance(wkt, str) and wkt.strip():
            try:
                return parse_crs(wkt)
            except ValueError:
                pass
    name = attrs.get("grid_mapping_name", "")
    if isinstance(name, bytes):
        name = name.decode("latin-1")

    def f(key, default=0.0):
        v = attrs.get(key, default)
        if isinstance(v, (np.ndarray, list, tuple)):
            v = np.asarray(v).reshape(-1)[0]
        return float(v)

    a = f("semi_major_axis", 6378137.0)
    b = f("semi_minor_axis", 0.0)
    inv_f = f("inverse_flattening", 0.0)
    if inv_f:
        ellps = Ellipsoid(a, 1.0 / inv_f)
    elif b:
        ellps = Ellipsoid(a, (a - b) / a)
    else:
        ellps = Ellipsoid(a, 1.0 / 298.257223563)

    if name == "latitude_longitude" or not name:
        return EPSG4326
    if name == "transverse_mercator":
        return CRS("tmerc", ellps,
                   lon0=f("longitude_of_central_meridian"),
                   lat0=f("latitude_of_projection_origin"),
                   k0=f("scale_factor_at_central_meridian", 1.0),
                   x0=f("false_easting"), y0=f("false_northing"))
    if name == "albers_conical_equal_area":
        sp = attrs.get("standard_parallel", (0.0, 0.0))
        sp = np.asarray(sp).reshape(-1)
        return CRS("aea", ellps,
                   lon0=f("longitude_of_central_meridian"),
                   lat0=f("latitude_of_projection_origin"),
                   lat1=float(sp[0]), lat2=float(sp[-1]),
                   x0=f("false_easting"), y0=f("false_northing"))
    if name == "lambert_conformal_conic":
        sp = np.asarray(attrs.get("standard_parallel", (0.0,))).reshape(-1)
        return CRS("lcc", ellps,
                   lon0=f("longitude_of_central_meridian"),
                   lat0=f("latitude_of_projection_origin"),
                   lat1=float(sp[0]), lat2=float(sp[-1]),
                   x0=f("false_easting"), y0=f("false_northing"))
    if name == "sinusoidal":
        return CRS("sinu", Ellipsoid(a, 0.0),
                   lon0=f("longitude_of_projection_origin"),
                   x0=f("false_easting"), y0=f("false_northing"))
    if name == "geostationary":
        return CRS("geos", ellps,
                   lon0=f("longitude_of_projection_origin"),
                   h=f("perspective_point_height"),
                   x0=f("false_easting"), y0=f("false_northing"))
    if name == "mercator":
        return CRS("merc", ellps,
                   lon0=f("longitude_of_projection_origin"),
                   k0=f("scale_factor_at_projection_origin", 1.0),
                   x0=f("false_easting"), y0=f("false_northing"))
    raise ValueError(f"unsupported grid_mapping_name {name!r}")


# ---------------------------------------------------------------------------
# Variable model
# ---------------------------------------------------------------------------

@dataclass
class NCVar:
    name: str
    dims: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: np.dtype
    attrs: Dict[str, object]
    _reader: object = field(repr=False, default=None)

    def __getitem__(self, key):
        return self._reader(key)

    @property
    def nodata(self) -> Optional[float]:
        unsigned = str(self.attrs.get("_Unsigned", "")).lower() in ("true", "1")
        for k in ("_FillValue", "missing_value", "nodata"):
            if k in self.attrs:
                v = self.attrs[k]
                if isinstance(v, (np.ndarray, list, tuple)):
                    v = np.asarray(v).reshape(-1)[0]
                if unsigned and isinstance(v, np.signedinteger):
                    v = v.astype(v.dtype).view(
                        np.dtype(f"u{v.dtype.itemsize}"))
                try:
                    return float(v)
                except (TypeError, ValueError):
                    return None
        return None


class NetCDF:
    """Uniform facade over NetCDF-4 (h5py) and NetCDF-3 (built-in)."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as fp:
            magic = fp.read(8)
        if magic[:3] == b"CDF":
            self._nc3 = _NC3File(path)
            self.variables = self._nc3.variables
            self.attrs = self._nc3.attrs
            self._h5 = None
        elif magic[:8] == b"\x89HDF\r\n\x1a\n" and h5py is not None:
            self._nc3 = None
            self._h5 = h5py.File(path, "r")
            self.variables = {}
            self._h5_datasets: Dict[str, object] = {}
            self.attrs = {k: self._h5.attrs[k] for k in self._h5.attrs}

            def visit(name, obj):
                if isinstance(obj, h5py.Dataset):
                    attrs = {k: obj.attrs[k] for k in obj.attrs}
                    dims = tuple(
                        (d.label or (d[0].name.split("/")[-1] if len(d) else ""))
                        for d in obj.dims) if obj.dims else ()
                    if not any(dims):
                        dims = tuple(f"dim{i}" for i in range(obj.ndim))
                    self.variables[name.split("/")[-1]] = NCVar(
                        name.split("/")[-1], dims, obj.shape, obj.dtype,
                        attrs, _reader=obj.__getitem__)
                    self._h5_datasets[name.split("/")[-1]] = obj
            self._h5.visititems(visit)
        else:
            raise ValueError(f"{path}: not a NetCDF file")

    def close(self):
        if self._h5 is not None:
            self._h5.close()
        if self._nc3 is not None:
            self._nc3._fp.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # -- georeferencing ------------------------------------------------------

    def raster_vars(self) -> List[NCVar]:
        """Data variables with >= 2 dims whose trailing dims look spatial."""
        out = []
        coord_names = {"x", "y", "lon", "lat", "longitude", "latitude",
                       "time", "crs", "spatial_ref"}
        for v in self.variables.values():
            if v.name.lower() in coord_names or v.name.startswith("lambert"):
                continue
            if len(v.shape) >= 2 and v.shape[-1] > 1 and v.shape[-2] > 1 \
                    and v.dtype.kind in "iuf":
                out.append(v)
        return out

    def geoloc_vars(self) -> Optional[Tuple[NCVar, NCVar]]:
        """The 2-D (lon, lat) geolocation-array pair of a curvilinear
        product, or None for regular grids — the detection feeding the
        crawler's geo_loc record (the reference drives this from
        config rulesets, `crawl/extractor/info.go:502`; here CF 2-D
        coordinate variables are recognised directly)."""
        def find(names, std_names):
            for v in self.variables.values():
                sn = v.attrs.get("standard_name", b"")
                if isinstance(sn, bytes):
                    sn = sn.decode("latin-1")
                if (v.name.lower() in names or sn in std_names) \
                        and len(v.shape) == 2:
                    return v
            return None

        gx = find(("lon", "longitude", "lons"), ("longitude",))
        gy = find(("lat", "latitude", "lats"), ("latitude",))
        if gx is None or gy is None or gx.shape != gy.shape:
            return None
        return gx, gy

    def _axis_var(self, names: Sequence[str], std_names: Sequence[str]) -> Optional[NCVar]:
        for v in self.variables.values():
            sn = v.attrs.get("standard_name", b"")
            if isinstance(sn, bytes):
                sn = sn.decode("latin-1")
            if v.name.lower() in names or sn in std_names:
                if len(v.shape) == 1:
                    return v
        return None

    def geotransform(self, var: Optional[NCVar] = None) -> GeoTransform:
        xv = self._axis_var(("x", "lon", "longitude"),
                            ("projection_x_coordinate", "longitude"))
        yv = self._axis_var(("y", "lat", "latitude"),
                            ("projection_y_coordinate", "latitude"))
        if xv is None or yv is None:
            raise ValueError("no coordinate variables found")
        x = np.asarray(xv[:], np.float64)
        y = np.asarray(yv[:], np.float64)
        dx = (x[-1] - x[0]) / (len(x) - 1)
        dy = (y[-1] - y[0]) / (len(y) - 1)
        # coords are cell centres
        return GeoTransform(x[0] - dx / 2, dx, 0.0, y[0] - dy / 2, 0.0, dy)

    def crs(self, var: Optional[NCVar] = None) -> CRS:
        gm_name = None
        if var is not None:
            gm = var.attrs.get("grid_mapping")
            if isinstance(gm, bytes):
                gm = gm.decode("latin-1")
            gm_name = gm
        candidates = []
        if gm_name and gm_name in self.variables:
            candidates.append(self.variables[gm_name])
        for v in self.variables.values():
            if "grid_mapping_name" in v.attrs or "spatial_ref" in v.attrs:
                candidates.append(v)
        for c in candidates:
            try:
                return crs_from_cf(c.attrs)
            except ValueError:
                continue
        # lon/lat coordinate names imply geographic
        return EPSG4326

    def timestamps(self) -> Optional[np.ndarray]:
        tv = self._axis_var(("time", "t"), ("time",))
        if tv is None:
            return None
        units = tv.attrs.get("units", b"")
        if isinstance(units, bytes):
            units = units.decode("latin-1")
        if not units:
            return np.asarray(tv[:], np.float64)
        return cf_times_to_unix(np.asarray(tv[:]), units)

    def read_slice(self, var_name: str, time_index: Optional[int] = None,
                   window: Optional[Tuple[int, int, int, int]] = None,
                   step: int = 1) -> np.ndarray:
        """The band_query analogue: one (y, x) hyperslab of one timestep.
        window = (col0, row0, w, h), in FULL-resolution pixels.  With
        ``step`` > 1, every step-th pixel is returned — the NetCDF
        analogue of GeoTIFF overview reads for zoomed-out requests (no
        precomputed pyramids in the format, so this decimates on read)."""
        v = self.variables[var_name]
        if window is not None:
            c0, r0, w, h = window
            ys = slice(r0, r0 + h, step if step > 1 else None)
            xs = slice(c0, c0 + w, step if step > 1 else None)
        elif step > 1:
            ys = slice(None, None, step)
            xs = slice(None, None, step)
        else:
            ys = slice(None)
            xs = slice(None)
        if len(v.shape) == 2:
            return np.asarray(v[(ys, xs)])
        if len(v.shape) == 3:
            t = 0 if time_index is None else time_index
            return np.asarray(v[(t, ys, xs)])
        if len(v.shape) == 4:
            t = 0 if time_index is None else time_index
            return np.asarray(v[(t, 0, ys, xs)])
        raise ValueError(f"unsupported rank {len(v.shape)} for {var_name}")

    # -- ranged ingest -------------------------------------------------------

    def chunk_map(self, var_name: str) -> Dict[str, object]:
        """The chunk index of one variable, for ranged readers
        (docs/INGEST.md).  NetCDF-3 layouts are exact byte arithmetic
        (begin/record stride/row bytes — every hyperslab maps to row
        ranges); NetCDF-4 reports the HDF5 chunk shape and count (h5py
        owns the B-tree, so ranged NC4 reads stay with h5py)."""
        v = self.variables[var_name]
        if self._nc3 is not None:
            rd = v._reader
            if not isinstance(rd, _NC3Reader):
                raise ValueError(f"{var_name}: no NC3 layout")
            itemsize = rd.dt.itemsize
            return {"kind": "nc3", "begin": rd.begin,
                    "record": rd.is_record, "rec_stride": rd.rec_stride,
                    "itemsize": itemsize, "shape": tuple(v.shape),
                    "row_bytes": int(v.shape[-1]) * itemsize}
        ds = self._h5_datasets.get(var_name)
        if ds is None:
            raise KeyError(var_name)
        out: Dict[str, object] = {
            "kind": "hdf5", "shape": tuple(v.shape),
            "chunks": tuple(ds.chunks) if ds.chunks else None}
        try:
            out["nchunks"] = int(ds.id.get_num_chunks())
        except Exception:
            out["nchunks"] = None
        return out

    def read_slice_source(self, var_name: str, source,
                          time_index: Optional[int] = None,
                          window: Optional[Tuple[int, int, int, int]] = None,
                          step: int = 1) -> np.ndarray:
        """`read_slice` served by coalesced byte-range reads through a
        pluggable `ingest.source.ByteSource` — NetCDF-3 only (the flat
        layout makes every hyperslab a set of row ranges; NC4/HDF5
        chunk decode stays with h5py).  Byte-identical to `read_slice`
        by construction: same rows, same dtype normalisation, same
        ``_Unsigned`` handling."""
        if self._nc3 is None:
            raise ValueError("ranged hyperslabs require NetCDF-3")
        v = self.variables[var_name]
        rd = v._reader
        rank = len(v.shape)
        if rank not in (2, 3, 4):
            raise ValueError(f"unsupported rank {rank} for {var_name}")
        H, W = v.shape[-2], v.shape[-1]
        c0, r0, w, h = window if window is not None else (0, 0, W, H)
        if c0 < 0 or r0 < 0 or c0 + w > W or r0 + h > H:
            raise ValueError(
                f"window {(c0, r0, w, h)} outside raster {W}x{H}")
        itemsize = rd.dt.itemsize
        if rank == 2:
            base = rd.begin
        else:
            t = 0 if time_index is None else int(time_index)
            if not 0 <= t < v.shape[0]:
                raise IndexError(
                    f"record index {t} out of range for {var_name}")
            if rd.is_record:
                base = rd.begin + t * rd.rec_stride
            else:
                per0 = int(np.prod(v.shape[1:], dtype=np.int64))
                base = rd.begin + t * per0 * itemsize
            # rank 4 reads plane z=0 (matching read_slice), which is
            # the first H*W block of the record — no extra offset
        st = step if step > 1 else 1
        rows = range(r0, r0 + h, st)
        ranges = [(base + (r * W + c0) * itemsize, w * itemsize)
                  for r in rows]
        from ..ingest.source import fetch_ranges
        raws = fetch_ranges(source, ranges)
        arr = np.stack([np.frombuffer(raw, rd.dt)[::st] for raw in raws]) \
            if raws else np.zeros((0, 0), rd.dt)
        out = np.ascontiguousarray(arr).astype(rd.dt.newbyteorder("="))
        if str(v.attrs.get("_Unsigned", "")).lower() in ("true", "1") \
                and out.dtype.kind == "i":
            out = out.view(np.dtype(f"u{out.dtype.itemsize}"))
        return out


# ---------------------------------------------------------------------------
# NetCDF-3 classic parser
# ---------------------------------------------------------------------------

_NC3_DTYPES = {1: np.dtype(">i1"), 2: np.dtype("S1"), 3: np.dtype(">i2"),
               4: np.dtype(">i4"), 5: np.dtype(">f4"), 6: np.dtype(">f8")}


class _NC3File:
    """Streaming reader: only the header is parsed into memory; data reads
    seek + read the exact byte ranges (the band_query-style cheap-open
    property the GSKY_netCDF fork exists for)."""

    def __init__(self, path: str):
        import threading
        self.path = path
        self._fp = open(path, "rb")
        self._fp_lock = threading.Lock()
        self._size = os.fstat(self._fp.fileno()).st_size
        b = self._fp.read(4)
        if b[:3] != b"CDF" or b[3] not in (1, 2):
            raise ValueError("not a NetCDF classic file")
        self._64bit = b[3] == 2
        self.numrecs = self._u32()
        self.dims: List[Tuple[str, int]] = []
        self.attrs: Dict[str, object] = {}
        self.variables: Dict[str, NCVar] = {}
        self._parse_dims()
        self.attrs = self._parse_atts()
        self._parse_vars()

    def read_at(self, pos: int, n: int) -> bytes:
        # bound by the actual file: a corrupt header can declare
        # petabyte dims, and fp.read(n) PRE-ALLOCATES n bytes in C —
        # an uninterruptible multi-GB stall before any short read
        if pos < 0 or n < 0 or pos + n > self._size:
            raise ValueError(
                f"corrupt NetCDF: read [{pos}, {pos + n}) beyond "
                f"file size {self._size}")
        with self._fp_lock:  # shared handles are read from worker threads
            self._fp.seek(pos)
            return self._fp.read(n)

    # -- primitive header readers --

    def _u32(self) -> int:
        return struct.unpack(">I", self._fp.read(4))[0]

    def _u64(self) -> int:
        return struct.unpack(">Q", self._fp.read(8))[0]

    def _offset(self) -> int:
        return self._u64() if self._64bit else self._u32()

    def _header_read(self, n: int) -> bytes:
        """Header-controlled reads go through the same file-size bound
        as data reads: a corrupt length field must not make fp.read
        pre-allocate gigabytes (uninterruptible in C)."""
        if n < 0 or n > self._size:
            raise ValueError(
                f"corrupt NetCDF: header field declares {n} bytes "
                f"(file is {self._size})")
        return self._fp.read(n)

    def _name(self) -> str:
        n = self._u32()
        s = self._header_read(n).decode("utf-8")
        self._fp.read((4 - n % 4) % 4)
        return s

    def _parse_dims(self):
        tag = self._u32()
        n = self._u32()
        if tag == 0 and n == 0:
            return
        if tag != 0x0A:
            raise ValueError("bad NC_DIMENSION tag")
        for _ in range(n):
            name = self._name()
            size = self._u32()
            self.dims.append((name, size))

    def _parse_atts(self) -> Dict[str, object]:
        tag = self._u32()
        n = self._u32()
        out: Dict[str, object] = {}
        if tag == 0 and n == 0:
            return out
        if tag != 0x0C:
            raise ValueError("bad NC_ATTRIBUTE tag")
        for _ in range(n):
            name = self._name()
            typ = self._u32()
            cnt = self._u32()
            dt = _NC3_DTYPES[typ]
            nb = dt.itemsize * cnt
            raw = self._header_read(nb)
            self._fp.read((4 - nb % 4) % 4)
            if typ == 2:
                out[name] = raw.decode("latin-1")
            else:
                arr = np.frombuffer(raw, dt)
                out[name] = arr[0] if cnt == 1 else arr
        return out

    def _parse_vars(self):
        tag = self._u32()
        n = self._u32()
        if tag == 0 and n == 0:
            return
        if tag != 0x0B:
            raise ValueError("bad NC_VARIABLE tag")
        rec_vars = []
        for _ in range(n):
            name = self._name()
            ndims = self._u32()
            dimids = [self._u32() for _ in range(ndims)]
            attrs = self._parse_atts()
            typ = self._u32()
            vsize = self._u32()
            begin = self._offset()
            dt = _NC3_DTYPES[typ]
            dim_names = tuple(self.dims[d][0] for d in dimids)
            shape = tuple(self.dims[d][1] for d in dimids)
            is_record = bool(shape) and shape[0] == 0
            if is_record:
                shape = (self.numrecs,) + shape[1:]
            var = NCVar(name, dim_names, shape, dt.newbyteorder("="), attrs)
            var._reader = _NC3Reader(self, var, dt, begin, vsize, is_record)
            self.variables[name] = var
            if is_record:
                rec_vars.append(var)
        # record stride: sum of padded vsizes — EXCEPT with exactly one
        # record variable, where the classic format packs records without
        # padding (netCDF spec "note on vsize")
        if len(rec_vars) == 1:
            self._rec_stride = rec_vars[0]._reader.vsize_unpadded
        else:
            self._rec_stride = sum(v._reader.vsize_padded for v in rec_vars)
        for v in rec_vars:
            v._reader.rec_stride = self._rec_stride


class _NC3Reader:
    def __init__(self, f: _NC3File, var: NCVar, dt: np.dtype, begin: int,
                 vsize: int, is_record: bool):
        self.f = f
        self.var = var
        self.dt = dt
        self.begin = begin
        self.is_record = is_record
        per_rec = int(np.prod(var.shape[1:], dtype=np.int64)) if is_record \
            else int(np.prod(var.shape, dtype=np.int64))
        nb = per_rec * dt.itemsize
        self.vsize_unpadded = nb
        self.vsize_padded = nb + ((4 - nb % 4) % 4)
        self.rec_stride = self.vsize_padded

    def __call__(self, key):
        var = self.var
        if self.is_record:
            # materialise requested records only (seek per record)
            shape_rest = var.shape[1:]
            per_rec = int(np.prod(shape_rest, dtype=np.int64))
            if isinstance(key, tuple):
                tkey, rest = key[0], key[1:]
            else:
                tkey, rest = key, ()
            if isinstance(tkey, slice):
                idxs = range(var.shape[0])[tkey]
            else:
                t = int(tkey)
                if t < 0:
                    t += var.shape[0]
                if not 0 <= t < var.shape[0]:
                    raise IndexError(
                        f"record index {tkey} out of range for "
                        f"{var.name} with {var.shape[0]} records")
                idxs = [t]
            recs = []
            for t in idxs:
                off = self.begin + t * self.rec_stride
                raw = self.f.read_at(off, per_rec * self.dt.itemsize)
                recs.append(np.frombuffer(raw, self.dt).reshape(shape_rest))
            if isinstance(tkey, slice):
                arr = np.stack(recs)
                # rest indexes the per-record axes, not the time axis
                out = arr[(slice(None),) + rest] if rest else arr
            else:
                arr = recs[0]
                out = arr[rest] if rest else arr
        else:
            out = self._fixed(key, var)
        out = np.ascontiguousarray(out).astype(self.dt.newbyteorder("="))
        # NetCDF-3 has no unsigned types; honour the _Unsigned convention
        if str(var.attrs.get("_Unsigned", "")).lower() in ("true", "1") \
                and out.dtype.kind == "i":
            out = out.view(np.dtype(f"u{out.dtype.itemsize}"))
        return out

    def _fixed(self, key, var):
        """Fixed (non-record) variable read.  Selections on the leading
        axis read ONLY that byte range — a (T, H, W) stack stored as a
        fixed var must not materialise all T frames to serve one
        timestep (the band_query lesson, `netcdfdataset.cpp:6994`)."""
        itemsize = self.dt.itemsize
        if key is not None and var.shape:
            per0 = int(np.prod(var.shape[1:], dtype=np.int64))
            k0, rest = (key[0], key[1:]) if isinstance(key, tuple) \
                else (key, ())
            if isinstance(k0, (int, np.integer)):
                t = int(k0)
                if t < 0:
                    t += var.shape[0]
                if not 0 <= t < var.shape[0]:
                    raise IndexError(
                        f"index {k0} out of range for {var.name}")
                raw = self.f.read_at(self.begin + t * per0 * itemsize,
                                     per0 * itemsize)
                arr = np.frombuffer(raw, self.dt).reshape(var.shape[1:])
                return arr[rest] if rest else arr
            if isinstance(k0, slice):
                lo, hi, step = k0.indices(var.shape[0])
                if step == 1 and hi > lo:
                    raw = self.f.read_at(
                        self.begin + lo * per0 * itemsize,
                        (hi - lo) * per0 * itemsize)
                    arr = np.frombuffer(raw, self.dt).reshape(
                        (hi - lo,) + var.shape[1:])
                    return arr[(slice(None),) + rest] if rest else arr
        total = int(np.prod(var.shape, dtype=np.int64))
        raw = self.f.read_at(self.begin, total * itemsize)
        arr = np.frombuffer(raw, self.dt).reshape(var.shape)
        return arr[key] if key is not None else arr


# ---------------------------------------------------------------------------
# NetCDF-3 classic writer (for WCS NetCDF output + test fixtures)
# ---------------------------------------------------------------------------

def write_netcdf3(path: str, arrays: Dict[str, np.ndarray],
                  x: np.ndarray, y: np.ndarray,
                  crs: CRS = EPSG4326,
                  times: Optional[np.ndarray] = None,
                  nodata: Optional[float] = None,
                  global_attrs: Optional[Dict[str, str]] = None):
    """Minimal CF NetCDF-3 writer: variables shaped (y, x) or
    (time, y, x) — the WCS NetCDF output analogue of
    `utils/ogc_encoders.go:277-346` (GDAL NetCDF create path)."""
    for name, arr in arrays.items():
        shp = np.asarray(arr).shape
        want = (len(y), len(x))
        if shp[-2:] != want:
            # declaring (y, x) dims over differently-shaped data would
            # write a silently corrupt file (header/data size mismatch)
            raise ValueError(
                f"variable {name!r} shape {shp} does not match the "
                f"declared (y, x) dims {want}")
    dims: List[Tuple[str, int]] = []
    if times is not None:
        dims.append(("time", len(times)))
    dims.append(("y", len(y)))
    dims.append(("x", len(x)))

    # variable table entries: coordinate vars + data vars (all non-record)
    variables = []  # (name, dims, attrs, np_array)
    variables.append(("x", ("x",), {
        "standard_name": "projection_x_coordinate" if not crs.is_geographic
        else "longitude", "units": "m" if not crs.is_geographic else
        "degrees_east"}, np.asarray(x, np.float64)))
    variables.append(("y", ("y",), {
        "standard_name": "projection_y_coordinate" if not crs.is_geographic
        else "latitude", "units": "m" if not crs.is_geographic else
        "degrees_north"}, np.asarray(y, np.float64)))
    if times is not None:
        variables.append(("time", ("time",), {
            "standard_name": "time",
            "units": "seconds since 1970-01-01 00:00:00"},
            np.asarray(times, np.float64)))
    crs_attrs: Dict[str, object] = {"spatial_ref": crs.to_wkt()}
    variables.append(("crs", (), crs_attrs, np.zeros((), np.int32)))
    for vname, arr in arrays.items():
        va: Dict[str, object] = {"grid_mapping": "crs"}
        if arr.dtype.kind == "u":
            va["_Unsigned"] = "true"
        if nodata is not None:
            va["_FillValue"] = np.asarray(nodata, arr.dtype)
        vdims = ("time", "y", "x") if (times is not None and arr.ndim == 3) \
            else ("y", "x")
        variables.append((vname, vdims, va, arr))

    write_netcdf3_raw(path, dims, variables,
                      dict(global_attrs or {"Conventions": "CF-1.6"}))


def _nc3_name_pad(s: bytes) -> bytes:
    return struct.pack(">I", len(s)) + s + b"\0" * ((4 - len(s) % 4) % 4)


def _nc3_pack(arr: np.ndarray) -> Tuple[int, bytes, bool]:
    """-> (nc_type, big-endian bytes, was_unsigned).  NetCDF-3 has no
    unsigned types: u1/u2/u4 are bit-reinterpreted into the signed
    type of the same width with the _Unsigned convention."""
    k = np.dtype(arr.dtype).newbyteorder("=").str[1:]
    if k in ("u1", "u2", "u4"):
        typ = {"u1": 1, "u2": 3, "u4": 4}[k]
        raw = arr.astype(f">u{arr.dtype.itemsize}").view(
            _NC3_DTYPES[typ]).tobytes()
        return typ, raw, True
    if k == "i8":
        if arr.size and (arr.max() > 2**31 - 1 or arr.min() < -2**31):
            raise ValueError("int64 values exceed NetCDF-3 int range")
        arr = arr.astype(np.int32)
        k = "i4"
    if k not in ("i1", "i2", "i4", "f4", "f8"):
        raise ValueError(f"dtype {arr.dtype} not representable in "
                         "NetCDF-3 classic")
    typ = {"i1": 1, "i2": 3, "i4": 4, "f4": 5, "f8": 6}[k]
    return typ, arr.astype(_NC3_DTYPES[typ]).tobytes(), False


def _nc3_atts(d: Dict[str, object]) -> bytes:
    if not d:
        return struct.pack(">II", 0, 0)
    out = struct.pack(">II", 0x0C, len(d))
    for k, v in d.items():
        out += _nc3_name_pad(k.encode())
        if isinstance(v, str):
            raw = v.encode("latin-1")
            out += struct.pack(">II", 2, len(raw)) + raw \
                + b"\0" * ((4 - len(raw) % 4) % 4)
        else:
            arr = np.atleast_1d(np.asarray(v))
            typ, raw, _ = _nc3_pack(arr)
            out += struct.pack(">II", typ, len(arr)) + raw \
                + b"\0" * ((4 - len(raw) % 4) % 4)
    return out


def write_netcdf3_raw(path: str, dims, variables, global_attrs):
    """Low-level NetCDF-3 classic writer: ``dims`` is an ordered list
    of (name, size); ``variables`` a list of (name, dim_names, attrs,
    array) — the layout engine shared by the CF writer above and the
    GMT grid writer (`io.gmt.write_gmt`), which needs non-CF dimension
    names (side/xysize)."""
    dimid = {name: i for i, (name, _) in enumerate(dims)}
    header = b"CDF\x01" + struct.pack(">I", 0)  # numrecs 0 (no record vars)
    header += struct.pack(">II", 0x0A, len(dims))
    for dname, dsize in dims:
        header += _nc3_name_pad(dname.encode()) + struct.pack(">I", dsize)
    header += _nc3_atts(dict(global_attrs or {}))

    var_entries = []
    for vname, vdims, vattrs, arr in variables:
        typ, raw, _ = _nc3_pack(np.asarray(arr))
        ent = _nc3_name_pad(vname.encode())
        ent += struct.pack(">I", len(vdims))
        for dn in vdims:
            ent += struct.pack(">I", dimid[dn])
        ent += _nc3_atts(vattrs)
        vsize = len(raw) + ((4 - len(raw) % 4) % 4)
        ent += struct.pack(">II", typ, vsize)
        var_entries.append((ent, typ, vsize, raw))

    # compute begins
    fixed = len(header) + struct.pack(">II", 0x0B, len(var_entries)).__len__()
    total_entries = sum(len(e[0]) + 4 for e in var_entries)  # + begin u32
    begin = fixed + total_entries
    body = b""
    var_table = struct.pack(">II", 0x0B, len(var_entries))
    for ent, typ, vsize, raw in var_entries:
        var_table += ent + struct.pack(">I", begin)
        body += raw + b"\0" * (vsize - len(raw))
        begin += vsize
    with open(path, "wb") as fp:
        fp.write(header + var_table + body)
