"""PNG/JPEG encoding of rendered tiles.

Parity with `utils/ogc_encoders.go:82-142` (EncodePNG): 1-band byte
rasters are encoded as paletted PNG with index 0xFF transparent; 3 bands
become RGB with 0xFF-in-all-bands transparent; 4 bands RGBA.  PIL supplies
the (C-accelerated) codec.
"""

from __future__ import annotations

import asyncio
import contextvars
import io
import os
import struct
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

from ..obs import span as obs_span
from ..obs.metrics import ENCODE_SECONDS

NODATA_BYTE = 255

# -- sized encode pool -------------------------------------------------------
# PNG/JPEG encode is pure-CPU PIL work that used to run INLINE in the
# async GetMap handler, stalling the event loop for the encode of every
# tile.  The staged tile path runs encodes here instead: a bounded pool
# (GSKY_PNG_ENCODE_WORKERS) so concurrent requests' encodes overlap
# each other and the next request's device readback, without unbounded
# thread growth under burst load.

_POOL_ENV = "GSKY_PNG_ENCODE_WORKERS"
_pool: Optional[ThreadPoolExecutor] = None
_pool_lock = threading.Lock()
_pool_stats: Dict = {"workers": 0, "pending": 0, "queue_max": 0,
                     "encoded": 0, "errors": 0, "busy_s": 0.0}


def _pool_workers() -> int:
    try:
        v = int(os.environ.get(_POOL_ENV, 4))
    except ValueError:
        return 4
    return max(1, min(32, v))


def encode_pool() -> ThreadPoolExecutor:
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                n = _pool_workers()
                _pool_stats["workers"] = n
                _pool = ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix="gsky-png")
    return _pool


def encode_pool_stats() -> Dict:
    with _pool_lock:
        out = dict(_pool_stats)
    out["busy_s"] = round(out["busy_s"], 6)
    return out


def reset_encode_pool() -> None:
    """Shut the pool down so the next encode re-reads the sizing knob
    (tests; a serving process keeps one pool for its lifetime)."""
    global _pool
    with _pool_lock:
        pool, _pool = _pool, None
        for k, v in (("workers", 0), ("pending", 0), ("queue_max", 0),
                     ("encoded", 0), ("errors", 0), ("busy_s", 0.0)):
            _pool_stats[k] = v
    if pool is not None:
        pool.shutdown(wait=False)


async def encode_async(fn, *args, spans: Optional[Dict] = None, **kw):
    """Run one encode callable on the sized pool, awaitable from the
    event loop.  Exceptions propagate to the awaiting handler exactly
    as they would inline.  ``spans`` (the staged tile path's
    per-request record) gets ``encode_s`` and the observed
    ``encode_queue_max`` occupancy folded in."""
    loop = asyncio.get_running_loop()
    pool = encode_pool()
    with _pool_lock:
        _pool_stats["pending"] += 1
        occupancy = _pool_stats["pending"]
        if occupancy > _pool_stats["queue_max"]:
            _pool_stats["queue_max"] = occupancy
    if spans is not None:
        spans["encode_queue_max"] = max(
            spans.get("encode_queue_max", 0), occupancy)
    t0 = time.perf_counter()
    # pool threads start from an empty contextvars.Context; carry the
    # caller's (trace context included) across the hop explicitly
    ctx = contextvars.copy_context()
    cpu = [0.0]

    def _job():
        # inside the copied context so current_token() resolves: a
        # request cancelled while its encode queued gives its pool
        # slot back without burning CPU on bytes nobody will read
        from ..resilience import check_cancel
        check_cancel("encode")
        return fn(*args, **kw)

    def run():
        t1 = time.perf_counter()
        try:
            return ctx.run(_job)
        finally:
            cpu[0] = time.perf_counter() - t1
            with _pool_lock:
                _pool_stats["busy_s"] += cpu[0]

    ok = False
    try:
        with obs_span("encode") as esp:
            out = await loop.run_in_executor(pool, run)
            wait_s = max(0.0, time.perf_counter() - t0 - cpu[0])
            esp.set(cpu_s=round(cpu[0], 6), wait_s=round(wait_s, 6))
            try:
                ENCODE_SECONDS.labels(phase="cpu").observe(cpu[0])
                ENCODE_SECONDS.labels(phase="wait").observe(wait_s)
            except Exception:  # telemetry only - never fail the encode
                pass
        ok = True
        return out
    finally:
        # finally (not except Exception): a cancelled await must still
        # release its pending slot or the occupancy telemetry leaks
        with _pool_lock:
            _pool_stats["pending"] -= 1
            _pool_stats["encoded" if ok else "errors"] += 1
        if ok and spans is not None:
            spans["encode_s"] = spans.get("encode_s", 0.0) \
                + time.perf_counter() - t0

# zlib level 1 default: on satellite composites levels 6-9 buy ~10%
# smaller tiles for >2x the encode time, and the encode sits on the
# per-tile critical path.  Operators serving over thin links can trade
# CPU for bytes via GSKY_PNG_LEVEL or per-layer `png_compress_level`.
_LEVEL_ENV = "GSKY_PNG_LEVEL"
_DEFAULT_LEVEL = 1


def _resolve_level(level: Optional[int]) -> int:
    """Effective zlib level: explicit per-call (layer config) beats the
    GSKY_PNG_LEVEL env beats the level-1 default; anything outside 0-9
    is a configuration error, not a clamp."""
    if level is None:
        env = os.environ.get(_LEVEL_ENV)
        if env is None or env == "":
            return _DEFAULT_LEVEL
        try:
            level = int(env)
        except ValueError:
            raise ValueError(
                f"{_LEVEL_ENV} must be an integer 0-9, got {env!r}")
    level = int(level)
    if not 0 <= level <= 9:
        raise ValueError(
            f"PNG compress level must be 0-9, got {level}")
    return level


def encode_png(bands: Sequence[np.ndarray],
               palette: Optional[np.ndarray] = None,
               compress_level: Optional[int] = None) -> bytes:
    """bands: list of (H, W) uint8 arrays (1, 3 or 4 of them);
    palette: (256, 4) uint8 RGBA LUT for the 1-band case;
    compress_level: zlib 0-9 (None -> GSKY_PNG_LEVEL -> 1)."""
    level = _resolve_level(compress_level)
    if len(bands) == 1:
        img = Image.fromarray(bands[0], "P")
        if palette is None:
            # greyscale ramp with transparent nodata
            lut = np.stack([np.arange(256)] * 3 + [np.full(256, 255)], 1)
            lut = lut.astype(np.uint8)
            lut[NODATA_BYTE] = (0, 0, 0, 0)
        else:
            lut = np.asarray(palette, np.uint8)
            if lut.shape != (256, 4):
                raise ValueError("palette must be (256,4) RGBA")
        img.putpalette(lut[:, :3].reshape(-1).tobytes(), "RGB")
        img.info["transparency"] = bytes(lut[:, 3].tolist())
        buf = io.BytesIO()
        img.save(buf, "PNG", transparency=bytes(lut[:, 3].tolist()),
                 compress_level=level)
        return buf.getvalue()
    if len(bands) == 3:
        h, w = bands[0].shape
        rgba = np.zeros((h, w, 4), np.uint8)
        for i in range(3):
            rgba[..., i] = bands[i]
        nodata = (bands[0] == NODATA_BYTE) & (bands[1] == NODATA_BYTE) \
            & (bands[2] == NODATA_BYTE)
        rgba[..., 3] = np.where(nodata, 0, 255)
        img = Image.fromarray(rgba, "RGBA")
        buf = io.BytesIO()
        img.save(buf, "PNG", compress_level=level)
        return buf.getvalue()
    if len(bands) == 4:
        h, w = bands[0].shape
        rgba = np.stack(bands, axis=-1)
        img = Image.fromarray(rgba, "RGBA")
        buf = io.BytesIO()
        img.save(buf, "PNG", compress_level=level)
        return buf.getvalue()
    raise ValueError(f"cannot encode {len(bands)} bands as PNG")


def encode_rgba_png(rgba: np.ndarray,
                    compress_level: Optional[int] = None) -> bytes:
    """(H, W, 4) uint8 -> PNG bytes (the device palette / packed-RGB
    path output — already interleaved, no host assembly pass)."""
    buf = io.BytesIO()
    Image.fromarray(np.asarray(rgba, np.uint8), "RGBA").save(
        buf, "PNG", compress_level=_resolve_level(compress_level))
    return buf.getvalue()


def encode_jpeg(bands: Sequence[np.ndarray], quality: int = 85) -> bytes:
    """3-band JPEG (the tile_jpg_enc.go analogue)."""
    if len(bands) == 1:
        img = Image.fromarray(bands[0], "L")
    elif len(bands) == 3:
        img = Image.fromarray(np.stack(bands, axis=-1), "RGB")
    else:
        raise ValueError(f"cannot encode {len(bands)} bands as JPEG")
    buf = io.BytesIO()
    img.save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def decode_png(data: bytes) -> np.ndarray:
    """PNG bytes -> (H, W, 4) uint8 (used by tests and the empty-tile
    resizer `utils/empty_tile.go:14`)."""
    img = Image.open(io.BytesIO(data)).convert("RGBA")
    return np.asarray(img)


# -- APNG assembly -----------------------------------------------------------
# The temporal wave path (docs/PERF.md "Temporal waves") renders every
# animation frame to ordinary PNG bytes on the encode pool, then splices
# the frames into one Animated PNG container.  Assembly is pure chunk
# surgery — no pixel decode, no re-compression — so frame 0's IDAT
# stream rides VERBATIM: the animation's first frame and the equivalent
# single-timestep GetMap are the same compressed bytes.

_PNG_SIG = b"\x89PNG\r\n\x1a\n"


def _png_chunks(data: bytes) -> Iterator[Tuple[bytes, bytes]]:
    """Iterate (type, payload) over one PNG byte stream."""
    if data[:8] != _PNG_SIG:
        raise ValueError("not a PNG stream")
    off = 8
    n = len(data)
    while off + 12 <= n:
        ln = struct.unpack(">I", data[off:off + 4])[0]
        typ = data[off + 4:off + 8]
        yield typ, data[off + 8:off + 8 + ln]
        off += 12 + ln


def _png_chunk(typ: bytes, payload: bytes) -> bytes:
    return (struct.pack(">I", len(payload)) + typ + payload
            + struct.pack(">I", zlib.crc32(typ + payload) & 0xFFFFFFFF))


class ApngAssembler:
    """Incremental APNG container builder over pre-encoded PNG frames.

    ``frame(png)`` returns the wire bytes for that frame — the caller
    (the OWS animation handler) streams them as each frame's encode
    completes, so the client sees frame 0 while later timesteps are
    still on the device.  Frame 0 contributes the header: its IHDR,
    palette and transparency chunks verbatim, plus the ``acTL``
    animation control chunk; every frame gets an ``fcTL`` (full-frame,
    no blending — each timestep replaces the last) and its IDAT data
    (re-typed ``fdAT`` after frame 0).  All frames must share frame
    0's geometry and palette — true by construction for one GetMap
    sequence.  ``trailer()`` closes the stream."""

    def __init__(self, num_frames: int, delay_ms: int = 500,
                 num_plays: int = 0):
        if num_frames < 1:
            raise ValueError("APNG needs at least one frame")
        self.num_frames = int(num_frames)
        self.delay_ms = max(1, min(65535, int(delay_ms)))
        self.num_plays = int(num_plays)
        self._seq = 0
        self._n = 0
        self._w = 0
        self._h = 0

    def _next_seq(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    def _fctl(self) -> bytes:
        # full-canvas frame at (0,0), dispose none, blend source
        return _png_chunk(b"fcTL", struct.pack(
            ">IIIIIHHBB", self._next_seq(), self._w, self._h, 0, 0,
            self.delay_ms, 1000, 0, 0))

    def frame(self, png: bytes) -> bytes:
        """Splice one encoded PNG in; returns its container bytes."""
        if self._n >= self.num_frames:
            raise ValueError("more frames than declared in acTL")
        head: List[Tuple[bytes, bytes]] = []
        idats: List[bytes] = []
        for typ, payload in _png_chunks(png):
            if typ == b"IDAT":
                idats.append(payload)
            elif typ != b"IEND" and not idats:
                head.append((typ, payload))
        if not idats or not head or head[0][0] != b"IHDR":
            raise ValueError("malformed PNG frame")
        parts: List[bytes] = []
        if self._n == 0:
            ihdr = head[0][1]
            self._w = struct.unpack(">I", ihdr[0:4])[0]
            self._h = struct.unpack(">I", ihdr[4:8])[0]
            parts.append(_PNG_SIG)
            parts.append(_png_chunk(b"IHDR", ihdr))
            # acTL must precede the first IDAT; right after IHDR keeps
            # the frame's own ancillary chunk order untouched
            parts.append(_png_chunk(b"acTL", struct.pack(
                ">II", self.num_frames, self.num_plays)))
            for typ, payload in head[1:]:
                parts.append(_png_chunk(typ, payload))
            parts.append(self._fctl())
            for payload in idats:
                parts.append(_png_chunk(b"IDAT", payload))
        else:
            parts.append(self._fctl())
            for payload in idats:
                parts.append(_png_chunk(
                    b"fdAT",
                    struct.pack(">I", self._next_seq()) + payload))
        self._n += 1
        return b"".join(parts)

    def trailer(self) -> bytes:
        if self._n != self.num_frames:
            raise ValueError(
                f"assembled {self._n} of {self.num_frames} frames")
        return _png_chunk(b"IEND", b"")


def encode_apng(frames: Sequence[bytes], delay_ms: int = 500,
                num_plays: int = 0) -> bytes:
    """Whole-container convenience over `ApngAssembler` (tests/bench;
    the server streams per-frame instead)."""
    asm = ApngAssembler(len(frames), delay_ms, num_plays)
    return b"".join([asm.frame(f) for f in frames] + [asm.trailer()])


def empty_tile_png(width: int, height: int,
                   tile_image: Optional[bytes] = None,
                   compress_level: Optional[int] = None) -> bytes:
    """Transparent (or tiled-image) PNG of the requested size — the
    zoom-limit / error tile of `utils/empty_tile.go:14-53`."""
    canvas = Image.new("RGBA", (width, height), (0, 0, 0, 0))
    if tile_image:
        tile = Image.open(io.BytesIO(tile_image)).convert("RGBA")
        for x in range(0, width, tile.width):
            for y in range(0, height, tile.height):
                canvas.paste(tile, (x, y))
    buf = io.BytesIO()
    canvas.save(buf, "PNG", compress_level=_resolve_level(compress_level))
    return buf.getvalue()
