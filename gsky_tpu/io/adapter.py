"""Adapter-tier raster readers for formats outside the native set.

`ImageRaster` decodes anything PIL can open — Sentinel-2 JPEG2000
(openjpeg), PNG, JPEG, BMP — and georeferences via an ESRI world file
(`.j2w`/`.jgw`/`.pgw`/`.tfw`/`.wld`) next to the image, the classic
sidecar convention GDAL also honours.  PIL has no partial JP2 decode,
so the first window read materialises the full image and windows slice
from it (one decode per open handle; the scene cache keeps the device
copy anyway).

`RasterioRaster`/`GdalRaster` wrap those libraries when the deployment
image carries them (`io.registry` gates on import) — true windowed
reads, full GDAL format universe (HDF4 MODIS etc.), same tiff-like
interface.  This file has no hard dependency on either.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..geo.transform import GeoTransform

_WORLD_EXTS = (".wld", ".j2w", ".jgw", ".pgw", ".tfw", ".bpw")
_IMAGE_MAGICS = (
    b"\x00\x00\x00\x0cjP  ",        # JP2 signature box
    b"\xff\x4f\xff\x51",            # raw JPEG2000 codestream
    b"\x89PNG\r\n\x1a\n",
    b"\xff\xd8\xff",
    b"BM",
)


def read_world_file(path: str) -> Optional[GeoTransform]:
    """Six-line ESRI world file -> GeoTransform (world files give the
    CENTRE of the top-left pixel; GDAL shifts by half a pixel)."""
    base = os.path.splitext(path)[0]
    for ext in _WORLD_EXTS:
        for cand in (base + ext, base + ext.upper()):
            if os.path.exists(cand):
                try:
                    with open(cand) as fp:
                        vals = [float(fp.readline()) for _ in range(6)]
                except (OSError, ValueError):
                    return None
                dx, ry, rx, dy, cx, cy = vals
                return GeoTransform(cx - dx * 0.5 - rx * 0.5, dx, rx,
                                    cy - ry * 0.5 - dy * 0.5, ry, dy)
    return None


def sniff_image(path: str, magic: bytes) -> bool:
    return any(magic.startswith(m) for m in _IMAGE_MAGICS)


class ImageRaster:
    """PIL-decoded raster with world-file georeferencing."""

    def __init__(self, path: str):
        import threading

        from PIL import Image
        self.path = path
        img = Image.open(path)
        self.width, self.height = img.size
        self._img = img
        self._arr: Optional[np.ndarray] = None
        # handles are shared across decode worker threads via the
        # handle cache; PIL's lazy load() is not thread-safe
        self._decode_lock = threading.Lock()
        self.bands = len(img.getbands())
        self.nodata: Optional[float] = None
        self.overviews: Tuple = ()
        self.gt = read_world_file(path) or \
            GeoTransform(0.0, 1.0, 0.0, 0.0, 0.0, 1.0)
        self.crs = None        # sidecar .prj / ruleset srs supplies it

    def _array(self) -> np.ndarray:
        with self._decode_lock:
            if self._arr is None:
                a = np.asarray(self._img)
                if a.ndim == 2:
                    a = a[..., None]
                self._arr = a
            return self._arr

    def read(self, band: int = 1,
             window: Optional[Tuple[int, int, int, int]] = None,
             ifd=None) -> np.ndarray:
        a = self._array()
        b = min(max(band, 1), a.shape[-1]) - 1
        if window is None:
            return a[..., b]
        c0, r0, w, h = window
        return a[r0:r0 + h, c0:c0 + w, b]

    def close(self):
        try:
            self._img.close()
        except Exception:
            # a handle torn down twice (cache eviction racing a
            # context-manager exit) is already closed — nothing to free
            pass
        # under the decode lock: close() can race a decode thread
        # still inside _array() via the shared handle cache
        with self._decode_lock:
            self._arr = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def sniff_rasterio(path: str, magic: bytes) -> bool:
    return True                 # last-resort tier before PIL


class RasterioRaster:
    """rasterio-backed windowed reader (present only when the image
    ships rasterio)."""

    def __init__(self, path: str):
        import rasterio
        self._ds = rasterio.open(path)
        self.width = self._ds.width
        self.height = self._ds.height
        self.bands = self._ds.count
        self.nodata = self._ds.nodata
        self.overviews: Tuple = ()
        t = self._ds.transform
        self.gt = GeoTransform(t.c, t.a, t.b, t.f, t.d, t.e)
        self.crs = None

    def read(self, band: int = 1,
             window: Optional[Tuple[int, int, int, int]] = None,
             ifd=None) -> np.ndarray:
        import rasterio.windows as rw
        if window is None:
            return self._ds.read(band)
        c0, r0, w, h = window
        return self._ds.read(band, window=rw.Window(c0, r0, w, h))

    def close(self):
        self._ds.close()


def sniff_gdal(path: str, magic: bytes) -> bool:
    return True


class GdalRaster:
    """GDAL-backed reader (present only when the image ships GDAL) —
    the full driver universe (HDF4, JP2, GMT, ...)."""

    def __init__(self, path: str):
        from osgeo import gdal
        self._ds = gdal.Open(path)
        if self._ds is None:
            raise ValueError(f"GDAL cannot open {path}")
        self.width = self._ds.RasterXSize
        self.height = self._ds.RasterYSize
        self.bands = self._ds.RasterCount
        b1 = self._ds.GetRasterBand(1)
        self.nodata = b1.GetNoDataValue()
        self.overviews: Tuple = ()
        g = self._ds.GetGeoTransform()
        self.gt = GeoTransform(g[0], g[1], g[2], g[3], g[4], g[5])
        self.crs = None

    def read(self, band: int = 1,
             window: Optional[Tuple[int, int, int, int]] = None,
             ifd=None) -> np.ndarray:
        b = self._ds.GetRasterBand(band)
        if window is None:
            return b.ReadAsArray()
        c0, r0, w, h = window
        return b.ReadAsArray(c0, r0, w, h)

    def close(self):
        self._ds = None
