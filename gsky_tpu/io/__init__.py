from .geotiff import GeoTIFF, write_geotiff
from .png import encode_png, encode_rgba_png
from . import netcdf

__all__ = ["GeoTIFF", "write_geotiff", "encode_png", "encode_rgba_png",
           "netcdf"]
