"""VRT (virtual raster) granules: band-math / masking datasets assembled
from source files at drill time.

Reference behaviour being reproduced (not its implementation):
`worker/gdalprocess/vrt_manager.go:58-176` materialises user VRT XML —
auto-filling SRS / raster sizes (incl. fractional scaling) / geotransform
/ nodata / dtype from the first ``metadata-template`` source — into
/vsimem so GDAL can open it, and `worker/gdalprocess/drill.go:363-423`
drills through it with GDAL pixel functions (including Python ones);
`processor/drill_indexer.go:318-346` renders the per-granule VRT from a
Jet template with ``{RasterXSize, RasterYSize, Data, Masks}`` context.

Here there is no GDAL: the XML is parsed directly, the metadata template
fills from the repo's own GeoTIFF/NetCDF readers, and pixel functions
evaluate as numpy code with GDAL's Python pixel-function signature
``fn(in_ar, out_ar, xoff, yoff, xsize, ysize, raster_xsize,
raster_ysize, buf_radius, gt)``.  A second, preferred function language
``expression`` routes through the jit band-expression compiler
(`ops.expr`) with sources bound to ``b1..bN``.
"""

from __future__ import annotations

import math
import re
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..geo.crs import CRS, parse_crs
from ..geo.transform import GeoTransform

_DTYPES = {
    "byte": np.uint8, "uint16": np.uint16, "int16": np.int16,
    "uint32": np.uint32, "int32": np.int32, "float32": np.float32,
    "float64": np.float64,
}


@dataclass
class VRTSource:
    path: str
    metadata_template: bool = False


@dataclass
class VRTBand:
    band: int = 1
    dtype: str = ""
    nodata: Optional[float] = None
    pixel_fn_type: str = ""
    pixel_fn_language: str = ""
    pixel_fn_code: str = ""
    sources: List[VRTSource] = field(default_factory=list)


@dataclass
class VRTDataset:
    """Parsed (and, after `autofill`, materialised) VRT description."""

    raster_x_size: float = 0.0            # fractional before autofill
    raster_y_size: float = 0.0
    srs: str = ""
    geo_transform: Optional[Tuple[float, ...]] = None
    bands: List[VRTBand] = field(default_factory=list)

    @classmethod
    def parse(cls, xml_text: str) -> "VRTDataset":
        root = ET.fromstring(xml_text)
        if root.tag != "VRTDataset":
            raise ValueError(f"not a VRTDataset: <{root.tag}>")
        ds = cls(
            raster_x_size=float(root.get("rasterXSize", 0) or 0),
            raster_y_size=float(root.get("rasterYSize", 0) or 0),
            srs=(root.findtext("SRS") or "").strip())
        gt_text = (root.findtext("GeoTransform") or "").strip()
        if gt_text:
            ds.geo_transform = tuple(
                float(v) for v in gt_text.replace(",", " ").split())
        for ib, be in enumerate(root.findall("VRTRasterBand")):
            b = VRTBand(
                band=int(be.get("band", 0) or 0) or ib + 1,
                dtype=be.get("dataType", "") or "",
                pixel_fn_type=(be.findtext("PixelFunctionType") or "").strip(),
                pixel_fn_language=(be.findtext("PixelFunctionLanguage")
                                   or "").strip().lower(),
                pixel_fn_code=be.findtext("PixelFunctionCode") or "")
            nd = (be.findtext("NoDataValue") or "").strip()
            if nd:
                b.nodata = float(nd)
            for se in be.findall("SimpleSource"):
                fn = (se.findtext("SourceFilename") or "").strip()
                if fn:
                    b.sources.append(VRTSource(
                        path=fn,
                        metadata_template=se.get("metadata-template")
                        == "1"))
            ds.bands.append(b)
        if not ds.bands:
            raise ValueError("VRTDataset has no VRTRasterBand")
        return ds

    def autofill(self) -> "VRTDataset":
        """Fill SRS/sizes/geotransform/nodata/dtype from the first
        metadata-template source (`vrt_manager.go:70-160`), with the
        reference's fractional-size scaling rules."""
        src = None
        band = None
        for b in self.bands:
            for s in b.sources:
                if s.metadata_template:
                    src, band = s, b
                    break
            if src is not None:
                break
        if src is None:
            return self

        meta = _source_meta(src.path)
        if not self.srs.strip():
            self.srs = meta["srs"]
        x_size, y_size = float(meta["width"]), float(meta["height"])

        xs, ys = self.raster_x_size, self.raster_y_size
        if xs <= 0 and ys <= 0:
            xs, ys = x_size, y_size
        else:
            if 0 < xs < 1:
                xs = float(int(x_size * xs + 0.5))
            if 0 < ys < 1:
                ys = float(int(y_size * ys + 0.5))
            if xs <= 0 < ys:
                xs = float(int(ys * x_size / y_size + 0.5))
            elif ys <= 0 < xs:
                ys = float(int(xs * y_size / x_size + 0.5))
        self.raster_x_size = min(max(xs, 1.0), x_size)
        self.raster_y_size = min(max(ys, 1.0), y_size)

        if self.geo_transform is None:
            gt = list(meta["geo_transform"])
            if self.raster_x_size < x_size:
                gt[1] *= x_size / self.raster_x_size
            if self.raster_y_size < y_size:
                gt[5] *= y_size / self.raster_y_size
            self.geo_transform = tuple(gt)

        if band.nodata is None and meta["nodata"] is not None:
            band.nodata = meta["nodata"]
        if not band.dtype:
            band.dtype = meta["dtype"]
        return self


def _source_meta(path: str) -> dict:
    from .geotiff import GeoTIFF
    from .netcdf import NetCDF

    if path.lower().endswith((".nc", ".nc4")):
        with NetCDF(path) as nc:
            v = nc.raster_vars()[0]
            crs = nc.crs(v)
            gt = nc.geotransform()
            return {"srs": crs.to_wkt() if crs else "",
                    "width": v.shape[-1], "height": v.shape[-2],
                    "geo_transform": gt.to_gdal() if gt else
                    (0, 1, 0, 0, 0, 1),
                    "nodata": v.nodata,
                    "dtype": np.dtype(v.dtype).name.capitalize()}
    with GeoTIFF(path) as g:
        return {"srs": g.crs.to_wkt(), "width": g.width,
                "height": g.height, "geo_transform": g.gt.to_gdal(),
                "nodata": g.nodata,
                "dtype": np.dtype(g.dtype).name.capitalize()}


class VRTRaster:
    """Windowed reader over a materialised VRT: sources decode through
    the repo readers, the band's pixel function combines them."""

    def __init__(self, xml_text: str):
        self.ds = VRTDataset.parse(xml_text).autofill()
        if self.ds.geo_transform is None:
            raise ValueError("VRT has no GeoTransform and no "
                             "metadata-template source to derive it")
        self.width = int(self.ds.raster_x_size)
        self.height = int(self.ds.raster_y_size)
        self.gt = GeoTransform.from_gdal(self.ds.geo_transform)
        self.crs: Optional[CRS] = None
        if self.ds.srs.strip():
            self.crs = parse_crs(self.ds.srs)
        b0 = self.ds.bands[0]
        self.nodata = b0.nodata if b0.nodata is not None else float("nan")
        self.dtype = _DTYPES.get(b0.dtype.lower(), np.float32)

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def read(self, band: int = 1,
             window: Optional[Tuple[int, int, int, int]] = None,
             time_index: Optional[int] = None) -> np.ndarray:
        """window = (col0, row0, w, h) on the VRT grid."""
        b = self.ds.bands[band - 1]
        c0, r0, w, h = window or (0, 0, self.width, self.height)
        in_ar = [self._read_source(s, c0, r0, w, h, time_index)
                 for s in b.sources]
        if not in_ar:
            raise ValueError(f"VRT band {band} has no sources")
        if not b.pixel_fn_type:
            return in_ar[0]
        out = np.zeros((h, w), _DTYPES.get(b.dtype.lower(), np.float32))
        if b.pixel_fn_language in ("", "python"):
            fn = _compile_python_fn(b.pixel_fn_type, b.pixel_fn_code)
            fn(in_ar, out, c0, r0, w, h, self.width, self.height, 0,
               tuple(self.ds.geo_transform))
            return out
        if b.pixel_fn_language == "expression":
            # a bare expression string, not a bands list: compile it
            # directly (parse_band_expressions treats single-part
            # entries as band names, reference '='-split semantics)
            from ..ops.expr import compile_expr
            ce = compile_expr(b.pixel_fn_code.strip())
            env = {f"b{i + 1}": np.asarray(a, np.float32)
                   for i, a in enumerate(in_ar)}
            out[:] = np.asarray(ce(env, xp=np))
            return out
        raise ValueError(
            f"unsupported PixelFunctionLanguage {b.pixel_fn_language!r}")

    def _read_source(self, s: VRTSource, c0, r0, w, h,
                     time_index: Optional[int]) -> np.ndarray:
        from .geotiff import GeoTIFF
        from .netcdf import NetCDF

        is_nc = s.path.lower().endswith((".nc", ".nc4")) \
            or s.path.upper().startswith("NETCDF:")
        path, var = s.path, None
        if ":" in s.path and s.path.upper().startswith("NETCDF:"):
            parts = s.path.split(":")
            path = parts[1].strip('"')
            var = parts[-1].strip('"')
        if is_nc:
            with NetCDF(path) as nc:
                v = nc.variables[var] if var else nc.raster_vars()[0]
                sh, sw = v.shape[-2], v.shape[-1]
                sc0, sr0, scw, srh = self._src_window(sw, sh, c0, r0, w, h)
                data = nc.read_slice(v.name, time_index,
                                     (sc0, sr0, scw, srh))
        else:
            with GeoTIFF(path) as g:
                sw, sh = g.width, g.height
                sc0, sr0, scw, srh = self._src_window(sw, sh, c0, r0, w, h)
                data = g.read(1, (sc0, sr0, scw, srh))
        if data.shape != (h, w):
            # VRT grid is a scaled view of the source: nearest resample
            rr = (np.arange(h) + 0.5) * data.shape[0] / h
            cc = (np.arange(w) + 0.5) * data.shape[1] / w
            data = data[np.clip(rr.astype(int), 0, data.shape[0] - 1)
                        [:, None],
                        np.clip(cc.astype(int), 0, data.shape[1] - 1)]
        return data

    def _src_window(self, sw: int, sh: int, c0, r0, w, h):
        """Map a VRT-grid window onto a (possibly larger) source."""
        fx = sw / self.width
        fy = sh / self.height
        sc0 = int(math.floor(c0 * fx))
        sr0 = int(math.floor(r0 * fy))
        scw = max(1, int(math.ceil(w * fx)))
        srh = max(1, int(math.ceil(h * fy)))
        scw = min(scw, sw - sc0)
        srh = min(srh, sh - sr0)
        return sc0, sr0, scw, srh


def _compile_python_fn(name: str, code: str):
    """GDAL-style Python pixel function: the VRT ships the function body
    (trusted, server-registered templates — the reference executes these
    through GDAL's Python pixel functions, `vrt_manager.go` + GDAL
    gdal_pixfun docs).  Gated on GSKY_VRT_ENABLE_PYTHON (default on, the
    reference's `gdal_init.go` sets GDAL_VRT_ENABLE_PYTHON=YES) so
    operators can disable arbitrary-code pixel functions on workers whose
    gRPC port accepts caller-supplied rendered VRT XML; the jit
    'expression' language path stays available either way."""
    import os
    if os.environ.get("GSKY_VRT_ENABLE_PYTHON", "YES").upper() in (
            "NO", "0", "FALSE", "OFF"):
        raise ValueError(
            "Python pixel functions disabled (GSKY_VRT_ENABLE_PYTHON=NO); "
            "use an 'expression'-language PixelFunctionType instead")
    ns: dict = {"np": np, "numpy": np}
    exec(compile(code, "<vrt-pixel-function>", "exec"), ns)  # noqa: S102
    fn = ns.get(name)
    if fn is None:
        raise ValueError(f"pixel function {name!r} not defined by "
                         "PixelFunctionCode")
    return fn


# ---------------------------------------------------------------------------
# per-granule template rendering (`processor/drill_indexer.go:318-346`)
# ---------------------------------------------------------------------------

_RANGE_RE = re.compile(
    r"\{\{\s*range\s+(?:\w+\s*:?=\s*)?\.Masks\s*\}\}(.*?)\{\{\s*end\s*\}\}",
    re.S)
_FIELD_RE = re.compile(r"\{\{\s*\.?(?:\w+\.)*(\w+)\s*\}\}")


def render_vrt(template: str, data_path: str,
               mask_paths: Sequence[str] = (),
               raster_x_size: float = 0.0,
               raster_y_size: float = 0.0) -> str:
    """Render a WPS VRT template with the reference's context
    ``{RasterXSize, RasterYSize, Data, Masks}`` — supports the
    ``{{ .Data.Path }}`` / ``{{ range ... .Masks }}`` subset the shipped
    templates use (`templates/WPS_VRTs/masks_example.vrt`)."""

    def expand_range(m: "re.Match[str]") -> str:
        body = m.group(1)
        return "".join(
            _FIELD_RE.sub(lambda f: _mask_field(f, p), body)
            for p in mask_paths)

    def _mask_field(f: "re.Match[str]", path: str) -> str:
        return path if f.group(1) == "Path" else f.group(0)

    out = _RANGE_RE.sub(expand_range, template)

    def sub_field(m: "re.Match[str]") -> str:
        name = m.group(1)
        if name == "Path":
            return data_path
        if name == "RasterXSize":
            return _fmt_size(raster_x_size)
        if name == "RasterYSize":
            return _fmt_size(raster_y_size)
        return m.group(0)

    return _FIELD_RE.sub(sub_field, out)


def _fmt_size(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))
