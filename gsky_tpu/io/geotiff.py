"""GeoTIFF codec, from scratch (no GDAL).

Plays the role GDAL's GTiff driver plays for the reference: windowed band
reads feeding the warp executor (`worker/gdalprocess/warp.go:89-101`
opens + reads via GDAL) and the tiled streaming writer used by WCS
(`utils/ogc_encoders.go:277-538`).

Reader: classic TIFF + BigTIFF, little/big endian, striped + tiled,
chunky (PlanarConfiguration=1) and separate (2) layouts, compression
none/LZW/deflate/packbits, predictor 1/2/3, sample formats
uint/int/float 8/16/32/64 bits, GDAL_NODATA, GeoKey directory -> CRS,
overview IFDs.  Windowed reads touch only the strips/tiles that intersect
the window — the IO behaviour the reference gets from its block-cache
warp loop (`warp.go:259-345`).

Writer: tiled (or strip) GeoTIFF with deflate, geokeys from EPSG CRSs,
GDAL_NODATA, chunky multiband, optional `append_overview`.

A native C++ fast path for tile decode lives in `gsky_tpu/native`
(deflate/LZW + predictor), used automatically when built.
"""

from __future__ import annotations

import math
import struct
import zlib
from dataclasses import dataclass, field
from typing import BinaryIO, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..geo.crs import CRS, EPSG4326, parse_crs
from ..geo.transform import BBox, GeoTransform

# TIFF tag ids
T_WIDTH, T_HEIGHT = 256, 257
T_BITS, T_COMPRESSION, T_PHOTOMETRIC = 258, 259, 262
T_STRIP_OFFSETS, T_SAMPLES, T_ROWS_PER_STRIP, T_STRIP_COUNTS = 273, 277, 278, 279
T_PLANAR = 284
T_PREDICTOR = 317
T_COLORMAP = 320
T_TILE_W, T_TILE_H, T_TILE_OFFSETS, T_TILE_COUNTS = 322, 323, 324, 325
T_SAMPLE_FORMAT = 339
T_MODEL_PIXEL_SCALE, T_MODEL_TIEPOINT, T_MODEL_TRANSFORM = 33550, 33922, 34264
T_GEO_DIR, T_GEO_DOUBLES, T_GEO_ASCII = 34735, 34736, 34737
T_GDAL_METADATA, T_GDAL_NODATA = 42112, 42113
T_NEWSUBFILETYPE = 254

COMP_NONE, COMP_LZW, COMP_PACKBITS = 1, 5, 32773
COMP_DEFLATE, COMP_DEFLATE_OLD = 8, 32946

# TIFF field types -> (struct fmt, size)
_FIELD = {1: ("B", 1), 2: ("c", 1), 3: ("H", 2), 4: ("I", 4), 5: ("II", 8),
          6: ("b", 1), 8: ("h", 2), 9: ("i", 4), 10: ("ii", 8),
          11: ("f", 4), 12: ("d", 8), 16: ("Q", 8), 17: ("q", 8)}


def _np_dtype(bits: int, fmt: int):
    kind = {1: "u", 2: "i", 3: "f"}.get(fmt, "u")
    return np.dtype(f"{kind}{bits // 8}")


# ---------------------------------------------------------------------------
# Decompression
# ---------------------------------------------------------------------------

try:
    from ..native import codec as _native
except Exception:  # pragma: no cover - native build optional
    _native = None


def _lzw_decode(data: bytes, expected: int) -> bytes:
    """TIFF-variant LZW (MSB-first codes, early code-size change)."""
    if _native is not None:
        return _native.lzw_decode(data, expected)
    out = bytearray()
    table: List[bytes] = [bytes([i]) for i in range(256)] + [b"", b""]
    CLEAR, EOI = 256, 257
    bitpos = 0
    width = 9
    prev: Optional[bytes] = None
    n = len(data) * 8
    while bitpos + width <= n:
        byte0 = bitpos >> 3
        # read `width` bits MSB-first
        chunk = int.from_bytes(data[byte0:byte0 + 3].ljust(3, b"\0"), "big")
        code = (chunk >> (24 - (bitpos & 7) - width)) & ((1 << width) - 1)
        bitpos += width
        if code == CLEAR:
            table = table[:258]
            width = 9
            prev = None
            continue
        if code == EOI:
            break
        if prev is None:
            entry = table[code]
            out += entry
            prev = entry
        else:
            if code < len(table):
                entry = table[code]
            elif code == len(table):
                entry = prev + prev[:1]
            else:
                raise ValueError("corrupt LZW stream")
            out += entry
            table.append(prev + entry[:1])
            prev = entry
        # early change: TIFF bumps width when next code would not fit
        if len(table) + 1 >= (1 << width) and width < 12:
            width += 1
        if len(out) >= expected:
            break
    return bytes(out[:expected])


def _packbits_decode(data: bytes, expected: int) -> bytes:
    if _native is not None:
        return _native.packbits_decode(data, expected)
    out = bytearray()
    i = 0
    while i < len(data) and len(out) < expected:
        nv = data[i]
        n = nv - 256 if nv > 127 else nv
        i += 1
        if n >= 0:
            out += data[i:i + n + 1]
            i += n + 1
        elif n != -128:
            out += data[i:i + 1] * (1 - n)
            i += 1
    return bytes(out[:expected])


def _decompress(data: bytes, comp: int, expected: int) -> bytes:
    if comp == COMP_NONE:
        return data[:expected]
    if comp in (COMP_DEFLATE, COMP_DEFLATE_OLD):
        return zlib.decompress(data)[:expected]
    if comp == COMP_LZW:
        return _lzw_decode(data, expected)
    if comp == COMP_PACKBITS:
        return _packbits_decode(data, expected)
    raise ValueError(f"unsupported TIFF compression {comp}")


# ---------------------------------------------------------------------------
# IFD parsing
# ---------------------------------------------------------------------------

@dataclass
class IFD:
    tags: Dict[int, tuple]
    offset: int

    def val(self, tag: int, default=None):
        v = self.tags.get(tag)
        if v is None:
            return default
        return v[0] if len(v) == 1 else v

    def arr(self, tag: int) -> tuple:
        return self.tags.get(tag, ())

    @property
    def width(self) -> int:
        return int(self.val(T_WIDTH))

    @property
    def height(self) -> int:
        return int(self.val(T_HEIGHT))


@dataclass
class ChunkMap:
    """Per-chunk byte-range layout of one IFD (tile grid, or strips —
    modelled as a 1-wide chunk column of chunk_w == raster width).
    ``offsets``/``counts`` are the raw TIFF arrays, plane-major for
    PlanarConfiguration=2."""
    tiled: bool
    chunk_w: int
    chunk_h: int
    chunks_x: int
    chunks_y: int
    offsets: tuple
    counts: tuple
    samples: int
    planar: int

    @property
    def nchunks(self) -> int:
        return self.chunks_x * self.chunks_y

    def ranges_for(self, window: Tuple[int, int, int, int],
                   band: int = 1) -> List[Tuple[int, int]]:
        """(offset, nbytes) of every chunk a (col0, row0, w, h) window
        touches, row-major — the exact byte set a ranged reader fetches
        for that window."""
        c0, r0, w, h = window
        bi = band - 1
        plane_off = bi * self.nchunks if self.planar == 2 else 0
        out: List[Tuple[int, int]] = []
        for cy in range(r0 // self.chunk_h,
                        (r0 + h - 1) // self.chunk_h + 1):
            for cx in range(c0 // self.chunk_w,
                            (c0 + w - 1) // self.chunk_w + 1):
                idx = plane_off + cy * self.chunks_x + cx
                out.append((int(self.offsets[idx]), int(self.counts[idx])))
        return out


class GeoTIFF:
    """Reader.  Open, inspect, read windows; overview IFDs exposed as
    `overviews` (list of (factor, IFD))."""

    def __init__(self, path_or_fp: Union[str, BinaryIO]):
        import threading
        if isinstance(path_or_fp, (str, bytes)):
            self._fp = open(path_or_fp, "rb")
            self.path = path_or_fp
        else:
            self._fp = path_or_fp
            self.path = getattr(path_or_fp, "name", "<memory>")
        self._fp_lock = threading.Lock()
        try:
            cur = self._fp.tell()
            self._fp.seek(0, 2)
            self._file_size = self._fp.tell()
            self._fp.seek(cur)
        except OSError:
            self._file_size = 1 << 40
        self._parse_header()
        self._parse_geo()

    def close(self):
        self._fp.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # -- header -------------------------------------------------------------

    def _parse_header(self):
        fp = self._fp
        fp.seek(0)
        magic = fp.read(4)
        if magic[:2] == b"II":
            self._e = "<"
        elif magic[:2] == b"MM":
            self._e = ">"
        else:
            raise ValueError("not a TIFF file")
        ver = struct.unpack(self._e + "H", magic[2:4])[0]
        self.bigtiff = ver == 43
        if self.bigtiff:
            fp.read(4)  # offset size + pad
            first = struct.unpack(self._e + "Q", fp.read(8))[0]
        elif ver == 42:
            first = struct.unpack(self._e + "I", fp.read(4))[0]
        else:
            raise ValueError(f"bad TIFF version {ver}")
        self.ifds: List[IFD] = []
        off = first
        seen = set()
        try:
            while off and off not in seen and len(self.ifds) < 64:
                seen.add(off)
                ifd, off = self._read_ifd(off)
                self.ifds.append(ifd)
        except struct.error as e:
            raise ValueError(f"corrupt TIFF: {e}") from e
        if not self.ifds:
            raise ValueError("corrupt TIFF: no IFDs")
        main = [i for i in self.ifds
                if not (int(i.val(T_NEWSUBFILETYPE, 0)) & 1)]
        self.ifd = main[0] if main else self.ifds[0]
        self.overviews: List[Tuple[int, IFD]] = []
        for i in self.ifds:
            if i is self.ifd:
                continue
            if int(i.val(T_NEWSUBFILETYPE, 0)) & 1 or i.width < self.ifd.width:
                f = int(round(self.ifd.width / i.width))
                self.overviews.append((f, i))
        self.overviews.sort(key=lambda t: t[0])

    def _read_ifd(self, off: int) -> Tuple[IFD, int]:
        fp = self._fp
        e = self._e
        fp.seek(off)
        if self.bigtiff:
            n = struct.unpack(e + "Q", fp.read(8))[0]
            entry_size, count_fmt, off_fmt = 20, "Q", "Q"
        else:
            n = struct.unpack(e + "H", fp.read(2))[0]
            entry_size, count_fmt, off_fmt = 12, "I", "I"
        if entry_size * n > self._file_size:
            # a corrupt (esp. BigTIFF u64) entry count must not drive a
            # terabyte pre-allocation in fp.read
            raise ValueError(
                f"corrupt TIFF: IFD declares {n} entries")
        raw = fp.read(entry_size * n)
        next_off = struct.unpack(e + off_fmt, fp.read(struct.calcsize(off_fmt)))[0]
        tags = {}
        inline = 8 if self.bigtiff else 4
        for k in range(n):
            ent = raw[k * entry_size:(k + 1) * entry_size]
            tag, typ = struct.unpack(e + "HH", ent[:4])
            cnt = struct.unpack(e + count_fmt, ent[4:4 + struct.calcsize(count_fmt)])[0]
            if typ not in _FIELD:
                continue
            fmt, size = _FIELD[typ]
            total = size * cnt
            if total > self._file_size:
                # corrupt count: reading it would pre-allocate the
                # declared bytes in C (uninterruptible for huge values)
                raise ValueError(
                    f"corrupt TIFF: tag {tag} declares {total} bytes")
            payload = ent[4 + struct.calcsize(count_fmt):]
            if total <= inline:
                data = payload[:total]
            else:
                ptr = struct.unpack(e + off_fmt, payload[:struct.calcsize(off_fmt)])[0]
                cur = fp.tell()
                fp.seek(ptr)
                data = fp.read(total)
                fp.seek(cur)
            if typ == 2:  # ascii
                tags[tag] = (data.split(b"\0")[0].decode("latin-1"),)
            elif typ in (5, 10):  # (signed) rationals: numerator/denominator
                c = "I" if typ == 5 else "i"
                vals = struct.unpack(e + c * 2 * cnt, data)
                tags[tag] = tuple(vals[i] / (vals[i + 1] or 1)
                                  for i in range(0, len(vals), 2))
            else:
                tags[tag] = struct.unpack(e + fmt * cnt, data)
        return IFD(tags, off), next_off

    # -- geo metadata --------------------------------------------------------

    def _parse_geo(self):
        ifd = self.ifd
        scale = ifd.arr(T_MODEL_PIXEL_SCALE)
        tie = ifd.arr(T_MODEL_TIEPOINT)
        xform = ifd.arr(T_MODEL_TRANSFORM)
        if xform and len(xform) >= 16:
            self.gt = GeoTransform(xform[3], xform[0], xform[1],
                                   xform[7], xform[4], xform[5])
        elif scale and tie:
            sx, sy = scale[0], scale[1]
            px, py, _, gx, gy, _ = tie[:6]
            self.gt = GeoTransform(gx - px * sx, sx, 0.0,
                                   gy + py * sy, 0.0, -sy)
        else:
            self.gt = GeoTransform(0.0, 1.0, 0.0, 0.0, 0.0, -1.0)
        self.crs = self._geokeys_to_crs()
        nd = ifd.val(T_GDAL_NODATA)
        self.nodata: Optional[float] = None
        if nd is not None:
            try:
                self.nodata = float(str(nd).strip())
            except ValueError:
                pass

    def _geokeys_to_crs(self) -> CRS:
        d = self.ifd.arr(T_GEO_DIR)
        if not d:
            return EPSG4326
        keys = {}
        doubles = self.ifd.arr(T_GEO_DOUBLES)
        ascii_ = self.ifd.val(T_GEO_ASCII, "")
        for i in range(4, len(d), 4):
            kid, loc, cnt, val = d[i:i + 4]
            if loc == 0:
                keys[kid] = val
            elif loc == T_GEO_DOUBLES:
                keys[kid] = doubles[val:val + cnt]
            elif loc == T_GEO_ASCII:
                keys[kid] = ascii_[val:val + cnt].rstrip("|")
        # 3072 ProjectedCSType, 2048 GeographicType
        for key in (3072, 2048):
            code = keys.get(key)
            if isinstance(code, int) and 1024 <= code <= 32767:
                try:
                    return parse_crs(int(code))
                except ValueError:
                    pass
        # fall back to citation proj4/wkt-ish text if present
        for key in (1026, 2049, 3073):
            cit = keys.get(key)
            if isinstance(cit, str) and cit:
                try:
                    return parse_crs(cit)
                except ValueError:
                    pass
        return EPSG4326

    # -- structure -----------------------------------------------------------

    @property
    def width(self) -> int:
        return self.ifd.width

    @property
    def height(self) -> int:
        return self.ifd.height

    @property
    def count(self) -> int:
        return int(self.ifd.val(T_SAMPLES, 1))

    @property
    def dtype(self) -> np.dtype:
        bits = self.ifd.arr(T_BITS) or (8,)
        fmt = self.ifd.arr(T_SAMPLE_FORMAT) or (1,)
        return _np_dtype(int(bits[0]), int(fmt[0]))

    def bbox(self) -> BBox:
        return self.gt.bbox(self.width, self.height)

    def chunk_map(self, ifd: Optional[IFD] = None) -> "ChunkMap":
        """The byte-range layout of one IFD: per-chunk (offset, nbytes)
        over the tile/strip grid — what a ranged reader needs to fetch
        exactly the chunks a window touches (docs/INGEST.md)."""
        ifd = ifd or self.ifd
        W, H = ifd.width, ifd.height
        samples = int(ifd.val(T_SAMPLES, 1))
        planar = int(ifd.val(T_PLANAR, 1))
        if ifd.tags.get(T_TILE_OFFSETS):
            tw, th = int(ifd.val(T_TILE_W)), int(ifd.val(T_TILE_H))
            return ChunkMap(True, tw, th, (W + tw - 1) // tw,
                            (H + th - 1) // th,
                            ifd.arr(T_TILE_OFFSETS), ifd.arr(T_TILE_COUNTS),
                            samples, planar)
        rps = int(ifd.val(T_ROWS_PER_STRIP, H))
        return ChunkMap(False, W, rps, 1, (H + rps - 1) // rps,
                        ifd.arr(T_STRIP_OFFSETS), ifd.arr(T_STRIP_COUNTS),
                        samples, planar)

    # -- reading -------------------------------------------------------------

    def read(self, band: int = 1, window: Optional[Tuple[int, int, int, int]] = None,
             ifd: Optional[IFD] = None, *, source=None,
             out: Optional[np.ndarray] = None) -> np.ndarray:
        """Read one band (1-based, GDAL convention).  window =
        (col0, row0, w, h).  Returns (h, w) in storage dtype.

        ``source`` (an `ingest.source.ByteSource`) reroutes the block
        byte fetches through coalesced ranged reads instead of the
        handle's seek+read loop — same blocks, same decode, same
        assembly, so the output is byte-identical by construction.
        ``out`` decodes straight into a caller-provided (h, w) array
        (any assignable dtype — the ingest staging buffers pass
        page-grid-aligned f32 views here to skip the intermediate
        window copy)."""
        ifd = ifd or self.ifd
        W, H = ifd.width, ifd.height
        if window is None:
            window = (0, 0, W, H)
        c0, r0, w, h = window
        if c0 < 0 or r0 < 0 or c0 + w > W or r0 + h > H:
            raise ValueError(f"window {window} outside raster {W}x{H}")
        if w * h > (1 << 31):
            # corrupt headers can declare absurd dims; allocating the
            # output first would stall uninterruptibly
            raise ValueError(f"window {w}x{h} implausibly large")
        samples = int(ifd.val(T_SAMPLES, 1))
        planar = int(ifd.val(T_PLANAR, 1))
        bits = ifd.arr(T_BITS) or (8,)
        fmts = ifd.arr(T_SAMPLE_FORMAT) or (1,)
        dt = _np_dtype(int(bits[0]), int(fmts[0])).newbyteorder(self._e)
        comp = int(ifd.val(T_COMPRESSION, 1))
        pred = int(ifd.val(T_PREDICTOR, 1))
        if out is None:
            out = np.zeros((h, w), dtype=dt.newbyteorder("="))
        elif out.shape != (h, w):
            raise ValueError(f"out shape {out.shape} != window ({h}, {w})")
        bi = band - 1
        if not (0 <= bi < samples):
            raise ValueError(f"band {band} out of range (1..{samples})")

        if ifd.tags.get(T_TILE_OFFSETS):
            tw = int(ifd.val(T_TILE_W))
            th = int(ifd.val(T_TILE_H))
            offsets = ifd.arr(T_TILE_OFFSETS)
            counts = ifd.arr(T_TILE_COUNTS)
            tiles_x = (W + tw - 1) // tw
            tiles_y = (H + th - 1) // th
            plane_off = bi * tiles_x * tiles_y if planar == 2 else 0
            spp = 1 if planar == 2 else samples
            blocks = [(ty, tx)
                      for ty in range(r0 // th, (r0 + h - 1) // th + 1)
                      for tx in range(c0 // tw, (c0 + w - 1) // tw + 1)]
            raws = self._fetch_blocks(
                [(offsets[plane_off + ty * tiles_x + tx],
                  counts[plane_off + ty * tiles_x + tx])
                 for ty, tx in blocks], source)
            for (ty, tx), raw in zip(blocks, raws):
                block = self._decode_raw(raw, comp, pred, th, tw, spp, dt)
                data = block[..., 0 if planar == 2 else bi]
                # intersect tile with window
                br0, bc0 = ty * th, tx * tw
                rr0 = max(r0, br0)
                rr1 = min(r0 + h, br0 + th)
                cc0 = max(c0, bc0)
                cc1 = min(c0 + w, bc0 + tw)
                out[rr0 - r0:rr1 - r0, cc0 - c0:cc1 - c0] = \
                    data[rr0 - br0:rr1 - br0, cc0 - bc0:cc1 - bc0]
        else:
            rps = int(ifd.val(T_ROWS_PER_STRIP, H))
            offsets = ifd.arr(T_STRIP_OFFSETS)
            counts = ifd.arr(T_STRIP_COUNTS)
            strips = (H + rps - 1) // rps
            plane_off = bi * strips if planar == 2 else 0
            spp = 1 if planar == 2 else samples
            rows = list(range(r0 // rps, (r0 + h - 1) // rps + 1))
            raws = self._fetch_blocks(
                [(offsets[plane_off + s], counts[plane_off + s])
                 for s in rows], source)
            for s, raw in zip(rows, raws):
                srows = min(rps, H - s * rps)
                block = self._decode_raw(raw, comp, pred, srows, W, spp, dt)
                data = block[..., 0 if planar == 2 else bi]
                br0 = s * rps
                rr0 = max(r0, br0)
                rr1 = min(r0 + h, br0 + srows)
                out[rr0 - r0:rr1 - r0, :] = data[rr0 - br0:rr1 - br0, c0:c0 + w]
        return out

    def _fetch_blocks(self, ranges, source) -> List[bytes]:
        """Raw (compressed) bytes for each (offset, nbytes) block — via
        coalesced ranged reads through ``source`` when given, else the
        handle's own fp.  Bounds are enforced for BOTH paths: a corrupt
        header must not drive a huge pre-allocating read anywhere."""
        for offset, nbytes in ranges:
            if offset < 0 or nbytes < 0 \
                    or offset + nbytes > self._file_size:
                raise ValueError(
                    f"corrupt TIFF: block [{offset}, {offset + nbytes}) "
                    f"beyond file size {self._file_size}")
        if source is not None:
            from ..ingest.source import fetch_ranges
            return fetch_ranges(source, ranges)
        out = []
        with self._fp_lock:  # shared handles are read from worker threads
            for offset, nbytes in ranges:
                self._fp.seek(offset)
                out.append(self._fp.read(nbytes))
        return out

    def _decode_block(self, offset: int, nbytes: int, comp: int, pred: int,
                      rows: int, cols: int, samples: int, dt: np.dtype) -> np.ndarray:
        raw = self._fetch_blocks([(offset, nbytes)], None)[0]
        return self._decode_raw(raw, comp, pred, rows, cols, samples, dt)

    def _decode_raw(self, raw: bytes, comp: int, pred: int,
                    rows: int, cols: int, samples: int, dt: np.dtype) -> np.ndarray:
        expected = rows * cols * samples * dt.itemsize
        if expected > (1 << 31):
            # the decompress output buffer PRE-ALLOCATES its full size
            raise ValueError(
                f"corrupt TIFF: block declares {expected} bytes")
        data = _decompress(raw, comp, expected)
        if len(data) < expected:
            data = data + b"\0" * (expected - len(data))
        if pred == 3:
            # float predictor: per row, bytes stored plane-separated and
            # horizontally differenced as uint8
            if _native is not None:
                out = _native.unpredict_fp(data, rows, cols, samples,
                                           dt.itemsize)
                return np.frombuffer(out, dt.newbyteorder("<")).reshape(
                    rows, cols, samples).astype(dt.newbyteorder("="))
            b = np.frombuffer(data, np.uint8).reshape(rows, cols * samples * dt.itemsize)
            b = np.cumsum(b, axis=1, dtype=np.uint8)
            # deinterleave significance planes (big-endian order)
            b = b.reshape(rows, dt.itemsize, cols * samples)
            b = np.transpose(b, (0, 2, 1))[:, :, ::-1]  # to little-endian bytes
            arr = np.ascontiguousarray(b).view(dt.newbyteorder("<")).reshape(
                rows, cols, samples)
            return arr.astype(dt.newbyteorder("="))
        arr = np.frombuffer(data, dt).reshape(rows, cols, samples)
        if pred == 2:
            arr = arr.astype(dt.newbyteorder("="), copy=True)
            if _native is None or not _native.unpredict_h(arr):
                arr = np.cumsum(arr, axis=1, dtype=arr.dtype)
            return arr
        return arr.astype(dt.newbyteorder("="), copy=False).reshape(
            rows, cols, samples)

    def pick_overview(self, stride: float):
        """(fx, fy, ifd) for the coarsest overview whose decimation
        factor fits under ``stride`` source pixels per destination pixel
        — the decode-path overview selection of
        `worker/gdalprocess/warp.go:156-198`.  (1.0, 1.0, None) when
        full resolution is the right level."""
        best = None
        for f, ifd in self.overviews:
            if f <= stride:
                best = ifd
        if best is None:
            return 1.0, 1.0, None
        # exact ratios, not the rounded factor: odd-sized rasters have
        # overview dims like ceil(W/2), and the geotransform must match
        return self.width / best.width, self.height / best.height, best

    def read_window_geo(self, bbox: BBox, band: int = 1):
        """Read the pixel window covering a geographic bbox; returns
        (data, window_gt) or (None, None) when disjoint."""
        c0, r0 = self.gt.geo_to_pixel(bbox.xmin, bbox.ymax)
        c1, r1 = self.gt.geo_to_pixel(bbox.xmax, bbox.ymin)
        c0, c1 = sorted((c0, c1))
        r0, r1 = sorted((r0, r1))
        c0 = max(int(math.floor(c0)), 0)
        r0 = max(int(math.floor(r0)), 0)
        c1 = min(int(math.ceil(c1)), self.width)
        r1 = min(int(math.ceil(r1)), self.height)
        if c0 >= c1 or r0 >= r1:
            return None, None
        data = self.read(band, (c0, r0, c1 - c0, r1 - r0))
        return data, self.gt.window(c0, r0)


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

_SAMPLE_FMT = {"u": 1, "i": 2, "f": 3}


class GeoTIFFWriter:
    """Streaming tiled GeoTIFF writer.

    Tiles append to disk in any order as they are rendered (RAM stays
    O(tile)); the IFD is written at close().  This is the rebuild's
    answer to the reference's incremental WCS output flush
    (`ows.go:695,1088-1091` + `utils/ogc_encoders.go:277-538`): very
    large GetCoverage exports stream to the temp file instead of
    accumulating whole-coverage arrays in memory.  Unwritten tiles
    resolve to a shared nodata-filled block.  Thread-safe.
    """

    def __init__(self, path: str, bands: int, height: int, width: int,
                 dtype, gt: GeoTransform, crs: CRS,
                 nodata: Optional[float] = None, tile_size: int = 256,
                 compress: bool = True):
        import threading
        self.path = path
        self.bands = bands
        self.height = height
        self.width = width
        self.dtype = np.dtype(dtype)
        self.gt = gt
        self.crs = crs
        self.nodata = nodata
        self.tile_size = tile_size
        self.compress = compress
        self.tiles_x = (width + tile_size - 1) // tile_size
        self.tiles_y = (height + tile_size - 1) // tile_size
        self._lock = threading.Lock()
        self._tiles: dict = {}      # (ty, tx) -> (offset, nbytes)
        self._ovr: List[dict] = []  # reduced-resolution IFDs-to-be
        self._fp = open(path, "wb")
        self._fp.write(b"II*\0\0\0\0\0")   # IFD offset patched at close
        self._pos = 8
        self._closed = False

    def _encode_block(self, block: np.ndarray) -> bytes:
        ts = self.tile_size
        full = np.full((ts, ts, self.bands),
                       self.nodata if self.nodata is not None else 0,
                       dtype=self.dtype)
        h, w = block.shape[1], block.shape[2]
        full[:h, :w, :] = np.transpose(block, (1, 2, 0))
        raw = full.astype(self.dtype.newbyteorder("<")).tobytes()
        return zlib.compress(raw, 6) if self.compress else raw

    def write_tile(self, tx: int, ty: int, block: np.ndarray) -> None:
        """block: (bands, th, tw) in storage dtype; edge tiles may be
        smaller than tile_size (padded with nodata)."""
        blob = self._encode_block(np.asarray(block, self.dtype))
        with self._lock:
            off = self._pos
            self._fp.write(blob)
            self._pos += len(blob)
            self._tiles[(ty, tx)] = (off, len(blob))

    def write_region(self, x0: int, y0: int, data: np.ndarray) -> None:
        """Write a tile-aligned region (bands, h, w) at pixel (x0, y0);
        (x0, y0) must lie on a tile boundary."""
        ts = self.tile_size
        _, h, w = data.shape
        for ty in range(y0 // ts, (y0 + h + ts - 1) // ts):
            for tx in range(x0 // ts, (x0 + w + ts - 1) // ts):
                r0 = ty * ts - y0
                c0 = tx * ts - x0
                sub = data[:, max(r0, 0):r0 + ts, max(c0, 0):c0 + ts]
                if sub.shape[1] and sub.shape[2]:
                    self.write_tile(tx, ty, sub)

    def append_overview(self, data) -> None:
        """Append one reduced-resolution level: ``data`` is the whole
        decimated raster, (bands, oh, ow) or (oh, ow).  Tile data is
        written immediately; the overview IFD (NewSubfileType=1,
        GDAL-pyramid style) chains after the main IFD at close().  Call
        in coarsening order before close()."""
        data = np.asarray(data)
        if data.ndim == 2:
            data = data[None]
        oh, ow = data.shape[1], data.shape[2]
        ts = self.tile_size
        txs = (ow + ts - 1) // ts
        tys = (oh + ts - 1) // ts
        tiles = {}
        for ty in range(tys):
            for tx in range(txs):
                block = data[:, ty * ts:min((ty + 1) * ts, oh),
                             tx * ts:min((tx + 1) * ts, ow)] \
                    .astype(self.dtype)
                blob = self._encode_block(block)
                with self._lock:
                    off = self._pos
                    self._fp.write(blob)
                    self._pos += len(blob)
                tiles[(ty, tx)] = (off, len(blob))
        self._ovr.append({"h": oh, "w": ow, "tiles": tiles,
                          "tiles_x": txs, "tiles_y": tys})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        e = "<"
        fp = self._fp
        # shared nodata blob for never-written tiles; under self._lock —
        # close() can race a straggling write_tile from a cancelled
        # export's worker still draining
        with self._lock:
            missing = [k for ty in range(self.tiles_y)
                       for tx in range(self.tiles_x)
                       if (k := (ty, tx)) not in self._tiles]
            if missing:
                blob = self._encode_block(
                    np.full((self.bands, 1, 1),
                            self.nodata if self.nodata is not None
                            else 0,
                            self.dtype))
                off = self._pos
                fp.write(blob)
                self._pos += len(blob)
                for k in missing:
                    self._tiles[k] = (off, len(blob))

        dt = self.dtype
        gt_ = self.gt
        crs = self.crs
        geo_keys = []
        if crs.is_geographic:
            geo_keys += [(1024, 0, 1, 2), (1025, 0, 1, 1),
                         (2048, 0, 1, crs.epsg or 4326)]
        elif crs.epsg:
            geo_keys += [(1024, 0, 1, 1), (1025, 0, 1, 1),
                         (3072, 0, 1, crs.epsg)]
        else:
            geo_keys += [(1024, 0, 1, 1), (1025, 0, 1, 1),
                         (3072, 0, 1, 32767)]
        ascii_params = "" if (crs.epsg or crs.is_geographic) \
            else crs.to_proj4() + "|"
        if ascii_params:
            geo_keys.append((3073, T_GEO_ASCII, len(ascii_params), 0))
        geo_dir = [1, 1, 0, len(geo_keys)]
        for k in geo_keys:
            geo_dir += list(k)

        fmt_code = _SAMPLE_FMT[dt.kind]
        bands = self.bands
        tags: List[Tuple[int, int, Sequence]] = [
            (T_WIDTH, 3, [self.width]),
            (T_HEIGHT, 3, [self.height]),
            (T_BITS, 3, [dt.itemsize * 8] * bands),
            (T_COMPRESSION, 3,
             [COMP_DEFLATE if self.compress else COMP_NONE]),
            (T_PHOTOMETRIC, 3, [1]),
            (T_SAMPLES, 3, [bands]),
            (T_PLANAR, 3, [1]),
            (T_TILE_W, 3, [self.tile_size]),
            (T_TILE_H, 3, [self.tile_size]),
            (T_SAMPLE_FORMAT, 3, [fmt_code] * bands),
            (T_GEO_DIR, 3, geo_dir),
        ]
        if gt_.is_north_up and gt_.dy < 0:
            tags.append((T_MODEL_PIXEL_SCALE, 12, [gt_.dx, -gt_.dy, 0.0]))
            tags.append((T_MODEL_TIEPOINT, 12,
                         [0.0, 0.0, 0.0, gt_.x0, gt_.y0, 0.0]))
        else:
            tags.append((T_MODEL_TRANSFORM, 12,
                         [gt_.dx, gt_.rx, 0.0, gt_.x0,
                          gt_.ry, gt_.dy, 0.0, gt_.y0,
                          0.0, 0.0, 0.0, 0.0,
                          0.0, 0.0, 0.0, 1.0]))
        if ascii_params:
            tags.append((T_GEO_ASCII, 2, ascii_params))
        if self.nodata is not None:
            nd = str(int(self.nodata)) \
                if float(self.nodata).is_integer() \
                else repr(float(self.nodata))
            tags.append((T_GDAL_NODATA, 2, nd))
        order = [(ty, tx) for ty in range(self.tiles_y)
                 for tx in range(self.tiles_x)]
        tags.append((T_TILE_OFFSETS, 4,
                     [self._tiles[k][0] for k in order]))
        tags.append((T_TILE_COUNTS, 4,
                     [self._tiles[k][1] for k in order]))
        tags.sort(key=lambda t: t[0])

        ifd_off, next_ptr = self._write_ifd(tags)
        fp.seek(4)
        fp.write(struct.pack(e + "I", ifd_off))
        fp.seek(self._pos)

        # reduced-resolution IFD chain (GDAL pyramid layout)
        for ov in self._ovr:
            ord_o = [(ty, tx) for ty in range(ov["tiles_y"])
                     for tx in range(ov["tiles_x"])]
            otags = [
                (T_NEWSUBFILETYPE, 4, [1]),
                (T_WIDTH, 3, [ov["w"]]),
                (T_HEIGHT, 3, [ov["h"]]),
                (T_BITS, 3, [dt.itemsize * 8] * bands),
                (T_COMPRESSION, 3,
                 [COMP_DEFLATE if self.compress else COMP_NONE]),
                (T_PHOTOMETRIC, 3, [1]),
                (T_SAMPLES, 3, [bands]),
                (T_PLANAR, 3, [1]),
                (T_TILE_W, 3, [self.tile_size]),
                (T_TILE_H, 3, [self.tile_size]),
                (T_SAMPLE_FORMAT, 3, [fmt_code] * bands),
                (T_TILE_OFFSETS, 4,
                 [ov["tiles"][k][0] for k in ord_o]),
                (T_TILE_COUNTS, 4,
                 [ov["tiles"][k][1] for k in ord_o]),
            ]
            otags.sort(key=lambda t: t[0])
            o_off, o_next = self._write_ifd(otags)
            fp.seek(next_ptr)
            fp.write(struct.pack(e + "I", o_off))
            fp.seek(self._pos)
            next_ptr = o_next
        fp.close()

    def _write_ifd(self, tags) -> Tuple[int, int]:
        """Pack + write one IFD (out-of-line values first) at the current
        end of file.  Returns (ifd offset, file offset of its next-IFD
        pointer, which is left as 0)."""
        e = "<"
        fp = self._fp
        blobs2 = []
        entries = []
        for tag, typ, vals in tags:
            if typ == 2:
                data_b = vals.encode("latin-1") + b"\0"
                cnt = len(data_b)
            else:
                fmtc, size = _FIELD[typ]
                data_b = struct.pack(e + fmtc * len(vals), *vals)
                cnt = len(vals)
            if len(data_b) <= 4:
                entries.append((tag, typ, cnt, data_b.ljust(4, b"\0"),
                                None))
            else:
                entries.append((tag, typ, cnt, None, data_b))
        # the file-position bump shares self._pos with write_tile /
        # append_overview, so it follows the same lock discipline even
        # though close() is effectively single-threaded
        with self._lock:
            ool_pos = self._pos
            for i, (tag, typ, cnt, inline, data_b) in \
                    enumerate(entries):
                if data_b is not None:
                    entries[i] = (tag, typ, cnt,
                                  struct.pack(e + "I", ool_pos), None)
                    blobs2.append(data_b)
                    ool_pos += len(data_b)
            ifd_off = ool_pos
            for b2 in blobs2:
                fp.write(b2)
            fp.write(struct.pack(e + "H", len(entries)))
            for tag, typ, cnt, inline, _ in entries:
                fp.write(struct.pack(e + "HHI", tag, typ, cnt) + inline)
            next_ptr = ifd_off + 2 + 12 * len(entries)
            fp.write(struct.pack(e + "I", 0))
            self._pos = next_ptr + 4
        return ifd_off, next_ptr

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_geotiff(path: str, data, gt: GeoTransform, crs: CRS,
                  nodata: Optional[float] = None, tile_size: int = 256,
                  compress: bool = True,
                  overviews: Sequence[int] = ()):
    """Write a (H, W) or (bands, H, W) array (or sequence of 2D bands)
    as a tiled GeoTIFF via the streaming writer.  ``overviews`` lists
    decimation factors (e.g. (2, 4, 8)) to embed as reduced-resolution
    IFDs, sampled nearest (GDAL's default overview resampling) so
    values — including nodata — pass through exactly.  Samples are taken
    at block CENTRES (offset f//2), because readers georeference
    overviews extent-preservingly (`GeoTransform.scaled`): top-left
    sampling would misregister every overview render by (f-1)/2 source
    pixels, centre sampling by at most half of one."""
    if isinstance(data, np.ndarray) and data.ndim == 2:
        data = data[None]
    bands = len(data)
    H, W = data[0].shape
    dt = np.result_type(*[np.asarray(b).dtype for b in data]) \
        if not isinstance(data, np.ndarray) else data.dtype
    w = GeoTIFFWriter(path, bands, H, W, dt, gt, crs, nodata=nodata,
                      tile_size=tile_size, compress=compress)
    ts = tile_size
    for ty in range(w.tiles_y):
        for tx in range(w.tiles_x):
            r1 = min((ty + 1) * ts, H)
            c1 = min((tx + 1) * ts, W)
            block = np.stack([np.asarray(b)[ty * ts:r1, tx * ts:c1]
                              for b in data]).astype(dt)
            w.write_tile(tx, ty, block)
    for f in sorted(overviews):
        if f < 2 or H // f < 1 or W // f < 1:
            continue
        w.append_overview(np.stack(
            [np.asarray(b)[f // 2::f, f // 2::f][:H // f, :W // f]
             for b in data]).astype(dt))
    w.close()
