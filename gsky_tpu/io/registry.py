"""Pluggable raster-format registry.

The reference warps ANY GDAL-openable dataset — `GDALOpen` + driver
dispatch (`worker/gdalprocess/warp.go:89-101`).  The TPU-native stack
keeps fast from-scratch readers for the hot formats (GeoTIFF, NetCDF-3,
NetCDF-4/HDF5, GMT grids) and widens the format universe through this
registry: each entry sniffs magic bytes (the GDALOpenInfo header test)
and returns a handle with the uniform "tiff-like" interface the decode,
scene-cache and drill paths consume —

    .width .height .nodata .overviews
    .read(band, (col0, row0, w, h)) -> np.ndarray
    .close()

plus optionally .gt (GeoTransform) and .crs for the crawler.

Resolution order: native readers first (fast paths), then optional
adapters — rasterio or GDAL when importable in the deployment image,
else the PIL image adapter (JPEG2000/PNG/JPEG/BMP + ESRI world-file
georeferencing).  Register custom formats with `register()`.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

_Entry = Tuple[str, Callable[[str, bytes], bool], Callable[[str], object]]

_lock = threading.Lock()
_formats: List[_Entry] = []


def register(name: str, sniff: Callable[[str, bytes], bool],
             opener: Callable[[str], object],
             prepend: bool = False) -> None:
    """Add a format: ``sniff(path, magic16)`` decides cheaply,
    ``opener(path)`` returns a tiff-like handle."""
    with _lock:
        if prepend:
            _formats.insert(0, (name, sniff, opener))
        else:
            _formats.append((name, sniff, opener))


def formats() -> List[str]:
    with _lock:
        return [name for name, _, _ in _formats]


def open_raster(path: str):
    """Open ``path`` with the first matching format.  Raises ValueError
    listing the sniffed magic when nothing claims the file."""
    try:
        with open(path, "rb") as fp:
            magic = fp.read(16)
    except OSError as e:
        raise ValueError(f"cannot read {path}: {e}") from e
    with _lock:
        entries = list(_formats)
    for name, sniff, opener in entries:
        try:
            claimed = sniff(path, magic)
        except Exception:
            claimed = False
        if claimed:
            return opener(path)
    raise ValueError(
        f"no registered reader for {path} (magic {magic[:8]!r}; "
        f"formats: {formats()})")


# -- built-in formats --------------------------------------------------------

def _sniff_tiff(path: str, magic: bytes) -> bool:
    return magic[:4] in (b"II*\0", b"MM\0*", b"II+\0", b"MM\0+")


def _open_tiff(path: str):
    from .geotiff import GeoTIFF
    return GeoTIFF(path)


def _sniff_gmt(path: str, magic: bytes) -> bool:
    if magic[:3] != b"CDF":
        return False
    from .gmt import is_gmt
    return is_gmt(path)


def _open_gmt(path: str):
    from .gmt import GMTGrid
    return GMTGrid(path)


def _sniff_hdf4(path: str, magic: bytes) -> bool:
    return magic[:4] == b"\x0e\x03\x13\x01"


def _open_hdf4(path: str):
    from .hdf4 import HDF4
    return HDF4(path)


register("geotiff", _sniff_tiff, _open_tiff)
register("gmt", _sniff_gmt, _open_gmt)
register("hdf4", _sniff_hdf4, _open_hdf4)
# NetCDF proper stays on the dedicated NetCDF facade (variables +
# hyperslabs, not a flat band model) — decode/drill route it by
# granule metadata before consulting the registry.


def _register_adapters() -> None:
    """Optional adapter tier, best first.  rasterio/GDAL are not in the
    default image (gated imports); the PIL adapter always lands."""
    try:
        import rasterio  # noqa: F401
        from .adapter import RasterioRaster, sniff_rasterio
        register("rasterio", sniff_rasterio,
                 lambda p: RasterioRaster(p))
    except ImportError:
        pass
    try:
        from osgeo import gdal  # noqa: F401
        from .adapter import GdalRaster, sniff_gdal
        register("gdal", sniff_gdal, lambda p: GdalRaster(p))
    except ImportError:
        pass
    from .adapter import ImageRaster, sniff_image
    register("pil-image", sniff_image, lambda p: ImageRaster(p))


_register_adapters()
