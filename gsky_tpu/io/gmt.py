"""Native GMT grid reader/writer.

The reference serves GMT grids through a forked GDAL driver
(`libs/gdal/frmts/gsky_netcdf/gmtdataset.cpp:226-404`): a GMT v4 grid
is a NetCDF-classic container carrying 1-D bookkeeping variables
``dimension`` (nx, ny), ``x_range``/``y_range``/``z_range`` (2-vectors)
and ``spacing``, plus the flat row-major grid in a 1-D variable ``z``
whose first row is the NORTH edge.  ``z:node_offset`` selects pixel
(1) vs gridline (0) registration; gridline-registered grids offset the
geotransform by half a pixel exactly as the driver does
(`gmtdataset.cpp:349-374`).  ``scale_factor``/``add_offset`` are
carried as metadata, not applied to pixels (GDAL RasterIO semantics —
consumers see raw stored values).

This reader rides the repo's own NetCDF-classic parser; `GMTGrid`
exposes the GeoTIFF-shaped handle interface (width/height/read/nodata/
overviews) so the decode, scene-cache and drill paths serve GMT
granules unchanged through `io.registry`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..geo.transform import GeoTransform
from .netcdf import NetCDF


def is_gmt(path: str) -> bool:
    """Cheap signature check: NetCDF container whose variable set has
    the GMT bookkeeping shape (`gmtdataset.cpp:256-268`)."""
    try:
        with open(path, "rb") as fp:
            if fp.read(3) != b"CDF":
                return False
        with NetCDF(path) as nc:
            v = nc.variables
            return "dimension" in v and "z" in v \
                and len(v["z"].shape) == 1
    except Exception:
        return False


class GMTGrid:
    """One-band GMT grid with the tiff-like handle interface."""

    def __init__(self, path: str):
        self.path = path
        self._nc = NetCDF(path)
        v = self._nc.variables
        if "dimension" not in v or "z" not in v:
            self._nc.close()
            raise ValueError(f"not a GMT grid: {path}")
        nm = np.asarray(v["dimension"][:2], np.int64)
        self.width = int(nm[0])
        self.height = int(nm[1])
        if self.width <= 0 or self.height <= 0 \
                or self.width * self.height > (1 << 31):
            self._nc.close()
            raise ValueError(f"bad GMT dimensions {nm}: {path}")
        z = v["z"]
        if int(np.prod(z.shape)) < self.width * self.height:
            self._nc.close()
            raise ValueError(f"GMT z variable too small: {path}")
        self.scale_factor = float(z.attrs.get("scale_factor", 1.0))
        self.add_offset = float(z.attrs.get("add_offset", 0.0))
        # absent attribute defaults to PIXEL registration, matching the
        # reference driver (`gmtdataset.cpp:330` inits node_offset = 1
        # before reading the attr) — parity over GMT's own convention
        node_offset = int(np.asarray(
            z.attrs.get("node_offset", 1)).reshape(-1)[0])
        self.gt = self._geotransform(v, node_offset)
        # GMT marks holes with NaN (float grids); integer grids carry
        # no nodata marker in the v4 layout
        self.nodata: Optional[float] = (
            float("nan") if np.dtype(z.dtype).kind == "f" else None)
        self.dtype = z.dtype
        self.overviews: Tuple = ()

    def _geotransform(self, v, node_offset: int) -> GeoTransform:
        if "x_range" not in v or "y_range" not in v:
            return GeoTransform(0.0, 1.0, 0.0, 0.0, 0.0, 1.0)
        xr = np.asarray(v["x_range"][:2], np.float64)
        yr = np.asarray(v["y_range"][:2], np.float64)
        if node_offset == 1:       # pixel registration
            dx = (xr[1] - xr[0]) / self.width
            dy = (yr[0] - yr[1]) / self.height
            return GeoTransform(float(xr[0]), float(dx), 0.0,
                                float(yr[1]), 0.0, float(dy))
        # gridline registration: samples sit ON the range ends
        dx = (xr[1] - xr[0]) / max(self.width - 1, 1)
        dy = (yr[0] - yr[1]) / max(self.height - 1, 1)
        return GeoTransform(float(xr[0] - dx * 0.5), float(dx), 0.0,
                            float(yr[1] - dy * 0.5), 0.0, float(dy))

    def read(self, band: int = 1,
             window: Optional[Tuple[int, int, int, int]] = None,
             ifd=None) -> np.ndarray:
        """(h, w) array for ``window`` = (col0, row0, w, h); row 0 is
        the north edge, as the flat z layout stores it."""
        if window is None:
            window = (0, 0, self.width, self.height)
        c0, r0, w, h = window
        z = self._nc.variables["z"]
        if c0 == 0 and w == self.width:
            # full-width read: ONE contiguous slice instead of h
            # variable round-trips (the scene/drill caches read whole
            # grids this way)
            flat = np.asarray(z[r0 * w:(r0 + h) * w])
            return flat.reshape(h, w)
        rows = []
        # row-contiguous slices out of the flat variable; the NC3/HDF5
        # readers slice without materialising the whole grid
        for r in range(r0, r0 + h):
            start = r * self.width + c0
            rows.append(np.asarray(z[start:start + w]))
        return np.stack(rows) if rows else \
            np.zeros((0, w), np.asarray(z[0:0]).dtype)

    def close(self):
        self._nc.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def write_gmt(path: str, data: np.ndarray, x_range: Tuple[float, float],
              y_range: Tuple[float, float],
              node_offset: int = 1) -> None:
    """Write a pixel/gridline-registered GMT v4 grid (fixtures + the
    WCS 'gmt' output style).  ``data`` (H, W) with row 0 = north."""
    from .netcdf import write_netcdf3_raw

    H, W = data.shape
    data = np.ascontiguousarray(data)
    zmin = float(np.nanmin(data)) if data.size else 0.0
    zmax = float(np.nanmax(data)) if data.size else 0.0
    sx = (x_range[1] - x_range[0]) / (W if node_offset else max(W - 1, 1))
    sy = (y_range[1] - y_range[0]) / (H if node_offset else max(H - 1, 1))
    write_netcdf3_raw(
        path, [("side", 2), ("xysize", H * W)], [
            ("x_range", ("side",), {},
             np.asarray(x_range, np.float64)),
            ("y_range", ("side",), {},
             np.asarray(y_range, np.float64)),
            ("z_range", ("side",), {},
             np.asarray([zmin, zmax], np.float64)),
            ("spacing", ("side",), {}, np.asarray([sx, sy], np.float64)),
            ("dimension", ("side",), {}, np.asarray([W, H], np.int32)),
            ("z", ("xysize",),
             {"node_offset": np.asarray([node_offset], np.int32)},
             data.reshape(-1)),
        ], {"title": "", "source": "gsky_tpu"})
